# lgb.Booster — training handle + prediction.
#
# API parity with the reference R-package/R/lgb.Booster.R and
# lgb.Predictor.R (update, rollback, eval, save/load/dump, predict with
# rawscore/leafidx, lgb.get.eval.result); our own R6 implementation over
# the .Call glue (src/lightgbm_tpu_R.c).

Booster <- R6::R6Class(
  classname = "lgb.Booster",
  cloneable = FALSE,
  public = list(
    best_iter = -1L,
    record_evals = list(),

    initialize = function(params = list(), train_set = NULL,
                          modelfile = NULL, model_str = NULL) {
      if (!is.null(train_set)) {
        train_set$construct()
        private$train_set <- train_set
        private$num_dataset <- 1L
        pstr <- lgb.params2str(params)
        private$handle <- lgb.call(
          "LGBM_BoosterCreate_R", train_set$get_handle(), pstr,
          ret = lgb.null.handle())
      } else if (!is.null(modelfile)) {
        private$handle <- lgb.call(
          "LGBM_BoosterCreateFromModelfile_R", path.expand(modelfile),
          ret = lgb.null.handle())
      } else if (!is.null(model_str)) {
        private$handle <- lgb.call(
          "LGBM_BoosterLoadModelFromString_R", model_str,
          ret = lgb.null.handle())
      } else {
        stop("lgb.Booster: need train_set, modelfile or model_str")
      }
      class(self) <- c("lgb.Booster", class(self))
      invisible(self)
    },

    add_valid = function(data, name) {
      data$construct()
      lgb.call("LGBM_BoosterAddValidData_R", private$handle,
               data$get_handle())
      private$valid_sets <- c(private$valid_sets, list(data))
      private$name_valid_sets <- c(private$name_valid_sets, name)
      private$num_dataset <- private$num_dataset + 1L
      invisible(self)
    },

    reset_parameter = function(params) {
      lgb.call("LGBM_BoosterResetParameter_R", private$handle,
               lgb.params2str(params))
      invisible(self)
    },

    reset_training_data = function(train_set) {
      train_set$construct()
      lgb.call("LGBM_BoosterResetTrainingData_R", private$handle,
               train_set$get_handle())
      private$train_set <- train_set
      invisible(self)
    },

    update = function(train_set = NULL, fobj = NULL) {
      if (!is.null(train_set)) {
        self$reset_training_data(train_set)
      }
      if (is.null(fobj)) {
        lgb.call("LGBM_BoosterUpdateOneIter_R", private$handle)
      } else {
        preds <- private$inner_predict(0L)
        gpair <- fobj(preds, private$train_set)
        lgb.call("LGBM_BoosterUpdateOneIterCustom_R", private$handle,
                 as.numeric(gpair$grad), as.numeric(gpair$hess),
                 length(gpair$grad))
      }
      invisible(self)
    },

    rollback_one_iter = function() {
      lgb.call("LGBM_BoosterRollbackOneIter_R", private$handle)
      invisible(self)
    },

    current_iter = function() {
      lgb.call.return.int("LGBM_BoosterGetCurrentIteration_R",
                          private$handle)
    },

    eval = function(data, name, feval = NULL) {
      idx <- if (identical(data, private$train_set)) 0L else {
        m <- match(list(data), private$valid_sets)
        if (is.na(m)) stop("eval: dataset not added via add_valid")
        m
      }
      private$inner_eval(name, idx, feval)
    },

    eval_train = function(feval = NULL) {
      private$inner_eval("training", 0L, feval)
    },

    eval_valid = function(feval = NULL) {
      out <- list()
      for (i in seq_along(private$valid_sets)) {
        out <- c(out, private$inner_eval(private$name_valid_sets[i], i,
                                         feval))
      }
      out
    },

    save_model = function(filename, num_iteration = -1L) {
      lgb.call("LGBM_BoosterSaveModel_R", private$handle,
               as.integer(num_iteration), path.expand(filename))
      invisible(self)
    },

    save_model_to_string = function(num_iteration = -1L) {
      lgb.call.return.str("LGBM_BoosterSaveModelToString_R",
                          private$handle, as.integer(num_iteration))
    },

    dump_model = function(num_iteration = -1L) {
      lgb.call.return.str("LGBM_BoosterDumpModel_R", private$handle,
                          as.integer(num_iteration))
    },

    predict = function(data, num_iteration = -1L, rawscore = FALSE,
                       predleaf = FALSE, header = FALSE, reshape = FALSE) {
      if (is.character(data)) {
        tmp <- tempfile()
        lgb.call("LGBM_BoosterPredictForFile_R", private$handle,
                 path.expand(data), as.integer(header),
                 as.integer(rawscore), as.integer(predleaf),
                 as.integer(num_iteration), "", tmp)
        out <- as.matrix(read.table(tmp, sep = "\t"))
        file.remove(tmp)
        if (ncol(out) == 1L && !reshape) return(as.numeric(out[, 1L]))
        return(out)
      }
      nrow_ <- nrow(data)
      len <- lgb.call.return.int(
        "LGBM_BoosterCalcNumPredict_R", private$handle,
        as.integer(nrow_), as.integer(rawscore),
        as.integer(predleaf), as.integer(num_iteration))
      out <- numeric(len)
      if (inherits(data, "dgCMatrix")) {
        out <- lgb.call("LGBM_BoosterPredictForCSC_R", private$handle,
                        data@p, data@i, data@x, length(data@p),
                        length(data@x), nrow_, as.integer(rawscore),
                        as.integer(predleaf), as.integer(num_iteration),
                        "", ret = out)
      } else {
        data <- as.matrix(data)
        storage.mode(data) <- "double"
        out <- lgb.call("LGBM_BoosterPredictForMat_R", private$handle,
                        data, nrow(data), ncol(data),
                        as.integer(rawscore), as.integer(predleaf),
                        as.integer(num_iteration), "", ret = out)
      }
      per_row <- len %/% nrow_
      if (per_row > 1L || reshape) {
        # row-major [nrow, per_row] from the C ABI
        matrix(out, nrow = nrow_, ncol = per_row, byrow = TRUE)
      } else {
        out
      }
    },

    get_handle = function() private$handle,

    num_class = function() {
      lgb.call.return.int("LGBM_BoosterGetNumClasses_R", private$handle)
    },

    finalize = function() {
      if (!is.null(private$handle)) {
        tryCatch(lgb.call("LGBM_BoosterFree_R", private$handle),
                 error = function(e) NULL)
        private$handle <- NULL
      }
    }
  ),
  private = list(
    handle = NULL, train_set = NULL, valid_sets = list(),
    name_valid_sets = character(0), num_dataset = 0L,
    eval_names = NULL,

    get_eval_names = function() {
      if (is.null(private$eval_names)) {
        joined <- lgb.call.return.str("LGBM_BoosterGetEvalNames_R",
                                      private$handle)
        private$eval_names <- if (nzchar(joined))
          strsplit(joined, "\n", fixed = TRUE)[[1L]] else character(0)
      }
      private$eval_names
    },

    inner_predict = function(data_idx) {
      n <- lgb.call.return.int("LGBM_BoosterGetNumPredict_R",
                               private$handle, as.integer(data_idx))
      out <- numeric(n)
      lgb.call("LGBM_BoosterGetPredict_R", private$handle,
               as.integer(data_idx), ret = out)
    },

    inner_eval = function(data_name, data_idx, feval = NULL) {
      names <- private$get_eval_names()
      out <- list()
      if (length(names) > 0L) {
        vals <- numeric(length(names))
        vals <- lgb.call("LGBM_BoosterGetEval_R", private$handle,
                         as.integer(data_idx), ret = vals)
        for (i in seq_along(names)) {
          out[[length(out) + 1L]] <- list(
            data_name = data_name, name = names[i], value = vals[i],
            higher_better = .lgb_higher_better(names[i]))
        }
      }
      if (!is.null(feval)) {
        preds <- private$inner_predict(data_idx)
        ds <- if (data_idx == 0L) private$train_set
              else private$valid_sets[[data_idx]]
        res <- feval(preds, ds)
        out[[length(out) + 1L]] <- list(
          data_name = data_name, name = res$name, value = res$value,
          higher_better = isTRUE(res$higher_better))
      }
      out
    }
  )
)

#' Predict method for lgb.Booster.
predict.lgb.Booster <- function(object, data, num_iteration = -1L,
                                rawscore = FALSE, predleaf = FALSE,
                                header = FALSE, reshape = FALSE, ...) {
  object$predict(data, num_iteration = num_iteration, rawscore = rawscore,
                 predleaf = predleaf, header = header, reshape = reshape)
}

#' Load a model from a text file.
lgb.load <- function(filename = NULL, model_str = NULL) {
  if (!is.null(filename)) {
    Booster$new(modelfile = filename)
  } else if (!is.null(model_str)) {
    Booster$new(model_str = model_str)
  } else {
    stop("lgb.load: need filename or model_str")
  }
}

#' Save a model to a text file.
lgb.save <- function(booster, filename, num_iteration = -1L) {
  booster$save_model(filename, num_iteration)
}

#' Dump a model to JSON.
lgb.dump <- function(booster, num_iteration = -1L) {
  booster$dump_model(num_iteration)
}

#' Extract a recorded metric series from lgb.train / lgb.cv output.
lgb.get.eval.result <- function(booster, data_name, eval_name,
                                iters = NULL, is_err = FALSE) {
  rec <- booster$record_evals[[data_name]][[eval_name]]
  if (is.null(rec)) stop("lgb.get.eval.result: no such record")
  key <- if (is_err) "err" else "eval"
  if (is_err && length(rec$err) == 0L) {
    stop("lgb.get.eval.result: no error-bar record ",
         "(err is populated by lgb.cv aggregation only)")
  }
  out <- unlist(rec[[key]])
  if (!is.null(iters)) out <- out[iters]
  out
}
