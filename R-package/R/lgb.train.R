# lgb.train — the main training entry point (reference surface:
# R-package/R/lgb.train.R). Our own implementation over lgb.Booster.

lgb.train <- function(params = list(), data, nrounds = 100L,
                      valids = list(), obj = NULL, eval = NULL,
                      verbose = 1L, record = TRUE, eval_freq = 1L,
                      init_model = NULL, colnames = NULL,
                      categorical_feature = NULL,
                      early_stopping_rounds = NULL, callbacks = list(),
                      ...) {
  params <- modifyList(params, list(...))
  if (is.character(obj)) {
    params$objective <- obj
    obj <- NULL
  } else if (!is.null(params$objective) && is.function(params$objective)) {
    obj <- params$objective
    params$objective <- "none"
  }
  if (!lgb.check.r6.class(data, "lgb.Dataset")) {
    stop("lgb.train: data must be an lgb.Dataset")
  }
  if (!is.null(colnames)) data$set_colnames(colnames)
  if (!is.null(categorical_feature)) {
    data$set_categorical_feature(categorical_feature)
  }
  data$construct()

  booster <- if (!is.null(init_model)) {
    b <- if (is.character(init_model)) Booster$new(modelfile = init_model)
         else init_model
    b$reset_training_data(data)  # continue training on this data
    b
  } else {
    Booster$new(params = params, train_set = data)
  }
  for (name in names(valids)) {
    booster$add_valid(valids[[name]], name)
  }

  if (verbose > 0L && length(valids) > 0L) {
    callbacks <- c(callbacks, list(cb.print.evaluation(eval_freq)))
  }
  if (record) {
    callbacks <- c(callbacks, list(cb.record.evaluation()))
  }
  if (!is.null(early_stopping_rounds) && early_stopping_rounds > 0L) {
    callbacks <- c(callbacks,
                   list(cb.early.stop(early_stopping_rounds,
                                      verbose = verbose > 0L)))
  }
  cbs <- .lgb_categorize_callbacks(callbacks)

  env <- new.env()
  env$booster <- booster
  env$end_iteration <- nrounds
  env$met_early_stop <- FALSE
  start_iter <- booster$current_iter()
  for (i in seq_len(nrounds)) {
    env$iteration <- start_iter + i
    env$eval_list <- list()
    for (cb in cbs$before) cb(env)
    booster$update(fobj = obj)
    if (length(valids) > 0L || !is.null(eval)) {
      env$eval_list <- c(
        if (isTRUE(params$is_provide_training_metric))
          booster$eval_train(feval = eval) else list(),
        booster$eval_valid(feval = eval))
    }
    for (cb in cbs$after) cb(env)
    if (env$met_early_stop) break
  }
  if (booster$best_iter < 0L) booster$best_iter <- booster$current_iter()
  booster
}
