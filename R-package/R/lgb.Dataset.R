# lgb.Dataset — the binned training-data container.
#
# API parity with the reference R-package/R/lgb.Dataset.R (constructor,
# construct, dim/dimnames, slice, getinfo/setinfo, save_binary,
# set_categorical_feature, set_reference, lgb.Dataset.create.valid); the
# implementation is our own R6 wrapper over the .Call glue
# (src/lightgbm_tpu_R.c) into lib_lightgbm_tpu.so.

Dataset <- R6::R6Class(
  classname = "lgb.Dataset",
  cloneable = FALSE,
  public = list(
    initialize = function(data = NULL, params = list(), reference = NULL,
                          colnames = NULL, categorical_feature = NULL,
                          free_raw_data = TRUE, used_indices = NULL,
                          info = list(), ...) {
      extra <- list(...)
      for (key in c("label", "weight", "group", "init_score")) {
        if (!is.null(extra[[key]])) info[[key]] <- extra[[key]]
      }
      private$raw_data <- data
      private$params <- params
      private$reference <- reference
      private$colnames_ <- colnames
      private$categorical_feature <- categorical_feature
      private$free_raw_data <- isTRUE(free_raw_data)
      private$used_indices <- used_indices
      private$info <- info
      private$handle <- NULL
      invisible(self)
    },

    construct = function() {
      if (!is.null(private$handle)) return(invisible(self))
      params <- private$params
      if (!is.null(private$categorical_feature)) {
        cf <- private$categorical_feature
        if (is.character(cf)) {
          cf <- match(cf, private$colnames_) - 1L
          if (anyNA(cf)) stop("categorical_feature name not found")
        } else {
          cf <- as.integer(cf) - 1L  # R is 1-based
        }
        params$categorical_feature <- paste0(cf, collapse = ",")
      }
      pstr <- lgb.params2str(params)
      ref_handle <- NULL
      if (!is.null(private$reference)) {
        private$reference$construct()
        ref_handle <- private$reference$.__enclos_env__$private$handle
      }
      data <- private$raw_data
      if (!is.null(private$used_indices)) {
        # slice of an already-constructed dataset
        parent <- private$reference
        parent$construct()
        private$handle <- lgb.call(
          "LGBM_DatasetGetSubset_R",
          parent$.__enclos_env__$private$handle,
          as.integer(private$used_indices),
          length(private$used_indices), pstr,
          ret = lgb.null.handle())
      } else if (is.character(data)) {
        private$handle <- lgb.call(
          "LGBM_DatasetCreateFromFile_R", path.expand(data), pstr,
          ref_handle, ret = lgb.null.handle())
      } else if (inherits(data, "dgCMatrix")) {
        private$handle <- lgb.call(
          "LGBM_DatasetCreateFromCSC_R", data@p, data@i, data@x,
          length(data@p), length(data@x), nrow(data), pstr, ref_handle,
          ret = lgb.null.handle())
      } else {
        data <- as.matrix(data)
        storage.mode(data) <- "double"
        private$handle <- lgb.call(
          "LGBM_DatasetCreateFromMat_R", data, nrow(data), ncol(data),
          pstr, ref_handle, ret = lgb.null.handle())
      }
      if (!is.null(private$colnames_)) {
        lgb.call("LGBM_DatasetSetFeatureNames_R", private$handle,
                 paste0(private$colnames_, collapse = "\t"))
      }
      for (key in names(private$info)) {
        self$setinfo(key, private$info[[key]])
      }
      if (private$free_raw_data) private$raw_data <- NULL
      invisible(self)
    },

    get_handle = function() {
      self$construct()
      private$handle
    },

    dim = function() {
      self$construct()
      nd <- lgb.call.return.int("LGBM_DatasetGetNumData_R", private$handle)
      nf <- lgb.call.return.int("LGBM_DatasetGetNumFeature_R",
                                private$handle)
      c(nd, nf)
    },

    get_colnames = function() {
      self$construct()
      joined <- lgb.call.return.str("LGBM_DatasetGetFeatureNames_R",
                                    private$handle)
      strsplit(joined, "\n", fixed = TRUE)[[1L]]
    },

    set_colnames = function(colnames) {
      private$colnames_ <- colnames
      if (!is.null(private$handle)) {
        lgb.call("LGBM_DatasetSetFeatureNames_R", private$handle,
                 paste0(colnames, collapse = "\t"))
      }
      invisible(self)
    },

    getinfo = function(name) {
      self$construct()
      size <- lgb.call.return.int("LGBM_DatasetGetFieldSize_R",
                                  private$handle, name)
      if (size == 0L) return(NULL)
      if (name %in% c("group", "query")) {
        out <- integer(size)
      } else {
        out <- numeric(size)
      }
      out <- lgb.call("LGBM_DatasetGetField_R", private$handle, name,
                      ret = out)
      if (name %in% c("group", "query")) diff(out) else out
    },

    setinfo = function(name, info) {
      if (is.null(info)) return(invisible(self))
      self$construct()
      if (name %in% c("group", "query")) {
        info <- as.integer(info)
      } else {
        info <- as.numeric(info)
      }
      lgb.call("LGBM_DatasetSetField_R", private$handle, name, info,
               length(info))
      private$info[[name]] <- NULL
      invisible(self)
    },

    slice = function(idxset, ...) {
      Dataset$new(data = NULL, params = private$params, reference = self,
                  colnames = private$colnames_,
                  categorical_feature = private$categorical_feature,
                  free_raw_data = private$free_raw_data,
                  used_indices = idxset, info = list(...))
    },

    save_binary = function(fname) {
      self$construct()
      lgb.call("LGBM_DatasetSaveBinary_R", private$handle,
               path.expand(fname))
      invisible(self)
    },

    set_categorical_feature = function(categorical_feature) {
      if (!is.null(private$handle)) {
        stop("set_categorical_feature: dataset already constructed")
      }
      private$categorical_feature <- categorical_feature
      invisible(self)
    },

    set_reference = function(reference) {
      if (!is.null(private$handle)) {
        stop("set_reference: dataset already constructed")
      }
      private$reference <- reference
      invisible(self)
    },

    update_params = function(params) {
      private$params <- modifyList(private$params, params)
      invisible(self)
    },

    finalize = function() {
      if (!is.null(private$handle)) {
        tryCatch(lgb.call("LGBM_DatasetFree_R", private$handle),
                 error = function(e) NULL)
        private$handle <- NULL
      }
    }
  ),
  private = list(
    raw_data = NULL, params = list(), reference = NULL, colnames_ = NULL,
    categorical_feature = NULL, free_raw_data = TRUE, used_indices = NULL,
    info = list(), handle = NULL
  )
)

#' Construct an lgb.Dataset from a matrix, dgCMatrix or data file path.
lgb.Dataset <- function(data, params = list(), reference = NULL,
                        colnames = NULL, categorical_feature = NULL,
                        free_raw_data = TRUE, info = list(), ...) {
  if (is.null(colnames) && !is.null(dimnames(data)[[2L]])) {
    colnames <- dimnames(data)[[2L]]
  }
  Dataset$new(data = data, params = params, reference = reference,
              colnames = colnames, categorical_feature = categorical_feature,
              free_raw_data = free_raw_data, info = info, ...)
}

#' Validation dataset aligned to a training dataset's bin mappers.
lgb.Dataset.create.valid <- function(dataset, data, info = list(), ...) {
  if (!lgb.check.r6.class(dataset, "lgb.Dataset")) {
    stop("lgb.Dataset.create.valid: dataset must be an lgb.Dataset")
  }
  lgb.Dataset(data, reference = dataset, info = info, ...)
}

lgb.Dataset.construct <- function(dataset) {
  dataset$construct()
}

lgb.Dataset.save <- function(dataset, fname) {
  dataset$save_binary(fname)
}

lgb.Dataset.set.categorical <- function(dataset, categorical_feature) {
  dataset$set_categorical_feature(categorical_feature)
}

lgb.Dataset.set.reference <- function(dataset, reference) {
  dataset$set_reference(reference)
}

dim.lgb.Dataset <- function(x, ...) {
  x$dim()
}

dimnames.lgb.Dataset <- function(x) {
  list(NULL, x$get_colnames())
}

`dimnames<-.lgb.Dataset` <- function(x, value) {
  x$set_colnames(value[[2L]])
  x
}

slice <- function(dataset, ...) UseMethod("slice")

slice.lgb.Dataset <- function(dataset, idxset, ...) {
  dataset$slice(idxset, ...)
}

getinfo <- function(dataset, ...) UseMethod("getinfo")

getinfo.lgb.Dataset <- function(dataset, name, ...) {
  dataset$getinfo(name)
}

setinfo <- function(dataset, ...) UseMethod("setinfo")

setinfo.lgb.Dataset <- function(dataset, name, info, ...) {
  dataset$setinfo(name, info)
}
