# CLI-transport FALLBACK binding (lgb.cli.* namespace).
#
# The primary binding is the in-process .Call glue (src/lightgbm_tpu_R.c
# over native/lib_lightgbm_tpu.so) with the R6 surface in lgb.Dataset.R /
# lgb.Booster.R / lgb.train.R. This file keeps a zero-compile fallback
# that shells out to `python -m lightgbm_tpu` and round-trips through the
# text model format — for environments without a C toolchain. Functions
# are namespaced lgb.cli.* so they never shadow the primary surface.

.lgb_python <- function() {
  py <- Sys.getenv("LGBM_TPU_PYTHON", "python3")
  py
}

.lgb_repo <- function() {
  repo <- Sys.getenv("LGBM_TPU_HOME", "")
  if (nzchar(repo)) return(repo)
  # installed alongside the package
  system.file(package = "lightgbmtpu")
}

.lgb_cli <- function(args) {
  env <- paste0("PYTHONPATH=", shQuote(.lgb_repo()))
  rc <- system2(.lgb_python(), c("-m", "lightgbm_tpu", args),
                env = env, stdout = TRUE, stderr = TRUE)
  status <- attr(rc, "status")
  if (!is.null(status) && status != 0) {
    stop("lightgbm_tpu CLI failed:\n", paste(rc, collapse = "\n"))
  }
  invisible(rc)
}

#' Create a dataset descriptor (data written as TSV with the label in
#' column 0, the CLI's native layout).
lgb.cli.Dataset <- function(data, label = NULL, weight = NULL, group = NULL) {
  path <- tempfile(fileext = ".tsv")
  mat <- as.matrix(data)
  if (is.null(label)) label <- rep(0, nrow(mat))
  utils::write.table(cbind(label, mat), path, sep = "\t",
                     row.names = FALSE, col.names = FALSE)
  if (!is.null(weight)) {
    writeLines(as.character(weight), paste0(path, ".weight"))
  }
  if (!is.null(group)) {
    writeLines(as.character(group), paste0(path, ".query"))
  }
  structure(list(path = path, nrow = nrow(mat), ncol = ncol(mat)),
            class = "lgb.cli.Dataset")
}

#' Train a model (reference: lgb.train). `params` is a named list using
#' LightGBM parameter names; returns an lgb.Booster.
lgb.cli.train <- function(params = list(), data, nrounds = 100L,
                      valids = list(), verbose = -1L) {
  stopifnot(inherits(data, "lgb.cli.Dataset"))
  model_path <- tempfile(fileext = ".txt")
  args <- c("task=train",
            paste0("data=", data$path),
            paste0("num_trees=", as.integer(nrounds)),
            paste0("output_model=", model_path),
            paste0("verbose=", as.integer(verbose)))
  for (name in names(params)) {
    args <- c(args, paste0(name, "=", params[[name]]))
  }
  if (length(valids)) {
    vpaths <- vapply(valids, function(v) v$path, character(1))
    args <- c(args, paste0("valid=", paste(vpaths, collapse = ",")))
  }
  .lgb_cli(args)
  booster <- structure(list(model_path = model_path,
                            model_str = readLines(model_path)),
                       class = "lgb.cli.Booster")
  booster
}

#' Predict with a trained model (reference: predict.lgb.Booster).
lgb.cli.predict <- function(object, data, rawscore = FALSE,
                                predleaf = FALSE, ...) {
  ds <- if (inherits(data, "lgb.cli.Dataset")) data else lgb.cli.Dataset(data)
  out_path <- tempfile(fileext = ".txt")
  args <- c("task=predict",
            paste0("data=", ds$path),
            paste0("input_model=", object$model_path),
            paste0("output_result=", out_path),
            "verbose=-1")
  if (rawscore) args <- c(args, "predict_raw_score=true")
  if (predleaf) args <- c(args, "predict_leaf_index=true")
  .lgb_cli(args)
  res <- utils::read.table(out_path, sep = "\t")
  if (ncol(res) == 1) res[[1]] else as.matrix(res)
}

#' Feature importance parsed from the model text (reference:
#' lgb.importance over the dumped model).
lgb.cli.importance <- function(booster) {
  stopifnot(inherits(booster, "lgb.cli.Booster"))
  lines <- booster$model_str
  feat_line <- grep("^feature_names=", lines, value = TRUE)
  feats <- strsplit(sub("^feature_names=", "", feat_line), " ")[[1]]
  counts <- integer(length(feats))
  for (sf in grep("^split_feature=", lines, value = TRUE)) {
    idx <- as.integer(strsplit(sub("^split_feature=", "", sf), " ")[[1]])
    for (i in idx) counts[i + 1] <- counts[i + 1] + 1L
  }
  data.frame(Feature = feats, Frequency = counts)[order(-counts), ]
}

#' Save / load the LightGBM-compatible text model.
lgb.cli.save <- function(booster, filename) {
  writeLines(booster$model_str, filename)
  invisible(booster)
}

lgb.cli.load <- function(filename) {
  structure(list(model_path = filename, model_str = readLines(filename)),
            class = "lgb.cli.Booster")
}
