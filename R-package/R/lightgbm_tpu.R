# R interface to lightgbm_tpu (reference surface: R-package/R/ in
# LightGBM — lgb.Dataset / lgb.train / predict / lgb.importance).
#
# Transport: the framework's CLI (`python -m lightgbm_tpu`) and the
# LightGBM-compatible text model format. The reference binds in-process
# through lightgbm_R.cpp over the C API; the equivalent here is
# native/lib_lightgbm_tpu.so (the LGBM_* C ABI), which .Call glue can
# target — the CLI transport is used by default because it has no compiled
# dependency on the R toolchain.

.lgb_python <- function() {
  py <- Sys.getenv("LGBM_TPU_PYTHON", "python3")
  py
}

.lgb_repo <- function() {
  repo <- Sys.getenv("LGBM_TPU_HOME", "")
  if (nzchar(repo)) return(repo)
  # installed alongside the package
  system.file(package = "lightgbmtpu")
}

.lgb_cli <- function(args) {
  env <- paste0("PYTHONPATH=", shQuote(.lgb_repo()))
  rc <- system2(.lgb_python(), c("-m", "lightgbm_tpu", args),
                env = env, stdout = TRUE, stderr = TRUE)
  status <- attr(rc, "status")
  if (!is.null(status) && status != 0) {
    stop("lightgbm_tpu CLI failed:\n", paste(rc, collapse = "\n"))
  }
  invisible(rc)
}

#' Create a dataset descriptor (data written as TSV with the label in
#' column 0, the CLI's native layout).
lgb.Dataset <- function(data, label = NULL, weight = NULL, group = NULL) {
  path <- tempfile(fileext = ".tsv")
  mat <- as.matrix(data)
  if (is.null(label)) label <- rep(0, nrow(mat))
  utils::write.table(cbind(label, mat), path, sep = "\t",
                     row.names = FALSE, col.names = FALSE)
  if (!is.null(weight)) {
    writeLines(as.character(weight), paste0(path, ".weight"))
  }
  if (!is.null(group)) {
    writeLines(as.character(group), paste0(path, ".query"))
  }
  structure(list(path = path, nrow = nrow(mat), ncol = ncol(mat)),
            class = "lgb.Dataset")
}

#' Train a model (reference: lgb.train). `params` is a named list using
#' LightGBM parameter names; returns an lgb.Booster.
lgb.train <- function(params = list(), data, nrounds = 100L,
                      valids = list(), verbose = -1L) {
  stopifnot(inherits(data, "lgb.Dataset"))
  model_path <- tempfile(fileext = ".txt")
  args <- c("task=train",
            paste0("data=", data$path),
            paste0("num_trees=", as.integer(nrounds)),
            paste0("output_model=", model_path),
            paste0("verbose=", as.integer(verbose)))
  for (name in names(params)) {
    args <- c(args, paste0(name, "=", params[[name]]))
  }
  if (length(valids)) {
    vpaths <- vapply(valids, function(v) v$path, character(1))
    args <- c(args, paste0("valid=", paste(vpaths, collapse = ",")))
  }
  .lgb_cli(args)
  booster <- structure(list(model_path = model_path,
                            model_str = readLines(model_path)),
                       class = "lgb.Booster")
  booster
}

#' Predict with a trained model (reference: predict.lgb.Booster).
predict.lgb.Booster <- function(object, data, rawscore = FALSE,
                                predleaf = FALSE, ...) {
  ds <- if (inherits(data, "lgb.Dataset")) data else lgb.Dataset(data)
  out_path <- tempfile(fileext = ".txt")
  args <- c("task=predict",
            paste0("data=", ds$path),
            paste0("input_model=", object$model_path),
            paste0("output_result=", out_path),
            "verbose=-1")
  if (rawscore) args <- c(args, "predict_raw_score=true")
  if (predleaf) args <- c(args, "predict_leaf_index=true")
  .lgb_cli(args)
  res <- utils::read.table(out_path, sep = "\t")
  if (ncol(res) == 1) res[[1]] else as.matrix(res)
}

#' Feature importance parsed from the model text (reference:
#' lgb.importance over the dumped model).
lgb.importance <- function(booster) {
  stopifnot(inherits(booster, "lgb.Booster"))
  lines <- booster$model_str
  feat_line <- grep("^feature_names=", lines, value = TRUE)
  feats <- strsplit(sub("^feature_names=", "", feat_line), " ")[[1]]
  counts <- integer(length(feats))
  for (sf in grep("^split_feature=", lines, value = TRUE)) {
    idx <- as.integer(strsplit(sub("^split_feature=", "", sf), " ")[[1]])
    for (i in idx) counts[i + 1] <- counts[i + 1] + 1L
  }
  data.frame(Feature = feats, Frequency = counts)[order(-counts), ]
}

#' Save / load the LightGBM-compatible text model.
lgb.save <- function(booster, filename) {
  writeLines(booster$model_str, filename)
  invisible(booster)
}

lgb.load <- function(filename) {
  structure(list(model_path = filename, model_str = readLines(filename)),
            class = "lgb.Booster")
}
