# Training callbacks (reference surface: R-package/R/callback.R —
# cb.print.evaluation, cb.record.evaluation, cb.reset.parameter,
# cb.early.stop). Our own implementation: a callback is a function(env)
# where env carries booster/iteration/eval results, with a `before`
# attribute deciding whether it runs pre- or post-update.

cb.print.evaluation <- function(period = 1L) {
  callback <- function(env) {
    if (period <= 0L || length(env$eval_list) == 0L) return(invisible(NULL))
    if ((env$iteration - 1L) %% period != 0L) return(invisible(NULL))
    msgs <- vapply(env$eval_list, function(e) {
      sprintf("%s's %s:%g", e$data_name, e$name, e$value)
    }, character(1L))
    cat(sprintf("[%d]\t%s\n", env$iteration, paste(msgs, collapse = "\t")))
    invisible(NULL)
  }
  attr(callback, "name") <- "cb.print.evaluation"
  callback
}

cb.record.evaluation <- function() {
  callback <- function(env) {
    for (e in env$eval_list) {
      rec <- env$booster$record_evals
      if (is.null(rec[[e$data_name]])) rec[[e$data_name]] <- list()
      if (is.null(rec[[e$data_name]][[e$name]])) {
        rec[[e$data_name]][[e$name]] <- list(eval = list(), err = list())
      }
      rec[[e$data_name]][[e$name]]$eval <-
        c(rec[[e$data_name]][[e$name]]$eval, e$value)
      env$booster$record_evals <- rec
    }
    invisible(NULL)
  }
  attr(callback, "name") <- "cb.record.evaluation"
  callback
}

cb.reset.parameter <- function(new_params) {
  callback <- function(env) {
    params <- lapply(new_params, function(p) {
      if (is.function(p)) p(env$iteration, env$end_iteration) else
        p[min(env$iteration, length(p))]
    })
    env$booster$reset_parameter(params)
    invisible(NULL)
  }
  attr(callback, "name") <- "cb.reset.parameter"
  attr(callback, "before") <- TRUE
  callback
}

cb.early.stop <- function(stopping_rounds, verbose = TRUE) {
  best_score <- NULL
  best_iter <- NULL
  callback <- function(env) {
    evals <- Filter(function(e) e$data_name != "training", env$eval_list)
    if (length(evals) == 0L) return(invisible(NULL))
    if (is.null(best_score)) {
      best_score <<- rep(NA_real_, length(evals))
      best_iter <<- rep(0L, length(evals))
    }
    for (i in seq_along(evals)) {
      e <- evals[[i]]
      better <- is.na(best_score[i]) ||
        (e$higher_better && e$value > best_score[i]) ||
        (!e$higher_better && e$value < best_score[i])
      if (better) {
        best_score[i] <<- e$value
        best_iter[i] <<- env$iteration
      } else if (env$iteration - best_iter[i] >= stopping_rounds) {
        env$booster$best_iter <- best_iter[i]
        if (verbose) {
          cat(sprintf(
            "Early stopping, best iteration is %d (%s %s:%g)\n",
            best_iter[i], e$data_name, e$name, best_score[i]))
        }
        env$met_early_stop <- TRUE
      }
    }
    invisible(NULL)
  }
  attr(callback, "name") <- "cb.early.stop"
  callback
}

# internal: partition callbacks into pre-/post-update sets
.lgb_categorize_callbacks <- function(callbacks) {
  before <- Filter(function(cb) isTRUE(attr(cb, "before")), callbacks)
  after <- Filter(function(cb) !isTRUE(attr(cb, "before")), callbacks)
  list(before = before, after = after)
}
