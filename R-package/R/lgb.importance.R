# Feature importance + tree table (reference surface:
# R-package/R/lgb.importance.R and lgb.model.dt.tree.R). Our own
# implementation parsing the model's JSON dump with jsonlite.

lgb.importance <- function(model, percentage = TRUE) {
  tree_dt <- lgb.model.dt.tree(model)
  splits <- tree_dt[!is.na(tree_dt$split_feature), , drop = FALSE]
  if (nrow(splits) == 0L) {
    return(data.frame(Feature = character(0), Gain = numeric(0),
                      Cover = numeric(0), Frequency = numeric(0)))
  }
  agg <- stats::aggregate(
    cbind(Gain = splits$split_gain, Cover = splits$internal_count,
          Frequency = rep(1, nrow(splits))) ~ split_feature,
    data = splits, FUN = sum)
  names(agg)[1L] <- "Feature"
  if (percentage) {
    agg$Gain <- agg$Gain / sum(agg$Gain)
    agg$Cover <- agg$Cover / sum(agg$Cover)
    agg$Frequency <- agg$Frequency / sum(agg$Frequency)
  }
  agg[order(-agg$Gain), , drop = FALSE]
}

lgb.model.dt.tree <- function(model, num_iteration = -1L) {
  json <- jsonlite::fromJSON(model$dump_model(num_iteration),
                             simplifyVector = FALSE)
  feature_names <- unlist(json$feature_names)
  rows <- list()
  walk <- function(node, tree_index, parent = NA_integer_, depth = 0L) {
    if (!is.null(node$split_feature)) {
      fid <- as.integer(node$split_feature)
      rows[[length(rows) + 1L]] <<- data.frame(
        tree_index = tree_index,
        depth = depth,
        split_index = as.integer(node$split_index),
        split_feature = if (fid + 1L <= length(feature_names))
          feature_names[fid + 1L] else as.character(fid),
        split_gain = as.numeric(node$split_gain),
        threshold = as.numeric(node$threshold),
        decision_type = as.character(node$decision_type),
        internal_value = as.numeric(node$internal_value),
        internal_count = as.numeric(node$internal_count),
        leaf_index = NA_integer_, leaf_value = NA_real_,
        leaf_count = NA_real_, stringsAsFactors = FALSE)
      walk(node$left_child, tree_index, node$split_index, depth + 1L)
      walk(node$right_child, tree_index, node$split_index, depth + 1L)
    } else {
      rows[[length(rows) + 1L]] <<- data.frame(
        tree_index = tree_index, depth = depth,
        split_index = NA_integer_, split_feature = NA_character_,
        split_gain = NA_real_, threshold = NA_real_,
        decision_type = NA_character_, internal_value = NA_real_,
        internal_count = NA_real_,
        leaf_index = as.integer(node$leaf_index),
        leaf_value = as.numeric(node$leaf_value),
        leaf_count = as.numeric(node$leaf_count %||% NA),
        stringsAsFactors = FALSE)
    }
  }
  for (t in seq_along(json$tree_info)) {
    walk(json$tree_info[[t]]$tree_structure, t - 1L)
  }
  do.call(rbind, rows)
}

`%||%` <- function(a, b) if (is.null(a)) b else a

#' Bar plot of feature importance.
lgb.plot.importance <- function(tree_imp, top_n = 10L,
                                measure = "Gain", ...) {
  imp <- utils::head(tree_imp[order(-tree_imp[[measure]]), ], top_n)
  graphics::barplot(rev(imp[[measure]]), names.arg = rev(imp$Feature),
                    horiz = TRUE, las = 1,
                    main = paste("Feature importance by", measure), ...)
  invisible(imp)
}
