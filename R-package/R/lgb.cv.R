# lgb.cv — k-fold cross validation (reference surface:
# R-package/R/lgb.cv.R: folds, stratified option, per-fold boosters,
# aggregated mean/sd eval record, early stopping on the aggregate).
# Our own implementation.

lgb.cv <- function(params = list(), data, nrounds = 100L, nfold = 5L,
                   label = NULL, obj = NULL, eval = NULL, verbose = 1L,
                   record = TRUE, eval_freq = 1L, stratified = TRUE,
                   folds = NULL, early_stopping_rounds = NULL,
                   callbacks = list(), ...) {
  params <- modifyList(params, list(...))
  if (is.character(obj)) {
    params$objective <- obj
    obj <- NULL
  }
  if (!lgb.check.r6.class(data, "lgb.Dataset")) {
    stop("lgb.cv: data must be an lgb.Dataset")
  }
  if (!is.null(label)) data$setinfo("label", label)
  data$construct()
  n <- data$dim()[1L]

  if (is.null(folds)) {
    y <- data$getinfo("label")
    folds <- .lgb_make_folds(n, nfold, y, stratified)
  }

  boosters <- list()
  for (k in seq_along(folds)) {
    test_idx <- folds[[k]]
    train_idx <- setdiff(seq_len(n), test_idx)
    dtrain <- data$slice(train_idx)
    dtest <- data$slice(test_idx)
    dtrain$construct()
    dtest$construct()
    bst <- Booster$new(params = params, train_set = dtrain)
    bst$add_valid(dtest, "valid")
    boosters[[k]] <- bst
  }

  cv <- list(record_evals = list(), boosters = boosters,
             best_iter = -1L, best_score = NA_real_)
  class(cv) <- "lgb.CVBooster"

  best_score <- NA_real_
  best_iter <- 0L
  for (i in seq_len(nrounds)) {
    evals <- list()
    for (bst in boosters) {
      bst$update(fobj = obj)
      evals[[length(evals) + 1L]] <- bst$eval_valid(feval = eval)
    }
    if (length(evals[[1L]]) > 0L) {
      agg <- list()
      for (j in seq_along(evals[[1L]])) {
        vals <- vapply(evals, function(e) e[[j]]$value, numeric(1L))
        e0 <- evals[[1L]][[j]]
        agg[[j]] <- list(name = e0$name, mean = mean(vals),
                         sd = stats::sd(vals),
                         higher_better = e0$higher_better)
        if (record) {
          rec <- cv$record_evals[["valid"]]
          if (is.null(rec)) rec <- list()
          if (is.null(rec[[e0$name]])) {
            rec[[e0$name]] <- list(eval = list(), err = list())
          }
          rec[[e0$name]]$eval <- c(rec[[e0$name]]$eval, mean(vals))
          rec[[e0$name]]$err <- c(rec[[e0$name]]$err, stats::sd(vals))
          cv$record_evals[["valid"]] <- rec
        }
      }
      if (verbose > 0L && (i - 1L) %% eval_freq == 0L) {
        msgs <- vapply(agg, function(a) {
          sprintf("valid %s:%g+%g", a$name, a$mean, a$sd)
        }, character(1L))
        cat(sprintf("[%d]\t%s\n", i, paste(msgs, collapse = "\t")))
      }
      a0 <- agg[[1L]]
      better <- is.na(best_score) ||
        (a0$higher_better && a0$mean > best_score) ||
        (!a0$higher_better && a0$mean < best_score)
      if (better) {
        best_score <- a0$mean
        best_iter <- i
      } else if (!is.null(early_stopping_rounds) &&
                 i - best_iter >= early_stopping_rounds) {
        if (verbose > 0L) {
          cat(sprintf("Early stopping, best iteration is %d\n", best_iter))
        }
        break
      }
    }
  }
  cv$best_iter <- best_iter
  cv$best_score <- best_score
  cv
}

# internal: (stratified) fold assignment
.lgb_make_folds <- function(n, nfold, y = NULL, stratified = TRUE) {
  if (stratified && !is.null(y) && length(unique(y)) <= 32L) {
    folds <- vector("list", nfold)
    for (cls in unique(y)) {
      idx <- sample(which(y == cls))
      assign_to <- factor(rep_len(seq_len(nfold), length(idx)),
                          levels = seq_len(nfold))
      parts <- split(idx, assign_to)   # always nfold entries
      for (k in seq_len(nfold)) {
        folds[[k]] <- c(folds[[k]], parts[[k]])
      }
    }
    lapply(folds, sort)
  } else {
    idx <- sample(n)
    unname(split(idx, rep_len(seq_len(nfold), n)))
  }
}
