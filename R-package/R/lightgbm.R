# Top-level convenience API (reference surface: R-package/R/lightgbm.R,
# saveRDS.lgb.Booster.R, readRDS.lgb.Booster.R).

#' Simple training interface: builds the lgb.Dataset and trains.
lightgbm <- function(data, label = NULL, weight = NULL, params = list(),
                     nrounds = 100L, verbose = 1L, objective = "regression",
                     init_score = NULL, save_name = NULL, ...) {
  params$objective <- params$objective %||% objective
  dtrain <- if (lgb.check.r6.class(data, "lgb.Dataset")) data else
    lgb.Dataset(data, label = label, weight = weight,
                init_score = init_score)
  booster <- lgb.train(params = params, data = dtrain, nrounds = nrounds,
                       verbose = verbose, ...)
  if (!is.null(save_name)) booster$save_model(save_name)
  booster
}

#' Serialize a Booster into an RDS-safe object (handles are process-local;
#' the model travels as its text form).
saveRDS.lgb.Booster <- function(object, file, ...) {
  raw_model <- object$save_model_to_string()
  saveRDS(list(lgb_booster_model_str = raw_model,
               best_iter = object$best_iter,
               record_evals = object$record_evals), file = file, ...)
}

#' Restore a Booster written by saveRDS.lgb.Booster.
readRDS.lgb.Booster <- function(file, ...) {
  obj <- readRDS(file, ...)
  if (is.null(obj$lgb_booster_model_str)) {
    stop("readRDS.lgb.Booster: not a saved lgb.Booster")
  }
  booster <- Booster$new(model_str = obj$lgb_booster_model_str)
  booster$best_iter <- obj$best_iter
  booster$record_evals <- obj$record_evals
  booster
}

#' Unload/reload helper (reference: lgb.unloader.R) — frees handles held
#' by objects in an environment so the shared library can be unloaded.
lgb.unloader <- function(restore = TRUE, wipe = FALSE,
                         envir = .GlobalEnv) {
  if (wipe) {
    objs <- ls(envir = envir)
    for (nm in objs) {
      o <- get(nm, envir = envir)
      if (lgb.check.r6.class(o, "lgb.Booster") ||
          lgb.check.r6.class(o, "lgb.Dataset")) {
        rm(list = nm, envir = envir)
      }
    }
  }
  gc()
  try(dyn.unload(getLoadedDLLs()[["lightgbmtpu"]][["path"]]), silent = TRUE)
  if (restore) {
    library.dynam("lightgbmtpu", package = "lightgbmtpu",
                  lib.loc = .libPaths())
  }
  invisible(NULL)
}
