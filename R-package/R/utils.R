# Internal helpers shared by the lightgbm_tpu R package.
#
# Reference surface: R-package/R/utils.R (lgb.call / lgb.params2str /
# handle checks). Implementation is our own over the .Call glue in
# src/lightgbm_tpu_R.c; error reporting follows the same call_state +
# LGBM_GetLastError_R contract so either binding loads.

lgb.null.handle <- function() {
  methods::new("externalptr")
}

lgb.last.error <- function() {
  # out-arguments must be RUNTIME allocations: byte-compiled R dedupes
  # literal constants, so a C write into a passed literal (e.g. 0L)
  # would corrupt every other use of that constant in the function
  act_len <- integer(1L)
  msg <- .Call("LGBM_GetLastError_R", 4096L, act_len, character(1L),
               PACKAGE = "lightgbmtpu")
  stop("lightgbm_tpu: ", msg, call. = FALSE)
}

# run a .Call glue entry point with the trailing call_state flag and
# re-raise through LGBM_GetLastError on failure. call_state is a fresh
# allocation per call (see lgb.last.error note).
lgb.call <- function(fun_name, ..., ret = NULL) {
  call_state <- integer(1L)
  if (!is.null(ret)) {
    ret <- .Call(fun_name, ..., ret, call_state, PACKAGE = "lightgbmtpu")
  } else {
    ret <- .Call(fun_name, ..., call_state, PACKAGE = "lightgbmtpu")
  }
  if (call_state[1L] != 0L) lgb.last.error()
  ret
}

# glue string-out entry points RETURN a freshly allocated character
# vector; the placeholder argument only keeps reference arity
lgb.call.return.str <- function(fun_name, ...) {
  act_len <- integer(1L)
  buf_len <- 1024L * 1024L
  buf <- lgb.call(fun_name, ..., buf_len, act_len, ret = character(1L))
  if (act_len[1L] > buf_len) {
    buf_len <- act_len[1L]
    buf <- lgb.call(fun_name, ..., buf_len, act_len, ret = character(1L))
  }
  buf
}

# glue scalar-out entry points RETURN the scalar; the placeholder
# argument keeps reference arity
lgb.call.return.int <- function(fun_name, ...) {
  lgb.call(fun_name, ..., ret = integer(1L))
}

lgb.params2str <- function(params, ...) {
  if (!identical(class(params), "list")) {
    stop("params must be a list")
  }
  extra <- list(...)
  params <- modifyList(params, extra)
  pairs <- character(0)
  for (key in names(params)) {
    val <- params[[key]]
    if (is.null(val) || length(val) == 0L) next
    val <- paste0(as.character(unlist(val)), collapse = ",")
    pairs <- c(pairs, paste0(key, "=", val))
  }
  paste0(pairs, collapse = " ")
}

lgb.check.r6.class <- function(object, name) {
  all(c("R6", name) %in% class(object))
}

# the metrics where smaller is better (mirrors metric registry defaults)
.lgb_higher_better <- function(name) {
  grepl("auc|ndcg|map|acc", name)
}
