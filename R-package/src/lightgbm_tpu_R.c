/* .Call glue between R and lib_lightgbm_tpu.so — the in-process binding
 * the reference ships as src/lightgbm_R.cpp:1-633 (surface studied for
 * parity; implementation here is a fresh R-C-API binding over our own
 * LGBM_* C ABI, native/capi_shim.c).
 *
 * Exported symbols match the reference's lightgbm_R.h list exactly
 * (38 entry points, same names, same arity, same trailing call_state
 * error-flag convention) so R code written against either binding loads.
 *
 * Build inside R:   R CMD SHLIB lightgbm_tpu_R.c -L../../native -l_lightgbm_tpu
 * Smoke build (CI, no R toolchain): cc -c with the fallback declarations
 * below (scripts/check_r_glue.py) — layout/ABI of the R API is provided
 * by R itself at package-install time.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>
#include <stdio.h>
#include <limits.h>

#if defined(__has_include)
#  if __has_include(<Rinternals.h>)
#    define LGBMR_HAVE_R 1
#  endif
#endif

#ifdef LGBMR_HAVE_R
#  include <R.h>
#  include <Rinternals.h>
#  include <R_ext/Rdynload.h>
#else
/* Minimal declarations of the official R C API used below. Only
 * DECLARATIONS: the definitions live in libR at package load time; for
 * the no-R smoke build they just have to typecheck. */
typedef void *SEXP;
extern SEXP R_NilValue;
extern SEXP Rf_protect(SEXP);
extern void Rf_unprotect(int);
extern SEXP R_MakeExternalPtr(void *, SEXP, SEXP);
extern void *R_ExternalPtrAddr(SEXP);
extern void R_ClearExternalPtr(SEXP);
extern double *REAL(SEXP);
extern int *INTEGER(SEXP);
extern const char *R_CHAR(SEXP);
extern SEXP STRING_ELT(SEXP, int);
extern SEXP Rf_mkChar(const char *);
extern void SET_STRING_ELT(SEXP, int, SEXP);
extern int Rf_asInteger(SEXP);
extern double Rf_asReal(SEXP);
extern int Rf_length(SEXP);
extern void Rf_error(const char *, ...);
extern SEXP Rf_ScalarInteger(int);
extern SEXP Rf_mkString(const char *);
#  define CHAR(x) R_CHAR(x)
typedef struct { const char *name; void *(*fun)(void); int numArgs; } R_CallMethodDef;
typedef void *DllInfo;
extern void R_registerRoutines(DllInfo *, const void *, const R_CallMethodDef *,
                               const void *, const void *);
extern void R_useDynamicSymbols(DllInfo *, int);
#endif

/* ---- our C ABI (subset used; prototypes must match c_api.h) ---------- */
typedef void *DatasetHandle;
typedef void *BoosterHandle;
extern const char *LGBM_GetLastError(void);
extern int LGBM_DatasetCreateFromFile(const char *, const char *,
                                      const DatasetHandle, DatasetHandle *);
extern int LGBM_DatasetCreateFromMat(const void *, int, int32_t, int32_t, int,
                                     const char *, const DatasetHandle,
                                     DatasetHandle *);
extern int LGBM_DatasetCreateFromCSC(const void *, int, const int32_t *,
                                     const void *, int, int64_t, int64_t,
                                     int64_t, const char *,
                                     const DatasetHandle, DatasetHandle *);
extern int LGBM_DatasetGetSubset(const DatasetHandle, const int32_t *, int32_t,
                                 const char *, DatasetHandle *);
extern int LGBM_DatasetSetFeatureNames(DatasetHandle, const char **, int);
extern int LGBM_DatasetGetFeatureNames(DatasetHandle, char **, int *);
extern int LGBM_DatasetSaveBinary(DatasetHandle, const char *);
extern int LGBM_DatasetFree(DatasetHandle);
extern int LGBM_DatasetSetField(DatasetHandle, const char *, const void *,
                                int, int);
extern int LGBM_DatasetGetField(DatasetHandle, const char *, int *,
                                const void **, int *);
extern int LGBM_DatasetGetNumData(DatasetHandle, int *);
extern int LGBM_DatasetGetNumFeature(DatasetHandle, int *);
extern int LGBM_BoosterCreate(const DatasetHandle, const char *,
                              BoosterHandle *);
extern int LGBM_BoosterCreateFromModelfile(const char *, int *,
                                           BoosterHandle *);
extern int LGBM_BoosterLoadModelFromString(const char *, int *,
                                           BoosterHandle *);
extern int LGBM_BoosterFree(BoosterHandle);
extern int LGBM_BoosterMerge(BoosterHandle, BoosterHandle);
extern int LGBM_BoosterAddValidData(BoosterHandle, const DatasetHandle);
extern int LGBM_BoosterResetTrainingData(BoosterHandle, const DatasetHandle);
extern int LGBM_BoosterResetParameter(BoosterHandle, const char *);
extern int LGBM_BoosterGetNumClasses(BoosterHandle, int *);
extern int LGBM_BoosterUpdateOneIter(BoosterHandle, int *);
extern int LGBM_BoosterUpdateOneIterCustom(BoosterHandle, const float *,
                                           const float *, int *);
extern int LGBM_BoosterRollbackOneIter(BoosterHandle);
extern int LGBM_BoosterGetCurrentIteration(BoosterHandle, int *);
extern int LGBM_BoosterGetEvalCounts(BoosterHandle, int *);
extern int LGBM_BoosterGetEvalNames(BoosterHandle, int *, char **);
extern int LGBM_BoosterGetEval(BoosterHandle, int, int *, double *);
extern int LGBM_BoosterGetNumPredict(BoosterHandle, int, int64_t *);
extern int LGBM_BoosterGetPredict(BoosterHandle, int, int64_t *, double *);
extern int LGBM_BoosterPredictForFile(BoosterHandle, const char *, int, int,
                                      int, const char *, const char *);
extern int LGBM_BoosterCalcNumPredict(BoosterHandle, int, int, int, int64_t *);
extern int LGBM_BoosterPredictForCSC(BoosterHandle, const void *, int,
                                     const int32_t *, const void *, int,
                                     int64_t, int64_t, int64_t, int, int,
                                     const char *, int64_t *, double *);
extern int LGBM_BoosterPredictForMat(BoosterHandle, const void *, int, int32_t,
                                     int32_t, int, int, int, const char *,
                                     int64_t *, double *);
extern int LGBM_BoosterSaveModel(BoosterHandle, int, const char *);
extern int LGBM_BoosterSaveModelToString(BoosterHandle, int, int64_t,
                                         int64_t *, char *);
extern int LGBM_BoosterDumpModel(BoosterHandle, int, int64_t, int64_t *,
                                 char *);

#define C_API_DTYPE_FLOAT32 0
#define C_API_DTYPE_FLOAT64 1
#define C_API_DTYPE_INT32 2
#define C_API_PREDICT_NORMAL 0
#define C_API_PREDICT_RAW_SCORE 1
#define C_API_PREDICT_LEAF_INDEX 2

/* ---- helpers --------------------------------------------------------- */

/* the reference's call_state convention: INTEGER(call_state)[0] set
 * nonzero on failure; R-side lgb.call re-raises with LGBM_GetLastError */
#define FAIL(cs)                          \
  do {                                    \
    INTEGER(cs)[0] = -1;                  \
    return R_NilValue;                    \
  } while (0)
#define CHECK_CALL(x, cs)                 \
  do {                                    \
    if ((x) != 0) FAIL(cs);               \
  } while (0)

static const char *lgbmr_str(SEXP x) { return CHAR(STRING_ELT(x, 0)); }

static void *lgbmr_handle(SEXP x) { return R_ExternalPtrAddr(x); }

static SEXP lgbmr_wrap_handle(void *h, SEXP out) {
  /* out is an R environment-allocated externalptr placeholder created by
   * the R side (lgb.null.handle); store the address in place */
  (void)out;
  return R_MakeExternalPtr(h, R_NilValue, R_NilValue);
}

/* predict type from the two reference-style flags */
static int lgbmr_pred_type(SEXP is_rawscore, SEXP is_leafidx) {
  if (Rf_asInteger(is_leafidx)) return C_API_PREDICT_LEAF_INDEX;
  if (Rf_asInteger(is_rawscore)) return C_API_PREDICT_RAW_SCORE;
  return C_API_PREDICT_NORMAL;
}

/* join `n` C strings into buf with '\n', truncating at buf_len */
static int lgbmr_join(char **strs, int n, char *buf, int buf_len) {
  int used = 0;
  for (int i = 0; i < n; ++i) {
    int l = (int)strlen(strs[i]);
    if (used + l + 2 > buf_len) return -1;
    memcpy(buf + used, strs[i], (size_t)l);
    used += l;
    buf[used++] = (i + 1 < n) ? '\n' : '\0';
  }
  if (n == 0 && buf_len > 0) buf[0] = '\0';
  return used;
}

/* ---- error ----------------------------------------------------------- */

SEXP LGBM_GetLastError_R(SEXP buf_len, SEXP actual_len, SEXP err_msg) {
  const char *msg = LGBM_GetLastError();
  int need = (int)strlen(msg) + 1;
  (void)buf_len;
  (void)err_msg;
  INTEGER(actual_len)[0] = need;
  return Rf_mkString(msg);
}

/* ---- Dataset --------------------------------------------------------- */

SEXP LGBM_DatasetCreateFromFile_R(SEXP filename, SEXP parameters,
                                  SEXP reference, SEXP out, SEXP call_state) {
  DatasetHandle h = NULL;
  DatasetHandle ref =
      (reference == R_NilValue) ? NULL : lgbmr_handle(reference);
  CHECK_CALL(LGBM_DatasetCreateFromFile(lgbmr_str(filename),
                                        lgbmr_str(parameters), ref, &h),
             call_state);
  return lgbmr_wrap_handle(h, out);
}

SEXP LGBM_DatasetCreateFromCSC_R(SEXP indptr, SEXP indices, SEXP data,
                                 SEXP nindptr, SEXP nelem, SEXP num_row,
                                 SEXP parameters, SEXP reference, SEXP out,
                                 SEXP call_state) {
  DatasetHandle h = NULL;
  DatasetHandle ref =
      (reference == R_NilValue) ? NULL : lgbmr_handle(reference);
  CHECK_CALL(LGBM_DatasetCreateFromCSC(
                 INTEGER(indptr), C_API_DTYPE_INT32, INTEGER(indices),
                 REAL(data), C_API_DTYPE_FLOAT64, (int64_t)Rf_asInteger(nindptr),
                 (int64_t)Rf_asInteger(nelem), (int64_t)Rf_asInteger(num_row),
                 lgbmr_str(parameters), ref, &h),
             call_state);
  return lgbmr_wrap_handle(h, out);
}

SEXP LGBM_DatasetCreateFromMat_R(SEXP data, SEXP nrow, SEXP ncol,
                                 SEXP parameters, SEXP reference, SEXP out,
                                 SEXP call_state) {
  DatasetHandle h = NULL;
  DatasetHandle ref =
      (reference == R_NilValue) ? NULL : lgbmr_handle(reference);
  /* R matrices are column-major doubles */
  CHECK_CALL(LGBM_DatasetCreateFromMat(REAL(data), C_API_DTYPE_FLOAT64,
                                       Rf_asInteger(nrow), Rf_asInteger(ncol),
                                       0 /* col major */, lgbmr_str(parameters),
                                       ref, &h),
             call_state);
  return lgbmr_wrap_handle(h, out);
}

SEXP LGBM_DatasetGetSubset_R(SEXP handle, SEXP used_row_indices,
                             SEXP len_used_row_indices, SEXP parameters,
                             SEXP out, SEXP call_state) {
  DatasetHandle h = NULL;
  int n = Rf_asInteger(len_used_row_indices);
  /* R passes 1-based row indices; the C ABI wants 0-based */
  int32_t *idx0 = (int32_t *)malloc(sizeof(int32_t) * (size_t)n);
  if (idx0 == NULL) FAIL(call_state);
  for (int i = 0; i < n; ++i) idx0[i] = INTEGER(used_row_indices)[i] - 1;
  int rc = LGBM_DatasetGetSubset(lgbmr_handle(handle), idx0, n,
                                 lgbmr_str(parameters), &h);
  free(idx0);
  CHECK_CALL(rc, call_state);
  return lgbmr_wrap_handle(h, out);
}

SEXP LGBM_DatasetSetFeatureNames_R(SEXP handle, SEXP feature_names,
                                   SEXP call_state) {
  /* feature_names arrives '\t'-joined (utils.R convention) */
  const char *joined = lgbmr_str(feature_names);
  char *copy = strdup(joined);
  if (copy == NULL) FAIL(call_state);
  int n = 1;
  for (const char *p = joined; *p; ++p)
    if (*p == '\t') ++n;
  const char **names = (const char **)malloc(sizeof(char *) * (size_t)n);
  if (names == NULL) {
    free(copy);
    FAIL(call_state);
  }
  int i = 0;
  char *save = copy;
  for (char *tok = strtok(copy, "\t"); tok != NULL && i < n;
       tok = strtok(NULL, "\t"))
    names[i++] = tok;
  int rc = LGBM_DatasetSetFeatureNames(lgbmr_handle(handle), names, i);
  free(names);
  free(save);
  CHECK_CALL(rc, call_state);
  return R_NilValue;
}

SEXP LGBM_DatasetGetFeatureNames_R(SEXP handle, SEXP buf_len, SEXP actual_len,
                                   SEXP feature_names, SEXP call_state) {
  int n = 0;
  CHECK_CALL(LGBM_DatasetGetNumFeature(lgbmr_handle(handle), &n), call_state);
  /* NOTE: the LGBM_*Names C ABI (like the reference's) copies into
   * caller buffers with no length parameter; 4096 matches the C ABI's
   * own internal cap for a single name */
  char **strs = (char **)malloc(sizeof(char *) * (size_t)(n > 0 ? n : 1));
  if (strs == NULL) FAIL(call_state);
  for (int i = 0; i < n; ++i) {
    strs[i] = (char *)malloc(4096);
    if (strs[i] == NULL) {
      for (int j = 0; j < i; ++j) free(strs[j]);
      free(strs);
      FAIL(call_state);
    }
  }
  int got = 0;
  SEXP result = feature_names;
  int rc = LGBM_DatasetGetFeatureNames(lgbmr_handle(handle), strs, &got);
  if (rc == 0) {
    int blen = Rf_asInteger(buf_len);
    char *buf = (char *)malloc((size_t)(blen > 0 ? blen : 1));
    if (buf != NULL) {
      int need = 1;
      for (int i = 0; i < got; ++i) need += (int)strlen(strs[i]) + 1;
      INTEGER(actual_len)[0] = need;
      if (lgbmr_join(strs, got, buf, blen) >= 0)
        result = Rf_mkString(buf);
      free(buf);
    }
  }
  for (int i = 0; i < n; ++i) free(strs[i]);
  free(strs);
  CHECK_CALL(rc, call_state);
  return result;
}

SEXP LGBM_DatasetSaveBinary_R(SEXP handle, SEXP filename, SEXP call_state) {
  CHECK_CALL(LGBM_DatasetSaveBinary(lgbmr_handle(handle), lgbmr_str(filename)),
             call_state);
  return R_NilValue;
}

SEXP LGBM_DatasetFree_R(SEXP handle, SEXP call_state) {
  if (lgbmr_handle(handle) != NULL) {
    CHECK_CALL(LGBM_DatasetFree(lgbmr_handle(handle)), call_state);
    R_ClearExternalPtr(handle);
  }
  return R_NilValue;
}

SEXP LGBM_DatasetSetField_R(SEXP handle, SEXP field_name, SEXP field_data,
                            SEXP num_element, SEXP call_state) {
  int n = Rf_asInteger(num_element);
  const char *name = lgbmr_str(field_name);
  int rc;
  if (strcmp(name, "group") == 0 || strcmp(name, "query") == 0) {
    rc = LGBM_DatasetSetField(lgbmr_handle(handle), name,
                              INTEGER(field_data), n, C_API_DTYPE_INT32);
  } else if (strcmp(name, "init_score") == 0) {
    /* init_score is FLOAT64 in the C ABI contract (c_api.h SetField) */
    rc = LGBM_DatasetSetField(lgbmr_handle(handle), name, REAL(field_data),
                              n, C_API_DTYPE_FLOAT64);
  } else {
    /* label / weight arrive as doubles from R; the C ABI stores them
     * as float32 */
    float *f = (float *)malloc(sizeof(float) * (size_t)n);
    if (f == NULL) FAIL(call_state);
    for (int i = 0; i < n; ++i) f[i] = (float)REAL(field_data)[i];
    rc = LGBM_DatasetSetField(lgbmr_handle(handle), name, f, n,
                              C_API_DTYPE_FLOAT32);
    free(f);
  }
  CHECK_CALL(rc, call_state);
  return R_NilValue;
}

SEXP LGBM_DatasetGetFieldSize_R(SEXP handle, SEXP field_name, SEXP out,
                                SEXP call_state) {
  int n = 0, dtype = 0;
  const void *ptr = NULL;
  CHECK_CALL(LGBM_DatasetGetField(lgbmr_handle(handle), lgbmr_str(field_name),
                                  &n, &ptr, &dtype),
             call_state);
  INTEGER(out)[0] = n;
  return Rf_ScalarInteger(n);
}

SEXP LGBM_DatasetGetField_R(SEXP handle, SEXP field_name, SEXP field_data,
                            SEXP call_state) {
  int n = 0, dtype = 0;
  const void *ptr = NULL;
  CHECK_CALL(LGBM_DatasetGetField(lgbmr_handle(handle), lgbmr_str(field_name),
                                  &n, &ptr, &dtype),
             call_state);
  if (dtype == C_API_DTYPE_FLOAT32) {
    const float *f = (const float *)ptr;
    for (int i = 0; i < n; ++i) REAL(field_data)[i] = (double)f[i];
  } else if (dtype == C_API_DTYPE_INT32) {
    const int32_t *v = (const int32_t *)ptr;
    for (int i = 0; i < n; ++i) INTEGER(field_data)[i] = v[i];
  } else {
    const double *d = (const double *)ptr;
    for (int i = 0; i < n; ++i) REAL(field_data)[i] = d[i];
  }
  return field_data;
}

SEXP LGBM_DatasetGetNumData_R(SEXP handle, SEXP out, SEXP call_state) {
  int n = 0;
  CHECK_CALL(LGBM_DatasetGetNumData(lgbmr_handle(handle), &n), call_state);
  INTEGER(out)[0] = n;
  return Rf_ScalarInteger(n);
}

SEXP LGBM_DatasetGetNumFeature_R(SEXP handle, SEXP out, SEXP call_state) {
  int n = 0;
  CHECK_CALL(LGBM_DatasetGetNumFeature(lgbmr_handle(handle), &n), call_state);
  INTEGER(out)[0] = n;
  return Rf_ScalarInteger(n);
}

/* ---- Booster --------------------------------------------------------- */

SEXP LGBM_BoosterCreate_R(SEXP train_data, SEXP parameters, SEXP out,
                          SEXP call_state) {
  BoosterHandle h = NULL;
  CHECK_CALL(LGBM_BoosterCreate(lgbmr_handle(train_data),
                                lgbmr_str(parameters), &h),
             call_state);
  return lgbmr_wrap_handle(h, out);
}

SEXP LGBM_BoosterFree_R(SEXP handle, SEXP call_state) {
  if (lgbmr_handle(handle) != NULL) {
    CHECK_CALL(LGBM_BoosterFree(lgbmr_handle(handle)), call_state);
    R_ClearExternalPtr(handle);
  }
  return R_NilValue;
}

SEXP LGBM_BoosterCreateFromModelfile_R(SEXP filename, SEXP out,
                                       SEXP call_state) {
  BoosterHandle h = NULL;
  int iters = 0;
  CHECK_CALL(LGBM_BoosterCreateFromModelfile(lgbmr_str(filename), &iters, &h),
             call_state);
  return lgbmr_wrap_handle(h, out);
}

SEXP LGBM_BoosterLoadModelFromString_R(SEXP model_str, SEXP out,
                                       SEXP call_state) {
  BoosterHandle h = NULL;
  int iters = 0;
  CHECK_CALL(LGBM_BoosterLoadModelFromString(lgbmr_str(model_str), &iters, &h),
             call_state);
  return lgbmr_wrap_handle(h, out);
}

SEXP LGBM_BoosterMerge_R(SEXP handle, SEXP other_handle, SEXP call_state) {
  CHECK_CALL(LGBM_BoosterMerge(lgbmr_handle(handle),
                               lgbmr_handle(other_handle)),
             call_state);
  return R_NilValue;
}

SEXP LGBM_BoosterAddValidData_R(SEXP handle, SEXP valid_data,
                                SEXP call_state) {
  CHECK_CALL(LGBM_BoosterAddValidData(lgbmr_handle(handle),
                                      lgbmr_handle(valid_data)),
             call_state);
  return R_NilValue;
}

SEXP LGBM_BoosterResetTrainingData_R(SEXP handle, SEXP train_data,
                                     SEXP call_state) {
  CHECK_CALL(LGBM_BoosterResetTrainingData(lgbmr_handle(handle),
                                           lgbmr_handle(train_data)),
             call_state);
  return R_NilValue;
}

SEXP LGBM_BoosterResetParameter_R(SEXP handle, SEXP parameters,
                                  SEXP call_state) {
  CHECK_CALL(LGBM_BoosterResetParameter(lgbmr_handle(handle),
                                        lgbmr_str(parameters)),
             call_state);
  return R_NilValue;
}

SEXP LGBM_BoosterGetNumClasses_R(SEXP handle, SEXP out, SEXP call_state) {
  int n = 0;
  CHECK_CALL(LGBM_BoosterGetNumClasses(lgbmr_handle(handle), &n), call_state);
  INTEGER(out)[0] = n;
  return Rf_ScalarInteger(n);
}

SEXP LGBM_BoosterUpdateOneIter_R(SEXP handle, SEXP call_state) {
  int fin = 0;
  CHECK_CALL(LGBM_BoosterUpdateOneIter(lgbmr_handle(handle), &fin),
             call_state);
  return R_NilValue;
}

SEXP LGBM_BoosterUpdateOneIterCustom_R(SEXP handle, SEXP grad, SEXP hess,
                                       SEXP len, SEXP call_state) {
  int n = Rf_asInteger(len);
  int fin = 0;
  float *g = (float *)malloc(sizeof(float) * (size_t)n);
  float *h = (float *)malloc(sizeof(float) * (size_t)n);
  if (g == NULL || h == NULL) {
    free(g);
    free(h);
    FAIL(call_state);
  }
  for (int i = 0; i < n; ++i) {
    g[i] = (float)REAL(grad)[i];
    h[i] = (float)REAL(hess)[i];
  }
  int rc = LGBM_BoosterUpdateOneIterCustom(lgbmr_handle(handle), g, h, &fin);
  free(g);
  free(h);
  CHECK_CALL(rc, call_state);
  return R_NilValue;
}

SEXP LGBM_BoosterRollbackOneIter_R(SEXP handle, SEXP call_state) {
  CHECK_CALL(LGBM_BoosterRollbackOneIter(lgbmr_handle(handle)), call_state);
  return R_NilValue;
}

SEXP LGBM_BoosterGetCurrentIteration_R(SEXP handle, SEXP out,
                                       SEXP call_state) {
  int it = 0;
  CHECK_CALL(LGBM_BoosterGetCurrentIteration(lgbmr_handle(handle), &it),
             call_state);
  INTEGER(out)[0] = it;
  return Rf_ScalarInteger(it);
}

SEXP LGBM_BoosterGetEvalNames_R(SEXP handle, SEXP buf_len, SEXP actual_len,
                                SEXP eval_names, SEXP call_state) {
  int n = 0;
  CHECK_CALL(LGBM_BoosterGetEvalCounts(lgbmr_handle(handle), &n), call_state);
  char **strs = (char **)malloc(sizeof(char *) * (size_t)(n > 0 ? n : 1));
  if (strs == NULL) FAIL(call_state);
  for (int i = 0; i < n; ++i) {
    strs[i] = (char *)malloc(4096);
    if (strs[i] == NULL) {
      for (int j = 0; j < i; ++j) free(strs[j]);
      free(strs);
      FAIL(call_state);
    }
  }
  int got = 0;
  SEXP result = eval_names;
  int rc = LGBM_BoosterGetEvalNames(lgbmr_handle(handle), &got, strs);
  if (rc == 0) {
    int blen = Rf_asInteger(buf_len);
    char *buf = (char *)malloc((size_t)(blen > 0 ? blen : 1));
    if (buf != NULL) {
      int need = 1;
      for (int i = 0; i < got; ++i) need += (int)strlen(strs[i]) + 1;
      INTEGER(actual_len)[0] = need;
      if (lgbmr_join(strs, got, buf, blen) >= 0)
        result = Rf_mkString(buf);
      free(buf);
    }
  }
  for (int i = 0; i < n; ++i) free(strs[i]);
  free(strs);
  CHECK_CALL(rc, call_state);
  return result;
}

SEXP LGBM_BoosterGetEval_R(SEXP handle, SEXP data_idx, SEXP out_result,
                           SEXP call_state) {
  int n = 0;
  CHECK_CALL(LGBM_BoosterGetEval(lgbmr_handle(handle), Rf_asInteger(data_idx),
                                 &n, REAL(out_result)),
             call_state);
  return out_result;
}

SEXP LGBM_BoosterGetNumPredict_R(SEXP handle, SEXP data_idx, SEXP out,
                                 SEXP call_state) {
  int64_t n = 0;
  CHECK_CALL(LGBM_BoosterGetNumPredict(lgbmr_handle(handle),
                                       Rf_asInteger(data_idx), &n),
             call_state);
  if (n > INT_MAX) {
    /* INTEGER() cannot hold it; a silent wrap would make the R side
     * allocate a wrong-sized buffer for the subsequent GetPredict. */
    Rf_error("prediction count %lld exceeds R integer range",
             (long long)n);
  }
  INTEGER(out)[0] = (int)n;
  return Rf_ScalarInteger((int)n);
}

SEXP LGBM_BoosterGetPredict_R(SEXP handle, SEXP data_idx, SEXP out_result,
                              SEXP call_state) {
  int64_t n = 0;
  CHECK_CALL(LGBM_BoosterGetPredict(lgbmr_handle(handle),
                                    Rf_asInteger(data_idx), &n,
                                    REAL(out_result)),
             call_state);
  return out_result;
}

SEXP LGBM_BoosterPredictForFile_R(SEXP handle, SEXP data_filename,
                                  SEXP data_has_header, SEXP is_rawscore,
                                  SEXP is_leafidx, SEXP num_iteration,
                                  SEXP parameter, SEXP result_filename,
                                  SEXP call_state) {
  CHECK_CALL(LGBM_BoosterPredictForFile(
                 lgbmr_handle(handle), lgbmr_str(data_filename),
                 Rf_asInteger(data_has_header),
                 lgbmr_pred_type(is_rawscore, is_leafidx),
                 Rf_asInteger(num_iteration), lgbmr_str(parameter),
                 lgbmr_str(result_filename)),
             call_state);
  return R_NilValue;
}

SEXP LGBM_BoosterCalcNumPredict_R(SEXP handle, SEXP num_row, SEXP is_rawscore,
                                  SEXP is_leafidx, SEXP num_iteration,
                                  SEXP out_len, SEXP call_state) {
  int64_t n = 0;
  CHECK_CALL(LGBM_BoosterCalcNumPredict(
                 lgbmr_handle(handle), Rf_asInteger(num_row),
                 lgbmr_pred_type(is_rawscore, is_leafidx),
                 Rf_asInteger(num_iteration), &n),
             call_state);
  INTEGER(out_len)[0] = (int)n;
  return Rf_ScalarInteger((int)n);
}

SEXP LGBM_BoosterPredictForCSC_R(SEXP handle, SEXP indptr, SEXP indices,
                                 SEXP data, SEXP nindptr, SEXP nelem,
                                 SEXP num_row, SEXP is_rawscore,
                                 SEXP is_leafidx, SEXP num_iteration,
                                 SEXP parameter, SEXP out_result,
                                 SEXP call_state) {
  int64_t n = 0;
  CHECK_CALL(LGBM_BoosterPredictForCSC(
                 lgbmr_handle(handle), INTEGER(indptr), C_API_DTYPE_INT32,
                 INTEGER(indices), REAL(data), C_API_DTYPE_FLOAT64,
                 (int64_t)Rf_asInteger(nindptr), (int64_t)Rf_asInteger(nelem),
                 (int64_t)Rf_asInteger(num_row),
                 lgbmr_pred_type(is_rawscore, is_leafidx),
                 Rf_asInteger(num_iteration), lgbmr_str(parameter), &n,
                 REAL(out_result)),
             call_state);
  return out_result;
}

SEXP LGBM_BoosterPredictForMat_R(SEXP handle, SEXP data, SEXP nrow, SEXP ncol,
                                 SEXP is_rawscore, SEXP is_leafidx,
                                 SEXP num_iteration, SEXP parameter,
                                 SEXP out_result, SEXP call_state) {
  int64_t n = 0;
  CHECK_CALL(LGBM_BoosterPredictForMat(
                 lgbmr_handle(handle), REAL(data), C_API_DTYPE_FLOAT64,
                 Rf_asInteger(nrow), Rf_asInteger(ncol), 0 /* col major */,
                 lgbmr_pred_type(is_rawscore, is_leafidx),
                 Rf_asInteger(num_iteration), lgbmr_str(parameter), &n,
                 REAL(out_result)),
             call_state);
  return out_result;
}

SEXP LGBM_BoosterSaveModel_R(SEXP handle, SEXP num_iteration, SEXP filename,
                             SEXP call_state) {
  CHECK_CALL(LGBM_BoosterSaveModel(lgbmr_handle(handle),
                                   Rf_asInteger(num_iteration),
                                   lgbmr_str(filename)),
             call_state);
  return R_NilValue;
}

SEXP LGBM_BoosterSaveModelToString_R(SEXP handle, SEXP num_iteration,
                                     SEXP buffer_len, SEXP actual_len,
                                     SEXP out_str, SEXP call_state) {
  int64_t need = 0;
  int blen = Rf_asInteger(buffer_len);
  char *buf = (char *)malloc((size_t)(blen > 0 ? blen : 1));
  if (buf == NULL) FAIL(call_state);
  int rc = LGBM_BoosterSaveModelToString(lgbmr_handle(handle),
                                         Rf_asInteger(num_iteration),
                                         (int64_t)blen, &need, buf);
  SEXP result = out_str;
  if (rc == 0) {
    INTEGER(actual_len)[0] = (int)need;
    if (need <= blen) result = Rf_mkString(buf);
  }
  free(buf);
  CHECK_CALL(rc, call_state);
  return result;
}

SEXP LGBM_BoosterDumpModel_R(SEXP handle, SEXP num_iteration, SEXP buffer_len,
                             SEXP actual_len, SEXP out_str, SEXP call_state) {
  int64_t need = 0;
  int blen = Rf_asInteger(buffer_len);
  char *buf = (char *)malloc((size_t)(blen > 0 ? blen : 1));
  if (buf == NULL) FAIL(call_state);
  int rc = LGBM_BoosterDumpModel(lgbmr_handle(handle),
                                 Rf_asInteger(num_iteration), (int64_t)blen,
                                 &need, buf);
  SEXP result = out_str;
  if (rc == 0) {
    INTEGER(actual_len)[0] = (int)need;
    if (need <= blen) result = Rf_mkString(buf);
  }
  free(buf);
  CHECK_CALL(rc, call_state);
  return result;
}

/* ---- registration ---------------------------------------------------- */

#define CALLDEF(name, n) {#name, (void *(*)(void)) & name, n}
static const R_CallMethodDef CallEntries[] = {
    CALLDEF(LGBM_GetLastError_R, 3),
    CALLDEF(LGBM_DatasetCreateFromFile_R, 5),
    CALLDEF(LGBM_DatasetCreateFromCSC_R, 10),
    CALLDEF(LGBM_DatasetCreateFromMat_R, 7),
    CALLDEF(LGBM_DatasetGetSubset_R, 6),
    CALLDEF(LGBM_DatasetSetFeatureNames_R, 3),
    CALLDEF(LGBM_DatasetGetFeatureNames_R, 5),
    CALLDEF(LGBM_DatasetSaveBinary_R, 3),
    CALLDEF(LGBM_DatasetFree_R, 2),
    CALLDEF(LGBM_DatasetSetField_R, 5),
    CALLDEF(LGBM_DatasetGetFieldSize_R, 4),
    CALLDEF(LGBM_DatasetGetField_R, 4),
    CALLDEF(LGBM_DatasetGetNumData_R, 3),
    CALLDEF(LGBM_DatasetGetNumFeature_R, 3),
    CALLDEF(LGBM_BoosterCreate_R, 4),
    CALLDEF(LGBM_BoosterFree_R, 2),
    CALLDEF(LGBM_BoosterCreateFromModelfile_R, 3),
    CALLDEF(LGBM_BoosterLoadModelFromString_R, 3),
    CALLDEF(LGBM_BoosterMerge_R, 3),
    CALLDEF(LGBM_BoosterAddValidData_R, 3),
    CALLDEF(LGBM_BoosterResetTrainingData_R, 3),
    CALLDEF(LGBM_BoosterResetParameter_R, 3),
    CALLDEF(LGBM_BoosterGetNumClasses_R, 3),
    CALLDEF(LGBM_BoosterUpdateOneIter_R, 2),
    CALLDEF(LGBM_BoosterUpdateOneIterCustom_R, 5),
    CALLDEF(LGBM_BoosterRollbackOneIter_R, 2),
    CALLDEF(LGBM_BoosterGetCurrentIteration_R, 3),
    CALLDEF(LGBM_BoosterGetEvalNames_R, 5),
    CALLDEF(LGBM_BoosterGetEval_R, 4),
    CALLDEF(LGBM_BoosterGetNumPredict_R, 4),
    CALLDEF(LGBM_BoosterGetPredict_R, 4),
    CALLDEF(LGBM_BoosterPredictForFile_R, 9),
    CALLDEF(LGBM_BoosterCalcNumPredict_R, 7),
    CALLDEF(LGBM_BoosterPredictForCSC_R, 13),
    CALLDEF(LGBM_BoosterPredictForMat_R, 10),
    CALLDEF(LGBM_BoosterSaveModel_R, 4),
    CALLDEF(LGBM_BoosterSaveModelToString_R, 6),
    CALLDEF(LGBM_BoosterDumpModel_R, 6),
    {NULL, NULL, 0}};

void R_init_lightgbmtpu(DllInfo *dll) {
  R_registerRoutines(dll, NULL, CallEntries, NULL, NULL);
  R_useDynamicSymbols(dll, 0);
}
