"""Measure the reference LightGBM's training throughput on this machine.

Builds /root/reference out-of-tree (its CMakeLists drops binaries into the
source dir via EXECUTABLE_OUTPUT_PATH; we redirect both output paths into
the build dir so the read-only reference tree stays pristine), generates
the exact synthetic datasets bench.py uses, trains with the same
hyperparameters through the reference CLI, and records the measured
mrow_iters/s:

- BENCH_BASELINE.json        — the HIGGS-like headline shape (legacy
                               layout, kept for round-over-round compat)
- BENCH_BASELINE_SHAPES.json — {shape: {...}} for the wide/sparse/
                               categorical shapes (bench.py reads these
                               for per-shape vs_baseline)

Usage: python scripts/measure_baseline.py [shape ...]
       (default: higgs; "all" = every bench.py shape)

The recorded `mrows_per_sec` is max(measured-here, REFERENCE_8T_FLOOR)
for the higgs shape: this box may expose fewer cores than the reference's
benchmark setup (docs/GPU-Performance.md:96-116 used 28 threads), and an
undersized baseline would flatter vs_baseline. REFERENCE_8T_FLOOR is the
8-thread measurement of this exact workload recorded in round 1's review
(VERDICT.md: 20.2 s train on 500k x 28 x 20 iters = 0.495 mrow_iters/s).
Other shapes record the raw measurement (threads = all visible cores).

MUST run on an otherwise-idle machine: this box exposes ONE cpu to the
process, and a concurrently-running test suite silently tripled the
reference's per-iteration time in round 2's first measurement.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = "/root/reference"
BUILD_DIR = os.environ.get("REF_BUILD_DIR", "/tmp/lgbm_ref_build")
REFERENCE_8T_FLOOR = 0.495  # mrow_iters/s, 8 threads, measured in round 1

sys.path.insert(0, REPO)


def build_reference() -> str:
    exe = os.path.join(BUILD_DIR, "lightgbm")
    if os.path.exists(exe):
        return exe
    os.makedirs(BUILD_DIR, exist_ok=True)
    subprocess.run(
        ["cmake", REFERENCE, "-DCMAKE_BUILD_TYPE=Release",
         f"-DEXECUTABLE_OUTPUT_PATH={BUILD_DIR}",
         f"-DLIBRARY_OUTPUT_PATH={BUILD_DIR}"],
        cwd=BUILD_DIR, check=True, capture_output=True)
    subprocess.run(["make", f"-j{os.cpu_count() or 1}"], cwd=BUILD_DIR,
                   check=True, capture_output=True)
    # older CMakeLists may ignore the output-path cache vars for one target
    if not os.path.exists(exe) and os.path.exists(os.path.join(REFERENCE, "lightgbm")):
        os.replace(os.path.join(REFERENCE, "lightgbm"), exe)
        for lib in ("lib_lightgbm.so",):
            src = os.path.join(REFERENCE, lib)
            if os.path.exists(src):
                os.replace(src, os.path.join(BUILD_DIR, lib))
    return exe


def _write_tsv(path: str, y, X) -> None:
    """Fast-enough TSV writer for wide matrices (np.savetxt is a Python
    loop; pandas' C writer is ~10x faster and keeps full precision
    unnecessary for binned training)."""
    import numpy as np
    X = np.round(np.asarray(X, np.float64), 4)
    try:
        import pandas as pd
        df = pd.DataFrame(np.column_stack([np.asarray(y, np.float64), X]))
        df.to_csv(path, sep="\t", header=False, index=False)
    except ImportError:
        np.savetxt(path, np.column_stack([y, X]), fmt="%.4g", delimiter="\t")


def measure_shape(exe: str, shape: str) -> dict:
    import bench

    n_rows, builder, max_bin = bench.SHAPES[shape]
    built = builder(n_rows)
    cat_idx = built[2] if len(built) == 3 else None
    X, y = built[0], built[1]

    # TSV cache keyed by (builder, rows): epsilon and epsilon15 share the
    # same matrix (they differ only in max_bin) — only the .bin cache
    # below needs the per-shape key
    data_path = os.path.join(
        BUILD_DIR, f"bench_{builder.__name__}_{n_rows}.train")
    if not os.path.exists(data_path):
        _write_tsv(data_path, y, X)

    conf = {
        "task": "train", "objective": "binary", "metric": "auc",
        "data": data_path, "num_trees": bench.N_ITERS,
        "learning_rate": 0.1, "num_leaves": bench.NUM_LEAVES,
        "max_bin": max_bin, "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100.0, "verbosity": 1,
        "num_threads": os.cpu_count() or 1,
        "output_model": os.path.join(BUILD_DIR, f"bench_{shape}_model.txt"),
    }
    if cat_idx is not None:
        conf["categorical_feature"] = ",".join(str(c) for c in cat_idx)
    if shape == "multiclass":
        conf.update(objective="multiclass", num_class=5,
                    metric="multi_logloss")

    # one untimed run loads/caches the binned dataset file; the timed run
    # then measures training the way bench.py does (construct untimed).
    # NOTE: the binary caches max_bin/categorical config, so the cache is
    # keyed per shape (epsilon vs epsilon15 differ only in max_bin).
    bin_path = data_path + f".{shape}.bin"
    if not os.path.exists(bin_path):
        warm = [exe, f"data={data_path}", "task=train", "num_trees=1",
                f"max_bin={max_bin}", "save_binary=true",
                f"objective={conf['objective']}", "min_data_in_leaf=1",
                f"output_model={os.path.join(BUILD_DIR, 'warm_model.txt')}"]
        if conf.get("num_class"):
            warm.append(f"num_class={conf['num_class']}")
        if cat_idx is not None:
            warm.append("categorical_feature=" + ",".join(str(c) for c in cat_idx))
        subprocess.run(warm, check=True, capture_output=True, cwd=BUILD_DIR)
        os.replace(data_path + ".bin", bin_path)
    conf["data"] = bin_path
    args = [exe] + [f"{k}={v}" for k, v in conf.items()]

    t0 = time.time()
    out = subprocess.run(args, check=True, capture_output=True, text=True)
    wall = time.time() - t0
    # exclude data-load time using the reference's own log timestamps if
    # present; otherwise charge the full wall time to training
    train_time = wall
    for line in out.stdout.splitlines():
        if "seconds elapsed, finished iteration" in line:
            try:
                train_time = float(line.split()[1])
            except (ValueError, IndexError):
                pass

    measured = n_rows * bench.N_ITERS / train_time / 1e6
    rec = measured if shape != "higgs" else max(measured, REFERENCE_8T_FLOOR)
    return {
        "mrows_per_sec": round(rec, 4),
        "measured_here": round(measured, 4),
        "train_seconds": round(train_time, 3),
        "wall_seconds": round(wall, 3),
        "threads": os.cpu_count() or 1,
        "rows": n_rows, "features": int(X.shape[1]),
        "iters": bench.N_ITERS,
        "num_leaves": bench.NUM_LEAVES, "max_bin": max_bin,
    }


def main():
    import bench

    shapes = sys.argv[1:] or ["higgs"]
    if shapes == ["all"]:
        shapes = list(bench.SHAPES)
    exe = build_reference()

    shapes_path = os.path.join(REPO, "BENCH_BASELINE_SHAPES.json")
    all_results = {}
    if os.path.exists(shapes_path):
        with open(shapes_path) as fh:
            all_results = json.load(fh)

    for shape in shapes:
        result = measure_shape(exe, shape)
        if shape == "higgs":
            result["reference_8thread_floor"] = REFERENCE_8T_FLOOR
            with open(os.path.join(REPO, "BENCH_BASELINE.json"), "w") as fh:
                json.dump(result, fh, indent=1)
        else:
            all_results[shape] = result
            with open(shapes_path, "w") as fh:
                json.dump(all_results, fh, indent=1)
        print(shape, json.dumps(result), flush=True)


if __name__ == "__main__":
    main()
