"""Measure the reference LightGBM's training throughput on this machine.

Builds /root/reference out-of-tree (its CMakeLists drops binaries into the
source dir via EXECUTABLE_OUTPUT_PATH; we redirect both output paths into
the build dir so the read-only reference tree stays pristine), generates
the exact synthetic dataset bench.py uses, trains with the same
hyperparameters through the reference CLI, and writes BENCH_BASELINE.json
at the repo root with the measured mrow_iters/s.

bench.py reads BENCH_BASELINE.json to report an honest vs_baseline.

The recorded `mrows_per_sec` is max(measured-here, REFERENCE_8T_FLOOR):
this box may expose fewer cores than the reference's benchmark setup
(docs/GPU-Performance.md:96-116 used 28 threads), and an undersized
baseline would flatter vs_baseline. REFERENCE_8T_FLOOR is the 8-thread
measurement of this exact workload recorded in round 1's review
(VERDICT.md: 20.2 s train on 500k x 28 x 20 iters = 0.495 mrow_iters/s).

MUST run on an otherwise-idle machine: this box exposes ONE cpu to the
process, and a concurrently-running test suite silently tripled the
reference's per-iteration time in round 2's first measurement.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE = "/root/reference"
BUILD_DIR = os.environ.get("REF_BUILD_DIR", "/tmp/lgbm_ref_build")
REFERENCE_8T_FLOOR = 0.495  # mrow_iters/s, 8 threads, measured in round 1

sys.path.insert(0, REPO)


def build_reference() -> str:
    exe = os.path.join(BUILD_DIR, "lightgbm")
    if os.path.exists(exe):
        return exe
    os.makedirs(BUILD_DIR, exist_ok=True)
    subprocess.run(
        ["cmake", REFERENCE, "-DCMAKE_BUILD_TYPE=Release",
         f"-DEXECUTABLE_OUTPUT_PATH={BUILD_DIR}",
         f"-DLIBRARY_OUTPUT_PATH={BUILD_DIR}"],
        cwd=BUILD_DIR, check=True, capture_output=True)
    subprocess.run(["make", f"-j{os.cpu_count() or 1}"], cwd=BUILD_DIR,
                   check=True, capture_output=True)
    # older CMakeLists may ignore the output-path cache vars for one target
    if not os.path.exists(exe) and os.path.exists(os.path.join(REFERENCE, "lightgbm")):
        os.replace(os.path.join(REFERENCE, "lightgbm"), exe)
        for lib in ("lib_lightgbm.so",):
            src = os.path.join(REFERENCE, lib)
            if os.path.exists(src):
                os.replace(src, os.path.join(BUILD_DIR, lib))
    return exe


def main():
    import numpy as np

    from bench import MAX_BIN, N_FEATURES, N_ITERS, N_ROWS, NUM_LEAVES, synth_higgs

    exe = build_reference()
    X, y = synth_higgs(N_ROWS, N_FEATURES)
    # the row count keys the cache: a BENCH_ROWS change must not silently
    # reuse a stale dataset while the throughput math uses the new count
    data_path = os.path.join(BUILD_DIR, f"bench_{N_ROWS}.train")
    if not os.path.exists(data_path):
        arr = np.column_stack([y, X])
        np.savetxt(data_path, arr, fmt="%.6g", delimiter="\t")

    conf = {
        "task": "train", "objective": "binary", "metric": "auc",
        "data": data_path, "num_trees": N_ITERS, "learning_rate": 0.1,
        "num_leaves": NUM_LEAVES, "max_bin": MAX_BIN, "min_data_in_leaf": 1,
        "min_sum_hessian_in_leaf": 100.0, "verbosity": 1,
        "num_threads": os.cpu_count() or 1,
        "output_model": os.path.join(BUILD_DIR, "bench_model.txt"),
    }
    args = [exe] + [f"{k}={v}" for k, v in conf.items()]

    # one untimed run loads/caches the binned dataset file; the timed run
    # then measures training the way bench.py does (construct untimed)
    bin_path = data_path + ".bin"
    if not os.path.exists(bin_path):
        subprocess.run([exe, f"data={data_path}", "task=train", "num_trees=1",
                        f"max_bin={MAX_BIN}", "save_binary=true",
                        "objective=binary", "min_data_in_leaf=1",
                        f"output_model={os.path.join(BUILD_DIR, 'warm_model.txt')}"],
                       check=True, capture_output=True, cwd=BUILD_DIR)
    conf["data"] = bin_path
    args = [exe] + [f"{k}={v}" for k, v in conf.items()]

    t0 = time.time()
    out = subprocess.run(args, check=True, capture_output=True, text=True)
    wall = time.time() - t0
    # exclude data-load time using the reference's own log timestamps if
    # present; otherwise charge the full wall time to training
    train_time = wall
    for line in out.stdout.splitlines():
        if "seconds elapsed, finished iteration" in line:
            try:
                train_time = float(line.split()[1])
            except (ValueError, IndexError):
                pass

    measured = N_ROWS * N_ITERS / train_time / 1e6
    result = {
        "mrows_per_sec": round(max(measured, REFERENCE_8T_FLOOR), 4),
        "measured_here": round(measured, 4),
        "reference_8thread_floor": REFERENCE_8T_FLOOR,
        "train_seconds": round(train_time, 3),
        "wall_seconds": round(wall, 3),
        "threads": os.cpu_count() or 1,
        "rows": N_ROWS, "features": N_FEATURES, "iters": N_ITERS,
        "num_leaves": NUM_LEAVES, "max_bin": MAX_BIN,
    }
    with open(os.path.join(REPO, "BENCH_BASELINE.json"), "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
