"""Overload-resilience gates for the serving tier (ISSUE 12).

Four phases, one committed artifact (OVERLOAD_r01.json via
BENCH_SHAPE=overload):

1. **overload** — open-loop bench at ~2x saturation. Capacity is made
   deterministic with `faults.slow_predict` (every coalesced dispatch
   pays a fixed service time, so saturation = micro_batch / service_s
   rows/s regardless of host speed). Gates: every offered request is
   RESOLVED (completed or promptly rejected with a structured
   retriable ServingOverload/DeadlineExceeded — zero silently dropped
   futures), admitted-request p99 stays bounded (within the deadline
   envelope, and a bounded multiple of the at-capacity p99) instead of
   growing with the backlog, and admitted predictions are bit-identical
   to an unloaded reference predict.
2. **breaker** — `faults.fail_predict(n)` trips the per-model circuit
   breaker after n consecutive failures; requests are then refused
   with "breaker_open" WITHOUT touching the model, and after the reset
   window a half-open probe recovers it.
3. **single_flight** — `faults.compile_storm` wedges the cold-bucket
   first compile; N concurrent cold requests must pay exactly ONE
   simulated trace (leads == 1) and all complete.
4. **cold_start** — two child processes share a
   `tpu_compile_cache_dir`: the second (a "restarted replica") must
   warm its whole bucket ladder + first request with ZERO compile-cache
   misses (every program loads from disk) and produce bit-identical
   predictions.

Usage: python scripts/overload_smoke.py [--out OVERLOAD_r01.json]
Exits nonzero on any gate failure; prints one machine-readable JSON
line per phase plus a final summary line.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

N_FEATURES = 10
SERVICE_S = 0.02          # injected per-dispatch service time
MICRO_BATCH = 8           # rows per coalesced dispatch
# deadline below the full-queue wait (48/8 dispatches x ~25ms ≈ 150ms),
# so the overload run exercises ALL THREE rejection paths: early
# entries expire in the queue (deadline_expired) until the EWMA
# converges, after which the shed policy refuses at admission, and
# bursts past the cap are queue_full
DEADLINE_MS = 80.0
MAX_QUEUE = 48


def _train(params_extra=None):
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.randn(3000, N_FEATURES).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    params = {"objective": "binary", "verbose": -1, "num_leaves": 15,
              "min_data_in_leaf": 5}
    params.update(params_extra or {})
    ds = lgb.Dataset(X, y, params=dict(params))
    booster = lgb.train(dict(params), ds, num_boost_round=20,
                        verbose_eval=False)
    return X, booster, params


def _open_loop(reg, X, qps: float, seconds: float, seed: int):
    """Offer Poisson arrivals at `qps` via submit(); resolve everything.
    Returns (admitted_latencies_s, rejections, failures, results)."""
    from lightgbm_tpu.serving import ServingOverload
    rng = np.random.RandomState(seed)
    n_req = max(1, int(qps * seconds))
    gaps = rng.exponential(1.0 / qps, size=n_req)
    arrivals = np.cumsum(gaps)
    lock = threading.Lock()
    lats, results = [], {}
    rejections = []      # (reason, latency_s, retriable)
    failures = []        # future-side structured failures
    pending = [0]

    def on_done(fut, arrival_abs, idx):
        dt = time.perf_counter() - arrival_abs
        exc = fut.exception()
        with lock:
            pending[0] -= 1
            if exc is None:
                lats.append(dt)
                if idx not in results:
                    results[idx] = fut.result()
            else:
                failures.append((type(exc).__name__,
                                 getattr(exc, "reason", None), dt,
                                 bool(getattr(exc, "retriable", False))))

    start = time.perf_counter()
    for i in range(n_req):
        target = start + arrivals[i]
        now = time.perf_counter()
        if target > now:
            time.sleep(target - now)
        idx = i % 256
        arrival_abs = time.perf_counter()
        try:
            fut = reg.submit("main", X[idx])
        except ServingOverload as exc:
            with lock:
                rejections.append(
                    (exc.reason, time.perf_counter() - arrival_abs,
                     bool(exc.retriable)))
            continue
        with lock:
            pending[0] += 1
        fut.add_done_callback(
            lambda f, a=arrival_abs, j=idx: on_done(f, a, j))
    deadline = time.time() + 30
    while time.time() < deadline:
        with lock:
            if pending[0] == 0:
                break
        time.sleep(0.01)
    with lock:
        return (sorted(lats), list(rejections), list(failures),
                dict(results), n_req, pending[0])


def phase_overload() -> dict:
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving import ModelRegistry
    from lightgbm_tpu.testing import faults

    X, booster, _ = _train()
    serve = lgb.Booster(model_str=booster.model_to_string(), params={
        "tpu_serving_deadline_ms": DEADLINE_MS,
        "tpu_serving_max_queue": MAX_QUEUE,
        "tpu_predict_micro_batch": MICRO_BATCH,
        "tpu_predict_micro_batch_window_ms": 2.0,
    })
    ref = booster.predict(X[:256])   # unloaded bit-identity reference

    reg = ModelRegistry(warmup_rows=64)
    reg.publish("main", serve)
    reg.submit("main", X[0]).result(timeout=30)   # settle the batcher

    capacity = MICRO_BATCH / SERVICE_S            # rows/s at saturation
    seconds = float(os.environ.get("OVERLOAD_SECONDS", 2.5))
    faults.slow_predict(SERVICE_S)
    try:
        (cap_lats, cap_rej, cap_fail, _cap_res, cap_n,
         cap_pending) = _open_loop(reg, X, 0.4 * capacity, seconds, seed=3)
        (ov_lats, ov_rej, ov_fail, ov_res, ov_n,
         ov_pending) = _open_loop(reg, X, 2.0 * capacity, seconds, seed=7)
    finally:
        faults.reset()
    pred_stats = reg.stats()["models"]["main"]
    reg.close()

    def p99(lats):
        return lats[int(len(lats) * 0.99)] if lats else None

    cap_p99, ov_p99 = p99(cap_lats), p99(ov_lats)
    n_rejected = len(ov_rej) + len(ov_fail)
    n_resolved = len(ov_lats) + n_rejected
    rejected_structured = (
        all(retriable for _, _, retriable in ov_rej)
        and all(retriable for _, _, _, retriable in ov_fail))
    max_rej_latency = max(
        [lat for _, lat, _ in ov_rej]
        + [lat for _, _, lat, _ in ov_fail] + [0.0])
    # bit-identity on admitted requests: shedding changes WHETHER a
    # request is answered, never WHAT is answered
    bit_identical = all(
        float(v) == float(ref[idx]) for idx, v in ov_res.items())
    deadline_s = DEADLINE_MS / 1e3
    bound_s = deadline_s + 0.35    # queue-expiry envelope + dispatch slack
    gates = {
        "zero_dropped": ov_pending == 0 and n_resolved == ov_n
        and cap_pending == 0,
        "rejections_structured_retriable": rejected_structured
        and n_rejected > 0,
        "rejections_prompt": max_rej_latency <= deadline_s + 0.5,
        "admitted_p99_bounded": ov_p99 is not None
        and ov_p99 <= bound_s
        and (cap_p99 is None or ov_p99 <= max(20 * cap_p99, bound_s)),
        "some_traffic_admitted": len(ov_lats) >= MICRO_BATCH,
        "bit_identical_admitted": bit_identical and len(ov_res) > 0,
    }
    reasons = {}
    for reason, _, _ in ov_rej:
        reasons[reason] = reasons.get(reason, 0) + 1
    for _, reason, _, _ in ov_fail:
        reasons[reason] = reasons.get(reason, 0) + 1
    return {
        "phase": "overload", "ok": all(gates.values()), "gates": gates,
        "capacity_rows_per_s": capacity,
        "offered_qps": {"at_capacity": 0.4 * capacity,
                        "overload": 2.0 * capacity},
        "seconds_per_run": seconds,
        "at_capacity": {"offered": cap_n, "completed": len(cap_lats),
                        "rejected": len(cap_rej) + len(cap_fail),
                        "p50_ms": round(cap_lats[len(cap_lats) // 2] * 1e3,
                                        2) if cap_lats else None,
                        "p99_ms": round(cap_p99 * 1e3, 2)
                        if cap_p99 else None},
        "overload": {"offered": ov_n, "completed": len(ov_lats),
                     "rejected_at_submit": len(ov_rej),
                     "rejected_in_queue": len(ov_fail),
                     "pending_after_grace": ov_pending,
                     "p50_ms": round(ov_lats[len(ov_lats) // 2] * 1e3, 2)
                     if ov_lats else None,
                     "p99_ms": round(ov_p99 * 1e3, 2) if ov_p99 else None,
                     "p99_multiple_of_capacity":
                     round(ov_p99 / cap_p99, 2)
                     if (ov_p99 and cap_p99) else None,
                     "max_rejection_latency_ms":
                     round(max_rej_latency * 1e3, 2),
                     "rejection_reasons": reasons},
        "deadline_ms": DEADLINE_MS, "max_queue": MAX_QUEUE,
        "admission": pred_stats.get("admission"),
    }


def phase_breaker() -> dict:
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving import ModelRegistry, ServingOverload
    from lightgbm_tpu.testing import faults

    X, booster, _ = _train()
    reg = ModelRegistry(warmup_rows=16, breaker_failures=3,
                        breaker_reset_s=0.4)
    reg.publish("m", lgb.Booster(model_str=booster.model_to_string()))
    reg.predict("m", X[:4])

    faults.fail_predict(3)
    injected = 0
    for _ in range(3):
        try:
            reg.predict("m", X[:4])
        except ServingOverload:
            break
        except Exception:
            injected += 1
    tripped_reason = None
    t_reject0 = time.perf_counter()
    try:
        reg.predict("m", X[:4])
    except ServingOverload as exc:
        tripped_reason = exc.reason
    reject_latency = time.perf_counter() - t_reject0
    faults.reset()

    time.sleep(0.5)               # past the reset window: half-open
    probe_ok = True
    try:
        reg.predict("m", X[:4])   # the single probe; success closes it
        reg.predict("m", X[:4])
    except Exception:
        probe_ok = False
    st = reg.stats()["models"]["m"]["breaker"]
    reg.close()
    gates = {
        "tripped_after_failures": injected == 3
        and tripped_reason == "breaker_open",
        "rejection_without_device_time": reject_latency < 0.05,
        "recovered_via_half_open": probe_ok and st["state"] == "closed"
        and st["recoveries"] >= 1,
    }
    return {"phase": "breaker", "ok": all(gates.values()), "gates": gates,
            "breaker": st, "injected_failures": injected,
            "reject_latency_ms": round(reject_latency * 1e3, 3)}


def phase_single_flight() -> dict:
    from lightgbm_tpu.serving import Predictor
    from lightgbm_tpu.testing import faults

    X, booster, _ = _train()
    predictor = Predictor(booster, raw_score=True)   # cold: no warmup
    storm_s = 0.3
    n_threads = 12
    faults.compile_storm(storm_s)
    results, errs = [], []

    def worker(i):
        try:
            results.append(float(predictor.predict_one(X[i])))
        except Exception as exc:
            errs.append(repr(exc))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(n_threads)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    faults.reset()
    sf = dict(predictor._single_flight.counts)
    gates = {
        "exactly_one_compile": sf["leads"] == 1,
        "followers_waited": sf["waits"] >= n_threads - 1,
        "all_completed": len(results) == n_threads and not errs,
        # one shared trace, not one per request (would be ~3.6s)
        "storm_collapsed": wall < n_threads * storm_s / 2,
    }
    return {"phase": "single_flight", "ok": all(gates.values()),
            "gates": gates, "single_flight": sf, "threads": n_threads,
            "storm_seconds": storm_s, "wall_seconds": round(wall, 3),
            "errors": errs[:3]}


def _cold_child(cache_dir: str) -> None:
    """One 'replica': train deterministically, then warm the serving
    ladder + first request counting compile-cache traffic."""
    import jax.monitoring
    events = []
    jax.monitoring.register_event_listener(
        lambda name, **kw: events.append(name))
    X, booster, _ = _train({"tpu_compile_cache_dir": cache_dir})
    predictor = booster.serving_predictor(raw_score=True)
    events.clear()                 # count serving warmup only
    t0 = time.perf_counter()
    predictor.warmup(max_rows=64)
    first = predictor.predict_one(X[0])
    wall = time.perf_counter() - t0
    print(json.dumps({
        "hits": sum(1 for e in events if "cache_hit" in e),
        "misses": sum(1 for e in events if "cache_miss" in e),
        "warmup_seconds": round(wall, 3), "first_pred": float(first),
    }), flush=True)


def phase_cold_start() -> dict:
    import tempfile
    cache_dir = tempfile.mkdtemp(prefix="lgbm_tpu_overload_cc_")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the package-level default cache would mask the param under test
    env["LIGHTGBM_TPU_COMPILE_CACHE"] = "0"
    runs = []
    for i in range(2):
        res = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--cold-child", cache_dir],
            env=env, capture_output=True, text=True, timeout=600)
        line = next((ln for ln in reversed(res.stdout.splitlines())
                     if ln.startswith("{")), None)
        if res.returncode != 0 or line is None:
            return {"phase": "cold_start", "ok": False,
                    "error": (res.stdout + res.stderr)[-400:]}
        runs.append(json.loads(line))
    first, second = runs
    gates = {
        # replica 1 really compiled (the cache was genuinely cold)
        "first_replica_compiled": first["misses"] > 0,
        # replica 2 = the restarted replica: its whole ladder + first
        # bucketed request load from disk — no fresh trace anywhere
        "warm_replica_zero_misses": second["misses"] == 0
        and second["hits"] > 0,
        "bit_identical": first["first_pred"] == second["first_pred"],
    }
    return {"phase": "cold_start", "ok": all(gates.values()),
            "gates": gates, "cold_replica": first,
            "warm_replica": second}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "OVERLOAD_r01.json"))
    ap.add_argument("--cold-child", default=None)
    args = ap.parse_args()
    if args.cold_child:
        _cold_child(args.cold_child)
        return 0

    t0 = time.time()
    phases = {}
    for fn in (phase_overload, phase_breaker, phase_single_flight):
        rec = fn()
        phases[rec["phase"]] = rec
        print(json.dumps(rec), flush=True)
    rec = phase_cold_start()
    phases[rec["phase"]] = rec
    print(json.dumps(rec), flush=True)

    ok = all(p.get("ok") for p in phases.values())
    summary = {"shape": "overload", "ok": ok,
               "wall_seconds": round(time.time() - t0, 1),
               "phases": phases}
    with open(args.out, "w") as fh:
        json.dump(summary, fh, indent=1)
    print(json.dumps({"shape": "overload", "ok": ok,
                      "out": args.out}), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
