"""Watchdog-wrapped multichip dryrun gate.

The 8-device gate used to die with a bare rc 124 and no artifact saying
where (MULTICHIP_r05.json). This harness runs the same one-step
data-parallel dryrun (`__graft_entry__._dryrun_impl`) in a child process
with telemetry armed, and guarantees a diagnosis artifact either way:

- the child emits per-rank heartbeats (LGBM_TPU_HEARTBEAT_FILE — the
  grower dispatch seam in parallel/learners.py touches it on every
  call) and, on graceful termination, a partial telemetry snapshot
  (phase totals, counters, compile events);
- on timeout the parent SIGTERMs the child (giving its handler a grace
  window to dump the partial snapshot), then SIGKILLs, and writes
  `MULTICHIP_dryrun.json` carrying rc, per-rank last-seen heartbeat
  (iteration/phase/age), the partial snapshot, and the stderr tail —
  the "where did it die" evidence the next rc-124 needs;
- a C-level `faulthandler` handler rides the same SIGTERM (chained in
  FRONT of the Python handler): even a rank wedged inside an XLA
  compile/collective — where the Python-level handler can never run —
  leaves its per-thread Python stacks in the artifact (r05's evidence
  tail was a single JAX platform warning, useless for diagnosis; the
  stack dump says which frame each rank was blocked in).

Usage:
    python scripts/dryrun_multichip.py [n_devices] [--timeout SECONDS]
        [--out MULTICHIP_dryrun.json]
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# child: the dryrun body with telemetry + graceful partial-dump handler
# ---------------------------------------------------------------------------
def child_main(n_devices: int, evidence_dir: str) -> int:
    import faulthandler

    import jax
    # sitecustomize pins the platform via jax.config (ignores
    # JAX_PLATFORMS) — override in-process before any backend init
    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, REPO)
    from lightgbm_tpu import telemetry

    telemetry.enable(True)
    telemetry.install_observer()
    rank = int(os.environ.get("LGBM_TPU_RANK", "0") or 0)
    if not os.environ.get("LGBM_TPU_HEARTBEAT_FILE"):
        telemetry.set_heartbeat_file(
            os.path.join(evidence_dir, f"heartbeat_r{rank}.json"))


    def dump_partial(signum=None, frame=None):
        snap = {
            "rank": rank,
            "time": time.time(),
            "interrupted": signum is not None,
            "registry": telemetry.registry().snapshot(),
            "compile": telemetry.observer().snapshot(),
        }
        path = os.path.join(evidence_dir, f"partial_r{rank}.json")
        try:
            with open(path + ".tmp", "w") as fh:
                json.dump(snap, fh)
            os.replace(path + ".tmp", path)
        except OSError:
            pass
        if signum is not None:
            os._exit(124)

    signal.signal(signal.SIGTERM, dump_partial)
    # per-thread Python stacks on SIGTERM, written by faulthandler's
    # C-LEVEL handler so they land even when this rank is wedged inside
    # an XLA compile/collective where no Python bytecode (and hence no
    # Python signal handler) can run. Registered AFTER signal.signal —
    # faulthandler saves the handler installed at register time and
    # `chain=True` forwards into it, so the partial-telemetry JSON dump
    # still happens whenever Python is runnable.
    stacks_fh = open(os.path.join(evidence_dir, f"stacks_r{rank}.txt"),
                     "w")  # kept open: faulthandler dumps through the fd
    faulthandler.register(signal.SIGTERM, file=stacks_fh,
                          all_threads=True, chain=True)

    telemetry.heartbeat(0, phase="startup", rank=rank)
    import __graft_entry__ as g
    g._dryrun_impl(n_devices)
    telemetry.heartbeat(1, phase="done", rank=rank)
    dump_partial()
    return 0


# ---------------------------------------------------------------------------
# parent: watchdog + evidence collection
# ---------------------------------------------------------------------------
def collect_evidence(evidence_dir: str) -> dict:
    """Per-rank heartbeat + partial-telemetry files -> one dict."""
    now = time.time()
    ranks = {}
    for path in sorted(glob.glob(os.path.join(evidence_dir,
                                              "heartbeat_r*.json"))):
        try:
            with open(path) as fh:
                hb = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        ranks[str(hb.get("rank", "?"))] = {
            "last_iteration": hb.get("iteration"),
            "phase": hb.get("phase"),
            "age_seconds": round(now - float(hb.get("time", now)), 3),
        }
    stacks = {}
    for path in sorted(glob.glob(os.path.join(evidence_dir,
                                              "stacks_r*.txt"))):
        rank_id = os.path.basename(path)[len("stacks_r"):-len(".txt")]
        try:
            with open(path) as fh:
                text = fh.read().strip()
        except OSError:
            continue
        if text:
            # per-rank per-thread Python frames at SIGTERM time — the
            # "which frame was each rank blocked in" evidence; cap the
            # copy so a huge thread dump can't bloat the artifact
            stacks[rank_id] = text.splitlines()[-80:]
    partial = {}
    for path in sorted(glob.glob(os.path.join(evidence_dir,
                                              "partial_r*.json"))):
        try:
            with open(path) as fh:
                snap = json.load(fh)
        except (OSError, json.JSONDecodeError):
            continue
        phases = {p["name"]: round(p["seconds"], 4)
                  for p in snap.get("registry", {}).get("phases", [])}
        compile_info = snap.get("compile", {})
        partial[str(snap.get("rank", "?"))] = {
            "interrupted": snap.get("interrupted"),
            "phase_seconds": phases,
            "compiles": compile_info.get("total_compiles"),
            "compile_seconds": round(compile_info.get("total_seconds", 0.0),
                                     3),
            "grower_calls": next(
                (c["value"] for c in
                 snap.get("registry", {}).get("counters", [])
                 if c["name"] == "parallel/grower_calls"), 0),
        }
    return {"ranks": ranks, "partial_telemetry": partial,
            "sigterm_stacks": stacks}


def run_watchdog(n_devices: int, timeout: float, out_path: str) -> int:
    evidence_dir = tempfile.mkdtemp(prefix="dryrun_evidence_")
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["LGBM_TPU_HEARTBEAT_FILE"] = os.path.join(evidence_dir,
                                                  "heartbeat_r0.json")
    stderr_path = os.path.join(evidence_dir, "child.stderr")
    t0 = time.time()
    with open(stderr_path, "wb") as err_fh:
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--child",
             str(n_devices), evidence_dir],
            env=env, cwd=REPO, stderr=err_fh,
            stdout=subprocess.DEVNULL)
        timed_out = False
        try:
            rc = proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            timed_out = True
            # SIGTERM first: the child's handler dumps its partial
            # telemetry snapshot inside the grace window
            proc.terminate()
            try:
                rc = proc.wait(timeout=20)
            except subprocess.TimeoutExpired:
                proc.kill()
                rc = proc.wait()
            rc = 124

    evidence = collect_evidence(evidence_dir)
    try:
        with open(stderr_path, "rb") as fh:
            tail = fh.read()[-4096:].decode("utf-8", "replace")
        stderr_tail = tail.splitlines()[-12:]
    except OSError:
        stderr_tail = []
    # everything relevant is copied into the output JSON — don't leak a
    # dryrun_evidence_* directory per gate invocation
    import shutil
    shutil.rmtree(evidence_dir, ignore_errors=True)
    result = {
        "metric": "multichip_dryrun",
        "value": 1.0 if rc == 0 else 0.0,
        "unit": "ok",
        "rc": rc,
        "timed_out": timed_out,
        "n_devices": n_devices,
        "timeout_seconds": timeout,
        "wall_seconds": round(time.time() - t0, 2),
        "detail": dict(evidence, stderr_tail=stderr_tail),
    }
    with open(out_path, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps({k: v for k, v in result.items() if k != "detail"}),
          flush=True)
    return rc


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("n_devices", nargs="?", type=int, default=8)
    ap.add_argument("--timeout", type=float,
                    default=float(os.environ.get("DRYRUN_TIMEOUT", 1800)))
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "MULTICHIP_dryrun.json"))
    ap.add_argument("--child", nargs=2, metavar=("N", "EVIDENCE_DIR"),
                    help=argparse.SUPPRESS)
    args, _ = ap.parse_known_args()
    if args.child:
        return child_main(int(args.child[0]), args.child[1])
    return run_watchdog(args.n_devices, args.timeout, args.out)


if __name__ == "__main__":
    sys.exit(main())
