"""Pretty-print a telemetry run log (lightgbm_tpu/telemetry/runlog.py).

Usage:
    python scripts/telemetry_report.py <tpu_telemetry_dir | runlog.jsonl>
        [--json]

Renders every run recorded in the JSONL trail: header (topology,
schedule, versions), a per-iteration table (metrics, phase seconds,
compile activity, pass economics), events, and the summary totals.
`--json` emits one machine-readable digest instead (the shape the
MULTICHIP/BENCH artifacts use).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.telemetry import read_records, validate_record  # noqa: E402


def _fmt_seconds(s: float) -> str:
    return f"{s * 1e3:.1f}ms" if s < 1.0 else f"{s:.2f}s"


def digest(records):
    """Machine-readable roll-up of one run-log file."""
    runs = []
    cur = None
    for rec in records:
        validate_record(rec)
        if rec["type"] == "header":
            cur = {"header": rec, "iterations": [], "events": [],
                   "summary": None}
            runs.append(cur)
            continue
        if cur is None:  # tolerate trails beginning mid-run
            cur = {"header": None, "iterations": [], "events": [],
                   "summary": None}
            runs.append(cur)
        if rec["type"] == "iteration":
            cur["iterations"].append(rec)
        elif rec["type"] == "event":
            cur["events"].append(rec)
        elif rec["type"] == "summary":
            cur["summary"] = rec
    out = []
    for run in runs:
        hdr = run["header"] or {}
        iters = run["iterations"]
        compile_s = sum(r["compile"].get("seconds", 0.0) for r in iters)
        compiles = sum(r["compile"].get("compiles", 0) for r in iters)
        retraces = sum(r["compile"].get("retraces", 0) for r in iters)
        phase_tot = {}
        for r in iters:
            for name, p in r["phases"].items():
                phase_tot[name] = phase_tot.get(name, 0.0) + p["seconds"]
        rows_contracted = sum(r.get("pass", {}).get("rows_contracted", 0.0)
                              for r in iters)
        out.append({
            "run_id": hdr.get("run_id"),
            "rank": hdr.get("rank"),
            "platform": (hdr.get("devices") or {}).get("platform"),
            "num_devices": (hdr.get("devices") or {}).get("num_devices"),
            "boosting": hdr.get("boosting"),
            "start_iteration": hdr.get("start_iteration"),
            "iterations": len(iters),
            "last_iteration": iters[-1]["iteration"] if iters else None,
            "compiles": compiles, "compile_seconds": round(compile_s, 3),
            "retraces": retraces,
            "phase_seconds": {k: round(v, 4)
                              for k, v in sorted(phase_tot.items())},
            "rows_contracted": rows_contracted,
            "events": [{"kind": e["kind"],
                        "iteration": e.get("iteration")}
                       for e in run["events"]],
            "final_metrics": iters[-1]["metrics"] if iters else {},
            "status": (run["summary"] or {}).get("status"),
            "wall_seconds": (run["summary"] or {}).get("wall_seconds"),
        })
    return out


def render(records) -> str:
    lines = []
    for run in digest(records):
        lines.append("=" * 72)
        lines.append(f"run {run['run_id']}  rank={run['rank']}  "
                     f"platform={run['platform']} "
                     f"x{run['num_devices']}  boosting={run['boosting']}")
        lines.append(f"  iterations: {run['iterations']} "
                     f"(start {run['start_iteration']}, "
                     f"last {run['last_iteration']})  "
                     f"status={run['status']}  "
                     f"wall={run['wall_seconds']}s")
        lines.append(f"  compiles: {run['compiles']} "
                     f"({_fmt_seconds(run['compile_seconds'])}, "
                     f"{run['retraces']} retraces)")
        if run["phase_seconds"]:
            lines.append("  phases:")
            for name, secs in sorted(run["phase_seconds"].items(),
                                     key=lambda kv: -kv[1]):
                lines.append(f"    {name:<28} {_fmt_seconds(secs):>10}")
        if run["rows_contracted"]:
            lines.append(f"  rows contracted: {run['rows_contracted']:.0f}")
        for e in run["events"]:
            lines.append(f"  event: {e['kind']} @ iter {e['iteration']}")
        if run["final_metrics"]:
            lines.append("  final metrics: " + "  ".join(
                f"{k}={v:g}" for k, v in run["final_metrics"].items()))
    return "\n".join(lines)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("target", help="tpu_telemetry_dir or a runlog .jsonl")
    ap.add_argument("--json", action="store_true",
                    help="emit a machine-readable digest")
    args = ap.parse_args()

    if os.path.isdir(args.target):
        paths = sorted(glob.glob(os.path.join(args.target,
                                              "runlog_r*.jsonl")))
    else:
        paths = [args.target]
    if not paths:
        print(f"no runlog_r*.jsonl under {args.target}", file=sys.stderr)
        return 2

    ok = True
    for path in paths:
        try:
            records = read_records(path)
            for rec in records:
                validate_record(rec)
        except (OSError, ValueError) as exc:
            print(f"{path}: {exc}", file=sys.stderr)
            ok = False
            continue
        if args.json:
            print(json.dumps({"file": path, "runs": digest(records)}))
        else:
            print(f"--- {path} ({len(records)} records)")
            print(render(records))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
