"""Microbenchmarks for the partitioned-grower primitives on real TPU.

Validates the round-3 redesign before committing to it:
  1. row-gather of the transposed bin matrix  binned_T[:, src]
  2. i32 scatter (permutation inversion)      zeros.at[dest].set(iota)
  3. chunk-walk while_loop einsum vs lax.scan (per-step overhead)
  4. production batched_leaves_histogram cost at the same shapes
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

N = 2 * 1024 * 1024
G = 28
B = 64
CH = 8192
K = 12
S = 2 * K * 5  # 2K*(3 hi + 2 lo)

rng = np.random.default_rng(0)
binned_T = jnp.asarray(rng.integers(0, B, size=(G, N), dtype=np.uint8))
w3 = jnp.asarray(rng.standard_normal((N, 3)).astype(np.float32))
perm = jnp.asarray(rng.permutation(N).astype(np.int32))


def timeit(name, fn, *args, reps=20):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:45s} {dt*1e3:9.3f} ms")
    return dt


@jax.jit
def gather_T(bt, src):
    return bt[:, src]


@jax.jit
def gather_rows(bt, src):
    # row-major gather on the [N, G] layout instead
    return bt.T[src]


@jax.jit
def gather_w3(w, src):
    return w[src]


@jax.jit
def scatter_inv(dest):
    return jnp.zeros(N, jnp.int32).at[dest].set(
        jnp.arange(N, dtype=jnp.int32))


@jax.jit
def two_cumsums(bits):
    a = jnp.cumsum(bits.astype(jnp.int32))
    b = jnp.cumsum((~bits).astype(jnp.int32))
    return a, b


def chunk_step(bt, w, c):
    blk = jax.lax.dynamic_slice(bt, (0, c * CH), (G, CH))        # [G, CH]
    oh = (blk[:, :, None] ==
          jnp.arange(B, dtype=jnp.uint8)[None, None, :])          # [G,CH,B]
    u = jax.lax.dynamic_slice(w, (c * CH, 0), (CH, 3))
    u = jnp.tile(u, (1, S // 3 + 1))[:, :S].astype(jnp.bfloat16)
    return jnp.einsum("gcb,cs->gbs", oh.astype(jnp.bfloat16), u,
                      preferred_element_type=jnp.float32)


@jax.jit
def walk_while(bt, w, n_chunks):
    def cond(carry):
        c, _ = carry
        return c < n_chunks

    def body(carry):
        c, acc = carry
        return c + 1, acc + chunk_step(bt, w, c)

    _, acc = jax.lax.while_loop(
        cond, body, (jnp.int32(0), jnp.zeros((G, B, S), jnp.float32)))
    return acc


@jax.jit
def walk_scan(bt, w):
    def body(acc, c):
        return acc + chunk_step(bt, w, c), None

    acc, _ = jax.lax.scan(body, jnp.zeros((G, B, S), jnp.float32),
                          jnp.arange(N // CH, dtype=jnp.int32))
    return acc


@jax.jit
def update_slice_bits(bits, c, val):
    return jax.lax.dynamic_update_slice(bits, val, (c * CH,))


def main():
    print(f"devices: {jax.devices()}")
    print(f"N={N} G={G} B={B} CH={CH} S={S}")
    dt_g = timeit("gather binned_T[:, src]  (56MB u8)", gather_T, binned_T, perm)
    print(f"    -> {2 * N * G / dt_g / 1e9:.1f} GB/s effective")
    dt_gr = timeit("gather rows binned[src]  (row-major)", gather_rows,
                   binned_T, perm)
    print(f"    -> {2 * N * G / dt_gr / 1e9:.1f} GB/s effective")
    timeit("gather w3[src]           (24MB f32)", gather_w3, w3, perm)
    timeit("scatter inv (i32[N])", scatter_inv, perm)
    bits = perm % 2 == 0
    timeit("2x cumsum over N", two_cumsums, bits)
    val = jnp.ones(CH, bool)
    timeit("dynamic_update_slice [N] bool", update_slice_bits, bits,
           jnp.int32(5), val)

    full = N // CH
    dt_full = timeit(f"while-walk {full} chunks (full N)", walk_while,
                     binned_T, w3, jnp.int32(full), reps=5)
    print(f"    -> {N * G * B * S * 2 / dt_full / 1e12:.1f} TFLOP/s")
    dt_scan = timeit(f"scan-walk  {full} chunks (full N)", walk_scan,
                     binned_T, w3, reps=5)
    print(f"    -> {N * G * B * S * 2 / dt_scan / 1e12:.1f} TFLOP/s")
    for frac in (2, 8, 32):
        nc = full // frac
        dt = timeit(f"while-walk {nc} chunks (N/{frac})", walk_while,
                    binned_T, w3, jnp.int32(nc), reps=10)
        print(f"    -> per-chunk {dt/nc*1e6:.1f} us")

    # current kernel for comparison
    from lightgbm_tpu.ops import histogram as hist_ops
    leaf_id = jnp.zeros(N, jnp.int32)
    ids = jnp.arange(24, dtype=jnp.int32)
    binned = binned_T.T.copy()

    @jax.jit
    def current(b, w, lid, lv):
        return hist_ops.batched_leaves_histogram(
            b, w, lid, lv, B, 16384, bf16=True)

    dt_cur = timeit("production batched_leaves_histogram C=24", current,
                    binned, w3, leaf_id, ids, reps=5)
    print(f"    -> {N * G * B * 120 * 2 / dt_cur / 1e12:.1f} TFLOP/s")


if __name__ == "__main__":
    main()
