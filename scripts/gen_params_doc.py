"""Generate docs/Parameters.md from the Config dataclasses + alias table
(reference: docs/Parameters.md, the canonical flag reference — ours is
generated so it cannot drift from the whitelist).

Usage: python scripts/gen_params_doc.py
"""
from __future__ import annotations

import dataclasses
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)



# one-line description per parameter (reference: docs/Parameters.md —
# rewritten, not copied; TPU-specific flags documented from our code)
DESCRIPTIONS = {
    # core
    "task": "what to do: train, predict, or convert_model",
    "seed": "master seed fanned out to data/feature/bagging/drop seeds",
    "boosting_type": "gbdt, dart, goss, or rf",
    "objective": "loss to optimize: regression, regression_l1, huber, "
                 "fair, poisson, binary, multiclass, multiclassova, "
                 "lambdarank, xentropy, xentlambda, none",
    "tree_learner": "serial, or distributed: feature, data, voting "
                    "(mapped onto a jax device mesh)",
    # io
    "max_bin": "max number of histogram bins per feature",
    "min_data_in_bin": "minimum rows per value bin during bin finding",
    "bin_construct_sample_cnt": "rows sampled to find bin boundaries",
    "data_random_seed": "seed for the bin-finding row sample",
    "output_model": "path the trained model text is written to",
    "output_result": "path predictions are written to (task=predict)",
    "convert_model": "output path for task=convert_model (if-else C++)",
    "input_model": "model text to load (predict / continued training)",
    "verbosity": "<0 fatal only, 0 warnings, 1 info, >1 debug",
    "num_iteration_predict": "use only the first N iterations to predict",
    "is_pre_partition": "multi-machine: data files are pre-partitioned "
                        "per rank (no row sharding by the loader)",
    "is_enable_sparse": "kept for API compat (storage is dense+EFB)",
    "enable_load_from_binary_file": "reuse <data>.bin when present "
                                    "(checksummed, memory-mapped; "
                                    "skips parsing AND binning; a "
                                    "cache whose fingerprint does not "
                                    "match the data file + binning "
                                    "params is refused)",
    "use_two_round_loading": "stream the file twice instead of holding "
                             "raw values in memory (subsumed by "
                             "tpu_ingest, kept for the multi-process "
                             "loader)",
    "is_save_binary_file": "write <data>.bin after construction (v2 "
                           "ingest cache: versioned + checksummed + "
                           "source-fingerprinted)",
    "enable_bundle": "exclusive feature bundling (EFB)",
    "max_conflict_rate": "max fraction of conflicting rows per bundle",
    "has_header": "data files carry a header row",
    "label_column": "label selector: index or name:colname",
    "weight_column": "per-row weight column selector",
    "group_column": "ranking query/group column selector",
    "ignore_column": "columns dropped before binning",
    "categorical_column": "columns treated as categorical (indices or "
                          "name:c1,c2)",
    "data_filename": "training data path (CLI)",
    "valid_data_filenames": "validation data paths (CLI)",
    "snapshot_freq": "save the model every N iterations",
    "tpu_checkpoint_dir": "directory for crash-consistent full-state "
                          "checkpoints (model + RNG + DART ledger + "
                          "scores + early-stop history); training "
                          "resumes BIT-IDENTICALLY from the newest "
                          "valid snapshot on restart (empty = off)",
    "tpu_checkpoint_interval": "write a checkpoint every N iterations",
    "tpu_checkpoint_keep": "checkpoints retained per rank (older ones "
                           "are rotated out; corrupt/truncated "
                           "snapshots fall back to the previous good "
                           "one on resume)",
    "tpu_elastic_resume": "accept checkpoints taken at a DIFFERENT "
                          "world size: scores re-shard onto the new "
                          "device/process layout; across DEVICE-count "
                          "changes the resumed model is byte-identical "
                          "to an uninterrupted run (process-count "
                          "changes restore exact state but f32 "
                          "summation order differs). false = refuse "
                          "world-size changes",
    "tpu_io_retries": "retries per critical durable write (checkpoint/"
                      "artifact/cache) on transient IO errors; "
                      "exhaustion raises a structured DurableWriteError "
                      "naming path, errno and attempts",
    "tpu_io_backoff_s": "initial retry backoff for durable writes, "
                        "doubling per attempt",
    "tpu_io_deadline_s": "wall-clock budget for one durable write "
                         "including retries (0 = unbounded); a slow-IO "
                         "stall fails the write instead of wedging "
                         "training",
    "tpu_telemetry_dir": "observability directory: a structured JSONL "
                         "run log (header + one record per iteration + "
                         "events + summary; see README Observability) "
                         "plus end-of-run Prometheus text-exposition "
                         "metric dumps, one file per rank (empty = off)",
    "tpu_telemetry": "collect span timers / counters / compile events "
                     "without writing files (exit dump only — the "
                     "LGBM_TPU_TIMETAG behavior, config-exposed)",
    "tpu_telemetry_prometheus": "write metrics_r<rank>.prom (+ the "
                                "cross-rank metrics_aggregate.prom on "
                                "rank 0) into tpu_telemetry_dir at end "
                                "of run",
    "tpu_ingest": "streaming ingest (lightgbm_tpu/ingest): build "
                  "datasets by a chunked two-pass pipeline (pass 1 "
                  "sketches bin bounds from a streamed row sample, "
                  "pass 2 re-streams and bins against the frozen "
                  "bounds) — bit-identical to in-memory construction "
                  "at any chunk size; false restores the "
                  "load-everything path",
    "tpu_ingest_chunk_rows": "rows per streamed ingest chunk",
    "tpu_ingest_device_shards": "land the binned matrix directly as "
                                "per-device row shards under a "
                                "single-process data/voting-parallel "
                                "mesh (host blocks freed as they ship, "
                                "so the dataset can exceed one "
                                "device's HBM)",
    "tpu_sweep_size": "declared width of a many-model sweep "
                      "(engine.train_sweep): 0 accepts any length of "
                      "param-dict list, > 0 refuses a list of any other "
                      "length (a supervisor can pin the fleet size it "
                      "provisioned). Sweep membership never changes a "
                      "model's trees: model k of a vmapped sweep is "
                      "byte-identical to training its config alone",
    "tpu_sweep_name_prefix": "serving.ModelRegistry name prefix for "
                             "sweep models published without explicit "
                             "names: model k lands as '<prefix>/<k>' "
                             "through one shared publish_many "
                             "budget/eviction pass",
    "is_predict_raw_score": "predict raw scores instead of transformed",
    "is_predict_leaf_index": "predict leaf indices per tree",
    "is_predict_contrib": "predict TreeSHAP feature contributions",
    "pred_early_stop": "stop accumulating trees once the margin is safe",
    "pred_early_stop_freq": "check the margin every N iterations",
    "pred_early_stop_margin": "margin threshold for prediction early stop",
    "tpu_predict_cache": "device-resident compiled forest cache: trees "
                         "are stacked/padded/transferred once per model "
                         "version instead of per predict call (false = "
                         "per-call restack, for A/B timing)",
    "tpu_predict_bucket_min": "smallest row bucket of the power-of-two "
                              "predict dispatch ladder; batches pad up "
                              "the ladder so arbitrary sizes reuse a "
                              "handful of compiled programs (<= 0 "
                              "disables bucketing)",
    "tpu_predict_chunk": "rows per predict dispatch chunk (0 = auto: "
                         "512k matmul / 128k walk)",
    "tpu_predict_pipeline": "double-buffered predict chunk loop: "
                            "dispatch chunk k+1 before fetching chunk "
                            "k so transfer and compute overlap",
    "tpu_predict_quantize": "quantized serving forest layout: none = "
                            "bit-exact f32 stacks; f16 = f16 leaf "
                            "values + bf16 path/category tables "
                            "(decisions stay bit-exact); int8 = "
                            "additionally codes split thresholds "
                            "fixed-point against the per-feature bin "
                            "bounds (8-bit code space) with a single "
                            "default-precision selection einsum. "
                            "Value prediction only; pred_leaf and "
                            "prediction early stop stay exact f32",
    "tpu_predict_quantize_tol": "accuracy gate for quantized layouts: "
                                "max |raw-score delta| vs the f32 "
                                "stack on a calibration batch, "
                                "relative to the batch's score scale; "
                                "a lossier layout is refused with an "
                                "error instead of served",
    "tpu_serving_budget_mb": "serving.ModelRegistry device-memory "
                             "budget for compiled stacks across all "
                             "resident models, in MiB (0 = unlimited); "
                             "least-recently-used models' stacks are "
                             "evicted past it (host trees stay, the "
                             "next request restacks)",
    "tpu_serving_max_queue": "max queued Predictor.submit() requests; "
                             "past it new requests are refused with a "
                             "structured retriable ServingOverload "
                             "(reason queue_full) instead of queueing "
                             "late (0 = unbounded)",
    "tpu_serving_max_inflight": "max concurrent synchronous predict() "
                                "calls per Predictor; excess requests "
                                "are refused with reason inflight_full "
                                "(0 = unbounded)",
    "tpu_serving_deadline_ms": "default per-request deadline: requests "
                               "whose EWMA-estimated queue wait already "
                               "exceeds it are shed at admission, and "
                               "requests that expire while queued fail "
                               "with DeadlineExceeded before any device "
                               "work; per-call deadline_ms= overrides "
                               "(0 = no deadline)",
    "tpu_serving_model_qps": "per-model token-bucket rate in "
                             "serving.ModelRegistry (tokens/s, burst = "
                             "one second's worth; 0 = unlimited): a hot "
                             "model sheds with reason rate_limited "
                             "instead of starving other residents",
    "tpu_serving_breaker_failures": "consecutive predict failures "
                                    "before a model's circuit breaker "
                                    "opens (overload rejections never "
                                    "count; 0 disables the breaker)",
    "tpu_serving_breaker_reset_s": "seconds an open breaker waits "
                                   "before half-opening for a single "
                                   "probe; failed probes re-open with "
                                   "exponential backoff",
    "tpu_compile_cache_dir": "persistent XLA compilation cache "
                             "directory: bucket-ladder and grower "
                             "programs persist to disk so restarted "
                             "trainers / cold serving replicas warm "
                             "from a file read instead of re-tracing "
                             "(empty = package default)",
    "tpu_predict_warmup_rows": "Predictor.warmup() compiles bucket "
                               "programs up to this many rows",
    "tpu_predict_micro_batch": "max concurrent single-row requests "
                               "Predictor.submit() coalesces into one "
                               "device dispatch (0 = no micro-batching)",
    "tpu_predict_micro_batch_window_ms": "how long submit() waits for "
                                         "co-arriving rows before "
                                         "dispatching the micro-batch",
    "tpu_export_dir": "directory to write a self-contained exported-"
                      "forest artifact (StableHLO via jax.export) after "
                      "training; serving replicas load it without the "
                      "training stack (empty = no export)",
    "tpu_export_layouts": "comma-separated quantized layouts packed "
                          "alongside f32 in the artifact (e.g. "
                          "\"f16,int8\"; \"none\" = f32 only)",
    "tpu_export_buckets": "number of power-of-two row buckets exported "
                          "per layout, starting at "
                          "tpu_predict_bucket_min",
    "use_missing": "handle NaN/missing specially (false = plain values)",
    "zero_as_missing": "treat zeros as missing (sparse semantics)",
    "sparse_threshold": "column sparsity above which EFB treats the "
                        "column as sparse when bundling",
    "init_score_file": "initial scores sidecar for the training data",
    "valid_init_score_file": "initial-score sidecars for valid sets",
    # tree
    "min_data_in_leaf": "minimum rows per leaf",
    "min_sum_hessian_in_leaf": "minimum hessian sum per leaf",
    "lambda_l1": "L1 regularization on leaf values",
    "lambda_l2": "L2 regularization on leaf values",
    "min_gain_to_split": "minimum gain to accept a split",
    "num_leaves": "max leaves per tree",
    "feature_fraction": "features sampled per tree",
    "feature_fraction_seed": "seed for the per-tree feature sample",
    "max_depth": "max tree depth (<=0 = unlimited)",
    "top_k": "features each shard submits in voting-parallel elections",
    "max_cat_threshold": "max categories grouped on one side of a "
                         "categorical split",
    "histogram_pool_size": "kept for API compat (the TPU grower keeps "
                           "its histogram cache on device)",
    "linear_tree": "piecewise-linear leaves: fit a ridge regression "
                   "per leaf over the features split on along the "
                   "leaf's root path, replacing the constant output "
                   "with intercept + coeff . x (requires raw feature "
                   "values; keep_raw is armed automatically)",
    "linear_lambda": "linear_tree: L2 on the fitted slopes (the "
                     "intercept is never penalized)",
    "tpu_linear_max_features": "linear_tree: per-leaf design width cap "
                               "— the first N distinct root-path split "
                               "features, nearest the leaf first (the "
                               "static [leaves, N] shape the linear "
                               "kernels compile against)",
    "gpu_platform_id": "kept for API compat (no OpenCL here)",
    "gpu_device_id": "kept for API compat",
    "gpu_use_dp": "kept for API compat",
    "tpu_hist_chunk": "rows per histogram contraction step",
    "tpu_double_precision": "f64 accumulation paths where supported",
    "tpu_batch_k": "nodes speculatively expanded per histogram pass "
                   "(auto-selected by shape when unset)",
    "tpu_hist_bf16": "bf16 hi+lo MXU histogram contraction",
    "tpu_hist_subtract": "sibling-subtraction histogram cache (build "
                         "the smaller child, derive the larger); "
                         "auto-disabled when the cache exceeds budget",
    "tpu_hist_compact": "gather-compacted small-node histogram passes: "
                        "when the nodes expanded in one pass jointly "
                        "hold few rows, contract only their gathered "
                        "rows instead of the full dataset (ignored by "
                        "the feature-parallel learner)",
    "tpu_compact_threshold": "row fraction below which a pass takes the "
                             "compacted path (also sizes the gather "
                             "buffer; >= 1.0 forces compaction, <= 0 "
                             "disables it)",
    "tpu_hist_reduce": "data-parallel histogram merge collective: "
                       "scatter (default) ReduceScatters the histogram "
                       "over the stored-group axis so each device owns "
                       "groups/num_devices of the result and finds "
                       "splits only on its owned features; allreduce "
                       "restores the full-psum schedule (every device "
                       "scores every feature). Trees are bit-identical "
                       "either way; voting keeps its elected-slice "
                       "exchange and ignores this",
    "tpu_hist_pallas": "retired; accepted for compatibility, warns and "
                       "uses the XLA path (see profiles/README.md "
                       "postmortem)",
    "tpu_hist_quantize": "quantized-gradient training: none (default) "
                         "= bit-exact f32 histogram path; int16/int8 = "
                         "per-iteration gradients/hessians scaled and "
                         "stochastically rounded to narrow integer "
                         "codes (deterministic per-(seed, iteration, "
                         "class) keys), histograms accumulated in the "
                         "exact int32 domain — scatter/allreduce/"
                         "sibling-subtraction merges stay bitwise "
                         "schedule-invariant — and dequantized once at "
                         "the split-scoring seam. int8 also widens the "
                         "leaf batch per pass (3 channels vs 5 in the "
                         "same 128-lane tile). Refused under "
                         "multi-process training",
    "tpu_hist_quantize_tol": "train-time accuracy gate for quantized "
                             "histograms: at setup one calibration "
                             "tree is grown with the quantized "
                             "pipeline and one with f32; the config "
                             "is refused with an error when the max "
                             "per-row leaf-value delta (relative to "
                             "the f32 tree's leaf-value scale) "
                             "exceeds this tolerance",
    # boosting
    "num_iterations": "boosting rounds",
    "learning_rate": "shrinkage applied to each tree",
    "bagging_fraction": "rows sampled per bagging refresh",
    "bagging_freq": "refresh the bag every N iterations (0 = off)",
    "bagging_seed": "seed for bagging",
    "early_stopping_round": "stop when no metric improves for N rounds",
    "drop_rate": "DART: fraction of trees dropped per iteration",
    "max_drop": "DART: max trees dropped per iteration",
    "skip_drop": "DART: probability of skipping the drop",
    "uniform_drop": "DART: drop trees uniformly instead of by weight",
    "xgboost_dart_mode": "DART: xgboost-style normalization",
    "drop_seed": "DART: seed for the drop choice",
    "top_rate": "GOSS: keep fraction of largest gradients",
    "other_rate": "GOSS: sample fraction of the rest",
    "tpu_guard_nonfinite": "raise a descriptive error (objective/metric "
                           "+ iteration) when gradients, hessians or "
                           "metric values go NaN/Inf instead of "
                           "silently growing garbage trees",
    # objective
    "is_unbalance": "binary: reweight classes to balance label mass",
    "sigmoid": "sigmoid scale for binary/xentropy objectives",
    "huber_delta": "huber loss delta",
    "fair_c": "fair loss c",
    "poisson_max_delta_step": "poisson: max delta step safeguard",
    "gaussian_eta": "regression hessian eta",
    "scale_pos_weight": "binary: weight multiplier on positives",
    "boost_from_average": "start scores from the label average",
    "label_gain": "lambdarank: gain per integer relevance label",
    "max_position": "lambdarank: NDCG truncation position",
    "num_class": "number of classes (multiclass objectives)",
    # metric
    "metric_types": "metrics to evaluate (comma list)",
    "metric_freq": "evaluate every N iterations",
    "output_freq": "CLI metric print frequency",
    "is_provide_training_metric": "also evaluate on the training data",
    "ndcg_eval_at": "NDCG/MAP truncation positions",
    # network
    "num_machines": "machine count for distributed training",
    "local_listen_port": "kept for API compat (jax.distributed wires "
                         "processes via the coordinator address)",
    "time_out": "kept for API compat",
    "machine_list_filename": "host list file (rank order)",
    "machines": "inline comma-separated host list",
    "tpu_collective_timeout_s": "deadline for every host-level "
                                "collective dispatch: on expiry the "
                                "rank dumps per-thread stacks + a "
                                "rank_failure event and exits rc 113 "
                                "instead of hanging on a dead peer "
                                "(0 = off; must exceed worst-case "
                                "compile time — the first dispatch of "
                                "a new shape compiles under the guard)",
    "tpu_heartbeat_dir": "per-rank liveness directory: "
                         "heartbeat_r<rank>.json on every dispatch/"
                         "iteration, rank_failure_r<rank>.json on "
                         "watchdog expiry — what an external "
                         "supervisor reads to tell which rank died "
                         "and why",
    "tpu_heartbeat_lease_s": "heartbeat lease: a supervisor declares a "
                             "rank dead when its heartbeat is older "
                             "than this (stamped into the heartbeat "
                             "file)",
}

def main():
    from lightgbm_tpu import config as C

    aliases_by_target = {}
    for alias, target in C.ALIAS_TABLE.items():
        aliases_by_target.setdefault(target, []).append(alias)

    sections = [
        ("Core", C.Config, ("task", "objective", "boosting_type",
                            "tree_learner", "seed")),
        ("IO / Dataset", C.IOConfig, None),
        ("Tree", C.TreeConfig, None),
        ("Boosting", C.BoostingConfig, None),
        ("Objective", C.ObjectiveConfig, None),
        ("Metric", C.MetricConfig, None),
        ("Network", C.NetworkConfig, None),
    ]

    out = ["# Parameters",
           "",
           "Generated by `python scripts/gen_params_doc.py` from the "
           "config whitelist (`lightgbm_tpu/config.py`) — unknown keys "
           "are fatal, exactly like the reference "
           "(`config.h:351-483`). Aliases follow the reference alias "
           "table.",
           ""]
    for title, cls, only in sections:
        out.append(f"## {title}")
        out.append("")
        out.append("| parameter | default | aliases | description |")
        out.append("|---|---|---|---|")
        if dataclasses.is_dataclass(cls):
            fields = dataclasses.fields(cls)
        else:
            fields = []
        for f in fields:
            if only is not None and f.name not in only:
                continue
            if f.name in ("io", "tree", "boosting", "objective_config",
                          "metric", "network", "raw_params"):
                continue
            if f.default is not dataclasses.MISSING:
                default = f.default
            elif f.default_factory is not dataclasses.MISSING:  # type: ignore
                default = f.default_factory()  # type: ignore
            else:
                default = ""
            al = ", ".join(sorted(aliases_by_target.get(f.name, [])))
            d = DESCRIPTIONS.get(f.name, "")
            out.append(f"| `{f.name}` | `{default}` | {al} | {d} |")
        out.append("")
    os.makedirs(os.path.join(REPO, "docs"), exist_ok=True)
    path = os.path.join(REPO, "docs", "Parameters.md")
    with open(path, "w") as fh:
        fh.write("\n".join(out) + "\n")
    print(path, f"({len(out)} lines)")


if __name__ == "__main__":
    main()
