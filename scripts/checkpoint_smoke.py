"""End-to-end kill-and-resume smoke test over the real CLI.

Unlike tests/test_checkpoint.py (which simulates preemption with an
in-process exception), this drives `python -m lightgbm_tpu` in a
subprocess and delivers an actual SIGKILL mid-training — no atexit, no
finally-blocks, exactly what a preempted pod looks like — then reruns
the identical command and asserts the resumed run's model is
byte-identical to an uninterrupted one.

Usage: python scripts/checkpoint_smoke.py
Exits 0 on success, 1 on any mismatch.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ROUNDS = 60
KILL_AFTER_SNAPSHOTS = 3   # wait until a few checkpoints exist, then kill


def cli_cmd(train_path: str, model_path: str, ckpt_dir: str = ""):
    cmd = [sys.executable, "-m", "lightgbm_tpu", "task=train",
           f"data={train_path}", "objective=binary", "boosting_type=dart",
           "bagging_fraction=0.7", "bagging_freq=1", "num_leaves=15",
           f"num_trees={ROUNDS}", "seed=7", "verbose=-1",
           f"output_model={model_path}"]
    if ckpt_dir:
        cmd += [f"tpu_checkpoint_dir={ckpt_dir}", "tpu_checkpoint_interval=1"]
    return cmd


def main() -> int:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    with tempfile.TemporaryDirectory() as tmp:
        rng = np.random.RandomState(0)
        X = rng.randn(1500, 10)
        y = (X[:, 0] + X[:, 1] * X[:, 2] + rng.randn(1500) * 0.3 > 0)
        train_path = os.path.join(tmp, "train.tsv")
        np.savetxt(train_path, np.column_stack([y.astype(int), X]),
                   delimiter="\t", fmt="%.6f")

        base_model = os.path.join(tmp, "model_base.txt")
        print("[smoke] uninterrupted run ...")
        subprocess.run(cli_cmd(train_path, base_model),
                       env=env, cwd=REPO, check=True)

        ckpt_dir = os.path.join(tmp, "ckpts")
        model = os.path.join(tmp, "model.txt")
        print("[smoke] preemptible run (will be SIGKILLed) ...")
        proc = subprocess.Popen(cli_cmd(train_path, model, ckpt_dir),
                                env=env, cwd=REPO)
        deadline = time.time() + 600
        killed = False
        while time.time() < deadline:
            if proc.poll() is not None:
                break  # finished before we could kill it — still a valid run
            snaps = [f for f in os.listdir(ckpt_dir)
                     if f.startswith("ckpt_")] if os.path.isdir(ckpt_dir) \
                else []
            if len(snaps) >= KILL_AFTER_SNAPSHOTS:
                os.kill(proc.pid, signal.SIGKILL)
                proc.wait()
                killed = True
                break
            time.sleep(0.05)
        print(f"[smoke] killed mid-run: {killed} "
              f"(snapshots: {sorted(os.listdir(ckpt_dir))})")

        print("[smoke] resume run (same command) ...")
        subprocess.run(cli_cmd(train_path, model, ckpt_dir),
                       env=env, cwd=REPO, check=True)

        with open(base_model, "rb") as fh:
            base = fh.read()
        with open(model, "rb") as fh:
            resumed = fh.read()
        if base != resumed:
            print("[smoke] FAIL: resumed model differs from uninterrupted "
                  "run")
            return 1
        print(f"[smoke] OK: byte-identical final model "
              f"({len(base)} bytes, {ROUNDS} rounds, killed={killed})")
        return 0


if __name__ == "__main__":
    sys.exit(main())
