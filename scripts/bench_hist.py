"""Microbenchmark of histogram-kernel formulations on the live accelerator.

Explores the design space for the hottest op (SURVEY.md §7: segment
histograms) before committing to one:
  v0  current: per-leaf one-hot einsum, f32 HIGHEST      [round-1 shipped]
  v1  per-leaf one-hot einsum, default precision
  v2  per-leaf one-hot bf16 x (hi+lo) split weights
  v3  per-leaf channel-separated VPU reduce
  v4  K-leaf batched one-hot einsum (cfb,cls->lfbs) f32
  v5  K-leaf batched bf16 x (hi+lo)
  v6  segment-sum scatter over leaf*B+bin

Prints ms/pass and effective rows/s for each; run on TPU:
    python scripts/bench_hist.py
"""
from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

N = 1 << 19          # 524288 rows
F = 28
B = 64
K = 32               # batched leaves
CHUNK = 1 << 15
L = 255

rng = np.random.RandomState(0)
binned_np = rng.randint(0, B, size=(N, F)).astype(np.uint8)
w_np = rng.randn(N, 3).astype(np.float32)
w_np[:, 2] = 1.0
leaf_np = rng.randint(0, L, size=N).astype(np.int32)
batch_leaves_np = np.arange(K, dtype=np.int32)


def timeit(fn, *args, iters=10):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


def chunked(hist_chunk_fn, binned, w, init):
    n_chunks = binned.shape[0] // CHUNK
    bc = binned.reshape(n_chunks, CHUNK, F)
    wc = w.reshape(n_chunks, CHUNK, -1)

    def body(acc, xs):
        b, ww = xs
        return acc + hist_chunk_fn(b, ww), None

    hist, _ = jax.lax.scan(body, init, (bc, wc))
    return hist


@jax.jit
def v0_highest(binned, w):
    def chunk_fn(b, ww):
        oh = (b[:, :, None] == jnp.arange(B, dtype=b.dtype)[None, None, :])
        return jnp.einsum("cfb,cs->fbs", oh.astype(jnp.float32), ww,
                          preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)
    return chunked(chunk_fn, binned, w, jnp.zeros((F, B, 3), jnp.float32))


@jax.jit
def v1_default(binned, w):
    def chunk_fn(b, ww):
        oh = (b[:, :, None] == jnp.arange(B, dtype=b.dtype)[None, None, :])
        return jnp.einsum("cfb,cs->fbs", oh.astype(jnp.float32), ww,
                          preferred_element_type=jnp.float32)
    return chunked(chunk_fn, binned, w, jnp.zeros((F, B, 3), jnp.float32))


def _hi_lo(w):
    hi = w.astype(jnp.bfloat16)
    lo = (w - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    return hi, lo


@jax.jit
def v2_bf16(binned, w):
    def chunk_fn(b, ww):
        oh = (b[:, :, None] == jnp.arange(B, dtype=b.dtype)[None, None, :]
              ).astype(jnp.bfloat16)
        hi, lo = _hi_lo(ww)
        h = jnp.einsum("cfb,cs->fbs", oh, hi,
                       preferred_element_type=jnp.float32)
        h += jnp.einsum("cfb,cs->fbs", oh, lo,
                        preferred_element_type=jnp.float32)
        return h
    return chunked(chunk_fn, binned, w, jnp.zeros((F, B, 3), jnp.float32))


@jax.jit
def v3_vpu(binned, w):
    def chunk_fn(b, ww):
        oh = (b[:, :, None] == jnp.arange(B, dtype=b.dtype)[None, None, :])
        ohf = oh.astype(jnp.float32)
        outs = [(ohf * ww[:, None, None, s]).sum(0) for s in range(3)]
        return jnp.stack(outs, axis=-1)
    return chunked(chunk_fn, binned, w, jnp.zeros((F, B, 3), jnp.float32))


@jax.jit
def v4_batched_f32(binned, w, leaf_id, batch_leaves):
    wl = jnp.concatenate([w, leaf_id[:, None].astype(jnp.float32)], axis=1)

    def chunk_fn(b, wwl):
        ww, lid = wwl[:, :3], wwl[:, 3].astype(jnp.int32)
        lhot = (lid[:, None] == batch_leaves[None, :]).astype(jnp.float32)
        u = (lhot[:, :, None] * ww[:, None, :]).reshape(-1, K * 3)
        oh = (b[:, :, None] == jnp.arange(B, dtype=b.dtype)[None, None, :]
              ).astype(jnp.float32)
        h = jnp.einsum("cfb,cx->fbx", oh, u, preferred_element_type=jnp.float32)
        return h
    out = chunked(chunk_fn, binned, wl,
                  jnp.zeros((F, B, K * 3), jnp.float32))
    return out.reshape(F, B, K, 3).transpose(2, 0, 1, 3)


@jax.jit
def v5_batched_bf16(binned, w, leaf_id, batch_leaves):
    wl = jnp.concatenate([w, leaf_id[:, None].astype(jnp.float32)], axis=1)

    def chunk_fn(b, wwl):
        ww, lid = wwl[:, :3], wwl[:, 3].astype(jnp.int32)
        lhot = (lid[:, None] == batch_leaves[None, :]).astype(jnp.float32)
        u = (lhot[:, :, None] * ww[:, None, :]).reshape(-1, K * 3)
        hi, lo = _hi_lo(u)
        oh = (b[:, :, None] == jnp.arange(B, dtype=b.dtype)[None, None, :]
              ).astype(jnp.bfloat16)
        h = jnp.einsum("cfb,cx->fbx", oh, hi, preferred_element_type=jnp.float32)
        h += jnp.einsum("cfb,cx->fbx", oh, lo, preferred_element_type=jnp.float32)
        return h
    out = chunked(chunk_fn, binned, wl,
                  jnp.zeros((F, B, K * 3), jnp.float32))
    return out.reshape(F, B, K, 3).transpose(2, 0, 1, 3)


@jax.jit
def v6_segment(binned, w, leaf_id):
    # scatter-add over (leaf, bin) per feature: the "true" segment-sum
    idx = leaf_id[:, None].astype(jnp.int32) * B + binned.astype(jnp.int32)

    def per_feature(f_idx):
        return jax.ops.segment_sum(w, idx[:, f_idx], num_segments=L * B)
    out = jax.vmap(per_feature)(jnp.arange(F))
    return out.reshape(F, L, B, 3)


def main():
    print("devices:", jax.devices())
    binned = jnp.asarray(binned_np)
    binned_i32 = jnp.asarray(binned_np.astype(np.int32))
    w = jnp.asarray(w_np)
    leaf = jnp.asarray(leaf_np)
    bl = jnp.asarray(batch_leaves_np)

    rows = N / 1e6
    for name, fn, args in [
        ("v0_highest_u8", v0_highest, (binned, w)),
        ("v0_highest_i32", v0_highest, (binned_i32, w)),
        ("v1_default_u8", v1_default, (binned, w)),
        ("v2_bf16_u8", v2_bf16, (binned, w)),
        ("v3_vpu_u8", v3_vpu, (binned, w)),
        ("v4_batched_f32_u8(K=32)", v4_batched_f32, (binned, w, leaf, bl)),
        ("v5_batched_bf16_u8(K=32)", v5_batched_bf16, (binned, w, leaf, bl)),
        ("v6_segment_u8", v6_segment, (binned, w, leaf)),
    ]:
        try:
            ms = timeit(fn, *args)
            print(f"{name:28s} {ms:9.3f} ms/pass   {rows/ (ms/1e3):8.1f} Mrow/s")
        except Exception as e:  # noqa: BLE001
            print(f"{name:28s} FAILED: {type(e).__name__}: {e}")


if __name__ == "__main__":
    main()
