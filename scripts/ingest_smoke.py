"""Ingest smoke: stream a file whose RAW matrix exceeds an rlimit-enforced
memory budget, train, and match the unconstrained in-memory run.

Three child processes (fresh address spaces, so rlimits and peak-memory
accounting don't bleed):

1. `streamed`  — dataset CONSTRUCTION under a soft RLIMIT_AS of
   (pre-construction baseline + budget) with budget < the raw float64
   matrix size: the old load-everything path CANNOT fit, the chunked
   two-pass ingest (lightgbm_tpu/ingest) must. The cap is lifted for
   training (XLA's runtime handles mid-computation allocation failure
   badly) — corruption would fail the byte-compare below anyway.
2. `inmem`     — same construction cap, `tpu_ingest=false`: expected to
   die at the cap (proves the budget bites and the streamed path is
   doing real work, not that the budget was secretly roomy).
3. `reference` — no cap, `tpu_ingest=false` in-memory construction:
   the bit-identity oracle.

PASS = streamed child constructed under the cap AND its trained model
text is byte-identical to the reference's AND the in-memory child hit
the cap.

Usage: python scripts/ingest_smoke.py
Env: SMOKE_ROWS (default 600000), SMOKE_FEATURES (40), SMOKE_ITERS (3).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

ROWS = int(os.environ.get("SMOKE_ROWS", 2_000_000))
FEATURES = int(os.environ.get("SMOKE_FEATURES", 40))
ITERS = int(os.environ.get("SMOKE_ITERS", 2))
RAW_BYTES = ROWS * FEATURES * 8
# Half the raw matrix, with a floor: the floor covers the REAL fixed
# costs every capped run pays over the pre-cap baseline (the gathered
# bin/EFB samples, the 1-byte binned output, chunk buffers, grower
# compile arenas) — and, just as important, keeps the allocator out of
# the pathological near-zero-headroom regime (glibc/obmalloc retry
# storms were observed with <120MB of headroom on a 7GB-virtual jax
# process). The smoke therefore needs a raw matrix comfortably ABOVE
# the floor to prove anything: ~2M x 40 float64 = 640MB vs a 320MB cap.
_BUDGET_FLOOR = 256 << 20
# 0.6: the CPU backend's training footprint is ~2-3x the 1-byte binned
# matrix (host copy + padded copy + "device" copy — the CPU backend's
# device memory IS host RAM) plus labels/scores; at F=40 that is
# ~0.35x raw, and 0.6x leaves real headroom while staying far below raw
BUDGET = max(int(RAW_BYTES * 0.6), _BUDGET_FLOOR)

PARAMS = {
    # the smoke's claim is about CONSTRUCTION memory, so training is
    # kept cheap (the CPU backend pays the histogram flops for real):
    # few leaves, narrow bins, 2 iterations
    "objective": "binary", "verbose": -1, "max_bin": 31,
    "num_leaves": 7, "min_data_in_leaf": 20, "learning_rate": 0.1,
    # small streaming chunks: the text parser's per-chunk buffer must
    # fit the budget too
    "tpu_ingest_chunk_rows": 8192,
    # ... and so must the grower's per-pass working set: at the default
    # 65536-row histogram chunk the one-hot transient is
    # chunk * G*B * 4B = 335MB at F=40/max_bin=31 — row-count
    # INDEPENDENT, so it would dominate any budget; 8192 rows makes it
    # 42MB (training under a memory budget means sizing the chunk to it)
    "tpu_hist_chunk": 8192,
    # land the binned matrix straight into the device buffer, freeing
    # host blocks as they ship — without this the matrix exists three
    # times around trainer init (host + padded host + device), which on
    # the CPU backend (device memory IS host RAM) triples the footprint
    "tree_learner": "data",
    "tpu_ingest_device_shards": True,
    # pass 1's gathered row samples are a REAL fixed cost — the default
    # 200k-row bin sample is 200k*F*8B (64MB at F=40), most of the
    # budget. Streaming under a memory budget means sizing the sample
    # to it; bit-identity holds at any sample count (both construction
    # paths share the sampling code)
    "bin_construct_sample_cnt": 50_000,
}


def _vmsize() -> int:
    for line in open("/proc/self/status"):
        if line.startswith("VmSize"):
            return int(line.split()[1]) * 1024
    return 0


def _child(role: str, path: str, model_out: str) -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import jax.numpy as jnp
    import lightgbm_tpu as lgb

    # pre-rlimit warmup: everything a training run allocates that is NOT
    # data-proportional must land in the baseline the cap is measured
    # against — the XLA/Eigen thread pool (24 x 8MB stacks; without it
    # the capped run silently degrades to one thread), compiler arenas,
    # numpy/python allocator pools. A tiny end-to-end train touches all
    # of it.
    (jnp.ones((4096, 4096)) @ jnp.ones((4096, 4096))).block_until_ready()
    rng = np.random.RandomState(0)
    Xw = rng.randn(512, 8)
    lgb.train({"objective": "binary", "verbose": -1, "num_leaves": 4},
              lgb.Dataset(Xw, label=(Xw[:, 0] > 0).astype(float)),
              num_boost_round=1, verbose_eval=False)
    # ... including the ingest binning thread pool: its worker threads'
    # first mallocs each map a fresh glibc arena (64MB of ADDRESS SPACE
    # apiece — ~300MB observed for 8 workers), so warm them pre-cap on a
    # matrix big enough to take the pooled path (the arenas persist and
    # are reused after the pool is torn down). MALLOC_ARENA_MAX in
    # _spawn bounds whatever still leaks through.
    from lightgbm_tpu.dataset import Dataset as _Inner
    Xp = rng.randn(120_001, 6)
    _Inner.from_numpy(Xp, None, max_bin=15, chunk_rows=120_001)
    del Xp

    params = dict(PARAMS)
    if role in ("inmem", "reference"):
        params["tpu_ingest"] = False
    capped = role in ("streamed", "inmem")
    try:
        ds = lgb.Dataset(path, params=dict(params))
        if capped:
            # the budget covers CONSTRUCTION — the thing the streaming
            # subsystem claims needs no raw matrix. The soft RLIMIT_AS
            # is restored before training: XLA's runtime does not fail
            # allocations cleanly mid-computation (garbage results were
            # observed), and the trained model is byte-compared against
            # the uncapped reference anyway, which would expose any
            # corruption.
            import resource
            _, hard = resource.getrlimit(resource.RLIMIT_AS)
            limit = _vmsize() + BUDGET
            resource.setrlimit(resource.RLIMIT_AS, (limit, hard))
            try:
                ds.construct()
            finally:
                resource.setrlimit(resource.RLIMIT_AS,
                                   (resource.RLIM_INFINITY, hard))
        booster = lgb.train(dict(params), ds, num_boost_round=ITERS,
                            verbose_eval=False)
        booster.save_model(model_out)
        status = {"role": role, "ok": True,
                  "iterations": booster.current_iteration()}
    except MemoryError:
        import traceback
        status = {"role": role, "ok": False, "oom": True,
                  "at": traceback.format_exc(limit=6).splitlines()[-8:]}
    print("SMOKE_RESULT " + json.dumps(status), flush=True)


def _spawn(role: str, path: str, model_out: str) -> dict:
    env = dict(os.environ)
    env["SMOKE_ROLE"] = role
    env["SMOKE_PATH"] = path
    env["SMOKE_MODEL"] = model_out
    env["JAX_PLATFORMS"] = "cpu"
    # XLA:CPU's parallel codegen spawns ~32 fresh threads per compile
    # (8MB stack each — a ~256MB TRANSIENT spike that has nothing to do
    # with the data); serialize codegen in every child so capped and
    # uncapped runs compile the same way
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_cpu_parallel_codegen_split_count=1"
                        ).strip()
    # bound glibc's per-thread arena reservations (64MB of address space
    # each — poison under an RLIMIT_AS budget)
    env["MALLOC_ARENA_MAX"] = "4"
    res = subprocess.run([sys.executable, os.path.abspath(__file__)],
                         env=env, capture_output=True, text=True,
                         timeout=3600)
    for line in res.stdout.splitlines():
        if line.startswith("SMOKE_RESULT "):
            return json.loads(line[len("SMOKE_RESULT "):])
    return {"role": role, "ok": False, "rc": res.returncode,
            "tail": (res.stdout + res.stderr)[-600:]}


def main() -> int:
    role = os.environ.get("SMOKE_ROLE")
    if role:
        _child(role, os.environ["SMOKE_PATH"], os.environ["SMOKE_MODEL"])
        return 0

    import numpy as np

    print(f"[smoke] rows={ROWS} features={FEATURES} "
          f"raw={RAW_BYTES / 1e6:.0f}MB budget={BUDGET / 1e6:.0f}MB",
          file=sys.stderr)
    assert BUDGET < RAW_BYTES, (
        f"budget ({BUDGET / 1e6:.0f}MB) must be smaller than the raw "
        f"matrix ({RAW_BYTES / 1e6:.0f}MB) — raise SMOKE_ROWS/"
        f"SMOKE_FEATURES so the raw matrix exceeds the "
        f"{_BUDGET_FLOOR / 1e6:.0f}MB fixed-cost floor")
    tmp = tempfile.mkdtemp(prefix="ingest_smoke_")
    path = os.path.join(tmp, "smoke.tsv")
    rng = np.random.RandomState(7)
    # write in slabs so the PARENT does not hold the matrix either
    slab = 100_000
    with open(path, "w") as fh:
        for lo in range(0, ROWS, slab):
            m = min(slab, ROWS - lo)
            X = rng.randn(m, FEATURES)
            X[rng.rand(m, FEATURES) < 0.2] = 0.0
            y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float64)
            np.savetxt(fh, np.column_stack([y, X]), delimiter="\t",
                       fmt="%.7g")
            del X, y

    streamed = _spawn("streamed", path, os.path.join(tmp, "streamed.txt"))
    print(f"[smoke] streamed: {streamed}", file=sys.stderr)
    inmem = _spawn("inmem", path, os.path.join(tmp, "inmem.txt"))
    print(f"[smoke] inmem-under-cap: {inmem}", file=sys.stderr)
    reference = _spawn("reference", path, os.path.join(tmp, "ref.txt"))
    print(f"[smoke] reference: {reference}", file=sys.stderr)

    failures = []
    if not streamed.get("ok"):
        failures.append(f"streamed run failed (construction under the "
                        f"{BUDGET / 1e6:.0f}MB budget): {streamed}")
    if inmem.get("ok"):
        failures.append("in-memory construction SUCCEEDED under the "
                        "budget — the cap is not binding, the smoke "
                        "proves nothing")
    if not reference.get("ok"):
        failures.append(f"uncapped reference run failed: {reference}")
    if streamed.get("ok") and reference.get("ok"):
        a = open(os.path.join(tmp, "streamed.txt")).read()
        b = open(os.path.join(tmp, "ref.txt")).read()
        if a != b:
            failures.append("streamed-under-budget model differs from "
                            "the in-memory reference model")
        else:
            print("[smoke] models byte-identical", file=sys.stderr)

    print(json.dumps({
        "smoke": "ingest", "ok": not failures,
        "rows": ROWS, "features": FEATURES,
        "raw_mb": round(RAW_BYTES / 1e6, 1),
        "budget_mb": round(BUDGET / 1e6, 1),
        "streamed": streamed, "inmem_under_cap": inmem,
        "failures": failures,
    }), flush=True)
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(main())
