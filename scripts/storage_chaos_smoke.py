"""Storage chaos gate: training completes byte-identically under
injected disk faults, and every degradation is visible in telemetry.

The durable-IO story (ISSUE 18) in one headless smoke: a supervisor
runs the same training invocation twice — once fault-free (the
reference) and once with `LGBM_TPU_FAULT_PLAN` injecting the storage
shapes through `lightgbm_tpu/durable.py`'s in-layer sites:

- transient EIO on checkpoint publishes (absorbed by the retry
  policy, here raised via the `tpu_io_retries`/`tpu_io_backoff_s`
  params — which are fingerprint-EXCLUDED, so the chaos run's model
  must still be byte-identical to the reference's);
- a torn checkpoint write (half the payload reaches the tmp file, the
  publish dies pre-rename — atomicity must make it invisible);
- sustained slow-IO on the checkpoint rename (storage brown-out);
- EIO on run-log appends and heartbeat leases — best-effort streams
  that must DEGRADE (drop + count), never raise into training.

Acceptance: the chaos child exits 0, its `model_to_string` matches the
reference byte-for-byte, and its degradation report (durable.dropped()
+ the fault plan's fired audit) shows every injected fault was hit and
counted. A third stage trains under ENOSPC on checkpoint publishes
(absorbed by the retry budget, byte-identical again); a fourth proves
the ENOSPC escape hatch end-to-end in a child: with zero retries and a
full "disk", the checkpoint manager evicts its oldest snapshot (never
the newest) and the save lands.

Writes a machine-readable artifact (CHAOS_r01.json).

Usage:
    python scripts/storage_chaos_smoke.py [--rounds 8]
        [--out CHAOS_r01.json] [--timeout 240]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import json, os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import lightgbm_tpu as lgb
from lightgbm_tpu import durable
from lightgbm_tpu.testing import faults

spec = json.loads(os.environ["CHAOS_CHILD_SPEC"])
raw = np.load(spec["data"])
X, y = raw[:, 1:], raw[:, 0]
ds = lgb.Dataset(X, y)
booster = lgb.train(spec["params"], ds, num_boost_round=spec["rounds"],
                    verbose_eval=False)
with open(spec["out"], "w") as fh:
    fh.write(booster.model_to_string())
plan = faults._plan
print("CHAOS_REPORT " + json.dumps({{
    "dropped": durable.dropped(),
    "policy": durable.policy(),
    "fired": list(plan.fired) if plan is not None else [],
}}), flush=True)
"""

HATCH_CHILD = r"""
import json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, {repo!r})
from lightgbm_tpu import durable
from lightgbm_tpu.checkpoint import CheckpointManager
from lightgbm_tpu.testing import faults

directory = sys.argv[1]
mgr = CheckpointManager(directory, keep_last=5, rank=0)
mgr.save({{"iteration": 1}}, 1)
mgr.save({{"iteration": 2}}, 2)
durable.configure(retries=0, backoff_s=0.0)
faults.enospc(1, site="checkpoint.write")
mgr.save({{"iteration": 3}}, 3)   # hatch: evict iter 1, retry, land
assert mgr.available_iterations() == [2, 3], mgr.available_iterations()
payload, _ = mgr.load_latest()
assert payload["iteration"] == 3, payload
print("HATCH_REPORT " + json.dumps({{
    "fired": list(faults._plan.fired),
    "kept": mgr.available_iterations(),
}}), flush=True)
"""


def _run_child(code: str, spec: dict, timeout: float, fault_plan=None,
               argv=()):
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["CHAOS_CHILD_SPEC"] = json.dumps(spec or {})
    env.pop("LGBM_TPU_FAULT_PLAN", None)
    if fault_plan:
        env["LGBM_TPU_FAULT_PLAN"] = json.dumps(fault_plan)
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code.format(repo=REPO)] + list(argv),
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=timeout)
        rc, out = proc.returncode, proc.stdout + proc.stderr
    except subprocess.TimeoutExpired as exc:
        rc, out = 124, "timeout: " + str(exc)
    return rc, round(time.time() - t0, 2), out


def _report(out: str, tag: str):
    for line in out.splitlines():
        if line.startswith(tag + " "):
            return json.loads(line[len(tag) + 1:])
    return None


def run(args) -> dict:
    workdir = tempfile.mkdtemp(prefix="storage_chaos_")

    import numpy as np
    rng = np.random.RandomState(0)
    n, f = 600, 6
    X = rng.randn(n, f)
    y = (X[:, 0] + X[:, 1] * X[:, 2] + 0.3 * rng.randn(n) > 0).astype(
        np.float64)
    data_path = os.path.join(workdir, "data.npy")
    np.save(data_path, np.column_stack([y, X]))

    def params(tag):
        return {
            "objective": "binary", "verbose": -1, "num_leaves": 7,
            "bagging_fraction": 0.7, "bagging_freq": 1, "seed": 11,
            "tpu_checkpoint_dir": os.path.join(workdir, tag, "ckpts"),
            "tpu_checkpoint_interval": 1, "tpu_checkpoint_keep": 50,
            "tpu_telemetry_dir": os.path.join(workdir, tag, "telemetry"),
            "tpu_heartbeat_dir": os.path.join(workdir, tag, "heartbeats"),
            "tpu_heartbeat_lease_s": 5.0,
        }

    stages = []
    result = {"metric": "storage_chaos", "unit": "ok",
              "rounds": args.rounds, "stages": stages}

    def fail(msg):
        result["value"] = 0.0
        result["error"] = msg
        return result

    # stage 1: fault-free reference
    ref_spec = {"data": data_path, "params": params("ref"),
                "rounds": args.rounds,
                "out": os.path.join(workdir, "m_ref.txt")}
    rc, wall, out = _run_child(CHILD, ref_spec, args.timeout)
    stages.append({"stage": "reference", "rc": rc, "wall_seconds": wall})
    if rc != 0:
        return fail("reference run failed: " + out[-1500:])

    # stage 2: the chaos run. tpu_io_retries/tpu_io_backoff_s are raised
    # so the stacked first-publish gauntlet (EIO, EIO, torn, slow
    # rename) fits one write's budget — and being fingerprint-EXCLUDED,
    # the different IO policy must NOT change the model.
    chaos_params = dict(params("chaos"),
                        tpu_io_retries=3, tpu_io_backoff_s=0.01)
    chaos_plan = {
        "io_fail": {"checkpoint.write": ["EIO", 2],
                    "runlog.write": ["EIO", 2],
                    "watchdog.heartbeat.write": ["EIO", 3]},
        "torn": {"checkpoint": 1},
        "slow": {"checkpoint.rename": 0.05},
    }
    chaos_spec = {"data": data_path, "params": chaos_params,
                  "rounds": args.rounds,
                  "out": os.path.join(workdir, "m_chaos.txt")}
    rc, wall, out = _run_child(CHILD, chaos_spec, args.timeout,
                               fault_plan=chaos_plan)
    report = _report(out, "CHAOS_REPORT")
    stages.append({"stage": "chaos", "rc": rc, "wall_seconds": wall,
                   "report": report})
    if rc != 0:
        return fail("chaos run did not complete (best-effort fault "
                    "leaked or critical retry exhausted): " + out[-1500:])
    if report is None:
        return fail("chaos child produced no degradation report")
    result["degradations"] = report

    # every injected fault must have actually fired ...
    fired = report["fired"]
    for want in ("eio@checkpoint.write", "torn@checkpoint",
                 "slow@checkpoint.rename", "eio@runlog.write",
                 "eio@watchdog.heartbeat.write"):
        if want not in fired:
            return fail(f"injected fault never fired: {want} "
                        f"(fired: {fired})")
    # ... and every best-effort drop must be COUNTED, not silent
    dropped = report["dropped"]
    if dropped.get("telemetry.runlog") != 2:
        return fail(f"runlog drops miscounted: {dropped}")
    if dropped.get("watchdog.heartbeat") != 3:
        return fail(f"heartbeat drops miscounted: {dropped}")

    # stage 3: training under ENOSPC — the full-disk blips are absorbed
    # by the retry budget (the eviction hatch correctly declines while
    # there is no older snapshot to free) and the model still matches
    enospc_spec = {"data": data_path, "params": params("enospc"),
                   "rounds": args.rounds,
                   "out": os.path.join(workdir, "m_enospc.txt")}
    rc, wall, out = _run_child(
        CHILD, enospc_spec, args.timeout,
        fault_plan={"io_fail": {"checkpoint.write": ["ENOSPC", 2]}})
    report = _report(out, "CHAOS_REPORT")
    stages.append({"stage": "chaos_enospc", "rc": rc,
                   "wall_seconds": wall, "report": report})
    if rc != 0:
        return fail("training under ENOSPC did not complete: "
                    + out[-1500:])
    if report is None or "enospc@checkpoint.write" not in report["fired"]:
        return fail(f"ENOSPC never fired in training: {report}")

    # stage 4: ENOSPC escape hatch end-to-end in a child
    hatch_dir = os.path.join(workdir, "hatch_ckpts")
    rc, wall, out = _run_child(HATCH_CHILD, None, args.timeout,
                               argv=[hatch_dir])
    hatch = _report(out, "HATCH_REPORT")
    stages.append({"stage": "enospc_hatch", "rc": rc,
                   "wall_seconds": wall, "report": hatch})
    if rc != 0 or hatch is None:
        return fail("ENOSPC hatch stage failed: " + out[-1500:])
    if "enospc@checkpoint.write" not in hatch["fired"]:
        return fail(f"ENOSPC never fired in hatch stage: {hatch}")
    result["enospc_hatch"] = hatch

    # the verdict: same bytes, with and without the disk misbehaving
    ref = open(os.path.join(workdir, "m_ref.txt")).read()
    chaos = open(os.path.join(workdir, "m_chaos.txt")).read()
    enospc = open(os.path.join(workdir, "m_enospc.txt")).read()
    result["byte_identical"] = chaos == ref and enospc == ref
    result["value"] = 1.0 if result["byte_identical"] else 0.0
    if not result["byte_identical"]:
        result["error"] = ("chaos-run model differs from the fault-free "
                           "reference (eio/torn/slow: %s, enospc: %s)"
                           % (chaos == ref, enospc == ref))
    shutil.rmtree(workdir, ignore_errors=True)
    return result


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--rounds", type=int, default=8)
    ap.add_argument("--timeout", type=float,
                    default=float(os.environ.get("CHAOS_TIMEOUT", 240)))
    ap.add_argument("--out", default=os.path.join(REPO, "CHAOS_r01.json"))
    args = ap.parse_args()
    t0 = time.time()
    result = run(args)
    result["wall_seconds"] = round(time.time() - t0, 2)
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps({k: v for k, v in result.items()
                      if k != "stages"}), flush=True)
    return 0 if result.get("value") == 1.0 else 1


if __name__ == "__main__":
    sys.exit(main())
