"""Serving fast-path regression gates.

Phase 1 — steady state: repeated single-row predict must trigger ZERO
recompilations and ZERO forest restacks after warmup. Trains a tiny
model, warms the serving Predictor over its bucket ladder, then fires
repeated single-row predicts while counting jax backend compilations
(via jax.monitoring compile events) and CompiledForest restacks. Any
nonzero count means the low-latency path silently regressed to
retracing/restacking — the exact failure mode the shape-bucketed
dispatch and the model-version cache exist to prevent.

Phase 2 — hot swap under load: a ModelRegistry serves continuous
submit() traffic while a new model version is published mid-stream.
Gates: ZERO dropped/failed futures across the swap, no stale-version
results after publish() returns (every post-swap future resolves to
the NEW model's prediction), and ZERO compilations on already-seen
buckets after the swap (the incoming predictor warms its ladder
BEFORE the swap, so swap-time traffic never retraces).

Usage: python scripts/predict_latency_smoke.py
Exits nonzero on regression; prints one machine-readable JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def main() -> int:
    import jax.monitoring
    import lightgbm_tpu as lgb
    from lightgbm_tpu.serving import ModelRegistry

    compile_events = []
    jax.monitoring.register_event_listener(
        lambda name, **kw: compile_events.append(name)
        if "compil" in name else None)

    rng = np.random.RandomState(0)
    X = rng.randn(2000, 10).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    params = {"objective": "binary", "verbose": -1, "num_leaves": 15,
              "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, y, params=dict(params))
    booster = lgb.train(dict(params), ds, num_boost_round=20,
                        verbose_eval=False)

    predictor = booster.serving_predictor()
    warm = predictor.warmup(max_rows=64)
    # one settling request per path the loop exercises
    predictor.predict_one(X[0])
    predictor.predict(X[:3])

    stats0 = predictor.stats()
    compile_events.clear()
    reps = int(os.environ.get("SMOKE_REPS", 50))
    t0 = time.perf_counter()
    for i in range(reps):
        predictor.predict_one(X[i % len(X)])
        predictor.predict(X[i % 100:i % 100 + 3])
    wall = time.perf_counter() - t0
    stats1 = predictor.stats()

    compiles = len(compile_events)
    restacks = stats1["stack_restacks"] - stats0["stack_restacks"]
    steady_ok = compiles == 0 and restacks == 0

    # ---- phase 2: hot swap under load ----------------------------------
    booster_b = lgb.train(dict(params), ds, num_boost_round=35,
                          verbose_eval=False)
    pa = booster.predict(X[:64])
    pb = booster_b.predict(X[:64])
    assert not np.array_equal(pa, pb), "swap models must differ"

    reg = ModelRegistry(warmup_rows=64)
    reg.publish("m", booster)
    # settle the registry's submit/micro-batch route on model A
    reg.submit("m", X[0]).result(timeout=30)

    pre_futs, post_futs = [], []
    swapped = threading.Event()
    stop = threading.Event()

    def fire():
        i = 0
        while not stop.is_set() and i < 20000:
            # classify BEFORE submitting: a future counts as post-swap
            # only if publish() had returned before submit() started —
            # a submit racing the swap may legitimately resolve on the
            # old model (in-flight futures complete on the accepting
            # model), which must not flake the stale gate
            was_swapped = swapped.is_set()
            fut = reg.submit("m", X[i % 64])
            (post_futs if was_swapped else pre_futs).append((i % 64, fut))
            i += 1
            time.sleep(0.0005)            # paced open-loop-ish stream

    th = threading.Thread(target=fire)
    th.start()
    time.sleep(0.05)                      # load running against A
    reg.publish("m", booster_b)           # warms BEFORE the atomic swap
    swapped.set()
    compile_events.clear()                # post-swap compiles gate
    time.sleep(0.05)                      # load running against B
    stop.set()
    th.join()

    dropped = 0
    stale_after_swap = 0
    for i, fut in pre_futs + post_futs:
        try:
            val = fut.result(timeout=30)
        except Exception:
            dropped += 1
            continue
        if not (np.allclose(val, pa[i]) or np.allclose(val, pb[i])):
            dropped += 1                  # misrouted = dropped contract
    # futures submitted after publish() returned must be NEW-model only
    for i, fut in post_futs:
        try:
            if not np.allclose(fut.result(timeout=30), pb[i]):
                stale_after_swap += 1
        except Exception:
            pass                          # already counted as dropped
    # steady post-swap traffic on already-seen buckets: zero compiles
    for i in range(20):
        reg.submit("m", X[i % 64]).result(timeout=30)
    swap_compiles = len(compile_events)
    reg.close()

    swap_ok = (dropped == 0 and stale_after_swap == 0
               and swap_compiles == 0 and len(post_futs) > 0)
    ok = steady_ok and swap_ok
    print(json.dumps({
        "metric": "predict_latency_smoke",
        "value": 1 if ok else 0,
        "unit": "pass",
        "detail": {
            "reps": reps,
            "compiles_after_warmup": compiles,
            "restacks_after_warmup": int(restacks),
            "warmup_buckets": warm["buckets"],
            "warmup_seconds": round(warm["seconds"], 3),
            "p50_latency_ms": stats1.get("p50_latency_ms"),
            "steady_wall_seconds": round(wall, 3),
            "hot_swap": {
                "in_flight_futures": len(pre_futs),
                "post_swap_futures": len(post_futs),
                "dropped_or_misrouted": dropped,
                "stale_after_swap": stale_after_swap,
                "compiles_after_swap_on_seen_buckets": swap_compiles,
            },
        },
    }), flush=True)
    if not steady_ok:
        print("FAIL: fast path retraced (%d compiles) or restacked (%d) "
              "after warmup" % (compiles, restacks), file=sys.stderr)
    if not swap_ok:
        print("FAIL: hot swap dropped/misrouted %d future(s), %d stale "
              "post-swap result(s), %d post-swap compile(s), %d post-swap "
              "future(s)" % (dropped, stale_after_swap, swap_compiles,
                             len(post_futs)), file=sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
