"""Serving fast-path regression gate: repeated single-row predict must
trigger ZERO recompilations and ZERO forest restacks after warmup.

Trains a tiny model, warms the serving Predictor over its bucket
ladder, then fires repeated single-row predicts while counting jax
backend compilations (via jax.monitoring compile events) and
CompiledForest restacks. Any nonzero count means the low-latency path
silently regressed to retracing/restacking — the exact failure mode
the shape-bucketed dispatch and the model-version cache exist to
prevent.

Usage: python scripts/predict_latency_smoke.py
Exits nonzero on regression; prints one machine-readable JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def main() -> int:
    import jax.monitoring
    import lightgbm_tpu as lgb

    compile_events = []
    jax.monitoring.register_event_listener(
        lambda name, **kw: compile_events.append(name)
        if "compil" in name else None)

    rng = np.random.RandomState(0)
    X = rng.randn(2000, 10).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0).astype(np.float32)
    params = {"objective": "binary", "verbose": -1, "num_leaves": 15,
              "min_data_in_leaf": 5}
    ds = lgb.Dataset(X, y, params=dict(params))
    booster = lgb.train(dict(params), ds, num_boost_round=20,
                        verbose_eval=False)

    predictor = booster.serving_predictor()
    warm = predictor.warmup(max_rows=64)
    # one settling request per path the loop exercises
    predictor.predict_one(X[0])
    predictor.predict(X[:3])

    stats0 = predictor.stats()
    compile_events.clear()
    reps = int(os.environ.get("SMOKE_REPS", 50))
    t0 = time.perf_counter()
    for i in range(reps):
        predictor.predict_one(X[i % len(X)])
        predictor.predict(X[i % 100:i % 100 + 3])
    wall = time.perf_counter() - t0
    stats1 = predictor.stats()

    compiles = len(compile_events)
    restacks = stats1["stack_restacks"] - stats0["stack_restacks"]
    ok = compiles == 0 and restacks == 0
    print(json.dumps({
        "metric": "predict_latency_smoke",
        "value": 1 if ok else 0,
        "unit": "pass",
        "detail": {
            "reps": reps,
            "compiles_after_warmup": compiles,
            "restacks_after_warmup": int(restacks),
            "warmup_buckets": warm["buckets"],
            "warmup_seconds": round(warm["seconds"], 3),
            "p50_latency_ms": stats1.get("p50_latency_ms"),
            "steady_wall_seconds": round(wall, 3),
        },
    }), flush=True)
    if not ok:
        print("FAIL: fast path retraced (%d compiles) or restacked (%d) "
              "after warmup" % (compiles, restacks), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
