"""Exported-forest artifact gates (ISSUE 16).

Three phases, one committed artifact (EXPORT_r01.json via
BENCH_SHAPE=export):

1. **round_trip** — train, pack an artifact carrying the f32 + f16 +
   int8 layouts over the full bucket ladder, reload it in-process, and
   gate on byte-for-byte bit-identity against the live booster for
   every layout, fused probabilities AND raw margins.
2. **refusal** — a loader must never serve a wrong forest: flipped
   payload bytes are refused with the CRC-failing section named,
   a future format version is refused before any section is touched,
   a fingerprint mismatch (model re-trained since packing) is refused,
   and a plain text model file is recognised as not-an-artifact.
3. **cold_serve** — the headline gate. A child process arms a
   meta-path import blocker over the ENTIRE training stack
   (boosting/, learner/, ingest/, parallel/ and their front doors),
   loads the artifact cold through `lightgbm_tpu.export.runtime`,
   warms the exported ladder, then serves every pre-exported bucket
   while a `jax.monitoring` listener counts compile/trace traffic:
   gates are trainer-stack-absent, ZERO retraces in steady state, and
   bit-identical predictions vs the parent's live booster.

Usage: python scripts/export_smoke.py [--out EXPORT_r01.json]
Exits nonzero on any gate failure; prints one machine-readable JSON
line per phase plus a final summary line.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402

N_FEATURES = 12
LAYOUTS = ["none", "f16", "int8"]

# the serving replica's forbidden surface: the trainer packages the
# export-import-hygiene lint rule bans, plus their front doors
BLOCKED = (
    "lightgbm_tpu.boosting", "lightgbm_tpu.learner",
    "lightgbm_tpu.ingest", "lightgbm_tpu.parallel",
    "lightgbm_tpu.basic", "lightgbm_tpu.engine",
    "lightgbm_tpu.dataset", "lightgbm_tpu.cli",
    "lightgbm_tpu.sklearn", "lightgbm_tpu.objectives",
)


def _train():
    import lightgbm_tpu as lgb
    rng = np.random.RandomState(0)
    X = rng.rand(3000, N_FEATURES).astype(np.float32)
    y = (X[:, 0] + X[:, 1] * X[:, 2] > 0.7).astype(np.float32)
    params = {"objective": "binary", "verbose": -1, "num_leaves": 31,
              "min_data_in_leaf": 5, "seed": 3}
    ds = lgb.Dataset(X, y, params=dict(params))
    booster = lgb.train(dict(params), ds, num_boost_round=25,
                        verbose_eval=False)
    return X, booster


def _export(booster, X, out_dir):
    path = os.path.join(out_dir, "forest.artifact")
    info = booster.export_forest(path, layouts=list(LAYOUTS),
                                 calibration=X[:512])
    return path, info


def phase_round_trip(tmpdir: str) -> dict:
    from lightgbm_tpu.export import load_artifact

    X, booster = _train()
    path, info = _export(booster, X, tmpdir)
    rng = np.random.RandomState(11)
    Xt = rng.rand(200, N_FEATURES).astype(np.float32)
    Xt[:7, 3] = np.nan                      # missing-value routing too

    inner = booster._inner
    gates, deltas = {}, {}
    for mode in LAYOUTS:
        model = load_artifact(path, params={"tpu_predict_quantize": mode})
        inner.config.io.tpu_predict_quantize = mode
        ref = inner.predict(Xt)
        got = model.predict(Xt)
        ref_raw = inner.predict(Xt, raw_score=True)
        got_raw = model.predict(Xt, raw_score=True)
        gates["bit_identical_%s" % mode] = bool(
            np.array_equal(ref, got) and np.array_equal(ref_raw, got_raw))
        deltas[mode] = float(np.max(np.abs(ref - got)))
    inner.config.io.tpu_predict_quantize = "none"
    return {"phase": "round_trip", "ok": all(gates.values()),
            "gates": gates, "max_abs_delta": deltas,
            "artifact": {k: info[k] for k in
                         ("bytes", "sections", "layouts", "buckets")}}


def phase_refusal(tmpdir: str) -> dict:
    from lightgbm_tpu.export import (ArtifactError, is_artifact,
                                     load_artifact)

    X, booster = _train()
    path, _ = _export(booster, X, tmpdir)
    blob = open(path, "rb").read()
    gates, messages = {}, {}

    # 1. corrupted payload: flip a byte inside the LAST section and the
    # CRC check must name it when that section is first deserialized
    bad = os.path.join(tmpdir, "corrupt.artifact")
    with open(bad, "wb") as fh:
        fh.write(blob[:-3] + bytes([blob[-3] ^ 0xFF]) + blob[-2:])
    try:
        load_artifact(bad).predict(X[:16])
        gates["corruption_refused"] = False
    except ArtifactError as exc:
        msg = str(exc)
        messages["corruption"] = msg
        gates["corruption_refused"] = (
            ("checksum" in msg or "CRC" in msg)
            and ("fn/" in msg or "conv/" in msg or "leaves/" in msg
                 or "model_text" in msg))

    # 2. version skew: a future format number (byte-patched in place,
    # same width) must be refused at load, before any section is read
    skew = os.path.join(tmpdir, "skew.artifact")
    patched = blob.replace(b'"format": 1,', b'"format": 9,', 1)
    with open(skew, "wb") as fh:
        fh.write(patched)
    try:
        load_artifact(skew)
        gates["version_skew_refused"] = False
    except ArtifactError as exc:
        messages["version_skew"] = str(exc)
        gates["version_skew_refused"] = "format" in str(exc)

    # 3. stale artifact: the deployed config fingerprint moved on
    try:
        load_artifact(path, expect_fingerprint="0" * 16)
        gates["fingerprint_refused"] = False
    except ArtifactError as exc:
        messages["fingerprint"] = str(exc)
        gates["fingerprint_refused"] = "fingerprint" in str(exc)

    # 4. a plain text model is not an artifact
    model_txt = os.path.join(tmpdir, "model.txt")
    booster.save_model(model_txt)
    not_artifact = not is_artifact(model_txt)
    try:
        load_artifact(model_txt)
        gates["text_model_refused"] = False
    except ArtifactError as exc:
        messages["text_model"] = str(exc)
        gates["text_model_refused"] = not_artifact

    return {"phase": "refusal", "ok": all(gates.values()),
            "gates": gates, "messages": messages}


def _cold_child(artifact: str, ref_npz: str) -> None:
    """The 'serving replica': arm the trainer import blocker BEFORE any
    lightgbm_tpu import, load the artifact cold, warm the exported
    ladder, then serve every bucket counting compile traffic."""
    class _TrainerImportBlocker:
        def find_spec(self, name, path=None, target=None):
            for b in BLOCKED:
                if name == b or name.startswith(b + "."):
                    raise ImportError(
                        "training stack blocked in serving replica: "
                        + name)
            return None

    sys.meta_path.insert(0, _TrainerImportBlocker())
    blocker_armed = False
    try:
        import lightgbm_tpu.boosting  # noqa: F401
    except ImportError:
        blocker_armed = True

    import jax.monitoring
    events = []
    jax.monitoring.register_event_listener(
        lambda name, **kw: events.append(name))

    from lightgbm_tpu.export.runtime import ArtifactServer

    t0 = time.perf_counter()
    server = ArtifactServer(artifact)     # load + warm the full ladder
    warm_s = time.perf_counter() - t0
    warm_events = list(events)

    ref = np.load(ref_npz)
    X, prob_ref, raw_ref = ref["X"], ref["prob"], ref["raw"]
    buckets = list(server.model._buckets)

    # absorb per-program first-call compile-cache chatter, then demand
    # TOTAL silence in steady state
    for b in buckets:
        server.model.predict(X[:b])
        server.model.predict(X[:b], raw_score=True)
        server.predict(X[:b])
    events.clear()

    bit_identical = True
    for _ in range(2):                    # steady-state rounds
        for b in buckets:
            got = server.model.predict(X[:b])
            got_raw = server.model.predict(X[:b], raw_score=True)
            via_pred = server.predict(X[:b])
            one = server.predict_one(X[0])
            bit_identical = bit_identical and bool(
                np.array_equal(got, prob_ref[:b])
                and np.array_equal(got_raw, raw_ref[:b])
                and np.array_equal(via_pred, prob_ref[:b])
                and float(one) == float(prob_ref[0]))
    steady_events = list(events)

    trainer_loaded = sorted(
        m for m in sys.modules
        if any(m == b or m.startswith(b + ".") for b in BLOCKED))
    print(json.dumps({
        "blocker_armed": blocker_armed,
        "trainer_modules_loaded": trainer_loaded,
        "warmup_seconds": round(warm_s, 3),
        "warmup_events": len(warm_events),
        "steady_events": steady_events,
        "buckets": buckets,
        "bit_identical": bit_identical,
        "stats": server.stats(),
    }), flush=True)
    server.close()


def phase_cold_serve(tmpdir: str) -> dict:
    X, booster = _train()
    path, _ = _export(booster, X, tmpdir)
    top = max(booster._inner.config.io.tpu_predict_bucket_min << 3, 128)
    rng = np.random.RandomState(29)
    Xt = rng.rand(top, N_FEATURES).astype(np.float32)
    Xt[:5, 2] = np.nan
    ref_npz = os.path.join(tmpdir, "refs.npz")
    np.savez(ref_npz, X=Xt, prob=booster._inner.predict(Xt),
             raw=booster._inner.predict(Xt, raw_score=True))

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["LIGHTGBM_TPU_COMPILE_CACHE"] = "0"
    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--cold-child", path, "--ref", ref_npz],
        env=env, capture_output=True, text=True, timeout=600)
    line = next((ln for ln in reversed(res.stdout.splitlines())
                 if ln.startswith("{")), None)
    if res.returncode != 0 or line is None:
        return {"phase": "cold_serve", "ok": False,
                "error": (res.stdout + res.stderr)[-800:]}
    child = json.loads(line)
    retrace = [e for e in child["steady_events"]
               if "compil" in e or "trace" in e or "lower" in e]
    gates = {
        "blocker_armed": child["blocker_armed"],
        "trainer_stack_absent": child["trainer_modules_loaded"] == [],
        # the listener demonstrably sees compile traffic during warmup,
        # so the steady-state zero below is not vacuous
        "warmup_compiled": child["warmup_events"] > 0,
        "zero_retrace_steady_state": retrace == []
        and child["steady_events"] == [],
        "bit_identical": child["bit_identical"],
    }
    return {"phase": "cold_serve", "ok": all(gates.values()),
            "gates": gates, "child": child}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "EXPORT_r01.json"))
    ap.add_argument("--cold-child", default=None)
    ap.add_argument("--ref", default=None)
    args = ap.parse_args()
    if args.cold_child:
        _cold_child(args.cold_child, args.ref)
        return 0

    import tempfile
    t0 = time.time()
    phases = {}
    with tempfile.TemporaryDirectory(prefix="lgbm_tpu_export_") as tmp:
        for fn in (phase_round_trip, phase_refusal, phase_cold_serve):
            rec = fn(tmp)
            phases[rec["phase"]] = rec
            print(json.dumps(rec), flush=True)

    ok = all(p.get("ok") for p in phases.values())
    summary = {"shape": "export", "ok": ok,
               "wall_seconds": round(time.time() - t0, 1),
               "phases": phases}
    with open(args.out, "w") as fh:
        json.dump(summary, fh, indent=1)
    print(json.dumps({"shape": "export", "ok": ok, "out": args.out}),
          flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
