"""Per-round accuracy anchor (VERDICT r3 item 9): train ours on the real
chip and the reference binary on the same synthetic HIGGS-like split at
500-iteration scale, and record both holdout AUCs side by side in
ACCURACY_r{N}.json.

The reference anchors its quality story at HIGGS AUC 0.845239 @ 63 bins /
500 iters (docs/GPU-Performance.md:134); on synthetic data the absolute
number differs, so the artifact records the DELTA vs the reference binary
trained with identical hyperparameters on identical rows — accuracy
regressions then show up round-over-round like throughput ones.

Usage: python scripts/measure_accuracy.py [round_no] [rows] [iters]
       (reference half needs the CPU otherwise idle)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

PARAMS = {"objective": "binary", "metric": "auc", "verbose": -1,
          "max_bin": 63, "num_leaves": 255, "learning_rate": 0.1,
          "min_data_in_leaf": 1, "min_sum_hessian_in_leaf": 100.0}


def _auc(y, p):
    import numpy as np
    order = np.argsort(p)
    ranks = np.empty(len(p))
    ranks[order] = np.arange(1, len(p) + 1)
    pos = y > 0
    n_pos, n_neg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def main(round_no: int = 4, rows: int = 500_000, iters: int = 500):
    import numpy as np

    import bench
    import lightgbm_tpu as lgb
    from measure_baseline import BUILD_DIR, build_reference

    n_test = rows // 5
    X, y = bench.synth_higgs(rows + n_test, 28, seed=11)
    Xtr, ytr, Xte, yte = X[:rows], y[:rows], X[rows:], y[rows:]

    # ours, on whatever accelerator is attached
    ds = lgb.Dataset(Xtr, ytr, params=dict(PARAMS))
    t0 = time.time()
    booster = lgb.train(dict(PARAMS), ds, num_boost_round=iters,
                        verbose_eval=False)
    ours_wall = time.time() - t0
    ours_auc = float(_auc(yte, booster.predict(Xte, raw_score=True)))

    # reference binary, CPU
    exe = build_reference()
    os.makedirs(BUILD_DIR, exist_ok=True)
    tr = os.path.join(BUILD_DIR, f"acc_{rows}.train")
    te = os.path.join(BUILD_DIR, f"acc_{rows}.test")
    if not os.path.exists(tr):
        np.savetxt(tr, np.column_stack([ytr, Xtr]), fmt="%.6g",
                   delimiter="\t")
        np.savetxt(te, np.column_stack([yte, Xte]), fmt="%.6g",
                   delimiter="\t")
    model = os.path.join(BUILD_DIR, "acc_model.txt")
    conf = dict(PARAMS)
    conf.pop("verbose")
    conf.update(task="train", data=tr, num_trees=iters, verbosity=1,
                output_model=model, num_threads=os.cpu_count() or 1)
    t0 = time.time()
    subprocess.run([exe] + [f"{k}={v}" for k, v in conf.items()],
                   check=True, capture_output=True)
    ref_wall = time.time() - t0
    preds = os.path.join(BUILD_DIR, "acc_preds.txt")
    subprocess.run([exe, "task=predict", f"data={te}",
                    f"input_model={model}", f"output_result={preds}",
                    "predict_raw_score=true"],
                   check=True, capture_output=True)
    ref_auc = float(_auc(yte, np.loadtxt(preds)))

    result = {
        "rows": rows, "test_rows": n_test, "iters": iters,
        "max_bin": PARAMS["max_bin"], "num_leaves": PARAMS["num_leaves"],
        "ours_auc": round(ours_auc, 6), "ref_auc": round(ref_auc, 6),
        "auc_delta": round(ours_auc - ref_auc, 6),
        "ours_train_wall_s": round(ours_wall, 1),
        "ref_train_wall_s": round(ref_wall, 1),
        "reference_published_anchor": "HIGGS AUC 0.845239 @63 bins/500 "
                                      "iters (docs/GPU-Performance.md:134)",
    }
    out = os.path.join(REPO, f"ACCURACY_r{round_no:02d}.json")
    with open(out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    args = [int(float(a)) for a in sys.argv[1:]]
    main(*args)
