"""Per-round accuracy anchors: train ours on the real chip and the
reference binary on the same synthetic splits at 500-iteration scale,
and record the metric deltas side by side in ACCURACY_r{N}.json.

Three tasks (round-5 verdict item 9 widened this from binary-only):
- binary: HIGGS-shape holdout AUC (the reference anchors its quality
  story at HIGGS AUC 0.845239 @ 63 bins / 500 iters,
  docs/GPU-Performance.md:134; on synthetic data the absolute number
  differs, so the artifact records the DELTA against the reference
  binary trained with identical hyperparameters on identical rows)
- categorical: Expo-shape binary AUC with native categorical features
  on both sides (categorical_feature=0..7)
- ranking: lambdarank NDCG@10 on 100-doc queries

Usage: python scripts/measure_accuracy.py [round_no] [rows] [iters] [task ...]
       (reference half needs the CPU otherwise idle)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))

PARAMS = {"objective": "binary", "metric": "auc", "verbose": -1,
          "max_bin": 63, "num_leaves": 255, "learning_rate": 0.1,
          "min_data_in_leaf": 1, "min_sum_hessian_in_leaf": 100.0}


def _auc(y, p):
    import numpy as np
    order = np.argsort(p)
    ranks = np.empty(len(p))
    ranks[order] = np.arange(1, len(p) + 1)
    pos = y > 0
    n_pos, n_neg = pos.sum(), (~pos).sum()
    return (ranks[pos].sum() - n_pos * (n_pos + 1) / 2) / (n_pos * n_neg)


def _ndcg_at(y, p, qsizes, k=10):
    import numpy as np
    off, total, nq = 0, 0.0, 0
    for s in qsizes:
        yy, pp = y[off:off + s], p[off:off + s]
        off += s
        order = np.argsort(-pp)[:k]
        gains = (2.0 ** yy[order] - 1) / np.log2(np.arange(2, len(order) + 2))
        ideal = np.sort(yy)[::-1][:k]
        idcg = ((2.0 ** ideal - 1) / np.log2(np.arange(2, len(ideal) + 2))).sum()
        if idcg > 0:
            total += gains.sum() / idcg
            nq += 1
    return total / max(nq, 1)


def _ref_train_predict(exe, build_dir, tag, tr, te, conf, iters,
                       extra_train=(), raw=True):
    model = os.path.join(build_dir, f"acc_{tag}_model.txt")
    c = dict(conf)
    c.pop("verbose", None)
    c.update(task="train", data=tr, num_trees=iters, verbosity=1,
             output_model=model, num_threads=os.cpu_count() or 1)
    t0 = time.time()
    subprocess.run([exe] + [f"{k}={v}" for k, v in c.items()]
                   + list(extra_train), check=True, capture_output=True)
    wall = time.time() - t0
    preds = os.path.join(build_dir, f"acc_{tag}_preds.txt")
    args = [exe, "task=predict", f"data={te}", f"input_model={model}",
            f"output_result={preds}"]
    if raw:
        args.append("predict_raw_score=true")
    subprocess.run(args, check=True, capture_output=True)
    import numpy as np
    return np.loadtxt(preds), wall


def _binary_task(rows, iters, exe, build_dir):
    import numpy as np

    import bench
    import lightgbm_tpu as lgb

    n_test = rows // 5
    X, y = bench.synth_higgs(rows + n_test, 28, seed=11)
    Xtr, ytr, Xte, yte = X[:rows], y[:rows], X[rows:], y[rows:]

    ds = lgb.Dataset(Xtr, ytr, params=dict(PARAMS))
    t0 = time.time()
    booster = lgb.train(dict(PARAMS), ds, num_boost_round=iters,
                        verbose_eval=False)
    ours_wall = time.time() - t0
    ours = float(_auc(yte, booster.predict(Xte, raw_score=True)))

    tr = os.path.join(build_dir, f"acc_{rows}.train")
    te = os.path.join(build_dir, f"acc_{rows}.test")
    if not os.path.exists(tr):
        np.savetxt(tr, np.column_stack([ytr, Xtr]), fmt="%.6g", delimiter="\t")
        np.savetxt(te, np.column_stack([yte, Xte]), fmt="%.6g", delimiter="\t")
    preds, ref_wall = _ref_train_predict(exe, build_dir, "bin", tr, te,
                                         PARAMS, iters)
    ref = float(_auc(yte, preds))
    return {"metric": "auc", "ours": round(ours, 6), "ref": round(ref, 6),
            "delta": round(ours - ref, 6),
            "ours_train_wall_s": round(ours_wall, 1),
            "ref_train_wall_s": round(ref_wall, 1),
            "rows": rows, "iters": iters}


def _categorical_task(rows, iters, exe, build_dir):
    import numpy as np

    import bench
    import lightgbm_tpu as lgb

    n_test = rows // 5
    X, y, cat_idx = bench.synth_expo(rows + n_test, seed=13)
    Xtr, ytr, Xte, yte = X[:rows], y[:rows], X[rows:], y[rows:]
    params = dict(PARAMS, categorical_feature=cat_idx)

    ds = lgb.Dataset(Xtr, ytr, params=dict(params))
    t0 = time.time()
    booster = lgb.train(dict(params), ds, num_boost_round=iters,
                        verbose_eval=False)
    ours_wall = time.time() - t0
    ours = float(_auc(yte, booster.predict(Xte, raw_score=True)))

    tr = os.path.join(build_dir, f"acc_cat_{rows}.train")
    te = os.path.join(build_dir, f"acc_cat_{rows}.test")
    if not os.path.exists(tr):
        np.savetxt(tr, np.column_stack([ytr, Xtr]), fmt="%.6g", delimiter="\t")
        np.savetxt(te, np.column_stack([yte, Xte]), fmt="%.6g", delimiter="\t")
    cats = "categorical_feature=" + ",".join(str(c) for c in cat_idx)
    preds, ref_wall = _ref_train_predict(exe, build_dir, "cat", tr, te,
                                         PARAMS, iters, extra_train=[cats])
    ref = float(_auc(yte, preds))
    return {"metric": "auc", "ours": round(ours, 6), "ref": round(ref, 6),
            "delta": round(ours - ref, 6),
            "ours_train_wall_s": round(ours_wall, 1),
            "ref_train_wall_s": round(ref_wall, 1),
            "rows": rows, "iters": iters, "categorical": len(cat_idx)}


def _ranking_task(rows, iters, exe, build_dir):
    import numpy as np

    import lightgbm_tpu as lgb
    from measure_parity_sweep import _rank_data

    qlen = 100
    n_test = rows // 5
    X, y, nq, _ = _rank_data(rows + n_test, qlen=qlen, seed=17)
    ntr = (rows // qlen) * qlen
    Xtr, ytr, Xte, yte = X[:ntr], y[:ntr], X[ntr:], y[ntr:]
    qtr = [qlen] * (ntr // qlen)
    qte = [qlen] * (len(yte) // qlen)

    params = {"objective": "lambdarank", "metric": "ndcg", "verbose": -1,
              "max_bin": 63, "num_leaves": 255, "learning_rate": 0.1,
              "min_data_in_leaf": 1, "min_sum_hessian_in_leaf": 100.0}
    ds = lgb.Dataset(Xtr, ytr, group=qtr, params=dict(params))
    t0 = time.time()
    booster = lgb.train(dict(params), ds, num_boost_round=iters,
                        verbose_eval=False)
    ours_wall = time.time() - t0
    ours = float(_ndcg_at(yte, booster.predict(Xte, raw_score=True), qte))

    tr = os.path.join(build_dir, f"acc_rank_{rows}.train")
    te = os.path.join(build_dir, f"acc_rank_{rows}.test")
    if not os.path.exists(tr):
        np.savetxt(tr, np.column_stack([ytr, Xtr]), fmt="%.6g", delimiter="\t")
        np.savetxt(te, np.column_stack([yte, Xte]), fmt="%.6g", delimiter="\t")
        with open(tr + ".query", "w") as fh:
            fh.write("\n".join(str(q) for q in qtr))
        with open(te + ".query", "w") as fh:
            fh.write("\n".join(str(q) for q in qte))
    preds, ref_wall = _ref_train_predict(exe, build_dir, "rank", tr, te,
                                         params, iters, raw=True)
    ref = float(_ndcg_at(yte, preds, qte))
    return {"metric": "ndcg@10", "ours": round(ours, 6),
            "ref": round(ref, 6), "delta": round(ours - ref, 6),
            "ours_train_wall_s": round(ours_wall, 1),
            "ref_train_wall_s": round(ref_wall, 1),
            "rows": ntr, "iters": iters, "query_len": qlen}


def main(round_no: int = 5, rows: int = 500_000, iters: int = 500,
         tasks=("binary", "categorical", "ranking")):
    from measure_baseline import BUILD_DIR, build_reference
    exe = build_reference()
    os.makedirs(BUILD_DIR, exist_ok=True)

    out = os.path.join(REPO, f"ACCURACY_r{round_no:02d}.json")
    result = {}
    if os.path.exists(out):
        result = json.load(open(out))
    result.setdefault(
        "reference_published_anchor",
        "HIGGS AUC 0.845239 @63 bins/500 iters (docs/GPU-Performance.md:134)")
    fns = {"binary": _binary_task, "categorical": _categorical_task,
           "ranking": _ranking_task}
    for t in tasks:
        result[t] = fns[t](rows, iters, exe, BUILD_DIR)
        with open(out, "w") as fh:
            json.dump(result, fh, indent=1)
        print(t, json.dumps(result[t]))


if __name__ == "__main__":
    nums = [int(float(a)) for a in sys.argv[1:] if a.replace(".", "").isdigit()]
    names = [a for a in sys.argv[1:] if not a.replace(".", "").isdigit()]
    main(*nums, tasks=tuple(names) if names else ("binary", "categorical",
                                                  "ranking"))
