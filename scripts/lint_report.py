"""Lint gate artifact: run graftlint over lightgbm_tpu/ + scripts/ and
commit the machine-readable result (LINT_r01.json via BENCH_SHAPE=lint,
the elastic/overload smoke-gate discipline).

The artifact records per-rule counts, every unsuppressed finding (zero
for a green gate), every suppression WITH its written reason, and stale
baseline entries (also zero for green — the baseline must shrink, not
rot). CI and reviewers read the committed artifact; the tier-1 pytest
(tests/test_static_analysis.py) enforces the same zero-findings
contract on every run.

Usage: python scripts/lint_report.py [--out LINT_r01.json]
Exits 0 iff the gate is green; prints one JSON summary line.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from lightgbm_tpu.analysis import run  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "LINT_r01.json"))
    ap.add_argument("--baseline",
                    default=os.path.join(REPO, "graftlint_baseline.json"))
    args = ap.parse_args()

    report = run([os.path.join(REPO, "lightgbm_tpu"),
                  os.path.join(REPO, "scripts")],
                 baseline_path=args.baseline)
    doc = report.as_dict()
    # the committed artifact must be machine-portable (the OVERLOAD/
    # ELASTIC discipline): repo-relative paths, no local layout
    doc["paths"] = [os.path.relpath(p, REPO).replace(os.sep, "/")
                    for p in doc["paths"]]
    if doc["baseline"]["path"]:
        doc["baseline"]["path"] = os.path.relpath(
            doc["baseline"]["path"], REPO).replace(os.sep, "/")
    doc["gate"] = {
        "green": report.exit_code == 0 and not report.stale_baseline,
        "unsuppressed_findings": len(report.findings),
        "suppressions": len(report.suppressions),
        "stale_baseline_entries": len(report.stale_baseline),
    }
    with open(args.out, "w") as fh:
        json.dump(doc, fh, indent=2, sort_keys=True)
        fh.write("\n")
    print(json.dumps({"phase": "lint", "ok": doc["gate"]["green"],
                      "files_scanned": report.files_scanned,
                      "findings": len(report.findings),
                      "suppressed": len(report.suppressions),
                      "stale_baseline": len(report.stale_baseline),
                      "out": args.out}), flush=True)
    for f in report.findings:
        print(f.render(), file=sys.stderr)
    return 0 if doc["gate"]["green"] else 1


if __name__ == "__main__":
    sys.exit(main())
