"""Elastic train supervisor: kill -> detect -> shrink -> resume, end to end.

The preemptible-pod training story (ISSUE 11) in one headless gate:
a supervisor launches a training cohort at world size W, injects real
failures (a preempted rank, a wedged collective), watches the per-rank
heartbeat lease to tell WHICH rank died and why, and relaunches the
surviving cohort at a SHRUNKEN world size from the last checkpoint —
with bounded retry/backoff — until training completes. The final model
must be byte-identical to an uninterrupted reference run.

Two modes:

- `devices` (default; runs everywhere): world size = forced host device
  count inside one process per stage
  (`--xla_force_host_platform_device_count`, the multichip-gate
  pattern). The cycle is kill@W=4 -> wedge@4 (collective watchdog must
  exit RC_RANK_FAILURE, not hang) -> elastic resume @W'=2 -> kill ->
  elastic resume @W'=1 -> finish; final model compared byte-for-byte
  against an uninterrupted 1-device reference. PR 9's cross-device-count
  bit-identity is what makes the comparison exact.
- `processes`: a real multi-rank cohort under jax.distributed (2 ranks
  x 1 CPU device), `faults.kill_rank` killing rank 1 mid-run, rank 0's
  collective watchdog detecting the dead peer, then a single-process
  relaunch elastically re-sharding BOTH rank series
  (`checkpoint.elastic_local_state`) into one. Gated on the same
  capability probe as tests/test_multihost.py — jax CPU builds without
  multi-process collectives report `mode_unavailable` instead of
  failing. The gate is detection + successful elastic resume; bitwise
  equality against the uninterrupted original-world-size cohort is
  recorded but informational (cross-process row assembly permutes the
  f32 summation order, so it is not an invariant — devices mode
  carries the byte-identity acceptance).

Writes a machine-readable artifact (ELASTIC_r01.json): stages run,
ranks killed, detection latency, watchdog rc, resume outcomes,
byte-identity verdict.

Usage:
    python scripts/elastic_smoke.py [--rounds 12] [--mode devices]
        [--out ELASTIC_r01.json] [--timeout 240] [--max-retries 2]
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# rc contract: 77 = the injected preemption fired (expected death);
# 113 = watchdog.RC_RANK_FAILURE (detected wedge/dead peer); 0 = done
RC_PREEMPTED = 77
RC_RANK_FAILURE = 113

CHILD = r"""
import json, os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import lightgbm_tpu as lgb
from lightgbm_tpu.testing import faults

spec = json.loads(os.environ["ELASTIC_CHILD_SPEC"])
raw = np.load(spec["data"])
X, y = raw[:, 1:], raw[:, 0]
ds = lgb.Dataset(X, y)
try:
    booster = lgb.train(spec["params"], ds,
                        num_boost_round=spec["rounds"],
                        verbose_eval=False)
except faults.SimulatedPreemption as exc:
    print("CHILD_PREEMPTED", exc.iteration, flush=True)
    sys.exit({rc_preempted})
with open(spec["out"], "w") as fh:
    fh.write(booster.model_to_string())
print("CHILD_OK", flush=True)
"""


def _run_child(ndev: int, spec: dict, timeout: float,
               fault_plan: dict = None, extra_env: dict = None):
    """One training attempt at `ndev` forced host devices. Returns
    (rc, wall_seconds, output_tail)."""
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={ndev}"
                        ).strip()
    env["ELASTIC_CHILD_SPEC"] = json.dumps(spec)
    env.pop("LGBM_TPU_FAULT_PLAN", None)
    if fault_plan:
        env["LGBM_TPU_FAULT_PLAN"] = json.dumps(fault_plan)
    env.update(extra_env or {})
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             CHILD.format(repo=REPO, rc_preempted=RC_PREEMPTED)],
            env=env, cwd=REPO, capture_output=True, text=True,
            timeout=timeout)
        rc, out = proc.returncode, proc.stdout + proc.stderr
    except subprocess.TimeoutExpired as exc:
        rc, out = 124, "timeout: " + str(exc)
    return rc, round(time.time() - t0, 2), out[-2000:]


def _heartbeat_ages(hb_dir: str):
    sys.path.insert(0, REPO)
    from lightgbm_tpu.parallel import watchdog
    return watchdog.read_cohort(hb_dir, lease_s=5.0)


def run_devices_mode(args) -> dict:
    workdir = tempfile.mkdtemp(prefix="elastic_smoke_")
    ckpt_dir = os.path.join(workdir, "ckpts")
    hb_dir = os.path.join(workdir, "heartbeats")
    rounds = args.rounds
    rng_seed = 0

    import numpy as np
    rng = np.random.RandomState(rng_seed)
    n, f = 600, 6
    X = rng.randn(n, f)
    y = (X[:, 0] + X[:, 1] * X[:, 2] + 0.3 * rng.randn(n) > 0).astype(
        np.float64)
    data_path = os.path.join(workdir, "data.npy")
    np.save(data_path, np.column_stack([y, X]))

    base_params = {
        "objective": "binary", "verbose": -1, "num_leaves": 7,
        "tree_learner": "data", "tpu_hist_chunk": 64,
        "bagging_fraction": 0.7, "bagging_freq": 1, "seed": 11,
    }
    ckpt_params = dict(base_params,
                       tpu_checkpoint_dir=ckpt_dir,
                       tpu_checkpoint_interval=1,
                       tpu_checkpoint_keep=50,
                       tpu_heartbeat_dir=hb_dir,
                       tpu_heartbeat_lease_s=5.0)

    def spec(params, out_name):
        return {"data": data_path, "params": params, "rounds": rounds,
                "out": os.path.join(workdir, out_name)}

    stages = []
    result = {"metric": "elastic_smoke", "unit": "ok", "mode": "devices",
              "rounds": rounds, "world_sizes": [4, 4, 2, 1],
              "ranks_killed": [], "stages": stages}

    def run_stage(name, ndev, fault_plan, params, out_name, expect_rcs,
                  retries):
        """Launch (with bounded retry/backoff) until the child exits
        with one of the EXPECTED rcs; anything else is retried, then
        recorded as a failure."""
        last = None
        for attempt in range(retries + 1):
            if attempt:
                time.sleep(args.backoff * attempt)
            rc, wall, out = _run_child(ndev, spec(params, out_name),
                                       args.timeout,
                                       fault_plan=fault_plan)
            last = {"stage": name, "n_devices": ndev, "rc": rc,
                    "wall_seconds": wall, "attempt": attempt + 1}
            if rc in expect_rcs:
                break
            last["unexpected_output_tail"] = out.splitlines()[-6:]
        stages.append(last)
        return last

    # stage 1: cohort at W=4, rank preempted at iteration 5
    st = run_stage("kill_at_w4", 4, {"kill_at_iteration": 5},
                   ckpt_params, "m_w4.txt", {RC_PREEMPTED},
                   args.max_retries)
    if st["rc"] != RC_PREEMPTED:
        result["value"] = 0.0
        result["error"] = "stage kill_at_w4 did not preempt"
        return result
    result["ranks_killed"].append({"stage": "kill_at_w4", "rank": 0,
                                   "iteration": 5})
    cohort = _heartbeat_ages(hb_dir)
    st["cohort_after"] = {str(r): i["status"] for r, i in cohort.items()}

    # stage 2: wedge the next grower dispatch; the collective watchdog
    # must convert the hang into RC_RANK_FAILURE within timeout + grace
    wedge_params = dict(ckpt_params, tpu_collective_timeout_s=3.0)
    t_wedge = time.time()
    st = run_stage("wedge_at_w4", 4,
                   {"wedge": {"collective.call": 120}},
                   wedge_params, "m_wedge.txt", {RC_RANK_FAILURE},
                   args.max_retries)
    if st["rc"] != RC_RANK_FAILURE:
        result["value"] = 0.0
        result["error"] = ("wedged collective did not exit with "
                           f"RC_RANK_FAILURE ({st})")
        return result
    # detection latency: watchdog expiry stamp minus the rank's LAST
    # heartbeat (the supervisor-visible "how long was the rank silently
    # stuck before it was declared dead"); falls back to stage launch
    # when no heartbeat landed
    detect = None
    fail_path = os.path.join(hb_dir, "rank_failure_r0.json")
    if os.path.exists(fail_path):
        with open(fail_path) as fh:
            rec = json.load(fh)
        st["failure_site"] = rec.get("site")
        since = t_wedge
        hb_path = os.path.join(hb_dir, "heartbeat_r0.json")
        if os.path.exists(hb_path):
            try:
                with open(hb_path) as fh:
                    since = max(since, float(json.load(fh)["time"]))
            except (OSError, ValueError, KeyError):
                pass
        detect = round(rec["time"] - since, 2)
    result["detection_latency_s"] = detect
    result["watchdog_rc"] = RC_RANK_FAILURE
    result["ranks_killed"].append({"stage": "wedge_at_w4", "rank": 0,
                                   "site": st.get("failure_site")})
    for p in (fail_path, fail_path.replace(".json", ".stacks.txt")):
        if os.path.exists(p):
            os.unlink(p)  # consumed; later stages must not re-see it

    # stage 3: elastic resume at W'=2, preempted again at iteration 9
    st = run_stage("kill_at_w2", 2, {"kill_at_iteration": 9},
                   ckpt_params, "m_w2.txt", {RC_PREEMPTED},
                   args.max_retries)
    if st["rc"] != RC_PREEMPTED:
        result["value"] = 0.0
        result["error"] = "stage kill_at_w2 did not preempt"
        return result
    result["ranks_killed"].append({"stage": "kill_at_w2", "rank": 0,
                                   "iteration": 9})

    # stage 4: elastic resume at W'=1, run to completion
    st = run_stage("finish_at_w1", 1, None, ckpt_params, "m_final.txt",
                   {0}, args.max_retries)
    if st["rc"] != 0:
        result["value"] = 0.0
        result["error"] = f"final resume failed ({st})"
        return result

    # reference: uninterrupted 1-device run of the same invocation
    st = run_stage("serial_reference", 1, None, base_params, "m_ref.txt",
                   {0}, args.max_retries)
    if st["rc"] != 0:
        result["value"] = 0.0
        result["error"] = "serial reference run failed"
        return result

    final = open(os.path.join(workdir, "m_final.txt")).read()
    ref = open(os.path.join(workdir, "m_ref.txt")).read()
    result["byte_identical"] = final == ref
    result["resume_outcome"] = "completed"
    result["value"] = 1.0 if result["byte_identical"] else 0.0
    if not result["byte_identical"]:
        result["error"] = ("elastically-resumed model differs from the "
                           "uninterrupted serial reference")
    shutil.rmtree(workdir, ignore_errors=True)
    return result


# ---------------------------------------------------------------------------
# processes mode: a real multi-rank cohort (gated on backend capability)
# ---------------------------------------------------------------------------
def _free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


PROC_CHILD = r"""
import json, os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, {repo!r})
import lightgbm_tpu as lgb
from lightgbm_tpu.basic import Dataset
from lightgbm_tpu.parallel.multihost import init_distributed
from lightgbm_tpu.parallel.loader import two_round_load
from lightgbm_tpu.testing import faults

spec = json.loads(os.environ["ELASTIC_CHILD_SPEC"])
nproc = spec["nproc"]
if nproc > 1:
    assert init_distributed()
    rank = jax.process_index()
else:
    rank = 0
inner = two_round_load(spec["data"], max_bin=31, rank=rank,
                       num_machines=nproc, enable_bundle=False)
ds = Dataset._from_inner(inner)
try:
    booster = lgb.train(spec["params"], ds,
                        num_boost_round=spec["rounds"],
                        verbose_eval=False)
except faults.SimulatedPreemption as exc:
    print("CHILD_PREEMPTED", exc.iteration, flush=True)
    sys.exit({rc_preempted})
if rank == 0:
    with open(spec["out"], "w") as fh:
        fh.write(booster.model_to_string())
print("CHILD_OK", rank, flush=True)
"""


def _probe_multiprocess(timeout: float = 180) -> bool:
    probe = (
        "import sys; sys.path.insert(0, %r)\n"
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "from lightgbm_tpu.parallel.multihost import init_distributed\n"
        "assert init_distributed()\n"
        "import jax.numpy as jnp, numpy as np\n"
        "from jax.experimental import multihost_utils\n"
        "out = multihost_utils.process_allgather("
        "jnp.asarray(np.int64(jax.process_index())))\n"
        "assert sorted(np.asarray(out).tolist()) == [0, 1]\n" % REPO)
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["LGBM_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
        env["LGBM_TPU_NUM_MACHINES"] = "2"
        env["LGBM_TPU_RANK"] = str(rank)
        procs.append(subprocess.Popen([sys.executable, "-c", probe],
                                      env=env, stdout=subprocess.DEVNULL,
                                      stderr=subprocess.DEVNULL))
    ok = True
    for p in procs:
        try:
            ok = ok and p.wait(timeout=timeout) == 0
        except subprocess.TimeoutExpired:
            p.kill()
            ok = False
    return ok


def _launch_cohort(nproc: int, spec_for, timeout: float,
                   fault_plans: dict):
    """Launch an nproc-rank jax.distributed cohort; returns
    {rank: (rc, output_tail)}."""
    port = _free_port()
    procs = {}
    for rank in range(nproc):
        env = dict(os.environ)
        env.pop("PYTHONPATH", None)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        env["LGBM_TPU_COORDINATOR"] = f"127.0.0.1:{port}"
        env["LGBM_TPU_NUM_MACHINES"] = str(nproc)
        env["LGBM_TPU_RANK"] = str(rank)
        env["ELASTIC_CHILD_SPEC"] = json.dumps(spec_for(rank))
        env.pop("LGBM_TPU_FAULT_PLAN", None)
        if fault_plans.get(rank):
            env["LGBM_TPU_FAULT_PLAN"] = json.dumps(fault_plans[rank])
        procs[rank] = subprocess.Popen(
            [sys.executable, "-c",
             PROC_CHILD.format(repo=REPO, rc_preempted=RC_PREEMPTED)],
            env=env, cwd=REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True)
    out = {}
    for rank, p in procs.items():
        try:
            text, _ = p.communicate(timeout=timeout)
            out[rank] = (p.returncode, text[-1500:])
        except subprocess.TimeoutExpired:
            p.kill()
            out[rank] = (124, "<timeout>")
    return out


def run_processes_mode(args) -> dict:
    result = {"metric": "elastic_smoke", "unit": "ok",
              "mode": "processes", "rounds": args.rounds}
    if not _probe_multiprocess():
        # a backend limitation, not a failure of the elasticity layer —
        # report it honestly and leave the gate green
        result.update(value=1.0, mode_unavailable=True,
                      reason="multi-process collectives unavailable on "
                             "this jax CPU build (capability probe "
                             "failed); devices mode covers the cycle")
        return result

    import numpy as np
    workdir = tempfile.mkdtemp(prefix="elastic_smoke_proc_")
    ckpt_dir = os.path.join(workdir, "ckpts")
    hb_dir = os.path.join(workdir, "heartbeats")
    rng = np.random.RandomState(0)
    n, f = 800, 5
    X = rng.randn(n, f)
    y = X[:, 0] * 2 + X[:, 1] + 0.1 * rng.randn(n)
    data_path = os.path.join(workdir, "mh.tsv")
    np.savetxt(data_path, np.column_stack([y, X]), delimiter="\t",
               fmt="%.8g")
    params = {"objective": "regression", "tree_learner": "data",
              "num_leaves": 15, "min_data_in_leaf": 3, "verbose": -1,
              "tpu_hist_chunk": 64}
    ckpt_params = dict(params, tpu_checkpoint_dir=ckpt_dir,
                       tpu_checkpoint_interval=1, tpu_checkpoint_keep=50,
                       tpu_heartbeat_dir=hb_dir,
                       tpu_heartbeat_lease_s=5.0,
                       tpu_collective_timeout_s=60.0)

    def spec_for(out_name, p, nproc):
        return lambda rank: {"data": data_path, "params": p,
                             "rounds": args.rounds, "nproc": nproc,
                             "out": os.path.join(workdir, out_name)}

    stages = []
    result["stages"] = stages
    # uninterrupted 2-rank reference (the bitwise baseline: a W-rank
    # cohort's model; cross-process row assembly permutes f32 sums, so
    # serial is not the reference here)
    outs = _launch_cohort(2, spec_for("m_ref.txt", params, 2),
                          args.timeout, {})
    stages.append({"stage": "cohort_reference", "nproc": 2,
                   "rcs": {str(r): rc for r, (rc, _) in outs.items()}})
    if any(rc != 0 for rc, _ in outs.values()):
        result.update(value=0.0, error="reference cohort failed",
                      detail={str(r): t for r, (_, t) in outs.items()})
        return result

    # kill rank 1 at iteration 4; rank 0's watchdog must detect the
    # dead peer inside its next collective and exit RC_RANK_FAILURE
    outs = _launch_cohort(
        2, spec_for("m_killed.txt", ckpt_params, 2), args.timeout,
        {1: {"kill_rank": [1, 4]}})
    stages.append({"stage": "kill_rank1", "nproc": 2,
                   "rcs": {str(r): rc for r, (rc, _) in outs.items()}})
    result["ranks_killed"] = [{"stage": "kill_rank1", "rank": 1,
                               "iteration": 4}]
    if outs[1][0] != RC_PREEMPTED:
        result.update(value=0.0, error="rank 1 did not preempt",
                      detail=outs[1][1])
        return result
    if outs[0][0] != RC_RANK_FAILURE:
        result.update(value=0.0,
                      error="rank 0 did not detect the dead peer "
                            f"(rc {outs[0][0]})", detail=outs[0][1])
        return result
    result["watchdog_rc"] = RC_RANK_FAILURE

    # elastic resume at W'=1: both rank series re-shard into one
    # process. Same PROC_CHILD/two_round_load construction as the
    # cohort (num_machines=1 keeps every row local) so the dataset —
    # bin bounds included — is identical.
    outs = _launch_cohort(
        1, spec_for("m_final.txt", ckpt_params, 1), args.timeout, {})
    stages.append({"stage": "finish_at_1proc",
                   "rcs": {str(r): rc for r, (rc, _) in outs.items()}})
    if outs[0][0] != 0:
        result.update(value=0.0, error="single-process elastic resume "
                                       "failed", detail=outs[0][1])
        return result
    final = open(os.path.join(workdir, "m_final.txt")).read()
    ref = open(os.path.join(workdir, "m_ref.txt")).read()
    # informational, not gating: cross-process row assembly permutes
    # the f32 summation order, so cohort-vs-resumed bitwise equality is
    # not an invariant this layer can promise (the DEVICES-mode cycle
    # carries the byte-identity acceptance)
    result["byte_identical_to_cohort"] = final == ref
    result["resume_outcome"] = "completed"
    result["value"] = 1.0
    shutil.rmtree(workdir, ignore_errors=True)
    return result


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--mode", choices=("devices", "processes"),
                    default="devices")
    ap.add_argument("--rounds", type=int, default=12)
    ap.add_argument("--timeout", type=float,
                    default=float(os.environ.get("ELASTIC_TIMEOUT", 240)))
    ap.add_argument("--max-retries", type=int, default=2)
    ap.add_argument("--backoff", type=float, default=1.0,
                    help="seconds of backoff per retry attempt")
    ap.add_argument("--out", default=os.path.join(REPO,
                                                  "ELASTIC_r01.json"))
    args = ap.parse_args()
    t0 = time.time()
    result = (run_devices_mode(args) if args.mode == "devices"
              else run_processes_mode(args))
    result["wall_seconds"] = round(time.time() - t0, 2)
    with open(args.out, "w") as fh:
        json.dump(result, fh, indent=1)
    print(json.dumps({k: v for k, v in result.items()
                      if k != "stages"}), flush=True)
    return 0 if result.get("value") == 1.0 else 1


if __name__ == "__main__":
    sys.exit(main())
