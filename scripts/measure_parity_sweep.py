"""Throughput sweep for PARITY.md: ours (TPU) vs the reference binary
across row scales, plus a 500-iteration amortized point and a lambdarank
ranking point.

Usage:
  python scripts/measure_parity_sweep.py ours 500000 2000000 ...
  python scripts/measure_parity_sweep.py ref 500000 2000000 ...
  python scripts/measure_parity_sweep.py ours-amortized [rows iters]
  python scripts/measure_parity_sweep.py ref-amortized [rows iters]
  python scripts/measure_parity_sweep.py ours-ranking / ref-ranking

Results accumulate in PARITY_SWEEP.json (merged per key, so ours/ref can
run separately — the reference needs the CPU to itself).
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "scripts"))
OUT = os.path.join(REPO, "PARITY_SWEEP.json")

PARAMS = {"objective": "binary", "metric": "auc", "verbose": -1,
          "max_bin": 63, "num_leaves": 255, "learning_rate": 0.1,
          "min_data_in_leaf": 1, "min_sum_hessian_in_leaf": 100.0}


def _load():
    if os.path.exists(OUT):
        return json.load(open(OUT))
    return {}


def _save(data):
    with open(OUT, "w") as fh:
        json.dump(data, fh, indent=1)
    print(json.dumps(data, indent=1))


def _rank_data(n, f=28, qlen=100, seed=0):
    import numpy as np
    rng = np.random.RandomState(seed)
    X = rng.randn(n, f).astype(np.float32)
    score = X[:, 0] * 1.5 + X[:, 1] - 0.5 * X[:, 2] * X[:, 3]
    nq = n // qlen
    y = np.zeros(n, np.float32)
    for q in range(nq):
        s = slice(q * qlen, (q + 1) * qlen)
        ranks = np.argsort(np.argsort(-(score[s] + rng.randn(qlen))))
        y[s] = np.clip(4 - ranks // 25, 0, 4)
    return X, y, nq, qlen


def ours(rows_list, iters=15):
    import numpy as np

    import bench
    import lightgbm_tpu as lgb
    data = _load()
    for rows in rows_list:
        rows = int(rows)
        X, y = bench.synth_higgs(rows, 28)
        ds = lgb.Dataset(X, y, params=dict(PARAMS))
        ds.construct()
        lgb.train(dict(PARAMS), ds, num_boost_round=1, verbose_eval=False)
        times, last = [], [None]

        def cb(env):
            now = time.time()
            if last[0] is not None:
                times.append(now - last[0])
            last[0] = now

        lgb.train(dict(PARAMS), ds, num_boost_round=iters,
                  verbose_eval=False, callbacks=[cb])
        steady = float(np.mean(times[1:]))
        data.setdefault("ours", {})[str(rows)] = {
            "s_per_iter": round(steady, 4),
            "mrow_iters_per_s": round(rows / steady / 1e6, 3)}
        _save(data)
        del X, y, ds


def ref(rows_list, iters=15):
    from measure_baseline import BUILD_DIR, build_reference
    import numpy as np

    import bench
    exe = build_reference()
    data = _load()
    for rows in rows_list:
        rows = int(rows)
        path = os.path.join(BUILD_DIR, f"bench_{rows}.train")
        if not os.path.exists(path):
            X, y = bench.synth_higgs(rows, 28)
            np.savetxt(path, np.column_stack([y, X]), fmt="%.6g",
                       delimiter="\t")
        binp = path + ".bin"
        if not os.path.exists(binp):
            subprocess.run(
                [exe, f"data={path}", "task=train", "num_trees=1",
                 "max_bin=63", "save_binary=true", "objective=binary",
                 "min_data_in_leaf=1",
                 f"output_model={BUILD_DIR}/warm.txt"],
                check=True, capture_output=True, cwd=BUILD_DIR)
        conf = dict(PARAMS)
        conf.pop("verbose")
        conf.update(task="train", data=binp, num_trees=iters, verbosity=1,
                    output_model=f"{BUILD_DIR}/sweep_model.txt",
                    num_threads=os.cpu_count() or 1)
        args = [exe] + [f"{k}={v}" for k, v in conf.items()]
        t0 = time.time()
        out = subprocess.run(args, check=True, capture_output=True,
                             text=True)
        train_time = time.time() - t0
        for line in out.stdout.splitlines():
            if "seconds elapsed, finished iteration" in line:
                try:
                    train_time = float(line.split()[1])
                except (ValueError, IndexError):
                    pass
        data.setdefault("ref", {})[str(rows)] = {
            "s_per_iter": round(train_time / iters, 4),
            "mrow_iters_per_s": round(rows * iters / train_time / 1e6, 3)}
        _save(data)


def ours_amortized(rows=2_000_000, iters=500):
    import bench
    import lightgbm_tpu as lgb
    X, y = bench.synth_higgs(int(rows), 28)
    ds = lgb.Dataset(X, y, params=dict(PARAMS))
    t0 = time.time()
    ds.construct()
    lgb.train(dict(PARAMS), ds, num_boost_round=int(iters),
              verbose_eval=False)
    wall = time.time() - t0
    data = _load()
    data["ours_amortized"] = {
        "rows": int(rows), "iters": int(iters),
        "wall_s": round(wall, 1),
        "mrow_iters_per_s": round(rows * iters / wall / 1e6, 3)}
    _save(data)


def ref_amortized(rows=2_000_000, iters=500):
    ref([rows], iters=int(iters))
    data = _load()
    data["ref_amortized"] = dict(data["ref"][str(int(rows))],
                                 rows=int(rows), iters=int(iters))
    _save(data)


def ours_ranking(rows=2_000_000, iters=15):
    import numpy as np

    import lightgbm_tpu as lgb
    X, y, nq, qlen = _rank_data(int(rows))
    params = dict(PARAMS, objective="lambdarank", metric="ndcg")
    ds = lgb.Dataset(X, y, params=dict(params))
    ds.set_group(np.full(nq, qlen, np.int32))
    ds.construct()
    lgb.train(dict(params), ds, num_boost_round=1, verbose_eval=False)
    t0 = time.time()
    lgb.train(dict(params), ds, num_boost_round=int(iters),
              verbose_eval=False)
    wall = time.time() - t0
    data = _load()
    data["ours_ranking"] = {
        "rows": int(rows), "iters": int(iters), "wall_s": round(wall, 1),
        "mrow_iters_per_s": round(rows * iters / wall / 1e6, 3)}
    _save(data)


def ref_ranking(rows=2_000_000, iters=15):
    from measure_baseline import BUILD_DIR, build_reference
    import numpy as np
    exe = build_reference()
    rows = int(rows)
    X, y, nq, qlen = _rank_data(rows)
    path = os.path.join(BUILD_DIR, f"rank_{rows}.train")
    if not os.path.exists(path):
        np.savetxt(path, np.column_stack([y, X]), fmt="%.6g",
                   delimiter="\t")
        with open(path + ".query", "w") as fh:
            fh.write("\n".join([str(qlen)] * nq))
    conf = dict(PARAMS)
    conf.pop("verbose")
    conf.update(task="train", objective="lambdarank", metric="ndcg",
                data=path, num_trees=int(iters), verbosity=1,
                output_model=f"{BUILD_DIR}/rank_model.txt",
                num_threads=os.cpu_count() or 1)
    args = [exe] + [f"{k}={v}" for k, v in conf.items()]
    t0 = time.time()
    out = subprocess.run(args, check=True, capture_output=True, text=True)
    train_time = time.time() - t0
    for line in out.stdout.splitlines():
        if "seconds elapsed, finished iteration" in line:
            try:
                train_time = float(line.split()[1])
            except (ValueError, IndexError):
                pass
    data = _load()
    data["ref_ranking"] = {
        "rows": rows, "iters": int(iters),
        "wall_s": round(train_time, 1),
        "mrow_iters_per_s": round(rows * iters / train_time / 1e6, 3)}
    _save(data)


def _predict_fixture(rows=500_000, trees=100):
    """Shared file fixture for the prediction race: OUR model text (the
    formats cross-load, tests/test_reference_parity.py) + a TSV to score.
    Returns (model_path, data_path)."""
    from measure_baseline import BUILD_DIR
    import numpy as np

    import bench
    os.makedirs(BUILD_DIR, exist_ok=True)
    model = os.path.join(BUILD_DIR, f"predict_model_{rows}_{trees}.txt")
    data = os.path.join(BUILD_DIR, f"predict_data_{rows}.tsv")
    if not os.path.exists(data):
        X, y = bench.synth_higgs(rows, 28, seed=7)
        np.savetxt(data, np.column_stack([y, X]), fmt="%.6g",
                   delimiter="\t")
    if not os.path.exists(model):
        import lightgbm_tpu as lgb
        X, y = bench.synth_higgs(rows, 28, seed=7)
        ds = lgb.Dataset(X, y, params=dict(PARAMS))
        booster = lgb.train(dict(PARAMS), ds, num_boost_round=trees,
                            verbose_eval=False)
        booster.save_model(model)
    return model, data


def ours_predict(rows=500_000, trees=100):
    """Prediction throughput through OUR CLI file path (the reference's
    Predictor analogue, predictor.hpp:24-205)."""
    import numpy as np
    model, data_path = _predict_fixture(int(rows), int(trees))
    out_path = os.path.join(os.path.dirname(model), "ours_preds.txt")
    from lightgbm_tpu.cli import main as cli_main
    walls = []
    # 1 cold (jit compile) + 5 warm; the committed figure is the warm
    # MEDIAN (round-4 verdict: the single-shot number swung 2x with
    # relay session noise and the committed artifact landed on the bad
    # end)
    for _ in range(6):
        t0 = time.time()
        cli_main([f"task=predict", f"data={data_path}",
                  f"input_model={model}", f"output_result={out_path}"])
        walls.append(time.time() - t0)
    med = float(np.median(walls[1:]))
    data = _load()
    data["ours_predict"] = {
        "rows": int(rows), "trees": int(trees),
        "wall_s": round(med, 2),
        "wall_s_warm_min": round(min(walls[1:]), 2),
        "wall_s_warm_max": round(max(walls[1:]), 2),
        "wall_s_incl_compile": round(walls[0], 2),
        "mrows_per_s": round(int(rows) / med / 1e6, 3)}
    _save(data)


def ref_predict(rows=500_000, trees=100):
    from measure_baseline import BUILD_DIR, build_reference
    exe = build_reference()
    model, data_path = _predict_fixture(int(rows), int(trees))
    out_path = os.path.join(BUILD_DIR, "ref_preds.txt")
    args = [exe, "task=predict", f"data={data_path}",
            f"input_model={model}", f"output_result={out_path}",
            f"num_threads={os.cpu_count() or 1}"]
    t0 = time.time()
    subprocess.run(args, check=True, capture_output=True, text=True)
    wall = time.time() - t0
    data = _load()
    data["ref_predict"] = {
        "rows": int(rows), "trees": int(trees), "wall_s": round(wall, 2),
        "mrows_per_s": round(int(rows) / wall / 1e6, 3)}
    _save(data)


if __name__ == "__main__":
    mode = sys.argv[1]
    rest = sys.argv[2:]
    if mode == "ours":
        ours([int(float(r)) for r in rest])
    elif mode == "ref":
        ref([int(float(r)) for r in rest])
    elif mode == "ours-amortized":
        ours_amortized(*[int(float(r)) for r in rest])
    elif mode == "ref-amortized":
        ref_amortized(*[int(float(r)) for r in rest])
    elif mode == "ours-ranking":
        ours_ranking(*[int(float(r)) for r in rest])
    elif mode == "ref-ranking":
        ref_ranking(*[int(float(r)) for r in rest])
    elif mode == "ours-predict":
        ours_predict(*[int(float(r)) for r in rest])
    elif mode == "ref-predict":
        ref_predict(*[int(float(r)) for r in rest])
    else:
        raise SystemExit(f"unknown mode {mode}")
