"""Profile one training run on the attached accelerator and write the
kernel-level evidence for the histogram path (VERDICT r1 item 8: prove the
one-hot contraction fuses — no materialized [chunk, F, B] intermediate —
and measure the histogram op's effective bandwidth).

Writes:
  profiles/train_profile.json — top device ops by total time + the
      isolated histogram-op timing with effective HBM GB/s
  profiles/README.md          — human summary
  profiles/trace/             — the raw jax.profiler xplane artifact

Usage: python scripts/profile_train.py
"""
from __future__ import annotations

import collections
import glob
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np  # noqa: E402


def main():
    import jax
    import jax.numpy as jnp

    import lightgbm_tpu as lgb
    from lightgbm_tpu.ops import histogram as hist_ops

    out_dir = os.path.join(REPO, "profiles")
    trace_dir = os.path.join(out_dir, "trace")
    os.makedirs(trace_dir, exist_ok=True)

    # --- IN-TRAINING histogram pass cost --------------------------------
    # measured through the grower itself with fresh gradients each rep:
    # the runtime content-caches identical dispatches, and isolated
    # microbenchmarks compile to different buffer placements than the
    # training loop, so only the in-loop number is honest
    from lightgbm_tpu.learner.grow import FMETA_KEYS, GrowerConfig, make_grower
    N, F, B, K = 524288, 28, 64, 12
    chunk = 32768
    rng = np.random.RandomState(0)
    binned = jnp.asarray(rng.randint(0, B, size=(N, F)).astype(np.uint8))
    fmeta = {"num_bin": jnp.full(F, B, jnp.int32),
             "missing_type": jnp.zeros(F, jnp.int32),
             "default_bin": jnp.zeros(F, jnp.int32),
             "is_categorical": jnp.zeros(F, bool),
             "group": jnp.arange(F, dtype=jnp.int32),
             "offset": jnp.zeros(F, jnp.int32),
             "is_bundled": jnp.zeros(F, bool)}
    cfg = GrowerConfig(num_leaves=255, max_bins=B, chunk=chunk,
                       lambda_l1=0.0, lambda_l2=0.0, min_gain_to_split=0.0,
                       min_data_in_leaf=1, min_sum_hessian_in_leaf=100.0,
                       max_depth=-1, batch_k=K)
    grower = make_grower(cfg)
    ones = jnp.ones(N, jnp.float32)
    fmask = jnp.ones(F, bool)

    def grow_once(i):
        g = (binned[:, i % F] / (B / 2.0) - 1.0).astype(jnp.float32) \
            + 0.3 * jnp.asarray(rng.randn(N).astype(np.float32))
        st = grower(binned, g, ones, ones, fmask, fmeta)
        jax.block_until_ready(st.node_feature)
        return int(st.num_passes)

    grow_once(0)  # compile
    t0 = time.perf_counter()
    passes = sum(grow_once(i) for i in range(1, 4))
    tree_s = (time.perf_counter() - t0) / 3
    hist_s = (time.perf_counter() - t0) / passes  # upper bound per pass
    # bytes one pass MUST move if the one-hot is fused: read binned (u8)
    # + weights + leaf ids + bits once, write [2K, F, B, 3] f32
    essential_bytes = (N * F * 1 + N * 3 * 4 + N * 4 + N * 1
                       + 2 * K * F * B * 3 * 4)
    # bytes if the one-hot were materialized in HBM instead (bf16
    # [chunk, F, B] written + read per chunk, both bf16 passes)
    onehot_bytes = 2 * 2 * N * F * B * 2
    eff_gbs = essential_bytes / hist_s / 1e9

    # --- profiled training iteration ------------------------------------
    X = np.asarray(rng.randn(N, F), np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(np.float32)
    params = {"objective": "binary", "verbose": -1, "max_bin": 63,
              "num_leaves": 255, "min_sum_hessian_in_leaf": 100.0,
              "min_data_in_leaf": 1}
    ds = lgb.Dataset(X, y, params=dict(params))
    warm = lgb.train(dict(params), ds, num_boost_round=2,
                     verbose_eval=False)
    with jax.profiler.trace(trace_dir):
        lgb.train(dict(params), ds, num_boost_round=3, verbose_eval=False)

    from jax.profiler import ProfileData
    pbs = sorted(glob.glob(os.path.join(trace_dir, "**", "*.xplane.pb"),
                           recursive=True))
    tot = collections.Counter()
    cnt = collections.Counter()
    device_total_ns = 0
    for pb in pbs[-1:]:
        pd = ProfileData.from_serialized_xspace(open(pb, "rb").read())
        for plane in pd.planes:
            if "TPU" not in plane.name and "tpu" not in plane.name \
                    and "GPU" not in plane.name:
                continue
            for line in plane.lines:
                for ev in line.events:
                    tot[ev.name] += ev.duration_ns
                    cnt[ev.name] += 1
                    device_total_ns += ev.duration_ns

    top = [{"op": name[:120], "total_ms": round(ns / 1e6, 3),
            "count": cnt[name]} for name, ns in tot.most_common(20)]
    result = {
        "platform": jax.devices()[0].platform,
        "histogram_op": {
            "rows": N, "features": F, "bins": B, "children": 2 * K,
            "chunk": chunk,
            "seconds_per_tree": round(tree_s, 4),
            "passes_per_tree": round(passes / 3, 1),
            "seconds_per_pass_upper_bound": round(hist_s, 6),
            "essential_bytes_per_pass": essential_bytes,
            "effective_gb_per_s_lower_bound": round(eff_gbs, 1),
            "materialized_onehot_bytes": onehot_bytes,
            "onehot_fused": bool(hist_s * eff_gbs * 1e9 < onehot_bytes / 4),
        },
        "top_device_ops": top,
    }
    with open(os.path.join(out_dir, "train_profile.json"), "w") as fh:
        json.dump(result, fh, indent=1)

    fused_note = ("each pass moves far fewer bytes than a materialized "
                  "one-hot would require, so the one-hot feeds the "
                  "contraction without an HBM intermediate"
                  if result["histogram_op"]["onehot_fused"] else
                  "WARNING: timing is consistent with a materialized "
                  "one-hot intermediate")
    with open(os.path.join(out_dir, "README.md"), "w") as fh:
        fh.write(f"""# Training profile ({result['platform']})

Generated by `python scripts/profile_train.py`. All timings are measured
THROUGH the jitted tree grower with fresh inputs per repetition — the
runtime content-caches identical dispatches and isolated microbenchmarks
compile to different buffer placements, so naive op timings mislead.

## Histogram passes (batched_leaves_histogram, in-training)

- {N} rows x {F} features x {B} bins, {2 * K} child histograms/pass
- **{tree_s:.3f} s per 255-leaf tree**, {passes / 3:.0f} data passes/tree
  -> **<= {hist_s * 1e3:.2f} ms/pass** (tree time / passes; includes the
  split scans and commit bookkeeping riding the same loop)
- effective bandwidth >= **{eff_gbs:.0f} GB/s** over the essential
  {essential_bytes / 1e6:.0f} MB/pass (binned matrix + weights + outputs)
- a materialized bf16 one-hot would move >= {onehot_bytes / 1e9:.1f} GB
  per pass; {fused_note}

## Top device ops (3 boosting iterations)

| total ms | count | op |
|---|---|---|
""")
        for row in top[:12]:
            fh.write(f"| {row['total_ms']} | {row['count']} "
                     f"| `{row['op'][:80]}` |\n")
    print(json.dumps(result["histogram_op"]))
    for row in top[:8]:
        print(row)


if __name__ == "__main__":
    main()
