"""Crash-consistent checkpoint/resume for preemptible training.

TPU pods are preemptible; a 50k-iteration boosting run must survive its
host dying between any two iterations. This module provides:

- `atomic_write_bytes` / `atomic_write_text` — tmp file in the target
  directory + flush + fsync + atomic rename (+ directory fsync), so a
  reader never observes a partially-written file. `GBDT.save_model` and
  the snapshot store both write through it.
- `CheckpointManager` — a keep-last-K rotation of versioned full-state
  snapshots, one file per (iteration, process rank). Every snapshot
  carries a self-describing header with a SHA-256 of the payload;
  `load_latest` validates it and silently falls back past corrupt or
  truncated snapshots to the newest good one.
- `config_fingerprint` — a digest of every training-trajectory-relevant
  parameter plus the dataset shape. Resume refuses a snapshot whose
  fingerprint differs, because restoring RNG/score state into a run with
  different semantics would produce a model that is neither the old nor
  the new configuration's.
- array/RNG codecs used by `GBDT.checkpoint_state()` to serialize the
  exact f32 score arrays and numpy RNG states, which is what makes a
  resumed run *bit-identical* to an uninterrupted one (the deterministic
  JAX core does the rest: bagging/GOSS masks are pure functions of
  (seed, iteration)).

Snapshot file layout (`ckpt_00000023.r0`):

    LGBMTPU-CKPT/1 sha256=<hex> bytes=<payload-len>\\n
    <canonical-JSON payload>

The payload holds the model string, the boosting state dict, callback
states (early stopping / recorded evaluations) and the fingerprint.
"""
from __future__ import annotations

import base64
import hashlib
import json
import os
import re
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import log
from .testing import faults

FORMAT_VERSION = 1
_HEADER_RE = re.compile(
    rb"^LGBMTPU-CKPT/(\d+) sha256=([0-9a-f]{64}) bytes=(\d+)\n")

# params that do not change the training trajectory (or are expected to
# legitimately differ between the original and the resumed invocation)
_FINGERPRINT_EXCLUDE = {
    "tpu_checkpoint_dir", "tpu_checkpoint_interval", "tpu_checkpoint_keep",
    # observability never changes the training trajectory: a resumed run
    # may add/move/drop its telemetry sinks freely
    "tpu_telemetry_dir", "tpu_telemetry", "tpu_telemetry_prometheus",
    # ingest mechanics are bit-transparent (streamed/in-memory/cached
    # construction produce identical datasets at any chunk size or
    # landing, tests/test_ingest.py) — a resumed run may change them
    "tpu_ingest", "tpu_ingest_chunk_rows", "tpu_ingest_device_shards",
    # the histogram-merge collective is bit-transparent (scatter and
    # allreduce grow bit-identical trees, tests/test_scatter_reduce.py)
    # — a resumed run may switch schedules
    "tpu_hist_reduce",
    "output_model", "output_result", "input_model", "convert_model",
    "config_file", "machine_list_file", "snapshot_freq", "verbose",
    "metric_freq", "num_iterations", "num_threads", "task",
}


class CheckpointError(log.LightGBMError):
    """A snapshot failed validation (corrupt, truncated, wrong version)."""


# ---------------------------------------------------------------------------
# atomic file IO
# ---------------------------------------------------------------------------
def atomic_write_bytes(path: str, data: bytes) -> None:
    """Write `data` to `path` crash-consistently: a same-directory tmp
    file is written and fsync'd, then atomically renamed over the target
    (so an interrupt leaves either the old file or the new one, never a
    truncated hybrid), then the directory entry is fsync'd."""
    directory = os.path.dirname(os.path.abspath(path))
    faults.inject("checkpoint.write")
    fd, tmp_path = tempfile.mkstemp(
        dir=directory, prefix=os.path.basename(path) + ".tmp.")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
            fh.flush()
            os.fsync(fh.fileno())
        faults.inject("checkpoint.rename")
        os.replace(tmp_path, path)
    except BaseException:
        try:
            os.unlink(tmp_path)
        except OSError:
            pass
        raise
    # persist the rename itself (POSIX: directory fsync); best-effort on
    # filesystems that refuse O_RDONLY directory fds
    try:
        dfd = os.open(directory, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:  # pragma: no cover
        pass


def atomic_write_text(path: str, text: str) -> None:
    atomic_write_bytes(path, text.encode("utf-8"))


# ---------------------------------------------------------------------------
# codecs (JSON-safe encodings of numpy arrays and RNG states)
# ---------------------------------------------------------------------------
def encode_array(arr: np.ndarray) -> Dict[str, Any]:
    arr = np.ascontiguousarray(arr)
    return {"dtype": str(arr.dtype), "shape": list(arr.shape),
            "b64": base64.b64encode(arr.tobytes()).decode("ascii")}


def decode_array(enc: Dict[str, Any]) -> np.ndarray:
    raw = base64.b64decode(enc["b64"])
    return np.frombuffer(raw, dtype=np.dtype(enc["dtype"])).reshape(
        enc["shape"]).copy()


def encode_rng(rng: np.random.RandomState) -> Dict[str, Any]:
    """Serialize the exact Mersenne-Twister position so feature-fraction
    and DART drop sampling continue the original sequence on resume."""
    alg, keys, pos, has_gauss, cached = rng.get_state()
    return {"alg": alg, "keys": encode_array(np.asarray(keys)),
            "pos": int(pos), "has_gauss": int(has_gauss),
            "cached": float(cached)}


def decode_rng(enc: Dict[str, Any]) -> np.random.RandomState:
    rng = np.random.RandomState()
    rng.set_state((enc["alg"], decode_array(enc["keys"]).astype(np.uint32),
                   int(enc["pos"]), int(enc["has_gauss"]),
                   float(enc["cached"])))
    return rng


# ---------------------------------------------------------------------------
# config fingerprint
# ---------------------------------------------------------------------------
def config_fingerprint(raw_params: Dict[str, Any], num_data: int,
                       num_features: int, boosting_type: str) -> str:
    """Digest of the training trajectory's inputs. Two runs with the same
    fingerprint and the same data bytes walk identical iteration
    sequences, so a snapshot from one may seed the other."""
    items = sorted((str(k), str(v)) for k, v in raw_params.items()
                   if str(k) not in _FINGERPRINT_EXCLUDE)
    blob = json.dumps({"params": items, "rows": int(num_data),
                       "features": int(num_features),
                       "boosting": boosting_type,
                       "format": FORMAT_VERSION},
                      sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


# ---------------------------------------------------------------------------
# snapshot store
# ---------------------------------------------------------------------------
class CheckpointManager:
    """Keep-last-K rotation of checksummed snapshots in one directory.

    Files are `ckpt_<iteration:08d>.r<rank>`; under multi-host training
    every process writes its own rank file (scores are row-shard-local)
    and resumes from its own series — `lightgbm_tpu.engine` aligns the
    resume iteration across ranks."""

    _NAME_RE = re.compile(r"^ckpt_(\d{8})\.r(\d+)$")

    def __init__(self, directory: str, keep_last: int = 3,
                 rank: Optional[int] = None):
        self.directory = directory
        self.keep_last = max(1, int(keep_last))
        if rank is None:
            try:
                import jax
                rank = jax.process_index()
            except Exception:  # backend not initialized yet
                rank = 0
        self.rank = int(rank)
        os.makedirs(directory, exist_ok=True)
        self._sweep_stale_tmp()

    def _sweep_stale_tmp(self) -> None:
        """A REAL preemption between mkstemp and rename orphans a tmp
        file; nothing would ever reclaim it (the in-process cleanup only
        runs if the process survives), so each repeatedly-preempted run
        would leak one per kill. Sweep this rank's leftovers at startup
        — the single writer per rank makes any existing tmp stale by
        definition."""
        marker = f".r{self.rank}.tmp."
        for name in os.listdir(self.directory):
            if self._NAME_RE.match(name) is None and marker in name \
                    and name.startswith("ckpt_"):
                try:
                    os.unlink(os.path.join(self.directory, name))
                except OSError:  # pragma: no cover
                    pass

    # -- paths ----------------------------------------------------------
    def path_for(self, iteration: int) -> str:
        return os.path.join(self.directory,
                            f"ckpt_{int(iteration):08d}.r{self.rank}")

    def snapshots(self) -> List[Tuple[int, str]]:
        """(iteration, path) pairs for this rank, oldest first."""
        out = []
        for name in os.listdir(self.directory):
            m = self._NAME_RE.match(name)
            if m and int(m.group(2)) == self.rank:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        return sorted(out)

    def available_iterations(self) -> List[int]:
        return [it for it, _ in self.snapshots()]

    # -- write ----------------------------------------------------------
    def save(self, payload: Dict[str, Any], iteration: int) -> str:
        data = json.dumps(payload, sort_keys=True,
                          separators=(",", ":")).encode("utf-8")
        header = (f"LGBMTPU-CKPT/{FORMAT_VERSION} "
                  f"sha256={hashlib.sha256(data).hexdigest()} "
                  f"bytes={len(data)}\n").encode("ascii")
        path = self.path_for(iteration)
        atomic_write_bytes(path, header + data)
        self._rotate()
        return path

    def _rotate(self) -> None:
        snaps = self.snapshots()
        for _, path in snaps[:-self.keep_last]:
            try:
                os.unlink(path)
            except OSError as exc:  # pragma: no cover
                log.warning("Could not remove old checkpoint %s: %s",
                            path, exc)

    # -- read -----------------------------------------------------------
    def load(self, path: str) -> Dict[str, Any]:
        """Parse + validate one snapshot; raises CheckpointError on any
        corruption (bad header, truncation, checksum or JSON failure)."""
        faults.inject("checkpoint.read")
        with open(path, "rb") as fh:
            blob = fh.read()
        m = _HEADER_RE.match(blob)
        if not m:
            raise CheckpointError(f"{path}: missing/garbled header")
        version, digest, nbytes = (int(m.group(1)), m.group(2).decode(),
                                   int(m.group(3)))
        if version > FORMAT_VERSION:
            raise CheckpointError(
                f"{path}: format version {version} is newer than this "
                f"build supports ({FORMAT_VERSION})")
        payload = blob[m.end():]
        if len(payload) != nbytes:
            raise CheckpointError(
                f"{path}: truncated ({len(payload)} of {nbytes} payload "
                "bytes)")
        if hashlib.sha256(payload).hexdigest() != digest:
            raise CheckpointError(f"{path}: payload checksum mismatch")
        try:
            return json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as exc:
            raise CheckpointError(f"{path}: payload not parseable "
                                  f"({exc})") from exc

    def load_iteration(self, iteration: int) -> Dict[str, Any]:
        return self.load(self.path_for(iteration))

    def load_latest(self) -> Optional[Tuple[Dict[str, Any], str]]:
        """Newest snapshot that validates; corrupt ones are skipped with
        a warning (crash-mid-write leaves either no file or, with a
        non-atomic filesystem, a file this rejects — the previous
        snapshot then restores a slightly older but consistent state)."""
        for iteration, path in reversed(self.snapshots()):
            try:
                return self.load(path), path
            except (CheckpointError, OSError) as exc:
                log.warning("Skipping unusable checkpoint %s (%s); "
                            "falling back to the previous snapshot",
                            path, exc)
        return None
