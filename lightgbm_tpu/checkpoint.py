"""Crash-consistent checkpoint/resume for preemptible training.

TPU pods are preemptible; a 50k-iteration boosting run must survive its
host dying between any two iterations. This module provides:

- `atomic_write_bytes` / `atomic_write_text` — tmp file in the target
  directory + flush + fsync + atomic rename (+ directory fsync), so a
  reader never observes a partially-written file. `GBDT.save_model` and
  the snapshot store both write through it.
- `CheckpointManager` — a keep-last-K rotation of versioned full-state
  snapshots, one file per (iteration, process rank). Every snapshot
  carries a self-describing header with a SHA-256 of the payload;
  `load_latest` validates it and silently falls back past corrupt or
  truncated snapshots to the newest good one.
- `config_fingerprint` — a digest of every training-trajectory-relevant
  parameter plus the dataset shape. Resume refuses a snapshot whose
  fingerprint differs, because restoring RNG/score state into a run with
  different semantics would produce a model that is neither the old nor
  the new configuration's.
- array/RNG codecs used by `GBDT.checkpoint_state()` to serialize the
  exact f32 score arrays and numpy RNG states, which is what makes a
  resumed run *bit-identical* to an uninterrupted one (the deterministic
  JAX core does the rest: bagging/GOSS masks are pure functions of
  (seed, iteration)).

Snapshot file layout (`ckpt_00000023.r0`):

    LGBMTPU-CKPT/1 sha256=<hex> bytes=<payload-len>\\n
    <canonical-JSON payload>

The payload holds the model string, the boosting state dict, callback
states (early stopping / recorded evaluations) and the fingerprint.
"""
from __future__ import annotations

import base64
import hashlib
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import durable, log
from .testing import faults

FORMAT_VERSION = 1
_HEADER_RE = re.compile(
    rb"^LGBMTPU-CKPT/(\d+) sha256=([0-9a-f]{64}) bytes=(\d+)\n")

# params that do not change the training trajectory (or are expected to
# legitimately differ between the original and the resumed invocation)
_FINGERPRINT_EXCLUDE = {
    "tpu_checkpoint_dir", "tpu_checkpoint_interval", "tpu_checkpoint_keep",
    # observability never changes the training trajectory: a resumed run
    # may add/move/drop its telemetry sinks freely
    "tpu_telemetry_dir", "tpu_telemetry", "tpu_telemetry_prometheus",
    # ingest mechanics are bit-transparent (streamed/in-memory/cached
    # construction produce identical datasets at any chunk size or
    # landing, tests/test_ingest.py) — a resumed run may change them
    "tpu_ingest", "tpu_ingest_chunk_rows", "tpu_ingest_device_shards",
    # the histogram-merge collective is bit-transparent (scatter and
    # allreduce grow bit-identical trees, tests/test_scatter_reduce.py)
    # — a resumed run may switch schedules
    "tpu_hist_reduce",
    # sweep membership never changes a model's trajectory: a model
    # trained inside a vmapped sweep is byte-identical to training its
    # config alone (tests/test_sweep.py), and the registry name prefix
    # is serving-side bookkeeping
    "tpu_sweep_size", "tpu_sweep_name_prefix",
    # world-size-elastic resume (ISSUE 11): everything that names or
    # derives from the world size must stay OUT of the fingerprint —
    # a snapshot taken at W ranks must be accepted at W' ranks (trees
    # are bit-identical across device counts; scores re-shard through
    # restore). The watchdog/heartbeat knobs never change the
    # trajectory either; a resumed run may re-arm them freely
    "num_machines", "num_machine", "local_listen_port", "local_port",
    "time_out", "machine_list_filename",
    "tpu_collective_timeout_s", "tpu_heartbeat_dir",
    "tpu_heartbeat_lease_s", "tpu_elastic_resume",
    # serving-side admission/overload knobs (ISSUE 12) shape request
    # handling, never the training trajectory — and the compile cache
    # only changes WHERE programs load from, not what they compute
    "tpu_serving_max_queue", "tpu_serving_max_inflight",
    "tpu_serving_deadline_ms", "tpu_serving_model_qps",
    "tpu_serving_breaker_failures", "tpu_serving_breaker_reset_s",
    "tpu_serving_budget_mb", "tpu_compile_cache_dir",
    # predict-path layout/batching knobs (ISSUE 13 config-hygiene
    # sweep): bucket ladders, micro-batching, warmup, and the quantized
    # SERVING stacks change how predictions are dispatched, never how
    # trees are grown (quantized layouts are build-time derived from
    # the exact f32 forest; split decisions stay bit-exact) — a resumed
    # run may reshape its serving tier freely
    "tpu_predict_cache", "tpu_predict_bucket_min", "tpu_predict_chunk",
    "tpu_predict_pipeline", "tpu_predict_quantize",
    "tpu_predict_quantize_tol", "tpu_predict_warmup_rows",
    "tpu_predict_micro_batch", "tpu_predict_micro_batch_window_ms",
    # the train-side quantize GATE (ISSUE 20) only decides whether a
    # lossy config is ACCEPTED at setup; once training is running the
    # tolerance never touches the trajectory — a resumed run may
    # tighten or relax it freely (the MODE itself is fingerprinted
    # below)
    "tpu_hist_quantize_tol",
    # exported-forest artifacts (ISSUE 16): exporting serializes the
    # already-trained forest for serving replicas — which layouts and
    # buckets get packed never feeds back into training numerics
    "tpu_export_dir", "tpu_export_layouts", "tpu_export_buckets",
    # durable-IO retry policy (ISSUE 18, lightgbm_tpu/durable.py):
    # retries/backoff/deadline decide whether a run SURVIVES writing
    # its state, never what that state is — a resumed run may harden
    # or relax its storage policy freely
    "tpu_io_retries", "tpu_io_backoff_s", "tpu_io_deadline_s",
    "output_model", "output_result", "input_model", "convert_model",
    "config_file", "machine_list_file", "snapshot_freq", "verbose",
    "metric_freq", "num_iterations", "num_threads", "task",
}

# tpu_* params that DELIBERATELY participate in the fingerprint: each
# one changes the training trajectory (numerics, grow order, or failure
# behavior), so resume must refuse a snapshot taken under a different
# value. `config_fingerprint` hashes everything not excluded — this set
# is the EXPLICIT record of that decision for the tpu_* namespace, and
# graftlint's config-hygiene rule cross-checks it against config.py:
# every tpu_* field must appear in exactly one of the two sets, so a
# new knob cannot ship with its resume semantics undecided.
_FINGERPRINT_INCLUDED = {
    # histogram numerics/order: precision, bf16 accumulation, batched
    # grow order, compaction and subtraction reshape the f32 summation
    # tree (subtract/compact are bit-identical TODAY, but that identity
    # is a test-enforced property of the current kernels, not a
    # contract — keep them fingerprinted so resume never blends paths)
    "tpu_hist_chunk", "tpu_double_precision", "tpu_batch_k",
    "tpu_hist_bf16", "tpu_hist_subtract", "tpu_hist_compact",
    "tpu_compact_threshold", "tpu_hist_pallas",
    # quantized-gradient training (ISSUE 20): stochastically-rounded
    # integer gradients change every histogram sum and therefore every
    # split — resume must never blend a quantized trajectory with an
    # f32 one (the gate TOLERANCE is excluded above)
    "tpu_hist_quantize",
    # nonfinite guard aborts the trajectory instead of continuing it
    "tpu_guard_nonfinite",
    # piecewise-linear leaves: the per-leaf design width changes every
    # fitted coefficient table (linear_tree/linear_lambda are non-tpu
    # params and hash automatically)
    "tpu_linear_max_features",
}

assert not (_FINGERPRINT_INCLUDED & _FINGERPRINT_EXCLUDE), \
    "a tpu_* param cannot be both fingerprint-included and excluded"


class CheckpointError(log.LightGBMError):
    """A snapshot failed validation (corrupt, truncated, wrong version)."""


# ---------------------------------------------------------------------------
# atomic file IO — the implementation moved to lightgbm_tpu/durable.py
# (ISSUE 18), which adds retry/backoff/deadline and the criticality
# policy on top of the same tmp+fsync+rename publish. These wrappers
# stay as the historical import surface; the "checkpoint.*" injection
# sites keep their names (`checkpoint.write` / `checkpoint.rename`).
# ---------------------------------------------------------------------------
def atomic_write_bytes(path: str, data: bytes, site: str = "checkpoint",
                       **kw) -> None:
    """Write `data` to `path` crash-consistently (same-dir tmp + fsync +
    atomic rename + directory fsync), retrying transient storage faults
    per the durable-IO policy; raises `durable.DurableWriteError` when
    the budget is exhausted."""
    durable.atomic_write_bytes(path, data, site=site, **kw)


def atomic_write_text(path: str, text: str, site: str = "checkpoint",
                      **kw) -> None:
    durable.atomic_write_text(path, text, site=site, **kw)


# ---------------------------------------------------------------------------
# codecs (JSON-safe encodings of numpy arrays and RNG states)
# ---------------------------------------------------------------------------
def encode_array(arr: np.ndarray) -> Dict[str, Any]:
    arr = np.ascontiguousarray(arr)
    return {"dtype": str(arr.dtype), "shape": list(arr.shape),
            "b64": base64.b64encode(arr.tobytes()).decode("ascii")}


def decode_array(enc: Dict[str, Any]) -> np.ndarray:
    raw = base64.b64decode(enc["b64"])
    return np.frombuffer(raw, dtype=np.dtype(enc["dtype"])).reshape(
        enc["shape"]).copy()


def encode_rng(rng: np.random.RandomState) -> Dict[str, Any]:
    """Serialize the exact Mersenne-Twister position so feature-fraction
    and DART drop sampling continue the original sequence on resume."""
    alg, keys, pos, has_gauss, cached = rng.get_state()
    return {"alg": alg, "keys": encode_array(np.asarray(keys)),
            "pos": int(pos), "has_gauss": int(has_gauss),
            "cached": float(cached)}


def decode_rng(enc: Dict[str, Any]) -> np.random.RandomState:
    rng = np.random.RandomState()
    rng.set_state((enc["alg"], decode_array(enc["keys"]).astype(np.uint32),
                   int(enc["pos"]), int(enc["has_gauss"]),
                   float(enc["cached"])))
    return rng


# ---------------------------------------------------------------------------
# config fingerprint
# ---------------------------------------------------------------------------
def config_fingerprint(raw_params: Dict[str, Any], num_data: int,
                       num_features: int, boosting_type: str) -> str:
    """Digest of the training trajectory's inputs. Two runs with the same
    fingerprint and the same data bytes walk identical iteration
    sequences, so a snapshot from one may seed the other."""
    items = sorted((str(k), str(v)) for k, v in raw_params.items()
                   if str(k) not in _FINGERPRINT_EXCLUDE)
    blob = json.dumps({"params": items, "rows": int(num_data),
                       "features": int(num_features),
                       "boosting": boosting_type,
                       "format": FORMAT_VERSION},
                      sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()


# ---------------------------------------------------------------------------
# snapshot store
# ---------------------------------------------------------------------------
class CheckpointManager:
    """Keep-last-K rotation of checksummed snapshots in one directory.

    Files are `ckpt_<iteration:08d>.r<rank>`; under multi-host training
    every process writes its own rank file (scores are row-shard-local)
    and resumes from its own series — `lightgbm_tpu.engine` aligns the
    resume iteration across ranks."""

    _NAME_RE = re.compile(r"^ckpt_(\d{8})\.r(\d+)$")

    def __init__(self, directory: str, keep_last: int = 3,
                 rank: Optional[int] = None):
        self.directory = directory
        self.keep_last = max(1, int(keep_last))
        if rank is None:
            try:
                import jax
                rank = jax.process_index()
            except Exception:  # backend not initialized yet
                rank = 0
        self.rank = int(rank)
        os.makedirs(directory, exist_ok=True)
        self._sweep_stale_tmp()

    def _sweep_stale_tmp(self) -> None:
        """A REAL preemption between mkstemp and rename orphans a tmp
        file; nothing would ever reclaim it (the in-process cleanup only
        runs if the process survives), so each repeatedly-preempted run
        would leak one per kill. Sweep this rank's leftovers at startup
        — the single writer per rank makes any existing tmp stale by
        definition."""
        marker = f".r{self.rank}.tmp."
        for name in os.listdir(self.directory):
            if self._NAME_RE.match(name) is None and marker in name \
                    and name.startswith("ckpt_"):
                try:
                    os.unlink(os.path.join(self.directory, name))
                except OSError:  # pragma: no cover
                    pass

    # -- paths ----------------------------------------------------------
    def path_for(self, iteration: int) -> str:
        return os.path.join(self.directory,
                            f"ckpt_{int(iteration):08d}.r{self.rank}")

    def snapshots(self) -> List[Tuple[int, str]]:
        """(iteration, path) pairs for this rank, oldest first."""
        out = []
        for name in os.listdir(self.directory):
            m = self._NAME_RE.match(name)
            if m and int(m.group(2)) == self.rank:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, name)))
        return sorted(out)

    def available_iterations(self) -> List[int]:
        return [it for it, _ in self.snapshots()]

    # -- cross-rank discovery (world-size-elastic resume) ---------------
    def snapshots_all_ranks(self) -> Dict[int, List[Tuple[int, str]]]:
        """{rank: [(iteration, path), ...]} across EVERY rank series in
        the directory — the elastic-resume view: a shrunken cohort must
        read the dead ranks' row shards, a grown cohort's new ranks
        have no series of their own at all."""
        out: Dict[int, List[Tuple[int, str]]] = {}
        for name in os.listdir(self.directory):
            m = self._NAME_RE.match(name)
            if m:
                out.setdefault(int(m.group(2)), []).append(
                    (int(m.group(1)), os.path.join(self.directory, name)))
        for files in out.values():
            files.sort()
        return out

    def load_latest_any_rank(self) -> Optional[Tuple[Dict[str, Any], str]]:
        """Newest validating snapshot across ALL rank series (own rank
        preferred at equal iteration, then the lowest rank) — the
        starting point when THIS rank has no series (a cohort grown
        past the original world size)."""
        candidates: List[Tuple[int, int, str]] = []
        for rank, files in self.snapshots_all_ranks().items():
            for iteration, path in files:
                # own rank sorts first at equal iteration
                candidates.append(
                    (iteration, 0 if rank == self.rank else rank + 1, path))
        for iteration, _, path in sorted(candidates,
                                         key=lambda t: (-t[0], t[1])):
            try:
                return self.load(path), path
            except (CheckpointError, OSError) as exc:
                log.warning("Skipping unusable checkpoint %s (%s)",
                            path, exc)
        return None

    def load_world_iteration(self, iteration: int,
                             expected_ranks: Optional[int] = None
                             ) -> Dict[int, Dict[str, Any]]:
        """Every rank's VALIDATING payload at `iteration`; corrupt or
        truncated files are skipped (a rank that died mid-write is the
        expected producer of those). With `expected_ranks` (the
        snapshot's recorded world size), an incomplete set raises —
        reassembling a partial world would silently drop rows — and
        the error names which files were absent vs unreadable."""
        out: Dict[int, Dict[str, Any]] = {}
        bad: Dict[int, str] = {}
        for rank, files in self.snapshots_all_ranks().items():
            for it, path in files:
                if it == int(iteration):
                    try:
                        out[rank] = self.load(path)
                    except (CheckpointError, OSError) as exc:
                        bad[rank] = str(exc)
        if expected_ranks is not None:
            missing = [r for r in range(int(expected_ranks))
                       if r not in out]
            if missing:
                raise CheckpointError(
                    "Elastic resume needs every original rank's snapshot "
                    "at iteration %d, but rank file(s) %s are missing "
                    "from %s%s (the checkpoint directory must be shared "
                    "storage reachable by the resuming cohort)"
                    % (int(iteration), missing, self.directory,
                       "; unreadable: %s" % bad if bad else ""))
            # drop ranks BEYOND the recorded world: an earlier larger
            # cohort's leftover files (never rotated once their ranks
            # died) would otherwise pollute the reassembly with stale
            # overlapping row ownership
            out = {r: p for r, p in out.items()
                   if r < int(expected_ranks)}
        return out

    def latest_complete_iteration(
            self, expected_ranks: int, before: Optional[int] = None
    ) -> Optional[Tuple[int, Dict[int, Dict[str, Any]]]]:
        """Newest iteration at which EVERY rank 0..expected_ranks-1 has
        a validating snapshot (optionally capped at `before`, exclusive)
        — the elastic-resume fallback when a dying rank left the series
        skewed: rank 0 wrote iteration k but rank 1 only reached k-1,
        so k-1 is the newest state the whole world can reassemble.
        Returns (iteration, {rank: payload}) — the validated payloads
        ride along so callers don't decode every snapshot twice."""
        by_rank = self.snapshots_all_ranks()
        ranks = range(int(expected_ranks))
        if any(r not in by_rank for r in ranks):
            return None
        common = set.intersection(
            *(set(it for it, _ in by_rank[r]) for r in ranks))
        for it in sorted(common, reverse=True):
            if before is not None and it >= int(before):
                continue
            payloads = {}
            try:
                for r in ranks:
                    payloads[r] = self.load(dict(by_rank[r])[it])
            except (CheckpointError, OSError):
                continue
            return it, payloads
        return None

    # -- write ----------------------------------------------------------
    def save(self, payload: Dict[str, Any], iteration: int) -> str:
        """Durably publish one snapshot, THEN rotate. The ordering is
        the crash-safety invariant: old snapshots are deleted only
        after the new one is fully durable (fsync'd + renamed), so a
        save that dies anywhere mid-write leaves the previous newest
        snapshot loadable. On ENOSPC the oldest prunable snapshot is
        evicted (never the newest durable one) and the write retried
        once — the escape hatch for a checkpoint directory that filled
        up under keep_last pressure."""
        data = json.dumps(payload, sort_keys=True,
                          separators=(",", ":")).encode("utf-8")
        header = (f"LGBMTPU-CKPT/{FORMAT_VERSION} "
                  f"sha256={hashlib.sha256(data).hexdigest()} "
                  f"bytes={len(data)}\n").encode("ascii")
        path = self.path_for(iteration)
        durable.atomic_write_bytes(path, header + data, site="checkpoint",
                                   on_enospc=self._evict_for_space)
        self._rotate()
        return path

    def _evict_for_space(self) -> bool:
        """ENOSPC escape hatch: free the OLDEST prunable snapshot of
        this rank's series. The newest durable snapshot is never a
        candidate — it is the state a preempted run resumes from."""
        snaps = self.snapshots()
        for _, path in snaps[:-1]:
            try:
                os.unlink(path)
            except OSError:  # already gone / unremovable: try the next
                continue
            log.warning("Checkpoint save hit ENOSPC; evicted oldest "
                        "snapshot %s to retry", path)
            return True
        return False

    def _rotate(self) -> None:
        # runs ONLY after the new snapshot is fully durable (see save);
        # the injection site lets tests kill a run in the write->rotate
        # window and prove both neighbors stay loadable
        faults.inject("checkpoint.rotate")
        snaps = self.snapshots()
        for _, path in snaps[:-self.keep_last]:
            try:
                os.unlink(path)
            except OSError as exc:  # pragma: no cover
                log.warning("Could not remove old checkpoint %s: %s",
                            path, exc)

    # -- read -----------------------------------------------------------
    def load(self, path: str) -> Dict[str, Any]:
        """Parse + validate one snapshot; raises CheckpointError on any
        corruption (bad header, truncation, checksum or JSON failure)."""
        faults.inject("checkpoint.read")
        with open(path, "rb") as fh:
            blob = fh.read()
        m = _HEADER_RE.match(blob)
        if not m:
            raise CheckpointError(f"{path}: missing/garbled header")
        version, digest, nbytes = (int(m.group(1)), m.group(2).decode(),
                                   int(m.group(3)))
        if version > FORMAT_VERSION:
            raise CheckpointError(
                f"{path}: format version {version} is newer than this "
                f"build supports ({FORMAT_VERSION})")
        payload = blob[m.end():]
        if len(payload) != nbytes:
            raise CheckpointError(
                f"{path}: truncated ({len(payload)} of {nbytes} payload "
                "bytes)")
        if hashlib.sha256(payload).hexdigest() != digest:
            raise CheckpointError(f"{path}: payload checksum mismatch")
        try:
            return json.loads(payload.decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as exc:
            raise CheckpointError(f"{path}: payload not parseable "
                                  f"({exc})") from exc

    def load_iteration(self, iteration: int) -> Dict[str, Any]:
        return self.load(self.path_for(iteration))

    def load_latest(self) -> Optional[Tuple[Dict[str, Any], str]]:
        """Newest snapshot that validates; corrupt ones are QUARANTINED
        (renamed `*.corrupt`, pruned keep-last-1) and skipped with a
        warning — crash-mid-write leaves either no file or, with a
        non-atomic filesystem, a file this rejects; the previous
        snapshot then restores a slightly older but consistent state,
        and the quarantine keeps the bad bytes from being re-validated
        on every later resume."""
        for iteration, path in reversed(self.snapshots()):
            try:
                return self.load(path), path
            except (CheckpointError, OSError) as exc:
                log.warning("Skipping unusable checkpoint %s (%s); "
                            "falling back to the previous snapshot",
                            path, exc)
                if isinstance(exc, CheckpointError):
                    durable.quarantine(path, reason="checkpoint failed "
                                       "validation")
        return None


# ---------------------------------------------------------------------------
# world-size-elastic reassembly (ISSUE 11)
# ---------------------------------------------------------------------------
def payload_world(payload: Dict[str, Any]) -> Dict[str, Any]:
    """The world-size record a snapshot was taken under. Pre-elastic
    snapshots carry none — treat them as single-process (their scores
    cover the whole dataset, which is exactly what processes=1 means)."""
    return dict(payload.get("state", {}).get("world")
                or {"processes": 1, "rank": 0})


def elastic_local_state(payloads: Dict[int, Dict[str, Any]],
                        new_row_index: np.ndarray,
                        base_rank: Optional[int] = None) -> Dict[str, Any]:
    """Re-shard a W-rank snapshot set onto ONE rank of a W'-rank world.

    Every original rank's state carries its real-row score block plus
    the global row indices those rows came from (`row_index`, recorded
    by GBDT.checkpoint_state under multi-process training; implicit
    arange for processes=1). The blocks concatenate into the exact
    global [k, n_global] f32 score matrix, from which the new rank's
    partition (`new_row_index`) is sliced — per-row f32 values move
    untouched, so the elastically-resumed run stays byte-identical to
    an uninterrupted one.

    Returns a state dict (the `payload["state"]` shape) for the new
    rank: the base rank's state with `score`/`num_data`/`row_index`
    replaced. Host-RNG and callback state are replicated across ranks
    by construction, so any base rank is equivalent; `base_rank`
    defaults to the lowest available."""
    if not payloads:
        raise CheckpointError("Elastic resume: no snapshot payloads")
    ranks = sorted(payloads)
    if base_rank is None or base_rank not in payloads:
        base_rank = ranks[0]
    base = payloads[base_rank]

    blocks = []       # (global_indices, [k, n_local] real-row scores)
    n_global = 0
    k = None
    for rank in ranks:
        state = payloads[rank].get("state", {})
        if "num_data" not in state:
            raise CheckpointError(
                "Elastic resume: rank %d's snapshot predates world-size "
                "metadata (written by an older build); it can only be "
                "restored at its original world size" % rank)
        n_local = int(state["num_data"])
        score = decode_array(state["score"])
        if k is None:
            k = score.shape[0]
        elif score.shape[0] != k:
            raise CheckpointError(
                "Elastic resume: rank %d's score has %d classes, "
                "expected %d" % (rank, score.shape[0], k))
        if "row_index" in state:
            gidx = decode_array(state["row_index"]).astype(np.int64)
            if gidx.shape[0] != n_local:
                raise CheckpointError(
                    "Elastic resume: rank %d records %d row indices for "
                    "%d rows" % (rank, gidx.shape[0], n_local))
        elif len(ranks) == 1:
            gidx = np.arange(n_local, dtype=np.int64)
        else:
            raise CheckpointError(
                "Elastic resume: rank %d's snapshot carries no global "
                "row indices (pre-partitioned data files record none); "
                "restore at the original world size instead" % rank)
        blocks.append((gidx, score[:, :n_local]))
        n_global = max(n_global, int(gidx.max()) + 1 if n_local else 0)

    global_score = np.zeros((k, n_global), np.float32)
    covered = np.zeros(n_global, bool)
    for gidx, score in blocks:
        if covered[gidx].any():
            raise CheckpointError(
                "Elastic resume: overlapping row ownership across rank "
                "snapshots — the series mixes incompatible runs")
        global_score[:, gidx] = score
        covered[gidx] = True
    if not covered.all():
        raise CheckpointError(
            "Elastic resume: rank snapshots cover %d of %d global rows "
            "— a rank series is missing or stale"
            % (int(covered.sum()), n_global))

    new_idx = np.asarray(new_row_index, np.int64)
    if new_idx.size and (new_idx.min() < 0 or new_idx.max() >= n_global):
        raise CheckpointError(
            "Elastic resume: the resuming rank's partition indexes row "
            "%d but the snapshot world only covers %d rows — the "
            "dataset differs from the checkpointed run"
            % (int(new_idx.max()), n_global))
    state = dict(base["state"])
    state["score"] = encode_array(
        np.ascontiguousarray(global_score[:, new_idx]))
    state["num_data"] = int(new_idx.size)
    state["row_index"] = encode_array(new_idx)
    return state
