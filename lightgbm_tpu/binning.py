"""Per-feature value -> bin discretization (host side, numpy).

Behavioral re-implementation of the reference BinMapper
(`include/LightGBM/bin.h:60-208`, `src/io/bin.cpp:70-330`):

- numerical features: greedy equal-count binning over sampled distinct
  values (`GreedyFindBin`, bin.cpp:70-140), with zero always given its own
  bin (`FindBinWithZeroAsOneBin`, bin.cpp:141-198);
- missing handling: MissingType None / Zero / NaN (bin.h:20-24); the NaN
  bin, when present, is the LAST bin (bin.cpp:270-274);
- categorical features: most-frequent-first bin assignment covering 99% of
  mass, negatives -> NaN (bin.cpp:292-330);
- `default_bin` is the bin of value 0.0 (bin.cpp:331-340); histograms on
  device are built complete, so the reference's sparse default-bin-skip +
  `FixHistogram` reconstruction (dataset.cpp:747-767) is unnecessary here.

The binned matrix produced from these mappers is the HBM-resident tensor
all device kernels operate on.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from . import log

# Missing types (reference: bin.h:20-24)
MISSING_NONE = 0
MISSING_ZERO = 1
MISSING_NAN = 2

BIN_NUMERICAL = 0
BIN_CATEGORICAL = 1

# Reference: kZeroAsMissingValueRange / kZeroThreshold analogue (bin.h:15-18)
K_ZERO_RANGE = 1e-35
K_SPARSE_THRESHOLD_DEFAULT = 0.8


def _greedy_find_bin_seq(distinct_values: np.ndarray, counts: np.ndarray,
                         max_bin: int, total_cnt: int,
                         min_data_in_bin: int) -> List[float]:
    """Value-by-value form of the equal-count greedy binning — the
    direct transcription of the algorithm, kept as the equality oracle
    for the bin-by-bin fast path below (tests/test_binning.py)."""
    num_distinct = len(distinct_values)
    bin_upper_bound: List[float] = []
    if max_bin <= 0:
        log.fatal("max_bin must be > 0")
    if num_distinct <= max_bin:
        cur_cnt = 0
        for i in range(num_distinct - 1):
            cur_cnt += int(counts[i])
            if cur_cnt >= min_data_in_bin:
                bin_upper_bound.append((distinct_values[i] + distinct_values[i + 1]) / 2.0)
                cur_cnt = 0
        bin_upper_bound.append(np.inf)
        return bin_upper_bound

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    # values with very large counts get dedicated bins
    is_big = counts >= mean_bin_size
    rest_bin_cnt = max_bin - int(is_big.sum())
    rest_sample_cnt = total_cnt - int(counts[is_big].sum())
    mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)

    upper_bounds = [np.inf] * max_bin
    lower_bounds = [np.inf] * max_bin
    bin_cnt = 0
    lower_bounds[0] = distinct_values[0]
    cur_cnt = 0
    for i in range(num_distinct - 1):
        if not is_big[i]:
            rest_sample_cnt -= int(counts[i])
        cur_cnt += int(counts[i])
        if (is_big[i] or cur_cnt >= mean_bin_size or
                (is_big[i + 1] and cur_cnt >= max(1.0, mean_bin_size * 0.5))):
            upper_bounds[bin_cnt] = distinct_values[i]
            bin_cnt += 1
            lower_bounds[bin_cnt] = distinct_values[i + 1]
            if bin_cnt >= max_bin - 1:
                break
            cur_cnt = 0
            if not is_big[i]:
                rest_bin_cnt -= 1
                mean_bin_size = rest_sample_cnt / max(rest_bin_cnt, 1)
    bin_cnt += 1
    out = []
    for i in range(bin_cnt - 1):
        out.append((upper_bounds[i] + lower_bounds[i + 1]) / 2.0)
    out.append(np.inf)
    return out


def _greedy_find_bin(distinct_values: np.ndarray, counts: np.ndarray,
                     max_bin: int, total_cnt: int, min_data_in_bin: int) -> List[float]:
    """Equal-count greedy binning (reference: GreedyFindBin, bin.cpp:70-140).

    Returns bin upper bounds; last bound is +inf.

    Fast path: the value loop closes a bin only when a cumulative-count
    threshold or a dedicated-bin ("big" value) boundary is hit, so the
    closure indices can be found bin-by-bin with searchsorted/bisect on
    precomputed prefix sums — O(bins log n) instead of a python loop
    over up to sample_cnt distinct values (the loop dominated dataset
    construction at 2M rows: 3.2 s of the 8.3 s total). Each searchsorted
    landing is verified with exact integer arithmetic so the result is
    bit-identical to the sequential form (tests/test_binning.py fuzzes
    the equivalence).
    """
    num_distinct = len(distinct_values)
    if max_bin <= 0:
        log.fatal("max_bin must be > 0")
    if num_distinct <= max_bin:
        # small-distinct branch: the loop is <= max_bin steps already
        return _greedy_find_bin_seq(distinct_values, counts, max_bin,
                                    total_cnt, min_data_in_bin)

    if min_data_in_bin > 0:
        max_bin = max(1, min(max_bin, total_cnt // min_data_in_bin))
    mean_bin_size = total_cnt / max_bin
    is_big = counts >= mean_bin_size
    rest_bin_cnt = max_bin - int(is_big.sum())
    rest_all = total_cnt - int(counts[is_big].sum())
    mean_bin_size = rest_all / max(rest_bin_cnt, 1)

    c64 = counts.astype(np.int64)
    C = np.cumsum(c64)                       # C[i] = counts[0..i]
    # float view for the searchsorted keys: a float key against the
    # int64 array makes numpy promote (copy) the WHOLE array per call
    # (~0.16 ms at 200k distinct, x~124 calls per feature)
    Cf = C.astype(np.float64)
    Cnb = np.cumsum(np.where(is_big, 0, c64))  # non-big prefix
    big_idx = np.flatnonzero(is_big).tolist()  # sorted python list
    # candidates for the "next value is big" closure rule
    bigm1 = [b - 1 for b in big_idx]

    def cum(i, s):                           # counts[s..i], exact ints
        return int(C[i]) - (int(C[s - 1]) if s > 0 else 0)

    upper_bounds = [np.inf] * max_bin
    lower_bounds = [np.inf] * max_bin
    bin_cnt = 0
    lower_bounds[0] = distinct_values[0]
    s = 0                                    # current segment start
    last = num_distinct - 2                  # loop bound of the seq form
    import bisect
    while s <= last and bin_cnt < max_bin - 1:
        base = int(C[s - 1]) if s > 0 else 0
        # rule A: first big value in [s, last]
        a = bisect.bisect_left(big_idx, s)
        iA = big_idx[a] if a < len(big_idx) else num_distinct
        # rule B: first i with counts[s..i] >= mean_bin_size. Clamp to s:
        # once the remaining non-big mass is exhausted mean_bin_size is
        # 0 and searchsorted(C, base+0) resolves BEFORE the segment
        # start (the sequential form closes at s in that state) — an
        # unclamped iB re-closed the previous bin and emitted duplicate
        # bounds (round-5 review finding, fuzz-reproduced)
        iB = int(np.searchsorted(Cf, base + mean_bin_size, side="left"))
        while iB - 1 >= s and cum(iB - 1, s) >= mean_bin_size:
            iB -= 1
        while iB < num_distinct and cum(min(iB, num_distinct - 1), s) < mean_bin_size:
            iB += 1
        iB = max(iB, s)
        # rule C: first i with is_big[i+1] and counts[s..i] >= half-mean
        half = max(1.0, mean_bin_size * 0.5)
        i0 = int(np.searchsorted(Cf, base + half, side="left"))
        while i0 - 1 >= s and cum(i0 - 1, s) >= half:
            i0 -= 1
        while i0 < num_distinct and cum(min(i0, num_distinct - 1), s) < half:
            i0 += 1
        i0 = max(i0, s)
        cpos = bisect.bisect_left(bigm1, max(s, i0))
        iC = bigm1[cpos] if cpos < len(bigm1) else num_distinct
        i = min(iA, iB, iC)
        if i > last:
            break
        upper_bounds[bin_cnt] = distinct_values[i]
        bin_cnt += 1
        lower_bounds[bin_cnt] = distinct_values[i + 1]
        if not is_big[i]:
            rest_bin_cnt -= 1
            rest_sample = rest_all - int(Cnb[i])
            mean_bin_size = rest_sample / max(rest_bin_cnt, 1)
            # the new mean can reclassify nothing (is_big is fixed), so
            # only the thresholds move — state is fully captured here
        s = i + 1
    bin_cnt += 1
    out = []
    for i in range(bin_cnt - 1):
        out.append((upper_bounds[i] + lower_bounds[i + 1]) / 2.0)
    out.append(np.inf)
    return out


def _find_bin_with_zero_as_one_bin(distinct_values: np.ndarray, counts: np.ndarray,
                                   max_bin: int, total_sample_cnt: int,
                                   min_data_in_bin: int) -> List[float]:
    """Zero always gets a dedicated bin (reference: bin.cpp:141-198)."""
    left_mask = distinct_values <= -K_ZERO_RANGE
    right_mask = distinct_values > K_ZERO_RANGE
    zero_mask = ~left_mask & ~right_mask
    left_cnt_data = int(counts[left_mask].sum())
    cnt_zero = int(counts[zero_mask].sum())
    right_cnt_data = int(counts[right_mask].sum())

    left_cnt = int(np.argmax(distinct_values > -K_ZERO_RANGE)) \
        if (distinct_values > -K_ZERO_RANGE).any() else len(distinct_values)

    bin_upper_bound: List[float] = []
    if left_cnt > 0:
        denom = max(total_sample_cnt - cnt_zero, 1)
        left_max_bin = max(1, int(left_cnt_data / denom * (max_bin - 1)))
        bin_upper_bound = _greedy_find_bin(
            distinct_values[:left_cnt], counts[:left_cnt],
            left_max_bin, left_cnt_data, min_data_in_bin)
        bin_upper_bound[-1] = -K_ZERO_RANGE

    right_start = -1
    for i in range(left_cnt, len(distinct_values)):
        if distinct_values[i] > K_ZERO_RANGE:
            right_start = i
            break

    if right_start >= 0:
        right_max_bin = max_bin - 1 - len(bin_upper_bound)
        if right_max_bin <= 0:
            log.fatal("max_bin too small for zero-as-one-bin split")
        right_bounds = _greedy_find_bin(
            distinct_values[right_start:], counts[right_start:],
            right_max_bin, right_cnt_data, min_data_in_bin)
        bin_upper_bound.append(K_ZERO_RANGE)
        bin_upper_bound.extend(right_bounds)
    else:
        bin_upper_bound.append(np.inf)
    return bin_upper_bound


class BinMapper:
    """One feature's value->bin mapping (reference: BinMapper, bin.h:60-208)."""

    def __init__(self):
        self.num_bin: int = 1
        self.missing_type: int = MISSING_NONE
        self.is_trivial: bool = False
        self.sparse_rate: float = 0.0
        self.bin_type: int = BIN_NUMERICAL
        self.bin_upper_bound: np.ndarray = np.array([np.inf])
        self.bin_2_categorical: List[int] = []
        self.categorical_2_bin: Dict[int, int] = {}
        self.min_val: float = 0.0
        self.max_val: float = 0.0
        self.default_bin: int = 0  # bin of value 0.0

    def bin_info(self) -> str:
        """Reference: BinMapper::bin_info (bin.h:175-184) — the per-feature
        `feature_infos=` entry in the model text header."""
        if self.bin_type == BIN_CATEGORICAL:
            return ":".join(str(int(c)) for c in self.bin_2_categorical)
        return "[%s:%s]" % (repr(self.min_val), repr(self.max_val))

    # ------------------------------------------------------------------
    def find_bin(self, values: np.ndarray, total_sample_cnt: int, max_bin: int,
                 min_data_in_bin: int = 3, min_split_data: int = 0,
                 bin_type: int = BIN_NUMERICAL, use_missing: bool = True,
                 zero_as_missing: bool = False) -> None:
        """Construct the mapping from sampled values
        (reference: BinMapper::FindBin, bin.cpp:200-330).

        `values` are the sampled non-zero values; zeros are implied by
        `total_sample_cnt - len(values)` as in the reference's sparse
        sampling contract.
        """
        values = np.asarray(values, dtype=np.float64)
        na_mask = np.isnan(values)
        na_cnt = int(na_mask.sum())
        values = values[~na_mask]
        num_sample_values = len(values) + na_cnt

        if not use_missing:
            self.missing_type = MISSING_NONE
        elif zero_as_missing:
            self.missing_type = MISSING_ZERO
        else:
            self.missing_type = MISSING_NAN if na_cnt > 0 else MISSING_NONE

        self.bin_type = bin_type
        self.default_bin = 0
        zero_cnt = int(total_sample_cnt - num_sample_values)

        # distinct values with zero spliced in at its sorted position
        values = np.sort(values)
        distinct, counts = _distinct_with_zero(values, zero_cnt)
        if len(distinct) == 0:
            distinct = np.array([0.0])
            counts = np.array([max(zero_cnt, 1)])
        self.min_val = float(distinct[0])
        self.max_val = float(distinct[-1])

        if bin_type == BIN_NUMERICAL:
            if self.missing_type == MISSING_ZERO:
                bounds = _find_bin_with_zero_as_one_bin(
                    distinct, counts, max_bin, total_sample_cnt, min_data_in_bin)
                if len(bounds) == 2:
                    self.missing_type = MISSING_NONE
            elif self.missing_type == MISSING_NONE:
                bounds = _find_bin_with_zero_as_one_bin(
                    distinct, counts, max_bin, total_sample_cnt, min_data_in_bin)
            else:  # NaN: reserve the last bin for NaN (bin.cpp:270-274)
                bounds = _find_bin_with_zero_as_one_bin(
                    distinct, counts, max_bin - 1, total_sample_cnt - na_cnt,
                    min_data_in_bin)
                bounds.append(np.nan)
            self.bin_upper_bound = np.asarray(bounds, dtype=np.float64)
            self.num_bin = len(bounds)
            cnt_in_bin = self._count_in_bins(distinct, counts, na_cnt)
        else:
            # categorical: ints sorted by count desc, keep 99% mass
            # (reference: bin.cpp:292-330)
            distinct_int: Dict[int, int] = {}
            for v, c in zip(distinct, counts):
                iv = int(v)
                distinct_int[iv] = distinct_int.get(iv, 0) + int(c)
            items = sorted(distinct_int.items(), key=lambda kv: -kv[1])
            # avoid first bin being the zero category (bin.cpp:306-310)
            if len(items) > 1 and items[0][0] == 0:
                items[0], items[1] = items[1], items[0]
            cut_cnt = int((total_sample_cnt - na_cnt) * 0.99)
            self.bin_2_categorical = []
            self.categorical_2_bin = {}
            self.num_bin = 0
            used_cnt = 0
            eff_max_bin = min(len(items), max_bin)
            cnt_in_bin_list: List[int] = []
            for cat, c in items:
                if not (used_cnt < cut_cnt or self.num_bin < eff_max_bin):
                    break
                if cat < 0:
                    na_cnt += c
                    cut_cnt -= c
                    log.warning("Met negative value in categorical features, "
                                "will convert it to NaN")
                    continue
                self.bin_2_categorical.append(cat)
                self.categorical_2_bin[cat] = self.num_bin
                cnt_in_bin_list.append(c)
                used_cnt += c
                self.num_bin += 1
            # rare categories fall into the NaN/other handling
            if na_cnt > 0 or used_cnt < total_sample_cnt:
                self.missing_type = MISSING_NAN
            else:
                self.missing_type = MISSING_NONE
            cnt_in_bin = np.asarray(cnt_in_bin_list, dtype=np.int64)
            if self.num_bin == 0:
                self.num_bin = 1
                self.bin_2_categorical = [0]
                self.categorical_2_bin = {0: 0}
                cnt_in_bin = np.array([total_sample_cnt], dtype=np.int64)

        # trivial feature: only one populated bin (a constant nonzero column
        # still gets a synthetic empty zero bin from zero-as-one-bin)
        self.is_trivial = self.num_bin <= 1 or int((cnt_in_bin > 0).sum()) <= 1
        if bin_type == BIN_NUMERICAL:
            self.default_bin = self.value_to_bin(0.0)
        else:
            self.default_bin = self.categorical_2_bin.get(0, 0)
        if len(cnt_in_bin) > 0 and total_sample_cnt > 0:
            nz = int(cnt_in_bin[self.default_bin]) if self.default_bin < len(cnt_in_bin) else 0
            self.sparse_rate = nz / float(total_sample_cnt)
        # a numerical feature whose non-default mass can't satisfy
        # min_split_data on both sides is trivial (reference: NeedFilter)
        if (min_split_data > 0 and bin_type == BIN_NUMERICAL
                and not self.is_trivial):
            csum = np.cumsum(cnt_in_bin[:-1]) if len(cnt_in_bin) > 1 else np.array([])
            total = int(cnt_in_bin.sum())
            ok = np.any((csum >= min_split_data) & (total - csum >= min_split_data)) \
                if len(csum) else False
            if not ok:
                self.is_trivial = True

    def _count_in_bins(self, distinct: np.ndarray, counts: np.ndarray,
                       na_cnt: int) -> np.ndarray:
        cnt = np.zeros(self.num_bin, dtype=np.int64)
        finite_bounds = self.bin_upper_bound.copy()
        finite_bounds[np.isnan(finite_bounds)] = np.inf
        idx = np.searchsorted(finite_bounds, distinct, side="left")
        # searchsorted('left') gives first bound >= v, matching v <= bound
        np.add.at(cnt, np.minimum(idx, self.num_bin - 1), counts)
        if self.missing_type == MISSING_NAN:
            cnt[self.num_bin - 1] = na_cnt
        return cnt

    # ------------------------------------------------------------------
    def value_to_bin(self, value: float) -> int:
        """Reference: BinMapper::ValueToBin, bin.h:451-487 (binary search on
        upper bounds; NaN -> last bin when missing_type is NaN; zero-as-missing
        maps |v|<=eps to the default zero bin)."""
        if self.bin_type == BIN_CATEGORICAL:
            iv = int(value) if not np.isnan(value) else -1
            if iv < 0:
                return self.num_bin - 1
            return self.categorical_2_bin.get(iv, self.num_bin - 1)
        if np.isnan(value):
            if self.missing_type == MISSING_NAN:
                return self.num_bin - 1
            value = 0.0
        n_num = self.num_bin - (1 if self.missing_type == MISSING_NAN else 0)
        bounds = self.bin_upper_bound[:n_num]
        return int(np.searchsorted(bounds, value, side="left").clip(0, n_num - 1))

    def values_to_bins(self, values: np.ndarray) -> np.ndarray:
        """Vectorized ValueToBin over a column."""
        values = np.asarray(values, dtype=np.float64)
        out = np.zeros(len(values), dtype=np.int32)
        if self.bin_type == BIN_CATEGORICAL:
            nan_bin = self.num_bin - 1
            lut_keys = np.asarray(list(self.categorical_2_bin.keys()), dtype=np.int64)
            lut_vals = np.asarray(list(self.categorical_2_bin.values()), dtype=np.int64)
            iv = np.where(np.isnan(values), -1, values).astype(np.int64)
            out[:] = nan_bin
            if len(lut_keys):
                order = np.argsort(lut_keys)
                lut_keys, lut_vals = lut_keys[order], lut_vals[order]
                pos = np.searchsorted(lut_keys, iv)
                pos_c = np.clip(pos, 0, len(lut_keys) - 1)
                hit = (lut_keys[pos_c] == iv) & (iv >= 0)
                out[hit] = lut_vals[pos_c[hit]]
            return out
        nan_mask = np.isnan(values)
        n_num = self.num_bin - (1 if self.missing_type == MISSING_NAN else 0)
        bounds = self.bin_upper_bound[:n_num]
        vals = np.where(nan_mask, 0.0, values)
        out = np.searchsorted(bounds, vals, side="left").clip(0, n_num - 1).astype(np.int32)
        if self.missing_type == MISSING_NAN:
            out[nan_mask] = self.num_bin - 1
        return out

    def bin_to_value(self, bin_idx: int) -> float:
        """Reference: BinMapper::BinToValue (model thresholds use upper bounds)."""
        if self.bin_type == BIN_CATEGORICAL:
            return float(self.bin_2_categorical[bin_idx]) \
                if bin_idx < len(self.bin_2_categorical) else -1.0
        return float(self.bin_upper_bound[bin_idx])

    # ------------------------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "num_bin": self.num_bin,
            "missing_type": self.missing_type,
            "is_trivial": self.is_trivial,
            "sparse_rate": self.sparse_rate,
            "bin_type": self.bin_type,
            "bin_upper_bound": [float(x) for x in self.bin_upper_bound],
            "bin_2_categorical": list(self.bin_2_categorical),
            "min_val": self.min_val,
            "max_val": self.max_val,
            "default_bin": self.default_bin,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "BinMapper":
        m = cls()
        m.num_bin = int(d["num_bin"])
        m.missing_type = int(d["missing_type"])
        m.is_trivial = bool(d["is_trivial"])
        m.sparse_rate = float(d["sparse_rate"])
        m.bin_type = int(d["bin_type"])
        m.bin_upper_bound = np.asarray(d["bin_upper_bound"], dtype=np.float64)
        m.bin_2_categorical = [int(x) for x in d["bin_2_categorical"]]
        m.categorical_2_bin = {c: i for i, c in enumerate(m.bin_2_categorical)}
        m.min_val = float(d["min_val"])
        m.max_val = float(d["max_val"])
        m.default_bin = int(d["default_bin"])
        return m


def _distinct_with_zero(sorted_values: np.ndarray, zero_cnt: int):
    """Distinct values + counts with an implied zero block spliced in
    (reference: bin.cpp:230-262)."""
    if len(sorted_values) == 0:
        if zero_cnt > 0:
            return np.array([0.0]), np.array([zero_cnt], dtype=np.int64)
        return np.array([]), np.array([], dtype=np.int64)
    distinct, counts = np.unique(sorted_values, return_counts=True)
    if zero_cnt > 0 and not np.any(distinct == 0.0):
        pos = int(np.searchsorted(distinct, 0.0))
        distinct = np.insert(distinct, pos, 0.0)
        counts = np.insert(counts, pos, zero_cnt)
    elif zero_cnt > 0:
        counts = counts.copy()
        counts[distinct == 0.0] += zero_cnt
    return distinct, counts.astype(np.int64)


def sample_row_indices(n: int, sample_cnt: int = 200000,
                       seed: int = 1) -> Optional[np.ndarray]:
    """The sorted row indices `find_bin_mappers` samples for bin finding,
    or None when every row is used (n <= sample_cnt). Split out so the
    streaming ingest subsystem (lightgbm_tpu/ingest) can gather exactly
    these rows from a chunk stream and land on bit-identical bin bounds."""
    if n <= sample_cnt:
        return None
    rng = np.random.RandomState(seed)
    return np.sort(rng.choice(n, size=sample_cnt, replace=False))


def mappers_from_sample(sample: np.ndarray, total: int, max_bin: int,
                        min_data_in_bin: int = 3, min_split_data: int = 0,
                        categorical_features: Optional[Sequence[int]] = None,
                        use_missing: bool = True,
                        zero_as_missing: bool = False) -> List[BinMapper]:
    """Per-feature BinMappers from an already-gathered row sample.

    The shared core of `find_bin_mappers` (in-memory) and the ingest
    pass-1 sketch (streamed): both hand it the same sampled rows, so both
    produce bit-identical bounds."""
    f = sample.shape[1]
    cats = set(categorical_features or [])

    def _one(j):
        col = np.asarray(sample[:, j], dtype=np.float64)
        m = BinMapper()
        nonzero = col[(col != 0.0) | np.isnan(col)]
        m.find_bin(nonzero, total, max_bin, min_data_in_bin, min_split_data,
                   BIN_CATEGORICAL if j in cats else BIN_NUMERICAL,
                   use_missing, zero_as_missing)
        return m

    # thread pool: np.unique/sort/cumsum in find_bin release the GIL
    if f > 4 and total > 50_000:
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(max_workers=8) as ex:
            return list(ex.map(_one, range(f)))
    return [_one(j) for j in range(f)]


def find_bin_mappers(data: np.ndarray, max_bin: int, min_data_in_bin: int = 3,
                     min_split_data: int = 0,
                     sample_cnt: int = 200000, seed: int = 1,
                     categorical_features: Optional[Sequence[int]] = None,
                     use_missing: bool = True,
                     zero_as_missing: bool = False) -> List[BinMapper]:
    """Build per-feature BinMappers from a row-sampled slice of the data
    (reference: DatasetLoader::ConstructBinMappersFromTextData,
    dataset_loader.cpp:666-817 — sampling via `bin_construct_sample_cnt`)."""
    n, _ = data.shape
    idx = sample_row_indices(n, sample_cnt, seed)
    sample = data if idx is None else data[idx]
    total = n if idx is None else sample_cnt
    return mappers_from_sample(sample, total, max_bin, min_data_in_bin,
                               min_split_data, categorical_features,
                               use_missing, zero_as_missing)
