"""Structured JSONL run log: one readable trail per training run.

The rc-124 multichip timeout left NO artifact saying where it died;
this sink makes every run leave one. A `RunLog` appends self-contained
JSON records to `<tpu_telemetry_dir>/runlog_r<rank>.jsonl`:

- one `header` record per run start (config fingerprint, device
  topology, schedule, library versions) — a resumed run appends a new
  header, so the file reads as the full preemption history;
- one `iteration` record per boosting iteration: eval metric values,
  per-phase wall deltas, counter deltas (pass economics
  `rows_contracted`/`pass_rows`, bagging/DART activity), compile-event
  deltas from the observer;
- `event` records for discrete occurrences (resume, checkpoint saves,
  early stop, non-finite guard trips);
- a `summary` record on close with run totals.

Writes are append + flush per line (a preempted run's trail is readable
up to its last completed iteration; each line is independently
parseable). The heavyweight sibling — full-state snapshots — is
PR 3's checkpoint store; the run log is the cheap always-readable
narration alongside it.

`validate_record` is the schema contract tests and
scripts/telemetry_report.py both consume.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from .. import durable, log
from ..testing import faults
from . import metrics as metrics_mod
from .observer import observer as _observer

SCHEMA_VERSION = 1

RECORD_TYPES = ("header", "iteration", "event", "summary")

# required fields per record type (the round-trip contract)
_REQUIRED = {
    "header": ("type", "schema", "time", "rank", "world", "run_id",
               "fingerprint", "devices", "versions"),
    "iteration": ("type", "time", "iteration", "metrics", "phases",
                  "counters", "compile"),
    "event": ("type", "time", "kind"),
    "summary": ("type", "time", "iterations", "phases", "compile"),
}


def validate_record(rec: Dict[str, Any]) -> None:
    """Raise ValueError when `rec` violates the run-log schema."""
    if not isinstance(rec, dict):
        raise ValueError("run-log record must be a JSON object")
    rtype = rec.get("type")
    if rtype not in RECORD_TYPES:
        raise ValueError(f"unknown run-log record type: {rtype!r}")
    missing = [f for f in _REQUIRED[rtype] if f not in rec]
    if missing:
        raise ValueError(f"{rtype} record missing fields: {missing}")
    if rtype == "header" and int(rec["schema"]) > SCHEMA_VERSION:
        raise ValueError(
            f"run-log schema {rec['schema']} is newer than this build "
            f"supports ({SCHEMA_VERSION})")
    if rtype == "iteration":
        if not isinstance(rec["iteration"], int):
            raise ValueError("iteration record: 'iteration' must be int")
        for fld in ("metrics", "phases", "counters", "compile"):
            if not isinstance(rec[fld], dict):
                raise ValueError(f"iteration record: '{fld}' must be a dict")


def read_records(path: str) -> List[Dict[str, Any]]:
    """Parse a run-log file; truncated trailing lines (a run killed
    mid-write) are dropped, everything before them is returned."""
    out: List[Dict[str, Any]] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                break  # torn tail — the preemption case; keep the prefix
    return out


class RunLog:
    """Append-only JSONL sink for one rank.

    Best-effort stream: every OS-level failure (directory cannot be
    created, append/flush hits EIO or ENOSPC) is swallowed into the
    `telemetry/runlog_write_errors` counter with a rate-limited warning,
    and `write` reports it by returning False — narration must never
    raise into the training loop it narrates. Schema violations
    (ValueError) still raise: those are caller bugs, not disk weather.
    A failed handle is dropped and lazily reopened on the next write, so
    a transient full disk costs only the records written while full."""

    def __init__(self, directory: str, rank: int = 0):
        self.directory = directory
        self.rank = int(rank)
        self.path = os.path.join(directory, f"runlog_r{self.rank}.jsonl")
        self._fh = None
        try:
            os.makedirs(directory, exist_ok=True)
            self._fh = open(self.path, "a")
        except OSError as exc:
            durable.note_dropped("telemetry.runlog", self.path, exc,
                                 counter="telemetry/runlog_write_errors")

    def _open(self):
        if self._fh is None:
            self._fh = open(self.path, "a")
        return self._fh

    def write(self, rec: Dict[str, Any]) -> bool:
        """Append one record; returns False when the write was dropped."""
        rec.setdefault("time", time.time())
        validate_record(rec)
        try:
            faults.inject("runlog.write")
            fh = self._open()
            fh.write(json.dumps(rec, sort_keys=True,
                                separators=(",", ":")) + "\n")
            fh.flush()
            return True
        except OSError as exc:
            self.close()
            durable.note_dropped("telemetry.runlog", self.path, exc,
                                 counter="telemetry/runlog_write_errors")
            return False

    def close(self) -> None:
        if self._fh is None:
            return
        try:
            self._fh.close()
        except OSError:  # pragma: no cover
            pass
        self._fh = None


def _versions() -> Dict[str, str]:
    import numpy as np
    out = {"numpy": np.__version__}
    try:
        import jax
        out["jax"] = jax.__version__
    except Exception:  # pragma: no cover
        pass
    try:
        from .. import __version__ as own
        out["lightgbm_tpu"] = own
    except Exception:
        pass
    return out


def _device_topology() -> Dict[str, Any]:
    """Backend topology for the header (the backend is already up by the
    time training telemetry starts — booster init touched devices)."""
    try:
        import jax
        devs = jax.devices()
        return {"platform": devs[0].platform if devs else "none",
                "num_devices": len(devs),
                "num_processes": jax.process_count(),
                "local_devices": len(jax.local_devices())}
    except Exception:  # pragma: no cover — headless schema tests
        return {"platform": "unknown", "num_devices": 0,
                "num_processes": 1, "local_devices": 0}


class TrainRecorder:
    """Engine-facing glue: snapshots the registry + compile observer at
    iteration boundaries and writes per-iteration deltas, so a record
    says what THIS iteration cost — without ever touching device arrays
    or draining the async tree pipeline (the recorder must not tax the
    pipelined training path it measures)."""

    def __init__(self, gbdt, run_log: Optional[RunLog], rank: int,
                 world: int, fingerprint: str, params: Dict[str, Any],
                 prometheus: bool = True):
        self.gbdt = gbdt
        self.run_log = run_log
        self.rank = rank
        self.world = world
        self.prometheus = bool(prometheus)
        # when start_run enabled collection just for this run, close()
        # restores the disabled default so later runs in the same
        # process don't silently keep accumulating
        self.disable_on_close = False
        self.run_id = f"{int(time.time() * 1e3):x}-r{rank}"
        # remembered for the end-of-run collective: the run log itself
        # may be dropped mid-run (disk full), but the cross-rank
        # aggregation must still run on EVERY rank or the others hang
        self._directory = run_log.directory if run_log is not None else ""
        self._t_start = time.time()
        self._iterations = 0
        self._pass_log_seen = len(getattr(gbdt, "pass_log", []) or [])
        # baseline deltas at the CURRENT accumulator values: anything
        # collected before this run (a previous train() in the same
        # process under LGBM_TPU_TIMETAG, booster-construction spans)
        # must not be billed to iteration 0
        reg = metrics_mod.registry()
        self._phase_prev: Dict[str, tuple] = {
            name: (acc.total, acc.count) for name, acc in reg.phases.items()}
        self._counter_prev: Dict[str, tuple] = {
            key: (c.value, c.events) for key, c in reg.counters.items()}
        self._compile_prev = _observer().snapshot()
        if run_log is not None:
            run_log.write({
                "type": "header", "schema": SCHEMA_VERSION,
                "rank": rank, "world": world, "run_id": self.run_id,
                "fingerprint": fingerprint,
                "devices": _device_topology(),
                "versions": _versions(),
                "params": {str(k): str(v) for k, v in params.items()},
                "schedule": dict(getattr(gbdt, "_schedule_info", {}) or {}),
                "boosting": gbdt.model_name(),
                "num_data": int(getattr(gbdt, "_n", 0)),
                "start_iteration": int(getattr(gbdt, "iter_", 0)),
            })

    # -- delta plumbing ---------------------------------------------------
    def _phase_delta(self) -> Dict[str, Dict[str, float]]:
        reg = metrics_mod.registry()
        out = {}
        for name, acc in list(reg.phases.items()):
            prev = self._phase_prev.get(name, (0.0, 0))
            d_total, d_count = acc.total - prev[0], acc.count - prev[1]
            self._phase_prev[name] = (acc.total, acc.count)
            if d_count or d_total:
                out[name] = {"seconds": round(d_total, 6), "count": d_count}
        return out

    def _counter_delta(self) -> Dict[str, float]:
        reg = metrics_mod.registry()
        out = {}
        for key, c in list(reg.counters.items()):
            prev = self._counter_prev.get(key, (0.0, 0))
            dv = c.value - prev[0]
            self._counter_prev[key] = (c.value, c.events)
            if dv:
                name = c.name if not c.labels else \
                    c.name + "{" + ",".join(f"{k}={v}"
                                            for k, v in c.labels) + "}"
                out[name] = dv
        return out

    def _compile_delta(self) -> Dict[str, Any]:
        snap = _observer().snapshot()
        prev = self._compile_prev
        self._compile_prev = snap
        return {
            "compiles": snap["total_compiles"] - prev["total_compiles"],
            "seconds": round(snap["total_seconds"] - prev["total_seconds"], 6),
            "retraces": snap["retraces"] - prev["retraces"],
        }

    def _pass_economics(self) -> Dict[str, float]:
        plog = getattr(self.gbdt, "pass_log", None) or []
        new = plog[self._pass_log_seen:]
        self._pass_log_seen = len(plog)
        if not new:
            return {}
        return {
            "trees": len(new),
            "num_passes": sum(int(p[0]) for p in new),
            "table_high_water": max(int(p[1]) for p in new),
            "rows_contracted": sum(float(p[2]) for p in new if len(p) > 2),
            "comm_elems": sum(float(p[3]) for p in new if len(p) > 3),
            "comm_bytes": sum(float(p[4]) for p in new if len(p) > 4),
        }

    # -- record emission --------------------------------------------------
    def iteration(self, i: int, eval_results) -> None:
        """One record per boosting iteration; `eval_results` is the
        engine's (data_name, metric_name, value, bigger_better) list."""
        self._iterations += 1
        metrics_mod.heartbeat(i, phase="train", rank=self.rank)
        if self.run_log is None:
            return
        rec = {
            "type": "iteration", "iteration": int(i),
            "metrics": {f"{d}/{m}": float(v)
                        for d, m, v, _ in (eval_results or [])},
            "phases": self._phase_delta(),
            "counters": self._counter_delta(),
            "compile": self._compile_delta(),
        }
        passes = self._pass_economics()
        if passes:
            rec["pass"] = passes
        try:
            # OS-level failures are absorbed inside RunLog.write (counted
            # + rate-limited warning); only schema bugs surface here, and
            # those disable the sink — narration must never kill training
            self.run_log.write(rec)
        except ValueError as exc:
            log.warning("Run log write failed (%s); disabling run log", exc)
            self.run_log = None

    def event(self, kind: str, **fields) -> None:
        if self.run_log is None:
            return
        rec = {"type": "event", "kind": str(kind)}
        rec.update({k: v for k, v in fields.items()})
        try:
            self.run_log.write(rec)
        except ValueError as exc:
            log.warning("Run log write failed (%s); disabling run log", exc)
            self.run_log = None

    def close(self, status: str = "finished") -> None:
        """Prometheus dump + cross-rank aggregation + summary record.

        The aggregate collective runs BEFORE the summary is written and
        the log closed: it can wedge on a peer that died late, and the
        collective watchdog's rank_failure event must still have an
        OPEN run log to land in (the log of a run that died there
        correctly ends with the rank_failure event, no summary)."""
        if self.disable_on_close:
            metrics_mod.enable(False)
        if self._directory and self.prometheus:
            from . import export
            # per-rank file write and the cross-rank collective are
            # isolated from each other: a local write failure on one
            # rank must NOT skip its allgather participation, or every
            # other rank blocks in write_cross_rank_aggregate at end of
            # training
            try:
                export.write_prometheus(
                    os.path.join(self._directory,
                                 f"metrics_r{self.rank}.prom"),
                    extra_labels={"rank": str(self.rank)})
            except Exception as exc:  # export is best-effort narration
                log.warning("Telemetry export failed: %s", exc)
            # the aggregate is a COLLECTIVE: only run it on clean
            # finishes, when every rank reaches close() together. On an
            # error close the other ranks are still inside training
            # collectives — joining an allgather here would mismatch
            # them and wedge the job that was about to exit with a
            # diagnosable error.
            if self.world > 1 and status == "finished":
                try:
                    export.write_cross_rank_aggregate(self._directory,
                                                      self.rank,
                                                      self.world)
                except Exception as exc:
                    log.warning("Cross-rank telemetry aggregation "
                                "failed: %s", exc)
        reg = metrics_mod.registry()
        summary = {
            "type": "summary", "status": status,
            "iterations": self._iterations,
            "wall_seconds": round(time.time() - self._t_start, 3),
            "phases": {name: {"seconds": round(acc.total, 6),
                              "count": acc.count}
                       for name, acc in reg.phases.items()},
            "compile": _observer().snapshot(),
        }
        if self.run_log is not None:
            try:
                self.run_log.write(summary)
            except ValueError:  # pragma: no cover
                pass
            self.run_log.close()
