"""Compile/retrace observer over `jax.monitoring` events.

The measured 29-81s wide-shape compile tails and the pervasive retrace
risk on new shapes (ROADMAP "kill cold-start") are invisible today
outside manual profiling. jax emits monitoring events for every
compilation — `/jax/core/compile/backend_compile_duration` fires once
per backend compile with its wall time — but carries no clue WHICH
jitted entry point compiled. This observer supplies the attribution:
compile events are charged to the innermost open telemetry span
(`metrics.current_site()` — `tree/grow`, `predict/dispatch`, ...), so
the run log can say "iteration 0 spent 31s compiling under tree/grow".

Retrace counting: the first compile at a site is the expected trace;
every further one is a RETRACE (a new input signature reached the same
entry point). Sites crossing `retrace_warn` compiles log a warning once
— the retrace-storm tripwire the AOT-cache work needs a baseline for.

jax.monitoring has no per-listener deregistration, so `install()` is
once-per-process and `uninstall()` just deactivates the hooks (cheap
flag test per event).
"""
from __future__ import annotations

import os
import threading
from typing import Dict, Optional

from . import metrics

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
_UNATTRIBUTED = "(no-span)"


class CompileObserver:
    """Per-site compile/retrace accounting fed by jax.monitoring."""

    def __init__(self, retrace_warn: int = 10):
        self.retrace_warn = int(
            os.environ.get("LGBM_TPU_RETRACE_WARN", retrace_warn))
        self._lock = threading.Lock()
        self._registered = False
        self.active = False
        # site -> {"compiles": int, "seconds": float, "warned": bool}
        self.sites: Dict[str, Dict] = {}
        self.total_compiles = 0
        self.total_seconds = 0.0

    # -- listener plumbing ----------------------------------------------
    def install(self) -> None:
        """Register with jax.monitoring (idempotent) and activate."""
        self.active = True
        if self._registered:
            return
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(self._on_duration)
        self._registered = True

    def uninstall(self) -> None:
        self.active = False

    def reset(self) -> None:
        with self._lock:
            self.sites.clear()
            self.total_compiles = 0
            self.total_seconds = 0.0

    # -- event handling --------------------------------------------------
    def _on_duration(self, event: str, duration: float, **kwargs) -> None:
        if not self.active or event != _COMPILE_EVENT:
            return
        site = metrics.current_site() or _UNATTRIBUTED
        with self._lock:
            rec = self.sites.get(site)
            if rec is None:
                rec = self.sites[site] = {
                    "compiles": 0, "seconds": 0.0, "warned": False}
            rec["compiles"] += 1
            rec["seconds"] += float(duration)
            self.total_compiles += 1
            self.total_seconds += float(duration)
            # the unattributed bucket aggregates every compile outside a
            # span — many distinct entry points, not one retracing — so
            # it can't meaningfully "storm"
            storm = (site != _UNATTRIBUTED
                     and not rec["warned"]
                     and rec["compiles"] > max(1, self.retrace_warn))
            if storm:
                rec["warned"] = True
        if metrics.enabled():
            metrics.counter_add("compile/count", 1, {"site": site})
            metrics.counter_add("compile/seconds", float(duration),
                                {"site": site})
        if storm:
            from .. import log
            log.warning(
                "Retrace storm at '%s': %d compilations (%.1fs total) — "
                "the same entry point keeps seeing new input signatures; "
                "check shape bucketing / static-arg churn "
                "(LGBM_TPU_RETRACE_WARN tunes this threshold)",
                site, rec["compiles"], rec["seconds"])

    # -- views ------------------------------------------------------------
    def retraces(self, site: Optional[str] = None) -> int:
        """Compiles beyond the first per site (summed when site=None).
        The unattributed bucket is excluded from the sum: it aggregates
        many distinct entry points, so its count says nothing about any
        one of them retracing."""
        with self._lock:
            if site is not None:
                rec = self.sites.get(site)
                return max(0, rec["compiles"] - 1) if rec else 0
            return sum(max(0, r["compiles"] - 1)
                       for s, r in self.sites.items()
                       if s != _UNATTRIBUTED)

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "total_compiles": self.total_compiles,
                "total_seconds": self.total_seconds,
                "retraces": sum(max(0, r["compiles"] - 1)
                                for s, r in self.sites.items()
                                if s != _UNATTRIBUTED),
                "sites": {s: {"compiles": r["compiles"],
                              "seconds": r["seconds"]}
                          for s, r in self.sites.items()},
            }


_observer: Optional[CompileObserver] = None


def observer() -> CompileObserver:
    """The process-wide observer (created lazily, NOT auto-installed)."""
    global _observer
    if _observer is None:
        _observer = CompileObserver()
    return _observer


def install() -> CompileObserver:
    obs = observer()
    obs.install()
    return obs
