"""Metrics export: Prometheus text exposition + cross-rank aggregation.

File-target exposition (the node-exporter "textfile collector" pattern):
`write_prometheus(path)` atomically dumps the registry in the standard
text format, one file per rank, so a scraper — or a human with grep —
reads training/serving state without any server embedded in the trainer.
Counters export as `lgbmtpu_counter_total{name=...}`, span timers as
`lgbmtpu_phase_seconds_total`/`_count{phase=...}`, histograms with full
`_bucket{le=...}` series. Every sample carries the caller's extra labels
(the multihost rank).

Cross-rank aggregation: after a multi-process run every rank holds only
its shard's counters. `write_cross_rank_aggregate` allgathers each
rank's JSON snapshot through `parallel.multihost.allgather_bytes` and
rank 0 writes the merged view (counters/histograms summed, gauges kept
per-rank under a `rank` label — a heartbeat gauge MUST NOT be summed:
its per-rank last-seen value is exactly the evidence a hung-rank
post-mortem needs).
"""
from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional

from . import metrics as metrics_mod

_NAME_SANITIZE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return _NAME_SANITIZE.sub("_", name)


def _prom_value(v: float) -> str:
    """Exact sample rendering: '%g' would truncate to 6 significant
    digits — off by ~1e3 rows on a 1e7 row counter and lossy for
    last-seen-iteration gauges. Integers print as integers; floats via
    repr (shortest exact round-trip)."""
    f = float(v)
    if f.is_integer() and abs(f) < 2 ** 63:
        return str(int(f))
    return repr(f)


def _labels_str(items) -> str:
    if not items:
        return ""
    body = ",".join(f'{_prom_name(str(k))}="{v}"' for k, v in items)
    return "{" + body + "}"


def _merged_labels(base, extra: Optional[Dict[str, str]]):
    out = list(base or ())
    for k, v in sorted((extra or {}).items()):
        out.append((k, v))
    return out


def prometheus_text(snapshot: Dict[str, Any],
                    extra_labels: Optional[Dict[str, str]] = None) -> str:
    """Render a registry snapshot (metrics.Registry.snapshot()) in the
    Prometheus text exposition format."""
    lines: List[str] = []

    counters = snapshot.get("counters", [])
    if counters:
        lines.append("# TYPE lgbmtpu_counter_total counter")
        for c in counters:
            labels = _merged_labels([("name", c["name"])] +
                                    [tuple(kv) for kv in c["labels"]],
                                    extra_labels)
            lines.append(f"lgbmtpu_counter_total{_labels_str(labels)} "
                         f"{_prom_value(c['value'])}")

    gauges = snapshot.get("gauges", [])
    if gauges:
        lines.append("# TYPE lgbmtpu_gauge gauge")
        for g in gauges:
            labels = _merged_labels([("name", g["name"])] +
                                    [tuple(kv) for kv in g["labels"]],
                                    extra_labels)
            lines.append(f"lgbmtpu_gauge{_labels_str(labels)} "
                         f"{_prom_value(g['value'])}")

    phases = snapshot.get("phases", [])
    if phases:
        lines.append("# TYPE lgbmtpu_phase_seconds_total counter")
        lines.append("# TYPE lgbmtpu_phase_count_total counter")
        for p in phases:
            labels = _merged_labels([("phase", p["name"])], extra_labels)
            ls = _labels_str(labels)
            lines.append(f"lgbmtpu_phase_seconds_total{ls} "
                         f"{p['seconds']:.6f}")
            lines.append(f"lgbmtpu_phase_count_total{ls} {p['count']}")

    for h in snapshot.get("histograms", []):
        base = _prom_name("lgbmtpu_" + h["name"])
        lines.append(f"# TYPE {base} histogram")
        label_items = [tuple(kv) for kv in h["labels"]]
        cum = 0
        for bound, count in zip(h["bounds"], h["buckets"]):
            cum += count
            labels = _merged_labels(label_items + [("le", f"{bound:g}")],
                                    extra_labels)
            lines.append(f"{base}_bucket{_labels_str(labels)} {cum}")
        labels = _merged_labels(label_items + [("le", "+Inf")], extra_labels)
        lines.append(f"{base}_bucket{_labels_str(labels)} {h['count']}")
        plain = _merged_labels(label_items, extra_labels)
        lines.append(f"{base}_sum{_labels_str(plain)} "
                     f"{_prom_value(h['sum'])}")
        lines.append(f"{base}_count{_labels_str(plain)} {h['count']}")

    return "\n".join(lines) + "\n"


def write_prometheus(path: str,
                     extra_labels: Optional[Dict[str, str]] = None,
                     snapshot: Optional[Dict[str, Any]] = None
                     ) -> Optional[str]:
    """Atomically write the (current) registry as Prometheus text.

    Best-effort stream: a dump that cannot land (disk full, telemetry
    dir on a dead mount) is dropped into the
    `telemetry/prom_write_errors` counter instead of raising — metrics
    narration never takes down the run it narrates. Returns the path on
    success, None when the write was dropped."""
    from .. import durable
    snap = snapshot if snapshot is not None \
        else metrics_mod.registry().snapshot()
    ok = durable.atomic_write_text(
        path, prometheus_text(snap, extra_labels),
        site="telemetry.prom", critical=False, stream="telemetry.prom",
        counter="telemetry/prom_write_errors")
    return path if ok else None


# ---------------------------------------------------------------------------
# cross-rank aggregation
# ---------------------------------------------------------------------------
def merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-rank registry snapshots: counters/phases/histogram
    buckets sum across ranks; gauges stay per-rank (labeled by origin
    rank by the caller via each snapshot's position)."""
    counters: Dict[tuple, Dict] = {}
    phases: Dict[str, Dict] = {}
    hists: Dict[tuple, Dict] = {}
    gauges: List[Dict] = []
    for rank, snap in enumerate(snaps):
        for c in snap.get("counters", []):
            key = (c["name"], tuple(tuple(kv) for kv in c["labels"]))
            agg = counters.setdefault(key, {
                "name": c["name"], "labels": c["labels"],
                "value": 0.0, "events": 0})
            agg["value"] += c["value"]
            agg["events"] += c["events"]
        for p in snap.get("phases", []):
            agg = phases.setdefault(p["name"], {
                "name": p["name"], "seconds": 0.0, "count": 0})
            agg["seconds"] += p["seconds"]
            agg["count"] += p["count"]
        for h in snap.get("histograms", []):
            key = (h["name"], tuple(tuple(kv) for kv in h["labels"]),
                   tuple(h["bounds"]))
            agg = hists.setdefault(key, {
                "name": h["name"], "labels": h["labels"],
                "bounds": list(h["bounds"]),
                "buckets": [0] * len(h["buckets"]),
                "count": 0, "sum": 0.0, "min": None, "max": None})
            agg["buckets"] = [a + b for a, b in
                              zip(agg["buckets"], h["buckets"])]
            agg["count"] += h["count"]
            agg["sum"] += h["sum"]
            for fld, pick in (("min", min), ("max", max)):
                if h.get(fld) is not None:
                    agg[fld] = h[fld] if agg[fld] is None \
                        else pick(agg[fld], h[fld])
        for g in snap.get("gauges", []):
            gg = dict(g)
            gg["labels"] = list(g["labels"]) + [["rank", str(rank)]]
            gauges.append(gg)
    return {"counters": list(counters.values()),
            "phases": list(phases.values()),
            "histograms": list(hists.values()),
            "gauges": gauges}


def write_cross_rank_aggregate(directory: str, rank: int,
                               world: int) -> Optional[str]:
    """End-of-run collective: every rank contributes its snapshot, rank 0
    writes `metrics_aggregate.prom`. Must be called by ALL ranks (it is
    an allgather). Returns the written path on rank 0, None elsewhere.

    Deadline-guarded under its own site label: a rank that died during
    training must not convert the END of every survivor's run into an
    indefinite hang inside Prometheus export — with
    `tpu_collective_timeout_s` set, survivors exit RC_RANK_FAILURE with
    a `telemetry.aggregate`-sited rank_failure event instead."""
    import os

    from ..parallel.multihost import allgather_bytes
    blob = json.dumps(metrics_mod.registry().snapshot(),
                      sort_keys=True).encode("utf-8")
    # one guard, distinctly labeled: allgather_bytes arms its own
    # deadline under the site passed here (a second outer timer would
    # race it and make the recorded failure site nondeterministic)
    blobs = allgather_bytes(blob, site="telemetry.aggregate")
    if rank != 0:
        return None
    snaps = []
    for b in blobs:
        try:
            snaps.append(json.loads(b.decode("utf-8")))
        except (UnicodeDecodeError, json.JSONDecodeError):  # pragma: no cover
            snaps.append({})
    merged = merge_snapshots(snaps)
    path = os.path.join(directory, "metrics_aggregate.prom")
    return write_prometheus(path, extra_labels={"world": str(world)},
                            snapshot=merged)
