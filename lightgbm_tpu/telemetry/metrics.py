"""Labeled metrics registry + span-scoped timers.

The reference's only instrumentation is the compile-time TIMETAG wall
accumulators (`gbdt.cpp:53-62`); this registry is the production-shaped
replacement the ROADMAP items need: labeled counters/gauges and BUCKETED
histograms (serving latency as a real p50/p95/p99 distribution, not a
running mean, following the per-phase accounting of the GBDT accelerator
literature — XGBoost-GPU 1806.11248 §5, booster accelerators
2011.02022 §4), plus `span()` timers that charge asynchronously
dispatched device work to the right phase via `block_until_ready`.

Cost discipline: with telemetry disabled every entry point is a single
flag test returning a module-level singleton — no allocation, no locks
(tests/test_telemetry.py probes the disabled path with tracemalloc).
Enabled-path instruments append to plain dict/float slots under the GIL;
the only lock taken per event is the histogram's (shared with the
serving threads).
"""
from __future__ import annotations

import bisect
import os
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

LabelItems = Tuple[Tuple[str, str], ...]

# default histogram bounds: exponential 100us .. ~100s — wide enough for
# single-row serving latency AND wide-shape grower compile tails
DEFAULT_TIME_BUCKETS = tuple(1e-4 * (2.0 ** i) for i in range(21))


def _label_key(labels: Optional[Dict[str, Any]]) -> LabelItems:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


class Counter:
    """Monotonic accumulator. `value` is the accumulated total, `events`
    the number of inc() calls (the (value, count) pair tracing.counters()
    always reported)."""

    __slots__ = ("name", "labels", "value", "events")

    def __init__(self, name: str, labels: LabelItems = ()):
        self.name = name
        self.labels = labels
        self.value = 0.0
        self.events = 0

    def inc(self, v: float = 1.0) -> None:
        self.value += float(v)
        self.events += 1


class Gauge:
    """Last-write-wins scalar (heartbeats, queue depths)."""

    __slots__ = ("name", "labels", "value", "updated_at")

    def __init__(self, name: str, labels: LabelItems = ()):
        self.name = name
        self.labels = labels
        self.value = 0.0
        self.updated_at = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)
        self.updated_at = time.time()


class Histogram:
    """Fixed-bound bucketed histogram (Prometheus semantics: `buckets[i]`
    counts observations <= bounds[i], with a +Inf overflow bucket).

    Quantiles interpolate linearly inside the winning bucket — the
    standard exposition-format estimation, good to a bucket width. The
    instrument is safe for concurrent observers (serving threads)."""

    __slots__ = ("name", "labels", "bounds", "buckets", "count", "sum",
                 "_min", "_max", "_lock")

    def __init__(self, name: str, labels: LabelItems = (),
                 bounds: Iterable[float] = DEFAULT_TIME_BUCKETS):
        self.name = name
        self.labels = labels
        self.bounds = tuple(sorted(float(b) for b in bounds))
        self.buckets = [0] * (len(self.bounds) + 1)  # +Inf tail
        self.count = 0
        self.sum = 0.0
        self._min = None
        self._max = None
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self.buckets[i] += 1
            self.count += 1
            self.sum += v
            if self._min is None or v < self._min:
                self._min = v
            if self._max is None or v > self._max:
                self._max = v

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (0..1); None with no observations."""
        with self._lock:
            if self.count == 0:
                return None
            rank = q * self.count
            cum = 0
            for i, c in enumerate(self.buckets):
                if c == 0:
                    continue
                prev_cum = cum
                cum += c
                if cum >= rank:
                    lo = self.bounds[i - 1] if i > 0 else \
                        (self._min if self._min is not None else 0.0)
                    hi = self.bounds[i] if i < len(self.bounds) else \
                        (self._max if self._max is not None else lo)
                    frac = (rank - prev_cum) / c
                    est = lo + (hi - lo) * frac
                    # clamp to the observed range: interpolation inside
                    # the min/max bucket must not invent values outside it
                    if self._max is not None:
                        est = min(est, self._max)
                    if self._min is not None:
                        est = max(est, self._min)
                    return est
            return self._max

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {"bounds": list(self.bounds), "buckets": list(self.buckets),
                    "count": self.count, "sum": self.sum,
                    "min": self._min, "max": self._max}


class _PhaseAccum:
    """Span-timer accumulator: total seconds + span count per name (the
    shape tracing.totals() always reported)."""

    __slots__ = ("total", "count")

    def __init__(self):
        self.total = 0.0
        self.count = 0


class Registry:
    """One process-wide instrument store. Instruments are created on
    first use and keyed by (name, sorted label items); `snapshot()`
    returns a JSON-safe dict the exporters and the run log consume."""

    def __init__(self):
        self._lock = threading.Lock()
        self.counters: Dict[Tuple[str, LabelItems], Counter] = {}
        self.gauges: Dict[Tuple[str, LabelItems], Gauge] = {}
        self.histograms: Dict[Tuple[str, LabelItems], Histogram] = {}
        self.phases: Dict[str, _PhaseAccum] = {}

    # -- instrument lookup (create on first use) ------------------------
    def counter(self, name: str, labels: Optional[Dict] = None) -> Counter:
        key = (name, _label_key(labels))
        c = self.counters.get(key)
        if c is None:
            with self._lock:
                c = self.counters.setdefault(key, Counter(*key))
        return c

    def gauge(self, name: str, labels: Optional[Dict] = None) -> Gauge:
        key = (name, _label_key(labels))
        g = self.gauges.get(key)
        if g is None:
            with self._lock:
                g = self.gauges.setdefault(key, Gauge(*key))
        return g

    def histogram(self, name: str, labels: Optional[Dict] = None,
                  bounds: Iterable[float] = DEFAULT_TIME_BUCKETS) -> Histogram:
        key = (name, _label_key(labels))
        h = self.histograms.get(key)
        if h is None:
            with self._lock:
                h = self.histograms.get(key)
                if h is None:
                    h = Histogram(key[0], key[1], bounds)
                    self.histograms[key] = h
        return h

    def register_histogram(self, hist: Histogram) -> Histogram:
        """Adopt an externally-owned Histogram as a shared instrument:
        the owner keeps observing/reading it directly (always-on local
        stats) and the exporters see the SAME object — one series, one
        lock, instead of a local copy plus a registry twin."""
        with self._lock:
            self.histograms[(hist.name, hist.labels)] = hist
        return hist

    def phase(self, name: str) -> _PhaseAccum:
        p = self.phases.get(name)
        if p is None:
            with self._lock:
                p = self.phases.setdefault(name, _PhaseAccum())
        return p

    # -- views ----------------------------------------------------------
    def reset(self) -> None:
        with self._lock:
            self.counters.clear()
            self.gauges.clear()
            self.histograms.clear()
            self.phases.clear()

    def snapshot(self) -> Dict[str, Any]:
        """JSON-safe dump of every instrument (label items as lists)."""
        with self._lock:
            return {
                "counters": [
                    {"name": c.name, "labels": [list(kv) for kv in c.labels],
                     "value": c.value, "events": c.events}
                    for c in self.counters.values()],
                "gauges": [
                    {"name": g.name, "labels": [list(kv) for kv in g.labels],
                     "value": g.value, "updated_at": g.updated_at}
                    for g in self.gauges.values()],
                "histograms": [
                    dict({"name": h.name,
                          "labels": [list(kv) for kv in h.labels]},
                         **h.snapshot())
                    for h in self.histograms.values()],
                "phases": [
                    {"name": name, "seconds": p.total, "count": p.count}
                    for name, p in self.phases.items()],
            }


# ---------------------------------------------------------------------------
# module-global state: ONE registry, one enabled flag, one span stack
# ---------------------------------------------------------------------------
_registry = Registry()
_enabled = os.environ.get("LGBM_TPU_TIMETAG",
                          os.environ.get("LGBM_TPU_TELEMETRY", "")) \
    not in ("", "0", "false")

# innermost open span per thread — the compile observer charges jax
# compile events to it (observer.py)
_local = threading.local()


def registry() -> Registry:
    return _registry


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = bool(on)


def enabled() -> bool:
    return _enabled


def reset() -> None:
    _registry.reset()


def current_site() -> Optional[str]:
    """Name of this thread's innermost open span (compile attribution)."""
    stack = getattr(_local, "spans", None)
    return stack[-1] if stack else None


# ---------------------------------------------------------------------------
# fast-path helpers (the only functions hot loops should call)
# ---------------------------------------------------------------------------
def counter_add(name: str, value: float = 1.0,
                labels: Optional[Dict] = None) -> None:
    """Accumulate into a counter; free when telemetry is disabled."""
    if _enabled:
        _registry.counter(name, labels).inc(value)


def gauge_set(name: str, value: float, labels: Optional[Dict] = None) -> None:
    if _enabled:
        _registry.gauge(name, labels).set(value)


def observe(name: str, value: float, labels: Optional[Dict] = None,
            bounds: Iterable[float] = DEFAULT_TIME_BUCKETS) -> None:
    if _enabled:
        _registry.histogram(name, labels, bounds).observe(value)


class _NullSpan:
    """Disabled-path span: ONE module-level instance, allocation-free."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Wall-clock span charged to a phase accumulator. `block` is an
    optional array/pytree block_until_ready'd before the clock stops, so
    async device work lands in the right phase."""

    __slots__ = ("name", "block", "t0")

    def __init__(self, name: str, block=None):
        self.name = name
        self.block = block
        self.t0 = 0.0

    def __enter__(self):
        stack = getattr(_local, "spans", None)
        if stack is None:
            stack = _local.spans = []
        stack.append(self.name)
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        try:
            if self.block is not None:
                import jax
                jax.block_until_ready(self.block)
        finally:
            acc = _registry.phase(self.name)
            acc.total += time.perf_counter() - self.t0
            acc.count += 1
            _local.spans.pop()
        return False


def span(name: str, block=None):
    """Context manager timing a named phase (tracing.phase semantics);
    returns the shared no-op singleton when disabled."""
    if not _enabled:
        return _NULL_SPAN
    return _Span(name, block)


def block(x):
    """Block on device values inside an open span (when enabled)."""
    if _enabled and x is not None:
        import jax
        jax.block_until_ready(x)
    return x


# ---------------------------------------------------------------------------
# heartbeat: last-seen-iteration evidence for watchdogs
# ---------------------------------------------------------------------------
# cached at import: heartbeats must stay one env-dict lookup away from
# free in the common (unset) case
_HEARTBEAT_FILE = os.environ.get("LGBM_TPU_HEARTBEAT_FILE", "")


def set_heartbeat_file(path: str) -> None:
    global _HEARTBEAT_FILE
    _HEARTBEAT_FILE = path or ""


def heartbeat_file() -> str:
    return _HEARTBEAT_FILE


def heartbeat(iteration: int, phase: str = "train",
              rank: Optional[int] = None) -> None:
    """Record liveness: a gauge (when telemetry is on) and — when a
    heartbeat file is armed (LGBM_TPU_HEARTBEAT_FILE, set per rank by
    watchdog harnesses like scripts/dryrun_multichip.py, or derived
    from tpu_heartbeat_dir) — an atomically replaced one-line JSON file
    carrying (rank, iteration, phase, time, pid, lease_s), the artifact
    a timed-out run's parent reads to say WHERE each rank was. The
    lease stamp lets any reader (`parallel.watchdog.read_cohort`)
    classify the rank alive/expired without knowing the run's config.
    File writes go through the durable layer with fsync OFF and zero
    retries (evidence, not durability — a heartbeat sleeping in retry
    backoff reads as an expired lease): failures drop into the
    `watchdog/heartbeat_write_errors` counter, never into training."""
    if _enabled:
        _registry.gauge("heartbeat/iteration",
                        {"phase": phase}).set(float(iteration))
    if _HEARTBEAT_FILE:
        import json
        lease = 0.0
        try:
            from ..parallel import watchdog as _wd
            if rank is None:
                # watchdog.current_rank, NOT the raw env var: under
                # machine-list / explicit-param launches the rank is
                # resolved inside init_distributed and configured by
                # GBDT.init — the env default of 0 would stamp every
                # rank's heartbeat as rank 0 and collapse the
                # supervisor's cohort view into one entry
                rank = _wd.current_rank()
            lease = _wd.lease_s()
        except Exception:  # pragma: no cover — import-order edge
            if rank is None:
                rank = int(os.environ.get("LGBM_TPU_RANK", "0") or 0)
        rec = {"rank": int(rank), "iteration": int(iteration),
               "phase": str(phase), "time": time.time(),
               "pid": os.getpid()}
        if lease > 0:
            rec["lease_s"] = lease
        from .. import durable
        durable.best_effort_write_text(
            _HEARTBEAT_FILE, json.dumps(rec) + "\n",
            stream="watchdog.heartbeat",
            counter="watchdog/heartbeat_write_errors")
