"""Unified telemetry subsystem (subsumes the old flat `tracing.py`).

Four pieces, one import surface:

- `metrics` — labeled counters/gauges/bucketed histograms + span-scoped
  timers (`span(name, block=...)` charges async device work via
  block_until_ready). Zero-allocation when disabled.
- `runlog` — the structured JSONL run log: header + one record per
  boosting iteration + events + summary, written alongside PR 3's
  checkpoints so a preempted run leaves a readable trail.
- `observer` — compile/retrace accounting hooked into `jax.monitoring`,
  attributed to the innermost open span; warns on retrace storms.
- `export` — Prometheus text-exposition file dump with multihost rank
  labels and end-of-run cross-rank aggregation.

Enablement: metric collection turns on via `LGBM_TPU_TIMETAG=1` /
`LGBM_TPU_TELEMETRY=1` (the historical tracing switch), the
`tpu_telemetry` config param, or automatically for the duration of a
run when `tpu_telemetry_dir` is set. `lightgbm_tpu.tracing` remains as
a thin back-compat shim over this package.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

from .metrics import (DEFAULT_TIME_BUCKETS, Counter, Gauge, Histogram,
                      Registry, block, counter_add, current_site, enable,
                      enabled, gauge_set, heartbeat, observe, registry,
                      reset, set_heartbeat_file, span)
from .observer import CompileObserver, install as install_observer, observer
from .runlog import (SCHEMA_VERSION, RunLog, TrainRecorder, read_records,
                     validate_record)

__all__ = [
    "DEFAULT_TIME_BUCKETS", "Counter", "Gauge", "Histogram", "Registry",
    "RunLog", "TrainRecorder", "CompileObserver", "SCHEMA_VERSION",
    "active_recorder", "block", "counter_add", "current_site", "enable",
    "enabled", "gauge_set", "heartbeat", "observe", "observer",
    "install_observer", "registry", "reset", "read_records",
    "set_active_recorder", "set_heartbeat_file", "span",
    "start_run", "validate_record", "dump",
]

# the recorder of the training run currently in flight (engine.train
# installs/clears it): lets out-of-band reporters — the collective
# watchdog's expiry path above all — append structured events to the
# run log without plumbing a recorder reference through every layer
_ACTIVE_RECORDER: Optional["TrainRecorder"] = None


def set_active_recorder(rec: Optional["TrainRecorder"]) -> None:
    global _ACTIVE_RECORDER
    _ACTIVE_RECORDER = rec


def active_recorder() -> Optional["TrainRecorder"]:
    return _ACTIVE_RECORDER


def start_run(gbdt, params: Dict[str, Any]) -> Optional[TrainRecorder]:
    """Engine entry point: arm telemetry for one training run.

    Returns a TrainRecorder when telemetry is active (tpu_telemetry_dir
    set, tpu_telemetry=true, or the registry already enabled via env),
    None otherwise — the engine treats None as "stay silent". With a
    telemetry dir the recorder also owns the JSONL run log; without one
    it still keeps span/counter/compile accounting for the exit dump."""
    cfg = gbdt.config
    directory = getattr(cfg.io, "tpu_telemetry_dir", "") or ""
    want = bool(directory) or bool(getattr(cfg.io, "tpu_telemetry", False))
    if not (want or enabled()):
        return None
    was_enabled = enabled()
    enable(True)
    install_observer()

    rank, world = 0, 1
    try:
        import jax
        rank, world = jax.process_index(), jax.process_count()
    except Exception:  # pragma: no cover — backend-free unit tests
        pass

    run_log = None
    if directory:
        run_log = RunLog(directory, rank=rank)

    from .. import checkpoint as ckpt
    # global rows, matching engine._setup_checkpointing: the run-log
    # header's fingerprint must stay stable across world sizes so an
    # elastically-resumed run's trail chains to the original's
    n_fp = int(getattr(getattr(gbdt, "train_data", None),
                       "num_global_rows", 0) or getattr(gbdt, "_n", 0))
    fingerprint = ckpt.config_fingerprint(
        cfg.raw_params, n_fp,
        int(getattr(gbdt, "max_feature_idx", -1)) + 1, cfg.boosting_type)
    rec = TrainRecorder(gbdt, run_log, rank=rank, world=world,
                        fingerprint=fingerprint, params=params,
                        prometheus=bool(
                            getattr(cfg.io, "tpu_telemetry_prometheus",
                                    True)))
    # dir-based runs restore the disabled default at close (their output
    # is the run log + prom files); tpu_telemetry=true asked for the
    # TIMETAG-style accumulate-and-dump-at-exit behavior, so it stays on
    rec.disable_on_close = not was_enabled and run_log is not None \
        and not getattr(cfg.io, "tpu_telemetry", False)
    return rec


def dump() -> None:
    """Log the accumulated phase timers + counters (the TIMETAG exit
    printout shape; kept for tracing back-compat)."""
    from .. import log
    reg = registry()
    if reg.phases:
        log.info("=== phase timers ===")
        for name in sorted(reg.phases, key=lambda n: reg.phases[n].total,
                           reverse=True):
            acc = reg.phases[name]
            log.info("%-28s %8.3f s  x%d", name, acc.total, acc.count)
    counters = {}
    for c in reg.counters.values():
        if not c.labels:
            counters[c.name] = (c.value, c.events)
    if counters:
        log.info("=== counters ===")
        for name in sorted(counters, key=lambda n: counters[n][0],
                           reverse=True):
            v, e = counters[name]
            log.info("%-28s %12.0f  x%d", name, v, e)
    obs = observer()
    if obs.total_compiles:
        snap = obs.snapshot()
        log.info("=== compilation ===")
        for site, rec in sorted(snap["sites"].items(),
                                key=lambda kv: kv[1]["seconds"],
                                reverse=True):
            log.info("%-28s %8.3f s  x%d", site, rec["seconds"],
                     rec["compiles"])
