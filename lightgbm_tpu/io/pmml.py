"""PMML exporter (reference: pmml/pmml.py, 149 LoC — regression/binary
models only).

Emits a PMML 4.2 MiningModel whose segmentation sums one TreeModel per
boosted tree, predicates from the raw-space thresholds. Works from Tree
objects instead of re-parsing model text (the reference script walks the
text file); categorical one-vs-rest splits map to equal/notEqual
predicates like the reference's decision_type==1 case.

Usage:
    python -m lightgbm_tpu.io.pmml model.txt > model.pmml
    from lightgbm_tpu.io.pmml import model_to_pmml
"""
from __future__ import annotations

import itertools
from typing import List


def _tree_nodes(tree, feature_names: List[str], out: List[str],
                unique_id, indent: int) -> None:
    def emit(line, depth):
        out.append("\t" * depth + line)

    def predicate(parent_idx: int, is_left: bool, depth: int) -> None:
        feat = feature_names[tree.split_feature[parent_idx]]
        is_cat = bool(tree.decision_type[parent_idx] & 1)
        if is_cat:
            op = "equal" if is_left else "notEqual"
            # one-vs-rest: the single raw category in the node's bitset
            val = _cat_value(tree, parent_idx)
        else:
            op = "lessOrEqual" if is_left else "greaterThan"
            val = tree.threshold[parent_idx]
        emit(f'<SimplePredicate field="{feat}"  operator="{op}" '
             f'value="{val}" />', depth + 1)

    def walk(node_id: int, depth: int, is_left: bool, parent_idx: int):
        if node_id < 0:
            leaf = ~node_id
            score = tree.leaf_value[leaf]
            count = int(tree.leaf_count[leaf])
            emit(f'<Node id="{next(unique_id)}" score="{score}" '
                 f' recordCount="{count}">', depth)
            predicate(parent_idx, is_left, depth)
            emit("</Node>", depth)
            return
        score = tree.internal_value[node_id]
        count = int(tree.internal_count[node_id])
        emit(f'<Node id="{next(unique_id)}" score="{score}" '
             f' recordCount="{count}">', depth)
        predicate(parent_idx, is_left, depth)
        walk(tree.left_child[node_id], depth + 1, True, node_id)
        walk(tree.right_child[node_id], depth + 1, False, node_id)
        emit("</Node>", depth)

    emit('<TreeModel functionName="regression" '
         'splitCharacteristic="binarySplit">', indent)
    emit("<MiningSchema>", indent + 1)
    for name in feature_names:
        emit(f'<MiningField name="{name}"/>', indent + 2)
    emit("</MiningSchema>", indent + 1)
    if tree.num_leaves <= 1:
        emit(f'<Node id="{next(unique_id)}" score="{tree.leaf_value[0]}" '
             f'recordCount="{int(tree.leaf_count[0])}">', indent + 1)
        emit("<True/>", indent + 2)
        emit("</Node>", indent + 1)
    else:
        emit(f'<Node id="{next(unique_id)}" '
             f'score="{tree.internal_value[0]}" '
             f'recordCount="{int(tree.internal_count[0])}">', indent + 1)
        emit("<True/>", indent + 2)
        walk(tree.left_child[0], indent + 2, True, 0)
        walk(tree.right_child[0], indent + 2, False, 0)
        emit("</Node>", indent + 1)
    emit("</TreeModel>", indent)


def _cat_value(tree, node_idx: int):
    idx = int(tree.threshold_in_bin[node_idx])
    lo, hi = tree.cat_boundaries[idx], tree.cat_boundaries[idx + 1]
    words = tree.cat_threshold[lo:hi]
    for w, word in enumerate(words):
        for b in range(32):
            if int(word) >> b & 1:
                return w * 32 + b
    return 0


def model_to_pmml(booster) -> str:
    """Booster (or GBDT) -> PMML document string."""
    inner = getattr(booster, "_inner", booster)
    if inner.num_tree_per_iteration > 1:
        raise ValueError(
            "PMML export supports regression/binary models only "
            "(reference pmml/pmml.py has the same restriction)")
    feature_names = list(inner.feature_names)
    out: List[str] = []
    uid = itertools.count()
    out.append('<PMML version="4.2" xmlns="http://www.dmg.org/PMML-4_2">')
    out.append('\t<Header copyright="lightgbm_tpu"/>')
    out.append("\t<DataDictionary>")
    for name in feature_names:
        out.append(f'\t\t<DataField name="{name}" optype="continuous" '
                   'dataType="double"/>')
    out.append("\t</DataDictionary>")
    out.append('\t<MiningModel functionName="regression">')
    out.append("\t\t<MiningSchema>")
    for name in feature_names:
        out.append(f'\t\t\t<MiningField name="{name}"/>')
    out.append("\t\t</MiningSchema>")
    out.append('\t\t<Segmentation multipleModelMethod="sum">')
    for i, tree in enumerate(inner.models):
        out.append(f'\t\t\t<Segment id="{i}">')
        out.append("\t\t\t\t<True/>")
        _tree_nodes(tree, feature_names, out, uid, 4)
        out.append("\t\t\t</Segment>")
    out.append("\t\t</Segmentation>")
    out.append("\t</MiningModel>")
    out.append("</PMML>")
    return "\n".join(out) + "\n"


def main(argv=None) -> int:
    import sys
    argv = argv if argv is not None else sys.argv[1:]
    if len(argv) != 1:
        print("usage: python -m lightgbm_tpu.io.pmml <model.txt>",
              file=sys.stderr)
        return 2
    from ..basic import Booster
    booster = Booster(model_file=argv[0])
    sys.stdout.write(model_to_pmml(booster))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
