"""Data-file parsing: CSV / TSV / LibSVM with format auto-detection.

Behavioral port of the reference parser stack (`src/io/parser.cpp:1-258`,
`parser.hpp`): the format is detected from the first lines (tab/comma
separated vs `idx:value` pairs), the label is column 0 by default, and
LibSVM sparse rows are densified (the TPU dataset is dense-binned anyway).
A fast native path (C++, `native/parser.cpp`) is used when the compiled
extension is available; this numpy fallback is always correct.
"""
from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from .. import log


def detect_format(path: str, has_header: bool = False) -> str:
    """Reference: Parser::CreateParser autodetect (parser.cpp:200-258)."""
    with open(path) as fh:
        lines = []
        for _ in range(32):
            line = fh.readline()
            if not line:
                break
            if line.strip():
                lines.append(line.strip())
    if has_header and lines:
        lines = lines[1:]
    if not lines:
        log.fatal("Data file %s is empty" % path)
    sample = lines[0]
    tokens = sample.replace("\t", " ").replace(",", " ").split()
    colon = sum(1 for t in tokens if ":" in t)
    if colon >= max(1, len(tokens) - 1):
        return "libsvm"
    if "\t" in sample:
        return "tsv"
    if "," in sample:
        return "csv"
    return "tsv"  # whitespace separated


def load_data_file(path: str, has_header: bool = False,
                   label_column: int = 0
                   ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Load a data file into (features, label). Mirrors
    DatasetLoader::LoadFromFile's parsing stage (dataset_loader.cpp:159-217)
    without the distributed partitioning (see parallel/loader.py for that).
    """
    fmt = detect_format(path, has_header)
    if fmt == "libsvm":
        return _load_libsvm(path)
    delim = "," if fmt == "csv" else None
    # native fast path for single-character delimiters (tab/comma); the
    # whitespace-split variant stays in Python
    native_delim = None
    if fmt == "csv":
        native_delim = ","
    elif fmt == "tsv":
        with open(path) as fh:
            first = fh.readline()
        if "\t" in first:
            native_delim = "\t"
    if native_delim is not None:
        mat = _native_parse(path, native_delim, has_header)
        if mat is not None:
            labels = mat[:, label_column]
            data = np.delete(mat, label_column, axis=1)
            return np.ascontiguousarray(data), labels.copy()
    rows: List[List[float]] = []
    labels: List[float] = []
    with open(path) as fh:
        if has_header:
            fh.readline()
        for line in fh:
            line = line.strip()
            if not line:
                continue
            parts = line.split(delim) if delim else line.split()
            vals = [_parse_float(p) for p in parts]
            labels.append(vals[label_column])
            rows.append(vals[:label_column] + vals[label_column + 1:])
    data = np.asarray(rows, np.float64)
    return data, np.asarray(labels, np.float64)


_native_lib = None
_native_tried = False


def _native_parse(path: str, delim: str, has_header: bool):
    """Parse via native/parser_native.so (native/parser.cpp) when built;
    returns None to fall back to the Python path."""
    global _native_lib, _native_tried
    if not _native_tried:
        _native_tried = True
        import ctypes
        so = os.path.join(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))),
            "native", "parser_native.so")
        if os.path.exists(so):
            try:
                lib = ctypes.CDLL(so)
                lib.lgbm_tpu_parse_dense.restype = ctypes.c_int
                lib.lgbm_tpu_free.argtypes = [ctypes.POINTER(ctypes.c_double)]
                _native_lib = lib
            except OSError as e:
                log.warning("native parser unavailable: %s", e)
    if _native_lib is None:
        return None
    import ctypes
    rows = ctypes.c_int64(0)
    cols = ctypes.c_int64(0)
    data = ctypes.POINTER(ctypes.c_double)()
    rc = _native_lib.lgbm_tpu_parse_dense(
        path.encode(), ctypes.c_char(delim.encode()),
        1 if has_header else 0, ctypes.byref(rows), ctypes.byref(cols),
        ctypes.byref(data))
    if rc != 0:
        return None
    try:
        mat = np.ctypeslib.as_array(
            data, shape=(rows.value, cols.value)).copy()
    finally:
        _native_lib.lgbm_tpu_free(data)
    return mat


def _parse_float(tok: str) -> float:
    tok = tok.strip()
    if not tok or tok.lower() in ("na", "nan", "null", "none", "?"):
        return float("nan")
    try:
        return float(tok)
    except ValueError:
        return float("nan")


def _load_libsvm(path: str) -> Tuple[np.ndarray, np.ndarray]:
    labels: List[float] = []
    rows: List[List[Tuple[int, float]]] = []
    max_idx = -1
    with open(path) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            labels.append(_parse_float(parts[0]))
            row = []
            for tok in parts[1:]:
                if ":" not in tok:
                    continue
                idx_s, val_s = tok.split(":", 1)
                # qid tokens are query markers, not features
                if idx_s == "qid":
                    continue
                idx = int(idx_s)
                row.append((idx, _parse_float(val_s)))
                max_idx = max(max_idx, idx)
            rows.append(row)
    n = len(rows)
    data = np.zeros((n, max_idx + 1), np.float64)
    for i, row in enumerate(rows):
        for idx, val in row:
            data[i, idx] = val
    return data, np.asarray(labels, np.float64)


def load_query_file(path: str) -> Optional[np.ndarray]:
    """Reference: Metadata query file `<data>.query` (metadata.cpp)."""
    qfile = path + ".query"
    if not os.path.exists(qfile):
        return None
    with open(qfile) as fh:
        return np.asarray([int(x) for x in fh.read().split()], np.int64)


def load_weight_file(path: str) -> Optional[np.ndarray]:
    wfile = path + ".weight"
    if not os.path.exists(wfile):
        return None
    with open(wfile) as fh:
        return np.asarray([float(x) for x in fh.read().split()], np.float64)
