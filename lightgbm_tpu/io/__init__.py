from . import parser  # noqa: F401
