"""Host-side tree model: fixed-capacity struct-of-arrays + text round-trip.

Mirrors the reference `Tree` (include/LightGBM/tree.h:20-450,
src/io/tree.cpp): leaf-wise tree stored as parallel arrays over internal
nodes (children encode leaves as `~leaf`), with LightGBM's `Tree=` text
block format (tree.cpp:208-260) for model save/load — models written here
are loadable by the reference and vice versa for the feature subset both
support (numerical + one-vs-rest categorical splits).
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from . import log
from .binning import MISSING_NAN, MISSING_NONE, MISSING_ZERO

# decision_type bit layout (reference: tree.h:268-284)
_CAT_MASK = 1
_DEFAULT_LEFT_MASK = 2


def _avoid_inf(x: float) -> float:
    """Reference: Common::AvoidInf (clamps +-inf thresholds for text IO)."""
    if np.isnan(x):
        return 0.0
    if x >= 1e300:
        return 1e300
    if x <= -1e300:
        return -1e300
    return float(x)


class Tree:
    """One decision tree (host representation)."""

    def __init__(self, num_leaves: int = 1):
        self.num_leaves = num_leaves
        # False for models loaded from reference-LightGBM text (no tpu_*
        # lines); binned-matrix traversal requires attach_bin_metadata first
        self.has_bin_metadata = True
        m = max(num_leaves - 1, 1)
        self.split_feature_inner = np.zeros(m, np.int32)   # used-feature space
        self.split_feature = np.zeros(m, np.int32)         # original columns
        self.threshold_in_bin = np.zeros(m, np.int32)
        self.threshold = np.zeros(m, np.float64)
        self.decision_type = np.zeros(m, np.int32)
        self.split_gain = np.zeros(m, np.float64)
        self.left_child = np.full(m, -1, np.int32)
        self.right_child = np.full(m, -1, np.int32)
        self.leaf_value = np.zeros(num_leaves, np.float64)
        self.leaf_count = np.zeros(num_leaves, np.int64)
        self.internal_value = np.zeros(m, np.float64)
        self.internal_count = np.zeros(m, np.int64)
        self.shrinkage = 1.0
        # categorical bitsets (reference: tree.h:355-359, tree.cpp:71-97):
        # a categorical node stores a cat_idx in threshold=; the category
        # set is bits [cat_boundaries[idx], cat_boundaries[idx+1]) words of
        # cat_threshold (raw category values) / cat_threshold_inner (bins)
        self.num_cat = 0
        self.cat_boundaries = np.zeros(1, np.int32)        # word offsets
        self.cat_threshold = np.zeros(0, np.uint32)        # raw-value bitset
        self.cat_boundaries_inner = np.zeros(1, np.int32)
        self.cat_threshold_inner = np.zeros(0, np.uint32)  # bin-space bitset
        # device-traversal metadata (not serialized; rebuilt on load)
        self.node_missing = np.zeros(m, np.int32)
        self.node_nan_bin = np.zeros(m, np.int32)
        self.node_default_bin = np.zeros(m, np.int32)
        # EFB locators for binned traversal (efb.py): the stored column and
        # bin offset of each node's feature
        self.node_group = np.zeros(m, np.int32)
        self.node_offset = np.zeros(m, np.int32)
        self.node_bundled = np.zeros(m, bool)
        self.node_num_bin = np.zeros(m, np.int32)
        # piecewise-linear leaves (linear_tree=true): per-leaf slope
        # tables [L, k]; k=0 marks a constant-leaf tree. Feature slots
        # are -1-padded; leaf_value doubles as the fitted intercept.
        self.leaf_coeff = np.zeros((num_leaves, 0), np.float64)
        self.leaf_features = np.full((num_leaves, 0), -1, np.int32)        # original columns
        self.leaf_features_inner = np.full((num_leaves, 0), -1, np.int32)  # used-feature space

    # ------------------------------------------------------------------
    @staticmethod
    def _bitset(values) -> np.ndarray:
        """Reference: Common::ConstructBitset (common.h)."""
        values = [int(v) for v in values if v >= 0]
        nwords = (max(values) // 32 + 1) if values else 1
        words = np.zeros(nwords, np.uint32)
        for v in values:
            words[v // 32] |= np.uint32(1) << np.uint32(v % 32)
        return words

    @staticmethod
    def _in_bitset(words: np.ndarray, val: int) -> bool:
        """Reference: Common::FindInBitset."""
        if val < 0:
            return False
        w = val // 32
        if w >= len(words):
            return False
        return bool((int(words[w]) >> (val % 32)) & 1)

    def _push_cat(self, raw_values, bin_values) -> int:
        """Append one categorical node's bitsets; returns its cat_idx."""
        idx = self.num_cat
        raw_words = self._bitset(raw_values)
        bin_words = self._bitset(bin_values)
        self.cat_threshold = np.concatenate([self.cat_threshold, raw_words])
        self.cat_boundaries = np.append(
            self.cat_boundaries, self.cat_boundaries[-1] + len(raw_words)
        ).astype(np.int32)
        self.cat_threshold_inner = np.concatenate(
            [self.cat_threshold_inner, bin_words])
        self.cat_boundaries_inner = np.append(
            self.cat_boundaries_inner,
            self.cat_boundaries_inner[-1] + len(bin_words)).astype(np.int32)
        self.num_cat += 1
        return idx

    def cat_values(self, node: int) -> list:
        """Raw category values going left at a categorical node."""
        idx = int(self.threshold[node])
        lo, hi = self.cat_boundaries[idx], self.cat_boundaries[idx + 1]
        words = self.cat_threshold[lo:hi]
        return [w * 32 + b for w in range(len(words)) for b in range(32)
                if (int(words[w]) >> b) & 1]

    # ------------------------------------------------------------------
    @classmethod
    def from_grower_state(cls, state, dataset) -> "Tree":
        """Convert a TreeGrowerState (learner/grow.py) into a host Tree,
        resolving bin thresholds to raw-space values via the BinMappers
        (reference: SerialTreeLearner::Split computes threshold_double via
        BinToValue, serial_tree_learner.cpp:519-560)."""
        nl = int(state.num_leaves_used)
        t = cls(nl)
        m = nl - 1
        if m <= 0:
            t.leaf_value[0] = float(np.asarray(state.leaf_value)[0])
            cnt = np.asarray(state.count)
            t.leaf_count[0] = int(cnt[0])
            t._take_linear(state, dataset, nl)
            return t
        feat = np.asarray(state.node_feature)[:m]
        thr = np.asarray(state.node_threshold)[:m]
        dl = np.asarray(state.node_default_left)[:m]
        cat = np.asarray(state.node_is_cat)[:m]
        t.split_feature_inner = feat.astype(np.int32)
        t.split_feature = np.asarray(
            [dataset.real_feature_index(int(j)) for j in feat], np.int32)
        t.threshold_in_bin = thr.astype(np.int32)
        t.split_gain = np.asarray(state.node_gain)[:m].astype(np.float64)
        t.left_child = np.asarray(state.node_left)[:m].astype(np.int32)
        t.right_child = np.asarray(state.node_right)[:m].astype(np.int32)
        t.internal_value = np.asarray(state.node_value)[:m].astype(np.float64)
        t.internal_count = np.asarray(state.node_count)[:m].astype(np.int64)
        t.leaf_value = np.asarray(state.leaf_value)[:nl].astype(np.float64)
        t.leaf_count = np.asarray(state.count)[:nl].astype(np.int64)
        fm = dataset.feature_meta_arrays()
        for i in range(m):
            mapper = dataset.feature_mapper(int(feat[i]))
            t.node_missing[i] = mapper.missing_type
            t.node_nan_bin[i] = mapper.num_bin - 1
            t.node_default_bin[i] = mapper.default_bin
            t.node_group[i] = fm["group"][feat[i]]
            t.node_offset[i] = fm["offset"][feat[i]]
            t.node_bundled[i] = fm["is_bundled"][feat[i]]
            t.node_num_bin[i] = mapper.num_bin
            dt = 0
            if cat[i]:
                dt |= _CAT_MASK
                # one-vs-rest: the bin in thr goes left; serialize as a
                # cat_idx into single-category bitsets (tree.cpp:71-97);
                # threshold/threshold_in_bin both hold the cat_idx
                raw_val = int(mapper.bin_to_value(int(thr[i])))
                cat_idx = t._push_cat([raw_val], [int(thr[i])])
                t.threshold[i] = float(cat_idx)
                t.threshold_in_bin[i] = cat_idx
            else:
                if dl[i]:
                    dt |= _DEFAULT_LEFT_MASK
                t.threshold[i] = _avoid_inf(mapper.bin_to_value(int(thr[i])))
            # missing type bits 2-3 (tree.h:268-284)
            dt |= {MISSING_NONE: 0, MISSING_ZERO: 1 << 2, MISSING_NAN: 2 << 2}[
                mapper.missing_type]
            t.decision_type[i] = dt
        t._take_linear(state, dataset, nl)
        return t

    def _take_linear(self, state, dataset, nl: int) -> None:
        """Adopt linear-leaf tables from a grower state (duck-typed:
        constant-leaf states simply lack the attributes)."""
        coeff = getattr(state, "leaf_coeff", None)
        if coeff is None:
            return
        coeff = np.asarray(coeff)[:nl].astype(np.float64)
        inner = np.asarray(
            getattr(state, "leaf_features_inner"))[:nl].astype(np.int32)
        self.leaf_coeff = coeff
        self.leaf_features_inner = inner
        self.leaf_features = np.asarray(
            [[dataset.real_feature_index(int(j)) if j >= 0 else -1
              for j in row] for row in inner], np.int32).reshape(inner.shape)

    # ------------------------------------------------------------------
    def attach_bin_metadata(self, dataset) -> None:
        """Rebuild bin-space traversal metadata from a Dataset's BinMappers
        for trees loaded from reference-format model text (raw thresholds
        only). The bin threshold is the last bin whose upper bound is <=
        the real threshold, matching `left = value <= threshold_real`."""
        inner_of = {real: inner for inner, real
                    in enumerate(dataset.used_features)}
        m = self.num_leaves - 1
        inner_sets = {}
        fm = dataset.feature_meta_arrays()
        for i in range(m):
            real = int(self.split_feature[i])
            if real not in inner_of:
                log.fatal("Loaded model splits on feature %d which is "
                          "trivial/absent in the dataset" % real)
            inner = inner_of[real]
            mapper = dataset.feature_mapper(inner)
            self.split_feature_inner[i] = inner
            self.node_missing[i] = mapper.missing_type
            self.node_nan_bin[i] = mapper.num_bin - 1
            self.node_default_bin[i] = mapper.default_bin
            self.node_group[i] = fm["group"][inner]
            self.node_offset[i] = fm["offset"][inner]
            self.node_bundled[i] = fm["is_bundled"][inner]
            self.node_num_bin[i] = mapper.num_bin
            if self.is_categorical_node(i):
                # rebuild this node's bin-space bitset from its raw one
                idx = int(self.threshold[i])
                bin_vals = [mapper.categorical_2_bin[c]
                            for c in self.cat_values(i)
                            if c in mapper.categorical_2_bin]
                inner_sets[idx] = self._bitset(bin_vals)
                self.threshold_in_bin[i] = idx
            else:
                self.threshold_in_bin[i] = mapper.value_to_bin(
                    float(self.threshold[i]))
        if self.num_cat > 0:
            bounds = [0]
            for idx in range(self.num_cat):
                words = inner_sets.get(idx, np.zeros(1, np.uint32))
                bounds.append(bounds[-1] + len(words))
            self.cat_boundaries_inner = np.asarray(bounds, np.int32)
            self.cat_threshold_inner = (
                np.concatenate([inner_sets.get(i, np.zeros(1, np.uint32))
                                for i in range(self.num_cat)])
                if self.num_cat else np.zeros(0, np.uint32))
        if self.leaf_coeff.shape[1] > 0:
            # linear leaves address the USED-feature (inner) space of
            # whichever dataset training continues on — remap from the
            # original column ids; a regressed-on feature that is
            # trivial/absent here cannot be evaluated during replay
            remap = np.full(self.leaf_features.shape, -1, np.int32)
            for (r, c), real in np.ndenumerate(self.leaf_features):
                if real < 0:
                    continue
                if int(real) not in inner_of:
                    log.fatal("Loaded linear_tree model regresses on "
                              "feature %d which is trivial/absent in "
                              "the dataset" % int(real))
                remap[r, c] = inner_of[int(real)]
            self.leaf_features_inner = remap
        self.has_bin_metadata = True

    # ------------------------------------------------------------------
    @property
    def is_linear(self) -> bool:
        """True when this tree carries piecewise-linear leaf models."""
        return self.leaf_coeff.shape[1] > 0

    def is_categorical_node(self, i: int) -> bool:
        return bool(self.decision_type[i] & _CAT_MASK)

    def default_left_node(self, i: int) -> bool:
        return bool(self.decision_type[i] & _DEFAULT_LEFT_MASK)

    def missing_type_node(self, i: int) -> int:
        return int(self.decision_type[i] >> 2) & 3

    def apply_shrinkage(self, rate: float) -> None:
        """Reference: Tree::Shrinkage (tree.h:166-173)."""
        self.leaf_value *= rate
        self.internal_value *= rate
        self.leaf_coeff *= rate
        self.shrinkage *= rate

    def add_bias(self, val: float) -> None:
        """Reference: Tree::AddBias (boost_from_average path)."""
        self.leaf_value += val
        self.internal_value += val

    # ------------------------------------------------------------------
    def to_device(self):
        """Build the DeviceTree used by ops/predict.py."""
        import jax.numpy as jnp
        from .ops.predict import DeviceTree
        m = max(self.num_leaves - 1, 1)
        dl = np.asarray([self.default_left_node(i) for i in range(m)], bool)
        cat = np.asarray([self.is_categorical_node(i) for i in range(m)], bool)
        miss = np.asarray([self.missing_type_node(i) for i in range(m)], np.int32)
        # clamp the reference's +-1e300 AvoidInf sentinels into f32 range
        # (a f32 cast would overflow to inf with a RuntimeWarning)
        fmax = float(np.finfo(np.float32).max)
        thr32 = np.clip(self.threshold, -fmax, fmax)
        return DeviceTree(
            num_leaves=jnp.int32(self.num_leaves),
            split_feature=jnp.asarray(self.split_feature_inner),
            threshold_bin=jnp.asarray(self.threshold_in_bin),
            threshold_real=jnp.asarray(thr32, jnp.float32),
            default_left=jnp.asarray(dl),
            is_categorical=jnp.asarray(cat),
            left_child=jnp.asarray(self.left_child),
            right_child=jnp.asarray(self.right_child),
            node_missing=jnp.asarray(miss),
            node_nan_bin=jnp.asarray(self.node_nan_bin),
            node_default_bin=jnp.asarray(self.node_default_bin),
            node_group=jnp.asarray(self.node_group),
            node_offset=jnp.asarray(self.node_offset),
            node_bundled=jnp.asarray(self.node_bundled),
            node_num_bin=jnp.asarray(self.node_num_bin),
            leaf_value=jnp.asarray(self.leaf_value, jnp.float32),
            split_gain=jnp.asarray(self.split_gain, jnp.float32),
            internal_value=jnp.asarray(self.internal_value, jnp.float32),
            internal_count=jnp.asarray(self.internal_count, jnp.float32),
            leaf_count=jnp.asarray(self.leaf_count, jnp.float32),
            cat_boundaries=jnp.asarray(self.cat_boundaries, jnp.int32),
            cat_bitset=jnp.asarray(
                self.cat_threshold if len(self.cat_threshold)
                else np.zeros(1, np.uint32)),
            cat_boundaries_inner=jnp.asarray(self.cat_boundaries_inner, jnp.int32),
            cat_bitset_inner=jnp.asarray(
                self.cat_threshold_inner if len(self.cat_threshold_inner)
                else np.zeros(1, np.uint32)),
            leaf_coeff=jnp.asarray(self.leaf_coeff, jnp.float32),
            leaf_feat=jnp.asarray(self.leaf_features_inner, jnp.int32),
        )

    def to_device_raw(self):
        """DeviceTree for raw-feature traversal (split_feature = original
        column indices, decisions on real thresholds)."""
        dt = self.to_device()
        import jax.numpy as jnp
        return dt._replace(split_feature=jnp.asarray(self.split_feature),
                           leaf_feat=jnp.asarray(self.leaf_features))

    # ------------------------------------------------------------------
    def _leaf_output(self, leaf: int, row: np.ndarray) -> float:
        """Leaf value plus the linear term. A row with a non-finite value
        in any live feature slot gets the intercept only (the solver
        excluded such rows from the fit the same way)."""
        val = float(self.leaf_value[leaf])
        acc = 0.0
        for j in range(self.leaf_coeff.shape[1]):
            f = int(self.leaf_features[leaf, j])
            if f < 0:
                continue
            fval = row[f]
            if not np.isfinite(fval):
                return val
            acc += float(self.leaf_coeff[leaf, j]) * float(fval)
        return val + acc

    def predict_row(self, row: np.ndarray) -> float:
        """Scalar reference traversal (tree.h:416-450) for testing/host paths."""
        if self.num_leaves <= 1:
            return self._leaf_output(0, row)
        node = 0
        while node >= 0:
            fval = row[self.split_feature[node]]
            if self.is_categorical_node(node):
                idx = int(self.threshold[node])
                lo, hi = self.cat_boundaries[idx], self.cat_boundaries[idx + 1]
                go_left = (not np.isnan(fval)) and self._in_bitset(
                    self.cat_threshold[lo:hi], int(fval))
            else:
                mt = self.missing_type_node(node)
                is_missing = (mt == MISSING_NAN and np.isnan(fval)) or \
                             (mt == MISSING_ZERO and (np.isnan(fval) or abs(fval) <= 1e-35))
                if is_missing:
                    go_left = self.default_left_node(node)
                else:
                    go_left = fval <= self.threshold[node]
            node = self.left_child[node] if go_left else self.right_child[node]
        return self._leaf_output(~node, row)

    # ------------------------------------------------------------------
    # text model format (reference: Tree::ToString, tree.cpp:208-260)
    def to_string(self) -> str:
        m = self.num_leaves - 1
        out = []
        out.append(f"num_leaves={self.num_leaves}")
        out.append(f"num_cat={self.num_cat}")
        out.append("split_feature=" + " ".join(str(int(x)) for x in self.split_feature[:m]))
        out.append("split_gain=" + " ".join(repr(float(x)) for x in self.split_gain[:m]))
        out.append("threshold=" + " ".join(repr(float(x)) for x in self.threshold[:m]))
        out.append("decision_type=" + " ".join(str(int(x)) for x in self.decision_type[:m]))
        out.append("left_child=" + " ".join(str(int(x)) for x in self.left_child[:m]))
        out.append("right_child=" + " ".join(str(int(x)) for x in self.right_child[:m]))
        out.append("leaf_value=" + " ".join(repr(float(x)) for x in self.leaf_value[:self.num_leaves]))
        out.append("leaf_count=" + " ".join(str(int(x)) for x in self.leaf_count[:self.num_leaves]))
        out.append("internal_value=" + " ".join(repr(float(x)) for x in self.internal_value[:m]))
        out.append("internal_count=" + " ".join(str(int(x)) for x in self.internal_count[:m]))
        if self.num_cat > 0:
            out.append("cat_boundaries=" + " ".join(
                str(int(x)) for x in self.cat_boundaries[:self.num_cat + 1]))
            out.append("cat_threshold=" + " ".join(
                str(int(x)) for x in self.cat_threshold))
        out.append(f"shrinkage={self.shrinkage}")
        # extension over the reference format: bin-space metadata so loaded
        # models can still traverse binned matrices on device
        out.append("tpu_threshold_in_bin=" + " ".join(str(int(x)) for x in self.threshold_in_bin[:m]))
        out.append("tpu_split_feature_inner=" + " ".join(str(int(x)) for x in self.split_feature_inner[:m]))
        out.append("tpu_nan_bin=" + " ".join(str(int(x)) for x in self.node_nan_bin[:m]))
        out.append("tpu_default_bin=" + " ".join(str(int(x)) for x in self.node_default_bin[:m]))
        # EFB/group locators: without these a text-loaded tree cannot
        # traverse the stored (group-major) binned matrix — they used to
        # be silently zero after load, which corrupted continued-training
        # score replay on any dataset whose groups aren't all column 0
        out.append("tpu_node_group=" + " ".join(str(int(x)) for x in self.node_group[:m]))
        out.append("tpu_node_offset=" + " ".join(str(int(x)) for x in self.node_offset[:m]))
        out.append("tpu_node_bundled=" + " ".join(str(int(x)) for x in self.node_bundled[:m].astype(np.int32)))
        out.append("tpu_node_num_bin=" + " ".join(str(int(x)) for x in self.node_num_bin[:m]))
        if self.num_cat > 0:
            out.append("tpu_cat_boundaries_inner=" + " ".join(
                str(int(x)) for x in self.cat_boundaries_inner[:self.num_cat + 1]))
            out.append("tpu_cat_threshold_inner=" + " ".join(
                str(int(x)) for x in self.cat_threshold_inner))
        if self.is_linear:
            # piecewise-linear leaf tables, flattened row-major [L, k];
            # repr() keeps the f64 coefficients round-trip exact
            out.append(f"tpu_linear_k={self.leaf_coeff.shape[1]}")
            out.append("tpu_leaf_features=" + " ".join(
                str(int(x)) for x in self.leaf_features.ravel()))
            out.append("tpu_leaf_features_inner=" + " ".join(
                str(int(x)) for x in self.leaf_features_inner.ravel()))
            out.append("tpu_leaf_coeff=" + " ".join(
                repr(float(x)) for x in self.leaf_coeff.ravel()))
        return "\n".join(out) + "\n"

    @classmethod
    def from_string(cls, text: str) -> "Tree":
        kv = {}
        for line in text.strip().splitlines():
            if "=" in line:
                k, v = line.split("=", 1)
                kv[k.strip()] = v.strip()
        nl = int(kv["num_leaves"])
        t = cls(nl)
        m = nl - 1

        def arr(key, dtype, size, default=0):
            if key not in kv or not kv[key]:
                return np.full(size, default, dtype)
            vals = kv[key].split()
            return np.asarray([dtype(v) for v in vals], dtype)

        if m > 0:
            t.split_feature = arr("split_feature", np.int32, m)
            t.split_gain = arr("split_gain", np.float64, m)
            t.threshold = arr("threshold", np.float64, m)
            t.decision_type = arr("decision_type", np.int32, m)
            t.left_child = arr("left_child", np.int32, m)
            t.right_child = arr("right_child", np.int32, m)
            t.internal_value = arr("internal_value", np.float64, m)
            t.internal_count = arr("internal_count", np.int64, m)
            # complete bin metadata needs the group locators too: text
            # without them (reference models, or models saved before the
            # locators were serialized) must go through
            # attach_bin_metadata before binned traversal
            t.has_bin_metadata = ("tpu_threshold_in_bin" in kv
                                  and "tpu_node_group" in kv)
            t.threshold_in_bin = arr("tpu_threshold_in_bin", np.int32, m)
            t.split_feature_inner = arr("tpu_split_feature_inner", np.int32, m,
                                        default=-1)
            if (t.split_feature_inner < 0).all():
                t.split_feature_inner = t.split_feature.copy()
            t.node_nan_bin = arr("tpu_nan_bin", np.int32, m)
            t.node_default_bin = arr("tpu_default_bin", np.int32, m)
            t.node_group = arr("tpu_node_group", np.int32, m)
            t.node_offset = arr("tpu_node_offset", np.int32, m)
            t.node_bundled = arr("tpu_node_bundled", np.int32, m).astype(bool)
            t.node_num_bin = arr("tpu_node_num_bin", np.int32, m)
            t.node_missing = np.asarray(
                [t.missing_type_node(i) for i in range(m)], np.int32)
            t.num_cat = int(kv.get("num_cat", 0))
            if t.num_cat > 0:
                t.cat_boundaries = arr("cat_boundaries", np.int32, t.num_cat + 1)
                t.cat_threshold = np.asarray(
                    [np.uint32(v) for v in kv.get("cat_threshold", "").split()],
                    np.uint32)
                inner = kv.get("tpu_cat_threshold_inner", "")
                if inner:
                    t.cat_boundaries_inner = arr(
                        "tpu_cat_boundaries_inner", np.int32, t.num_cat + 1)
                    t.cat_threshold_inner = np.asarray(
                        [np.uint32(v) for v in inner.split()], np.uint32)
                else:
                    # reference text lacks bin-space bitsets; rebuilt on
                    # demand by attach_bin_metadata
                    t.has_bin_metadata = False
        t.leaf_value = arr("leaf_value", np.float64, nl)
        t.leaf_count = arr("leaf_count", np.int64, nl)
        t.shrinkage = float(kv.get("shrinkage", 1.0))
        k = int(kv.get("tpu_linear_k", 0))
        if k > 0:
            t.leaf_features = arr(
                "tpu_leaf_features", np.int32, nl * k, default=-1
            ).reshape(nl, k)
            t.leaf_features_inner = arr(
                "tpu_leaf_features_inner", np.int32, nl * k, default=-1
            ).reshape(nl, k)
            t.leaf_coeff = arr(
                "tpu_leaf_coeff", np.float64, nl * k).reshape(nl, k)
        return t

    # ------------------------------------------------------------------
    def to_json(self) -> dict:
        """Reference: Tree::ToJSON (tree.cpp:262-330)."""
        def node_json(idx: int) -> dict:
            if idx < 0:
                leaf = ~idx
                d = {"leaf_index": int(leaf),
                     "leaf_value": float(self.leaf_value[leaf]),
                     "leaf_count": int(self.leaf_count[leaf])}
                if self.is_linear:
                    live = self.leaf_features[leaf] >= 0
                    d["leaf_features"] = [
                        int(f) for f in self.leaf_features[leaf][live]]
                    d["leaf_coeff"] = [
                        float(c) for c in self.leaf_coeff[leaf][live]]
                return d
            if self.is_categorical_node(idx):
                thr = "||".join(str(c) for c in self.cat_values(idx))
            else:
                thr = float(self.threshold[idx])
            return {
                "split_index": int(idx),
                "split_feature": int(self.split_feature[idx]),
                "split_gain": float(self.split_gain[idx]),
                "threshold": thr,
                "decision_type": "==" if self.is_categorical_node(idx) else "<=",
                "default_left": self.default_left_node(idx),
                "missing_type": ["None", "Zero", "NaN"][self.missing_type_node(idx)],
                "internal_value": float(self.internal_value[idx]),
                "internal_count": int(self.internal_count[idx]),
                "left_child": node_json(int(self.left_child[idx])),
                "right_child": node_json(int(self.right_child[idx])),
            }
        return {"num_leaves": int(self.num_leaves), "shrinkage": self.shrinkage,
                "tree_structure": node_json(0) if self.num_leaves > 1 else
                {"leaf_value": float(self.leaf_value[0])}}
