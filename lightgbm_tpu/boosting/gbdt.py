"""GBDT: the gradient-boosting training loop.

TPU-native re-implementation of the reference GBDT engine
(`src/boosting/gbdt.{h,cpp}` — TrainOneIter at gbdt.cpp:380-474): owns the
tree learner, per-class scores, gradients, bagging, early stopping, model
(de)serialization and prediction. The training set lives on device as a
padded binned matrix; one `TrainOneIter` runs gradients (objective kernel),
bagging weight sampling, and `num_class` jitted tree growths, then updates
train/valid scores with vectorized leaf lookups.
"""
from __future__ import annotations

import contextlib
import copy
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import checkpoint as ckpt
from .. import log
from ..testing import faults
from ..config import Config
from ..dataset import Dataset, Metadata
from ..learner.grow import GrowerConfig, grow_tree
from ..metrics import Metric, create_metric, default_metric_for_objective
from ..objectives import ObjectiveFunction
from ..ops.predict import predict_leaf_binned, predict_value_binned
from ..tree import Tree

_K_EPSILON = 1e-15

# ceiling for the sibling-subtraction histogram cache ([M, G, B, 3] f32
# per class tree); beyond it the grower builds both children directly.
# Deliberately modest: a near-HBM-sized cache (Epsilon-shape at 2 GiB
# measured) thrashes the while-loop carry and stalls training outright
_SUBTRACT_CACHE_BUDGET = 256 << 20


_forest_jit_cache: Dict[str, object] = {}


def _forest_jit(fn_name: str, static=()):
    """Memoized module-level jax.jit of ops.predict.<fn_name>: one
    jitted dispatch over the stacked ensemble instead of one per tree
    (compiled once per (num_trees, max_nodes, num_rows) shape). The
    cache is module-global so traces survive across calls and boosters
    (a fresh jax.jit per call would retrace every time)."""
    f = _forest_jit_cache.get(fn_name)
    if f is None:
        import jax

        from ..ops import predict as predict_ops
        f = jax.jit(getattr(predict_ops, fn_name),
                    static_argnames=tuple(static) or None)
        _forest_jit_cache[fn_name] = f
    return f


def _jit_forest_raw(stacked, data):
    return _forest_jit("predict_forest_raw")(stacked, data)


def _jit_forest_binned(stacked, binned):
    return _forest_jit("predict_forest_binned")(stacked, binned)


def _jit_forest_raw_matmul(mf, data):
    return _forest_jit("predict_forest_raw_matmul")(mf, data)


def _jit_forest_leaf_matmul(mf, data):
    return _forest_jit("predict_forest_leaf_matmul")(mf, data)


def _jit_forest_leaf_raw(stacked, data):
    return _forest_jit("predict_forest_leaf_raw")(stacked, data)


def _jit_forest_f16(mf, data):
    return _forest_jit("predict_forest_f16")(mf, data)


def _jit_forest_quant(qf, data):
    return _forest_jit("predict_forest_quant")(qf, data)


def _jit_forest_es(stacked_kt, data, margin, freq):
    """Margin-based early-stop forest walk (freq is static: it feeds a
    `t % freq` under the iteration while_loop; margin stays a traced
    scalar so sweeping it does not retrace)."""
    import jax.numpy as jnp
    return _forest_jit("predict_forest_raw_early_stop", static=("freq",))(
        stacked_kt, data, jnp.float32(margin), freq=freq)


def objective_array_keys(obj) -> Tuple[str, ...]:
    """Names of the objective's row-array attributes. These are passed
    into gradient jits as ARGUMENTS, never closure captures: a captured
    [N] array gets inlined into the lowered module as a giant literal
    (measured 16 MB of HLO text at 2M rows) and defeats the persistent
    compile cache. Shared by the serial gradient jit below and the
    sweep grower (learner/sweep.py) so the discovery rule cannot
    drift."""
    import jax
    return tuple(sorted(k for k, v in vars(obj).items()
                        if isinstance(v, (np.ndarray, jax.Array))))


@contextlib.contextmanager
def objective_arrays_swapped(obj, arr_keys, arrs):
    """Temporarily rebind the objective's row arrays to the traced
    argument values for the duration of a trace (the companion of
    objective_array_keys)."""
    saved = {k: getattr(obj, k) for k in arr_keys}
    try:
        for k, v in arrs.items():
            setattr(obj, k, v)
        yield
    finally:
        for k, v in saved.items():
            setattr(obj, k, v)


def feature_fraction_mask(rng, frac: float, num_features: int,
                          num_features_padded: int) -> np.ndarray:
    """One per-tree feature_fraction sample
    (serial_tree_learner.cpp:239-257). Module-level because the sweep
    trainer (boosting/sweep.py) draws each model's masks from ITS own
    RandomState with the exact serial expression — sharing the code is
    what keeps the sweep's byte-identity-to-serial contract from
    drifting."""
    f = num_features
    if frac >= 1.0:
        mask = np.ones(f, bool)
    else:
        used = max(1, int(f * frac))
        idx = rng.choice(f, size=used, replace=False)
        mask = np.zeros(f, bool)
        mask[idx] = True
    if num_features_padded > f:
        mask = np.pad(mask, (0, num_features_padded - f))
    return mask


def _pad_to(arr: np.ndarray, n: int, value=0):
    pad = n - arr.shape[0]
    if pad <= 0:
        return arr
    width = [(0, pad)] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, width, constant_values=value)


# small-state fields fetched host-side to build the Tree (everything
# from_grower_state reads — NOT the [N]-sized leaf_id, which stays
# on device)
_SMALL_STATE_KEYS = (
    "num_leaves_used", "leaf_value", "count", "node_feature",
    "node_threshold", "node_default_left", "node_is_cat", "node_left",
    "node_right", "node_gain", "node_value", "node_count", "num_passes",
    "next_free", "comm_elems", "rows_contracted", "pass_rows")


class _HostState:
    """Host-numpy view of the grower small state (duck-typed for
    Tree.from_grower_state)."""

    def __init__(self, d):
        self.__dict__.update(d)


def _grow_and_update_impl(score, binned, grad, hess, row_weight, fmask,
                          shrinkage, n_valid, fmeta_args, cls, cfg,
                          qscale=None):
    """grow one tree + train-score update, fused into ONE device program.

    On a relay-attached TPU every eager op dispatch is a host round trip;
    fusing the per-tree path (grow -> leaf gather -> score add) plus
    returning only the small tree arrays cuts per-tree host traffic to one
    dispatch + one device_get (profiled round 2: the eager chain cost
    ~3x the tree growth itself)."""
    import jax.numpy as jnp

    state = grow_tree(binned, grad, hess, row_weight, fmask, *fmeta_args,
                      cfg, n_valid=n_valid, qscale=qscale)
    grew = state.num_leaves_used > 1
    leaf_vals = state.leaf_value * shrinkage
    delta = jnp.where(
        grew,
        leaf_vals[jnp.clip(state.leaf_id, 0, cfg.num_leaves - 1)], 0.0)
    score = score.at[cls].add(delta)
    small = {k: getattr(state, k) for k in _SMALL_STATE_KEYS}
    return score, small


def _grow_and_update(score, binned, grad, hess, row_weight, fmask,
                     shrinkage, n_valid, fmeta_args, cls, cfg, qscale=None):
    import jax
    import jax.numpy as jnp
    global _grow_and_update_jit
    if _grow_and_update_jit is None:
        _grow_and_update_jit = jax.jit(
            _grow_and_update_impl, static_argnames=("cls", "cfg"))
    return _grow_and_update_jit(score, binned, grad, hess, row_weight,
                                fmask, jnp.float32(shrinkage),
                                jnp.int32(n_valid), tuple(fmeta_args),
                                qscale=qscale, cls=cls, cfg=cfg)


_grow_and_update_jit = None


def _fit_linear_post(raw, grad, hess, row_weight, state, linear_lambda,
                     cfg, k_feats):
    """Post-growth piecewise-linear leaf fit + train-score values, ONE
    device program shared by the serial and distributed paths.

    The fit is deliberately OUTSIDE the grower: it consumes only
    schedule-invariant inputs (final leaf assignment, the leaf->root
    split-feature paths, raw feature values, grad/hess, row weights), so
    a serial grow and a scatter-reduce data-parallel grow that assign
    rows to the same leaves produce BIT-IDENTICAL coefficients — it is
    literally the same compiled program on identical operands (the
    serial-vs-scatter identity test pins this)."""
    import jax
    import jax.numpy as jnp
    global _fit_linear_jit
    if _fit_linear_jit is None:
        def impl(raw, grad, hess, row_weight, leaf_id, leaf_parent,
                 node_feature, node_left, node_right, num_leaves_used,
                 leaf_const, lam, cfg, k_feats):
            from ..learner.grow import leaf_path_features
            from ..linear.solver import fit_leaves, linear_row_values
            lid = jnp.clip(leaf_id, 0, cfg.num_leaves - 1)
            feats = leaf_path_features(leaf_parent, node_feature,
                                       node_left, node_right,
                                       num_leaves_used, k_feats)
            leaf_value, leaf_coeff, _ = fit_leaves(
                raw, grad, hess, row_weight, lid, feats, leaf_const,
                lam, cfg.num_leaves)
            vals = linear_row_values(raw, lid, leaf_value, leaf_coeff,
                                     feats)
            return leaf_value, leaf_coeff, feats, vals

        _fit_linear_jit = jax.jit(impl, static_argnames=("cfg", "k_feats"))
    return _fit_linear_jit(raw, grad, hess, row_weight, state.leaf_id,
                           state.leaf_parent, state.node_feature,
                           state.node_left, state.node_right,
                           state.num_leaves_used, state.leaf_value,
                           jnp.float32(linear_lambda), cfg=cfg,
                           k_feats=k_feats)


_fit_linear_jit = None


def _grow_and_update_multi_impl(score, binned, grads, hesses, row_weight,
                                fmasks, shrinkage, n_valid, fmeta_args, cfg,
                                qscales=None):
    """Grow ALL num_class trees of one boosting iteration in ONE device
    program (vmap over the class axis) and update every score row.

    The reference grows class trees sequentially (gbdt.cpp:410-462,
    one `tree_learner_->Train` per class). SURVEY.md §2.5 marks this the
    EP-analogue free win on TPU: the class trees of an iteration are
    independent given the gradients, so vmap fuses their histogram
    passes into wider contractions and collapses k dispatches + k
    compiled signatures into one."""
    import jax
    import jax.numpy as jnp

    def one(g, h, m, qs=None):
        return grow_tree(binned, g, h, row_weight, m, *fmeta_args,
                         cfg, n_valid=n_valid, qscale=qs)

    if qscales is None:
        state = jax.vmap(one)(grads, hesses, fmasks)
    else:
        # per-class dequant scales ride the class vmap with the grads
        state = jax.vmap(one)(grads, hesses, fmasks, qscales)

    def upd(lv, lid, grew):
        vals = lv * shrinkage
        return jnp.where(grew,
                         vals[jnp.clip(lid, 0, cfg.num_leaves - 1)], 0.0)

    delta = jax.vmap(upd)(state.leaf_value, state.leaf_id,
                          state.num_leaves_used > 1)
    small = {k: getattr(state, k) for k in _SMALL_STATE_KEYS}
    return score + delta, small


def _grow_and_update_multi(score, binned, grads, hesses, row_weight, fmasks,
                           shrinkage, n_valid, fmeta_args, cfg, qscales=None):
    import jax
    import jax.numpy as jnp
    global _grow_and_update_multi_jit
    if _grow_and_update_multi_jit is None:
        _grow_and_update_multi_jit = jax.jit(
            _grow_and_update_multi_impl, static_argnames=("cfg",))
    return _grow_and_update_multi_jit(score, binned, grads, hesses,
                                      row_weight, fmasks,
                                      jnp.float32(shrinkage),
                                      jnp.int32(n_valid),
                                      tuple(fmeta_args), qscales=qscales,
                                      cfg=cfg)


_grow_and_update_multi_jit = None


def _bagging_mask_impl(ridx, *, seed, n, n_pad, fraction):
    import jax
    import jax.numpy as jnp
    key = jax.random.fold_in(jax.random.PRNGKey(seed), ridx)
    # draw over the REAL rows only, then pad: threefry is not
    # prefix-stable across output shapes, so a (n_pad,) draw would make
    # the in-bag mask a function of the padded row count — which varies
    # with the device count, breaking the bit-identity of training
    # across world sizes that elastic resume relies on
    # (scripts/elastic_smoke.py). Over (n,) the mask is a pure function
    # of (seed, iteration, n) at ANY world size.
    u = jax.random.uniform(key, (n,))
    mask = (u < fraction).astype(jnp.float32)
    return jnp.pad(mask, (0, n_pad - n))


_bagging_mask_jit = None


_nonfinite_probe_jit = None


def _nonfinite_probe_device(grad, hess):
    """Device bool scalar: any non-finite gradient/hessian. Returned
    UNFETCHED so the pipelined path can overlap the reduction with tree
    growth and read it at the next flush instead of syncing here."""
    import jax
    import jax.numpy as jnp
    global _nonfinite_probe_jit
    if _nonfinite_probe_jit is None:
        _nonfinite_probe_jit = jax.jit(
            lambda g, h: ~(jnp.isfinite(g).all() & jnp.isfinite(h).all()))
    return _nonfinite_probe_jit(grad, hess)


def _bagging_mask_device(seed: int, refresh_idx, n: int, n_pad: int,
                         fraction: float):
    """[n_pad] f32 in-bag mask on device (no host RNG / H2D transfer)."""
    import jax
    import jax.numpy as jnp
    global _bagging_mask_jit
    if _bagging_mask_jit is None:
        _bagging_mask_jit = jax.jit(
            _bagging_mask_impl,
            static_argnames=("n", "n_pad", "fraction", "seed"))
    return _bagging_mask_jit(jnp.int32(refresh_idx), seed=seed, n=n,
                             n_pad=n_pad, fraction=float(fraction))


_quantize_iter_jit = None


def _quantize_iter_device(grad, hess, row_weight, it, *, seed, n, qmax,
                          hess_const):
    """Quantize one iteration's [k, n_pad] gradient/hessian stack for the
    low-precision histogram path (tpu_hist_quantize, ISSUE 20): one
    device program per iteration, vmapped over the class axis.

    Returns (q_grad, q_hess, w01, qscales): integer-valued [k, n_pad]
    gradient/hessian codes in [-qmax, qmax], the 0/1 row weight (any
    bagging/GOSS weighting is FOLDED INTO the codes — the grower's
    grad*row_weight product then stays integer), and the [k, 3]
    per-class dequantization scales. The rounding keys chain
    fold_in(fold_in(fold_in(PRNGKey(seed), iteration), class), 0|1) —
    structurally distinct from the bagging stream's
    fold_in(PRNGKey(seed), refresh) draw, so sharing the base seed
    cannot collide — and the uniform draw itself rides the serial (n,)
    shape inside quantize_gradients (world-size invariance, same
    rationale as _bagging_mask_impl)."""
    import jax
    import jax.numpy as jnp
    global _quantize_iter_jit
    if _quantize_iter_jit is None:
        def impl(grad, hess, row_weight, it, *, seed, n, qmax, hess_const):
            from ..ops.histogram import quantize_gradients
            base = jax.random.fold_in(jax.random.PRNGKey(seed), it)

            def one(g, h, cls_idx):
                kc = jax.random.fold_in(base, cls_idx)
                return quantize_gradients(
                    g, h, row_weight, n=n, qmax=qmax,
                    key_g=jax.random.fold_in(kc, 0),
                    key_h=jax.random.fold_in(kc, 1),
                    hess_const=hess_const)

            k = grad.shape[0]
            qg, qh, w01, qs = jax.vmap(one)(grad, hess,
                                            jnp.arange(k, dtype=jnp.int32))
            # w01 is class-independent (it only reads row_weight)
            return qg, qh, w01[0], qs

        _quantize_iter_jit = jax.jit(
            impl, static_argnames=("seed", "n", "qmax", "hess_const"))
    return _quantize_iter_jit(grad, hess, row_weight, jnp.int32(it),
                              seed=seed, n=n, qmax=qmax,
                              hess_const=hess_const)


_gate_grow_jit = None


def _gate_grow(binned, g, h, w, mask, fmeta, cfg, n_cal, qscale=None):
    """One calibration tree for the train-time quantize gate: grow under
    `cfg` and return (per-row leaf values, leaf-value table). Jitted with
    the static cfg so the quantized and f32 variants each compile once."""
    import jax
    import jax.numpy as jnp
    global _gate_grow_jit
    if _gate_grow_jit is None:
        def impl(binned, g, h, w, mask, n_valid, fmeta, cfg, qscale=None):
            state = grow_tree(binned, g, h, w, mask, *fmeta, cfg,
                              n_valid=n_valid, qscale=qscale)
            lid = jnp.clip(state.leaf_id, 0, cfg.num_leaves - 1)
            return state.leaf_value[lid], state.leaf_value

        _gate_grow_jit = jax.jit(impl, static_argnames=("cfg",))
    return _gate_grow_jit(binned, g, h, w, mask, jnp.int32(n_cal),
                          tuple(fmeta), cfg=cfg, qscale=qscale)


class GBDT:
    """Reference: class GBDT, gbdt.h:25-441."""

    def __init__(self, config: Config):
        self.config = config
        self.iter_ = 0
        self.models: List[Tree] = []          # flat: iter-major, class-minor
        self.num_class = max(config.objective_config.num_class, 1)
        self.num_tree_per_iteration = 1
        self.objective: Optional[ObjectiveFunction] = None
        self.train_data: Optional[Dataset] = None
        self.metrics: List[Metric] = []
        self.valid_sets: List[Dataset] = []
        self.valid_names: List[str] = []
        self.valid_metrics: List[List[Metric]] = []
        self.best_iter: Dict[str, int] = {}
        self.best_score: Dict[str, Dict[str, float]] = {}
        self.init_score_bias = 0.0
        self.average_output = False  # RF mode
        self.shrinkage_rate = config.boosting.learning_rate
        self._early_stop_counter: Dict = {}
        self.max_feature_idx = 0
        self.feature_names: List[str] = []
        self._eval_history: List[dict] = []
        self._stopped = False
        # 1-deep async pipeline (serial learner, no valid sets): the
        # grower's small tree arrays stay on device until the NEXT
        # iteration has been dispatched, so the synchronous relay fetch
        # + host Tree build overlap device compute instead of serializing
        # with it (measured ~130 ms/iter of pure dispatch/fetch latency
        # at 500k rows — more than the device time of the iteration)
        self._pending_small = None
        # device-resident stacked-forest cache (serving/forest.py):
        # every ensemble mutation must go through _bump_model_version()
        # so a cached stack can never outlive the model it was built from
        from ..serving.forest import CompiledForest
        self._compiled_forest = CompiledForest()
        # publish hook (serving/registry.py): callbacks fired on every
        # model-version bump, so a registry front end can track stack
        # budgets / swap visibility without polling
        self._version_listeners: List = []
        # persistent XLA program cache (ISSUE 12): every program this
        # booster traces — the grower passes AND the serving bucket
        # ladder — persists to disk, so a restarted trainer or a cold
        # serving replica warms from a file read instead of a re-trace
        if getattr(config.io, "tpu_compile_cache_dir", ""):
            from ..serving.forest import enable_compile_cache
            enable_compile_cache(config.io.tpu_compile_cache_dir)

    # ------------------------------------------------------------------
    def init(self, train_data: Dataset, objective: Optional[ObjectiveFunction],
             metric_names: Sequence[str] = ()) -> None:
        """Reference: GBDT::Init, gbdt.cpp:65-193."""
        import jax
        import jax.numpy as jnp

        self.train_data = train_data
        self.objective = objective
        if objective is not None:
            self.num_tree_per_iteration = objective.num_model_per_iteration()
        else:
            self.num_tree_per_iteration = self.num_class
        self.max_feature_idx = train_data.num_total_features - 1
        self.feature_names = list(train_data.feature_names)
        self.feature_infos_ = train_data.feature_infos()

        n = train_data.num_data
        f = train_data.num_features
        # distributed learner selection (reference: CreateTreeLearner's
        # {serial,feature,data,voting} axis, tree_learner.cpp:9-33)
        tl = self.config.tree_learner
        self._tree_learner_kind = tl if tl in ("data", "feature", "voting") \
            else "serial"
        ndev = len(jax.devices()) if self._tree_learner_kind != "serial" else 1
        self._num_shards = ndev
        # multi-host: jax.devices() is GLOBAL; this process holds a row
        # SHARD of the training data (parallel/loader.py partitioning) and
        # pads it to local-device granularity — the data-parallel grower
        # assembles the global row axis (multihost.global_row_array)
        nproc = jax.process_count()
        self._num_processes = nproc
        if nproc > 1 and self._tree_learner_kind not in ("data", "voting"):
            log.fatal("Multi-host training requires tree_learner=data or "
                      "voting (got %s)" % self._tree_learner_kind)
        local_dev = max(1, ndev // nproc)
        # arm the collective watchdog + heartbeat lease for this run
        # (parallel/watchdog.py): every host-level collective from here
        # on — including this init's own allgathers below — runs under
        # the deadline guard when tpu_collective_timeout_s is set
        import os as _os

        from .. import telemetry
        from ..parallel import watchdog
        net = self.config.network
        rank = jax.process_index()
        self._process_rank = rank
        hb_dir = net.tpu_heartbeat_dir
        # durable-IO retry policy for every storage write this run makes
        # (checkpoint snapshots, caches, artifacts, telemetry sinks)
        from .. import durable
        durable.configure(retries=self.config.io.tpu_io_retries,
                          backoff_s=self.config.io.tpu_io_backoff_s,
                          deadline_s=self.config.io.tpu_io_deadline_s)
        watchdog.configure(
            timeout_s=net.tpu_collective_timeout_s,
            failure_dir=hb_dir or None,
            lease_s=net.tpu_heartbeat_lease_s if hb_dir else None,
            rank=rank)
        if hb_dir:
            _os.makedirs(hb_dir, exist_ok=True)
            telemetry.set_heartbeat_file(
                _os.path.join(hb_dir, f"heartbeat_r{rank}.json"))
            telemetry.heartbeat(0, phase="init", rank=rank)

        # row-padding plan: chunk capped by the group-block budget, rows
        # padded to a chunk (x shard) multiple, padded size bucketed into
        # coarse power-of-two granules so nearby row counts share one
        # compiled signature (full rationale in ingest/landing.py, where
        # the plan lives so the streaming ingest subsystem can land
        # per-device shards that are byte-compatible with this init)
        from ..ingest.landing import plan_row_layout
        layout = plan_row_layout(
            n, train_data.num_groups, train_data.max_num_bin(),
            tpu_hist_chunk=self.config.tree.tpu_hist_chunk,
            tree_learner=self._tree_learner_kind, ndev=ndev, nproc=nproc)
        self._chunk = layout.chunk
        n_pad = layout.n_pad
        if nproc > 1:
            # every process must contribute an equal-sized row block to
            # the global array: pad all shards to the largest. Deadline-
            # guarded like every other host collective: a peer that died
            # before init must fail this rank with rc 113, not hang it
            from jax.experimental import multihost_utils
            with watchdog.deadline("gbdt.init.pad_sync"):
                n_pad = int(multihost_utils.process_allgather(
                    jnp.asarray(np.int64(n_pad))).max())
        self._n = n
        self._n_pad = n_pad

        # ingest may have landed the binned matrix as per-device row
        # shards already (ingest.ShardedLanding); reuse it when its
        # padding matches this plan, otherwise gather and re-pad
        # the scatter-reduce data-parallel schedule pads the stored-group
        # axis to a device multiple host-side (appended groups are empty
        # columns no feature maps to) — decide it here so the device-landed
        # reuse check and the grower see one consistent layout
        hist_reduce = self.config.tree.tpu_hist_reduce
        use_scatter = (self._tree_learner_kind == "data" and ndev > 1
                       and hist_reduce == "scatter")
        g_pad = (-(-int(train_data.num_groups) // ndev) * ndev
                 if use_scatter else int(train_data.num_groups))
        device_binned = getattr(train_data, "device_binned", None)
        if device_binned is not None:
            usable = (int(device_binned.shape[0]) == n_pad and nproc == 1
                      and self._tree_learner_kind in ("data", "voting"))
            if usable and g_pad > int(device_binned.shape[1]):
                # scatter needs the stored-group axis padded to a device
                # multiple; pad ON DEVICE (zero columns, row sharding
                # preserved) instead of bouncing the landed shards
                # through the host
                device_binned = jnp.pad(
                    device_binned,
                    ((0, 0), (0, g_pad - int(device_binned.shape[1]))))
            if usable:
                binned_host = None
            else:
                log.warning(
                    "Device-landed dataset does not match the training "
                    "layout (rows %d vs %d, learner %s, processes %d); "
                    "gathering to host and re-padding",
                    int(device_binned.shape[0]), n_pad,
                    self._tree_learner_kind, nproc)
                binned_host = _pad_to(
                    np.asarray(device_binned)[:n], n_pad)
                device_binned = None
        else:
            binned_host = _pad_to(train_data.binned, n_pad)
        fm = train_data.feature_meta_arrays()
        self._max_bins = int(train_data.max_num_bin())

        # the objective captures its statistics (bias, class counts, query
        # DCGs) from the REAL data, then pads its row arrays so the gradient
        # kernels line up with the padded scores (padded rows are masked by
        # row_weight 0 in the grower)
        if objective is not None:
            if train_data.metadata.label is None:
                log.fatal("Training data must have a label")
            objective.init(train_data.metadata, n)
            if nproc > 1:
                # label statistics (bias, class counts) were computed on
                # this shard only — sum them across processes (the
                # reference's distributed boost-from-average Allreduce,
                # gbdt.cpp:298-335)
                from jax.experimental import multihost_utils

                def _allreduce_sum(arr):
                    # f64 end-to-end: the reference Allreduces doubles
                    # (gbdt.cpp BoostFromAverage) and a 10M-row label sum
                    # loses real precision in f32. jax defaults to x32,
                    # so ship each double as (hi=f32, lo=residual-f32).
                    a = np.asarray(arr, np.float64)
                    hi = a.astype(np.float32)
                    lo = (a - hi.astype(np.float64)).astype(np.float32)
                    with watchdog.deadline("gbdt.boost_from_average"):
                        g = multihost_utils.process_allgather(
                            jnp.stack([jnp.asarray(hi), jnp.asarray(lo)]))
                    g = np.asarray(g, np.float64)  # [P, 2, ...]
                    return (g[:, 0] + g[:, 1]).sum(axis=0)

                objective.sync_distributed(_allreduce_sum)
            objective.pad_to(n_pad)

        self._base_weight = jnp.asarray(
            _pad_to(np.ones(n, np.float32), n_pad))

        # piecewise-linear leaves (linear_tree): the post-growth leaf
        # regression needs RAW feature values on device. Landed in the
        # USED-feature (inner) space so leaf_path_features' inner-space
        # indices address it directly; padding rows are ZEROS so the
        # padded score tail stays finite (the non-finite gradient probe
        # reduces over the whole padded array).
        self._linear = bool(self.config.tree.linear_tree)
        self._linear_k = int(self.config.tree.tpu_linear_max_features)
        self._raw = None
        if self._linear:
            if self.config.boosting_type not in ("gbdt", "goss"):
                raise log.LightGBMError(
                    "linear_tree supports boosting=gbdt/goss only (got "
                    "%s): dart re-normalization and RF averaging replay "
                    "trees through the binned-only path"
                    % self.config.boosting_type)
            if self.num_tree_per_iteration > 1:
                raise log.LightGBMError(
                    "linear_tree does not support multiclass training "
                    "(num_tree_per_iteration=%d); train one-vs-all "
                    "boosters or set linear_tree=false"
                    % self.num_tree_per_iteration)
            if nproc > 1:
                raise log.LightGBMError(
                    "linear_tree does not support multi-host training "
                    "(the leaf regression needs the global raw matrix "
                    "resident on every process); set linear_tree=false")
            if train_data.raw is None:
                raise log.LightGBMError(
                    "linear_tree requires raw feature values: construct "
                    "the training Dataset with keep_raw=true (params "
                    "routed through engine.train/sklearn arm this "
                    "automatically)")
            raw_inner = np.asarray(train_data.raw, np.float32)[
                :, train_data.used_features]
            self._raw = jnp.asarray(_pad_to(raw_inner, n_pad))
            # the async tree pipeline fuses grow+update into one program
            # keyed on constant leaf outputs; the linear fit is a second
            # program with its own score update, so run synchronous
            self._supports_pipeline = False

        # scores: [num_tree_per_iteration, n_pad]
        k = self.num_tree_per_iteration
        self._score = jnp.zeros((k, n_pad), jnp.float32)
        init_score = train_data.metadata.init_score
        if init_score is not None:
            isc = np.asarray(init_score, np.float32)
            if isc.size == n * k:
                self._score = jnp.asarray(
                    _pad_to(isc.reshape(k, n).T, n_pad).T.reshape(k, n_pad))
            else:
                self._score = self._score + jnp.asarray(_pad_to(isc, n_pad))[None, :]

        # metrics
        self.metrics = []
        for mname in metric_names:
            m = create_metric(mname, self.config)
            if m is not None:
                m.init(train_data.metadata, n)
                self.metrics.append(m)

        if self.config.tree.tpu_hist_pallas:
            log.warning("tpu_hist_pallas is retired: the hand-written "
                        "kernel measured slower than the XLA path "
                        "(profiles/README.md); using the XLA kernels")
        # --- execution-schedule auto-selection ----------------------------
        # (bit-identical trees for any batch_k; subtraction/compaction only
        # change f32 summation order). "wide" shapes (large groups*bins)
        # are channel-cost-bound in the histogram contraction, narrow
        # shapes are MXU-tile-bound — different best batch widths.
        L_cfg = self.config.tree.num_leaves
        g_cnt = max(1, int(train_data.num_groups))
        # "wide" = the histogram contraction is channel-cost-bound (the
        # [G*B, chunk] x [chunk, S] matmul's FLOPs scale with S) rather
        # than tile-bound; Bosch-shape (~22k) measured fastest at narrow
        # batches, HIGGS/Expo (~2k) at full-tile ones
        wide = g_cnt * self._max_bins > 8192
        k_cls = self.num_tree_per_iteration
        # sibling subtraction: per-node [M, G, B, 3] histogram cache must
        # fit the budget (vmap'd class trees each carry their own cache).
        # Node-table size rides the same budget: generous tables keep
        # late-boosting speculation wide (grow.py table notes) — use the
        # largest table_mult in [4, 12] whose cache still fits; without
        # the cache the table is [M]-scalar cheap, so take the max.
        slot_bytes = k_cls * g_cnt * self._max_bins * 3 * 4
        mult_fit = int((_SUBTRACT_CACHE_BUDGET // max(slot_bytes, 1) - 52)
                       // max(L_cfg, 1))
        subtract = (self.config.tree.tpu_hist_subtract
                    and self._tree_learner_kind == "serial"
                    # vmap'd class trees each carry a cache: the x k_cls
                    # scatter/memory traffic measured a net LOSS on the
                    # multiclass shape (0.62 vs 0.89 Mrow-iters/s)
                    and k_cls == 1
                    and mult_fit >= 6)
        # vmap'd class trees multiply every [M]-sized table op by k_cls:
        # the measured multiclass optimum is a smaller table
        table_mult = min(12, mult_fit) if subtract else \
            (6 if k_cls > 1 else 12)
        # gather-compacted small-node contraction: on wherever rows are
        # locally resident (serial + data/voting learners); the grower
        # additionally refuses it under feature_axis. The threshold is a
        # pure scheduling choice — for any value the grown trees match
        # the full-pass grower on order-invariant sums (grow.py notes).
        # Single-chunk runs have nothing to skip — the gather would only
        # add a second compiled kernel per signature — so the
        # auto-schedule keeps them on the full pass (measured: the win
        # is already 2.3x at 2 chunks / 100k CPU rows, see
        # profiles/README.md). Multiclass is excluded like subtraction:
        # the vmap over class trees batches the per-pass cond predicate,
        # which under jax's cond batching rule executes BOTH histogram
        # kernels every pass — a strict pessimization.
        # the grower re-guards on PER-SHARD rows (each shard compacts its
        # own block), so gate on the same quantity or the schedule log
        # would claim compact=True while the grower silently declines
        shard_rows = self._n_pad
        if self._tree_learner_kind in ("data", "voting"):
            shard_rows = self._n_pad // max(
                1, local_dev if nproc > 1 else ndev)
        compact_frac = float(self.config.tree.tpu_compact_threshold)
        compact = (self.config.tree.tpu_hist_compact
                   and compact_frac > 0.0
                   and self._tree_learner_kind != "feature"
                   and k_cls == 1
                   and shard_rows >= 2 * self._chunk)
        import os as _os
        if _os.environ.get("LGBM_TPU_TABLE_MULT"):      # debug override
            table_mult = int(_os.environ["LGBM_TPU_TABLE_MULT"])
        if _os.environ.get("LGBM_TPU_FORCE_SUBTRACT"):  # debug override
            subtract = _os.environ["LGBM_TPU_FORCE_SUBTRACT"] == "1"
        if _os.environ.get("LGBM_TPU_FORCE_COMPACT"):   # debug override
            compact = _os.environ["LGBM_TPU_FORCE_COMPACT"] == "1"
        if "tpu_batch_k" in self.config.raw_params:
            batch_k = self.config.tree.tpu_batch_k
        elif subtract:
            # one smaller-child channel set per node: 25*(3+2) fills the
            # 128-lane tile; wide shapes stay narrow (channel-cost-bound
            # passes + depth-bound trees — K=8 matches the channel cost
            # of the round-4 K=4 direct path while expanding 2x nodes)
            batch_k = 8 if wide else 24
        else:
            # Bosch-class data (wide AND heavily EFB-bundled — sparse
            # one-hot blocks) measured fastest at K=4: deep depth-bound
            # trees, channel-cost-bound passes. Unbundled wide shapes
            # (Epsilon) keep the full-tile default.
            bundled = g_cnt < 0.8 * max(1, train_data.num_features)
            batch_k = 4 if (wide and bundled) else 12
        # --- quantized-gradient training (tpu_hist_quantize, ISSUE 20) ---
        from ..ops.histogram import TRAIN_QUANTIZE_MODES, train_qmax
        quant_mode = str(self.config.tree.tpu_hist_quantize or "none").lower()
        if quant_mode not in TRAIN_QUANTIZE_MODES:  # config validates; belt
            raise log.LightGBMError(
                "tpu_hist_quantize must be one of %s (got %r)"
                % (TRAIN_QUANTIZE_MODES, quant_mode))
        if quant_mode != "none" and nproc > 1:
            raise log.LightGBMError(
                "tpu_hist_quantize=%s does not support multi-host "
                "training: the rounding-key stream and the calibration "
                "gate are defined over the global row axis resident on "
                "one process; train with tpu_hist_quantize=none"
                % quant_mode)
        # the integer range adapts to the row count so a full-column bin
        # sum can never overflow the exact int32 accumulator domain
        # (ops/histogram.train_qmax); precision degrades gracefully at
        # extreme n and the gate below judges the result
        quant_qmax = train_qmax(quant_mode, n) if quant_mode != "none" else 0
        # constant-hessian detection enables the hessian-channel comm
        # elision AND exact hessian codes (q_h == qmax * in_bag). GOSS is
        # excluded: its amplification weights fold into the quantized
        # codes, so in-bag hessians are not all equal
        quant_hess_const = bool(
            quant_mode != "none" and objective is not None
            and objective.is_constant_hessian()
            and self.config.boosting_type == "gbdt")
        self._quant_mode = quant_mode
        self._quant_qmax = quant_qmax
        self._quant_hess_const = quant_hess_const
        # the rounding-key base seed: data_random_seed is NOT sweep-
        # variable (boosting/sweep.SWEEP_VARIABLE_PARAMS), so a vmapped
        # sweep and a solo train of the same config derive identical
        # key chains — the sweep==solo byte-identity contract holds
        # under quantization too
        self._quant_seed = int(self.config.io.data_random_seed)
        if quant_mode == "int8" and "tpu_batch_k" not in self.config.raw_params:
            # int8 contracts 3 channels per node id instead of the bf16
            # hi+lo path's 5, so the same 128-lane MXU output tile (and,
            # on CPU, the same one-hot operand materialization) covers
            # 5/3 more leaves per pass. Widening the batch is free on
            # correctness: quantized histograms live in the exact int32
            # domain, where trees are bit-identical for ANY batch_k.
            batch_k = max(1, (batch_k * 5) // 3)
        log.info("Schedule: groups=%d max_bin=%d wide=%s subtract=%s "
                 "compact=%s@%.2f batch_k=%d table_mult=%d chunk=%d "
                 "quantize=%s qmax=%d",
                 g_cnt, self._max_bins, wide, subtract, compact,
                 compact_frac, batch_k, table_mult, self._chunk,
                 quant_mode, quant_qmax)
        # execution-schedule summary for the telemetry run-log header
        # (telemetry/runlog.py): the knobs that explain this run's pass
        # economics, host-readable without re-deriving the auto-selection
        self._schedule_info = {
            "tree_learner": self._tree_learner_kind,
            "num_shards": int(ndev), "num_processes": int(nproc),
            # data-parallel histogram-merge collective + per-device owned
            # histogram slice (scatter: groups/ndev after padding; other
            # schedules score the full group set everywhere)
            "hist_reduce": (hist_reduce if use_scatter else "allreduce")
            if self._tree_learner_kind == "data" else None,
            "owned_groups": int(g_pad // ndev) if use_scatter
            else int(g_cnt),
            "groups": int(g_cnt), "max_bin": int(self._max_bins),
            "wide": bool(wide), "subtract": bool(subtract),
            "compact": bool(compact), "compact_fraction": compact_frac,
            "batch_k": int(batch_k), "table_mult": int(table_mult),
            "chunk": int(self._chunk), "rows": int(n),
            "rows_padded": int(n_pad),
            "hist_quantize": quant_mode, "hist_qmax": int(quant_qmax),
            "hist_hess_const": bool(quant_hess_const),
        }
        self._grower_cfg = GrowerConfig(
            num_leaves=self.config.tree.num_leaves,
            max_bins=self._max_bins,
            feature_bins=int(train_data.num_bins_per_feature().max(initial=1)),
            batch_k=batch_k,
            hist_subtract=subtract,
            hist_compact=compact,
            compact_fraction=compact_frac,
            table_mult=table_mult,
            hist_bf16=self.config.tree.tpu_hist_bf16,
            chunk=self._chunk,
            lambda_l1=self.config.tree.lambda_l1,
            lambda_l2=self.config.tree.lambda_l2,
            min_gain_to_split=self.config.tree.min_gain_to_split,
            min_data_in_leaf=self.config.tree.min_data_in_leaf,
            min_sum_hessian_in_leaf=self.config.tree.min_sum_hessian_in_leaf,
            max_depth=self.config.tree.max_depth,
            hist_quantize=quant_mode,
            hist_qmax=quant_qmax,
            hist_hess_const=quant_hess_const,
            # the scatter schedule pads the stored-group axis to a
            # device multiple; the appended empty groups get 1-bin
            # width-plan entries HERE (the single source — the binned
            # matrices are padded to match below / in the grower prep)
            group_widths=tuple(
                int(b) for b in (train_data.groups.group_num_bin
                                 if train_data.groups is not None
                                 and train_data.groups.num_groups
                                 else train_data.num_bins_per_feature()))
            + (1,) * (g_pad - g_cnt if use_scatter else 0),
        )

        # build the distributed grower + finalize the (possibly feature-
        # padded) device-resident binned matrix
        self._dist_grower = None
        if self._tree_learner_kind != "serial" and ndev >= 1:
            from ..parallel import (DataParallelGrower, FeatureParallelGrower,
                                    VotingParallelGrower, make_mesh)
            if self._tree_learner_kind == "feature":
                mesh = make_mesh(axis_name="feature")
                self._dist_grower = FeatureParallelGrower(
                    mesh, self._grower_cfg, axis="feature")
                binned_host, fm = self._dist_grower.pad_features(binned_host, fm)
                # rebuild the static width plan over the PADDED feature
                # axis so the narrow-block bin-width discount survives
                # feature sharding (grow.py shard_group_widths)
                self._grower_cfg = self._grower_cfg._replace(
                    group_widths=tuple(int(b) for b in fm["num_bin"]))
                # the grower reads the DIST cfg (captured at construction,
                # before padding) — keep it in sync or the width plan
                # silently drops (round-5 review finding)
                self._dist_grower.cfg = self._dist_grower.cfg._replace(
                    group_widths=self._grower_cfg.group_widths)
            elif self._tree_learner_kind == "voting":
                mesh = make_mesh(axis_name="data")
                self._dist_grower = VotingParallelGrower(
                    mesh, self._grower_cfg, axis="data",
                    top_k=self.config.tree.top_k)
            else:
                mesh = make_mesh(axis_name="data")
                self._dist_grower = DataParallelGrower(
                    mesh, self._grower_cfg, axis="data",
                    hist_reduce=hist_reduce)
                if self._dist_grower.cfg.hist_scatter \
                        and binned_host is not None \
                        and g_pad > binned_host.shape[1]:
                    # pre-pad the stored-group axis ONCE here so the
                    # grower's per-call prep sees an already-aligned
                    # device-resident matrix (no host copy per
                    # dispatch); the matching 1-bin width-plan entries
                    # were appended at _grower_cfg construction above
                    extra = g_pad - binned_host.shape[1]
                    binned_host = np.concatenate(
                        [binned_host,
                         np.zeros((binned_host.shape[0], extra),
                                  binned_host.dtype)], axis=1)
            log.info("Using %s-parallel tree learner over %d devices",
                     self._tree_learner_kind, ndev)
        if (self._tree_learner_kind == "feature"
                and train_data.groups is not None
                and train_data.num_groups != train_data.num_features):
            log.fatal("feature-parallel requires unbundled features; "
                      "construct the Dataset with enable_bundle=false")
        # a device-landed matrix is already sharded the way the
        # data/voting shard_map wants (P(data, None)) — zero resharding
        self._binned = device_binned if device_binned is not None \
            else jnp.asarray(binned_host)
        # logical (possibly shard-padded) feature count for feature_fraction
        # masks; the stored binned width is the GROUP count (EFB)
        self._num_features_padded = int(fm["num_bin"].shape[0])
        self._fmeta = {k: jnp.asarray(v) for k, v in fm.items()}

        self._feature_rng = np.random.RandomState(self.config.tree.feature_fraction_seed)

        # final grower schedule (group widths may have been re-planned by
        # the feature-parallel padding above) for the run-log header
        from ..learner.grow import schedule_summary
        self._schedule_info["grower"] = schedule_summary(self._grower_cfg)

        # boost from average (gbdt.cpp:358-378): the score bump happens at
        # init; the bias itself is folded into the first trained tree via
        # AddBias (gbdt.cpp:446) so the saved model is self-contained
        if (objective is not None and objective.boost_from_average()
                and self.config.objective_config.boost_from_average
                and self.num_tree_per_iteration == 1):
            self.init_score_bias = objective.bias()
            if self.init_score_bias != 0.0:
                self._score = self._score + self.init_score_bias
                log.info("Start training from score %f", self.init_score_bias)
        self._pending_bias = self.init_score_bias

        # train-time accuracy gate (tpu_hist_quantize_tol): judge the
        # quantized config on a calibration slice BEFORE any tree is
        # grown — refuse a lossy setup instead of silently training with
        # it. Runs after boost-from-average so the calibration gradients
        # match the real iteration-0 score.
        if quant_mode != "none":
            self._hist_quant_gate()

    def _hist_quant_gate(self) -> None:
        """Setup-time gate for tpu_hist_quantize (the serving
        `_quant_gate` pattern applied to TRAINING): grow one calibration
        tree with the quantized pipeline and one with the f32 pipeline on
        the leading row chunk, both serial/full-pass (schedule knobs off
        so the comparison isolates quantization), and refuse the config
        when the worst per-row leaf-value delta — relative to the f32
        tree's leaf-value scale, floored at 1 — exceeds
        `tpu_hist_quantize_tol`."""
        import jax
        import jax.numpy as jnp

        from .. import telemetry, tracing
        from ..learner.grow import FMETA_KEYS
        from ..ops.histogram import quantize_gradients, train_qmax

        mode = self._quant_mode
        if self.objective is None:
            log.debug("tpu_hist_quantize=%s: custom-objective training "
                      "(explicit gradients) has no setup-time gradient "
                      "source — skipping the calibration gate", mode)
            return
        c = min(self._n_pad, self._chunk)
        n_cal = min(self._n, c)
        binned_cal = self._binned[:c]
        grad, hess = self._compute_gradients(self._score)
        k = self.num_tree_per_iteration
        g = grad.reshape(k, self._n_pad)[0, :c]
        h = hess.reshape(k, self._n_pad)[0, :c]
        w = (jnp.arange(c) < n_cal).astype(jnp.float32)
        mask = jnp.asarray(np.ones(self._num_features_padded, bool))
        fmeta = [self._fmeta[key] for key in FMETA_KEYS]
        # serial full-pass schedule, small tree: the gate isolates the
        # quantization delta (subtract/compact/scatter are separately
        # pinned bit-transparent by the schedule tests)
        cfg = self._grower_cfg._replace(
            data_axis=None, feature_axis=None, voting=False,
            hist_subtract=False, hist_compact=False,
            num_leaves=min(31, self.config.tree.num_leaves))
        qmax = train_qmax(mode, n_cal)
        kc = jax.random.fold_in(
            jax.random.fold_in(jax.random.PRNGKey(self._quant_seed), 0), 0)
        q_g, q_h, w01, qscale = quantize_gradients(
            g, h, w, n=n_cal, qmax=qmax,
            key_g=jax.random.fold_in(kc, 0),
            key_h=jax.random.fold_in(kc, 1),
            hess_const=self._quant_hess_const)
        vq, _ = _gate_grow(binned_cal, q_g, q_h, w01, mask, fmeta,
                           cfg._replace(hist_quantize=mode, hist_qmax=qmax),
                           n_cal, qscale=qscale)
        vf, lv_f = _gate_grow(binned_cal, g, h, w, mask, fmeta,
                              cfg._replace(hist_quantize="none", hist_qmax=0,
                                           hist_hess_const=False), n_cal)
        scale = max(float(jnp.max(jnp.abs(lv_f))), 1.0)
        delta = float(jnp.max(jnp.abs(vq[:n_cal] - vf[:n_cal]))) / scale
        telemetry.gauge_set("train/hist_quantize_gate_delta", delta)
        tracing.counter("train/hist_quantize_gate_runs", 1)
        log.debug("Hist-quantize gate (%s, qmax=%d): relative leaf-value "
                  "delta %.3g on %d calibration rows", mode, qmax, delta,
                  n_cal)
        tol = float(self.config.tree.tpu_hist_quantize_tol)
        if delta > tol:
            raise log.LightGBMError(
                "tpu_hist_quantize=%s refused: max calibration leaf-value "
                "delta %.3g vs the f32 grower exceeds "
                "tpu_hist_quantize_tol=%.3g (relative to the f32 tree's "
                "leaf-value scale, %d calibration rows). Raise the "
                "tolerance or train with tpu_hist_quantize=none."
                % (mode, delta, tol, n_cal))

    def add_valid(self, valid_data: Dataset, name: str,
                  metric_names: Sequence[str] = ()) -> None:
        """Reference: GBDT::AddValidDataset, gbdt.cpp:204-224."""
        import jax.numpy as jnp
        self.finalize_training()
        self.valid_sets.append(valid_data)
        self.valid_names.append(name)
        ms = []
        for mname in metric_names:
            m = create_metric(mname, self.config)
            if m is not None:
                m.init(valid_data.metadata, valid_data.num_data)
                ms.append(m)
        self.valid_metrics.append(ms)
        if not hasattr(self, "_valid_binned"):
            self._valid_binned = []
            self._valid_score = []
        if not hasattr(self, "_valid_raw"):
            self._valid_raw = []
        vb = jnp.asarray(valid_data.binned)
        self._valid_binned.append(vb)
        # linear trees evaluate coeff . x on raw values: land the valid
        # set's raw matrix (inner space, unpadded like vb) alongside
        vraw = None
        if getattr(self, "_linear", False) \
                or any(getattr(t, "is_linear", False) for t in self.models):
            if valid_data.raw is None:
                raise log.LightGBMError(
                    "linear_tree validation needs raw feature values: "
                    "construct the valid Dataset with keep_raw=true")
            vraw = jnp.asarray(np.asarray(valid_data.raw, np.float32)[
                :, self.train_data.used_features])
        self._valid_raw.append(vraw)
        k = self.num_tree_per_iteration
        vs = jnp.zeros((k, valid_data.num_data), jnp.float32)
        init_score = valid_data.metadata.init_score
        if init_score is not None:
            isc = np.asarray(init_score, np.float32)
            nv = valid_data.num_data
            if isc.size == nv * k:
                vs = jnp.asarray(isc.reshape(k, nv))
            else:
                vs = vs + jnp.asarray(isc)[None, :]
        if self.init_score_bias != 0.0:
            vs = vs + self.init_score_bias
        # replay existing trees (continued training on new valid set);
        # RF keeps scores as the running AVERAGE of contributions
        acc = jnp.zeros_like(vs)
        for it in range(self.iter_):
            for cls in range(k):
                tree = self.models[it * k + cls]
                acc = acc.at[cls].add(self._tree_values_device(
                    tree.to_device(), vb, vraw))
        if self.average_output and self.iter_ > 0:
            acc = acc / float(self.iter_)
        self._valid_score.append(vs + acc)

    # ------------------------------------------------------------------
    def _bagging_weights(self, iter_idx: int, grad=None, hess=None):
        """0/1 in-bag weights (reference: GBDT::Bagging, gbdt.cpp:225-286),
        built ON DEVICE: per-row Bernoulli(bagging_fraction) from the jax
        PRNG keyed by (bagging_seed, refresh index). DEVIATION from the
        reference: its BaggingHelper adapts probabilities within each
        block to guarantee an exact in-bag count (CHECK(cur_left_cnt ==
        bag_data_cnt)); plain Bernoulli sampling makes the in-bag count
        binomially distributed around n*fraction instead (see PARITY.md).
        GOSS overrides this using the gradient magnitudes
        (goss.hpp:87-131). Returns a [n_pad] device array (padding
        suffix zeroed) or None for no bagging."""
        bf = self.config.boosting.bagging_fraction
        freq = self.config.boosting.bagging_freq
        if bf >= 1.0 or freq <= 0:
            return None
        if iter_idx % freq == 0 or not hasattr(self, "_bag_cache"):
            self._bag_cache = _bagging_mask_device(
                self.config.boosting.bagging_seed, iter_idx // freq,
                self._n, self._n_pad, bf)
            from .. import tracing
            tracing.counter("boosting/bagging_refresh", 1)
        return self._bag_cache

    def _row_weight_from_bag(self, bag):
        """Normalize a bagging result (None / host [n] / device [n_pad])
        to the [n_pad] device row-weight the grower consumes."""
        import jax.numpy as jnp
        if bag is None:
            return self._base_weight
        if isinstance(bag, np.ndarray):
            return jnp.asarray(_pad_to(bag, self._n_pad))
        return bag

    def _feature_mask(self) -> np.ndarray:
        """Per-tree feature_fraction sample (serial_tree_learner.cpp:239-257)."""
        return feature_fraction_mask(
            self._feature_rng, self.config.tree.feature_fraction,
            self.train_data.num_features, self._num_features_padded)

    def _grow(self, grad, hess, row_weight, feature_mask, qscale=None):
        """Dispatch one tree growth to the serial or distributed grower."""
        import jax.numpy as jnp
        # padding is a row-suffix only in single-process runs (multi-host
        # assembles per-process blocks, each with its own padding tail)
        nv = jnp.int32(self._n) if self._num_processes == 1 else None
        if self._dist_grower is not None:
            return self._dist_grower(self._binned, grad, hess, row_weight,
                                     jnp.asarray(feature_mask), self._fmeta,
                                     n_valid=nv, qscale=qscale)
        from ..learner.grow import FMETA_KEYS
        return grow_tree(
            self._binned, grad, hess, row_weight, jnp.asarray(feature_mask),
            *[self._fmeta[k] for k in FMETA_KEYS], self._grower_cfg,
            n_valid=nv, qscale=qscale)

    # ------------------------------------------------------------------
    def _compute_gradients(self, score) -> Tuple:
        # one jitted program per iteration instead of an eager op chain
        # (each eager dispatch is a host round trip on relay-attached TPUs).
        # The objective's row arrays (label, weights, pair tensors, ...)
        # are passed as ARGUMENTS, not closure captures: a captured [N]
        # array gets inlined into the lowered module as a giant literal
        # (measured 16 MB of HLO text and ~12s of lowering at 2M rows)
        # and defeats the persistent compile cache, since the constant
        # bytes differ per dataset.
        if getattr(self, "_jit_grads", None) is None:
            import jax

            obj = self.objective
            arr_keys = objective_array_keys(obj)

            def f(s, arrs):
                with objective_arrays_swapped(obj, arr_keys, arrs):
                    return obj.get_gradients(s.reshape(-1))

            self._jit_grads = jax.jit(f)
            self._jit_grads_keys = arr_keys
        arrs = {k: getattr(self.objective, k) for k in self._jit_grads_keys}
        return self._jit_grads(score, arrs)

    def train_one_iter(self, gradients: Optional[np.ndarray] = None,
                       hessians: Optional[np.ndarray] = None) -> bool:
        """One boosting iteration (reference: GBDT::TrainOneIter,
        gbdt.cpp:380-474). Returns True when no further splits are possible
        (training should stop)."""
        # injection point: a dying TPU worker surfaces as a failed grow
        # dispatch (testing/faults.py)
        faults.inject("backend.grow")
        import jax.numpy as jnp

        from .. import tracing

        k = self.num_tree_per_iteration
        n_pad = self._n_pad
        if gradients is None or hessians is None:
            if self.objective is None:
                log.fatal("Custom objective training requires explicit "
                          "gradients and hessians")
            with tracing.phase("boosting/gradients"):
                grad, hess = self._compute_gradients(self._score)
                tracing.block(grad)
        else:
            grad = jnp.asarray(np.asarray(gradients, np.float32).reshape(k, -1))
            hess = jnp.asarray(np.asarray(hessians, np.float32).reshape(k, -1))
            if grad.shape[1] != n_pad:
                grad = jnp.asarray(_pad_to(np.asarray(grad).T, n_pad).T)
                hess = jnp.asarray(_pad_to(np.asarray(hess).T, n_pad).T)
            grad = grad.reshape(-1)
            hess = hess.reshape(-1)
        grad = grad.reshape(k, n_pad)
        hess = hess.reshape(k, n_pad)
        probe = self._nonfinite_probe(grad, hess)

        with tracing.phase("boosting/bagging"):
            bag = self._bagging_weights(self.iter_, grad, hess)
            row_weight = self._row_weight_from_bag(bag)

        # quantized-gradient training: replace the f32 moments with
        # integer codes + the 0/1 row weight for the grower; the RAW f32
        # moments are kept for consumers whose math stays full-precision
        # (the piecewise-linear leaf fit)
        grad_f32, hess_f32, row_weight_f32 = grad, hess, row_weight
        qscales = None
        if getattr(self, "_quant_mode", "none") != "none":
            with tracing.phase("boosting/quantize"):
                grad, hess, row_weight, qscales = _quantize_iter_device(
                    grad, hess, row_weight, self.iter_,
                    seed=self._quant_seed, n=self._n,
                    qmax=self._quant_qmax,
                    hess_const=self._quant_hess_const)

        import jax

        from ..learner.grow import FMETA_KEYS

        if k > 1 and self._dist_grower is None:
            self._raise_if_nonfinite(probe, self.iter_)
            return self._train_one_iter_multi(grad, hess, row_weight,
                                              qscales)

        import os
        if (self._dist_grower is None and k == 1 and not self.valid_sets
                and gradients is None
                and getattr(self, "_supports_pipeline", True)
                and not os.environ.get("LGBM_TPU_NO_PIPELINE")):
            return self._train_one_iter_pipelined(grad, hess, row_weight,
                                                  probe, qscales)
        self._raise_if_nonfinite(probe, self.iter_)

        # leaving the pipelined path (explicit gradients, a valid set
        # added mid-training, ...): drain the pending tree FIRST so
        # models stay in iteration order
        self._flush_pending()

        could_split_any = False
        for cls in range(k):
            mask = self._feature_mask()
            qs = None if qscales is None else qscales[cls]
            if getattr(self, "_linear", False):
                # piecewise-linear leaves: plain grow (serial OR
                # distributed), then the shared post-growth fit program
                # replaces the constant leaf outputs with fitted
                # intercept+slopes and returns the per-row training
                # values (pre-shrinkage) for the score update
                with tracing.phase("tree/grow"):
                    state = self._grow(grad[cls], hess[cls], row_weight,
                                       mask, qscale=qs)
                with tracing.phase("tree/linear_fit"):
                    # the leaf regression consumes the RAW f32 moments:
                    # quantization narrows the HISTOGRAM path only, the
                    # fitted intercept/slope normal equations stay exact
                    leaf_value, leaf_coeff, feats, vals = _fit_linear_post(
                        self._raw, grad_f32[cls], hess_f32[cls],
                        row_weight_f32, state,
                        self.config.tree.linear_lambda,
                        self._grower_cfg, self._linear_k)
                with tracing.phase("tree/extract"):
                    small = {key: getattr(state, key)
                             for key in _SMALL_STATE_KEYS}
                    small["leaf_value"] = leaf_value
                    small["leaf_coeff"] = leaf_coeff
                    small["leaf_features_inner"] = feats
                    host_state = _HostState(jax.device_get(small))
                    tree = Tree.from_grower_state(host_state,
                                                  self.train_data)
                self._log_pass_economics(host_state)
                if tree.num_leaves > 1:
                    tree.apply_shrinkage(self.shrinkage_rate)
                    with tracing.phase("boosting/update_score"):
                        self._score = self._score.at[cls].add(
                            jnp.float32(self.shrinkage_rate) * vals)
            elif self._dist_grower is None:
                # serial learner: grow + score update as ONE device
                # program, then ONE host fetch of the small tree arrays
                with tracing.phase("tree/grow"):
                    self._score, small = _grow_and_update(
                        self._score, self._binned, grad[cls], hess[cls],
                        row_weight, jnp.asarray(mask), self.shrinkage_rate,
                        self._n,
                        [self._fmeta[key] for key in FMETA_KEYS], cls,
                        self._grower_cfg, qscale=qs)
                with tracing.phase("tree/extract"):
                    host_state = _HostState(jax.device_get(small))
                    tree = Tree.from_grower_state(host_state,
                                                  self.train_data)
                self._log_pass_economics(host_state)
                if tree.num_leaves > 1:
                    tree.apply_shrinkage(self.shrinkage_rate)
            else:
                with tracing.phase("tree/grow"):
                    state = self._grow(grad[cls], hess[cls], row_weight,
                                       mask, qscale=qs)
                with tracing.phase("tree/extract"):
                    small = {key: getattr(state, key)
                             for key in _SMALL_STATE_KEYS}
                    host_state = _HostState(jax.device_get(small))
                    tree = Tree.from_grower_state(host_state,
                                                  self.train_data)
                self._log_pass_economics(host_state)
                if tree.num_leaves > 1:
                    tree.apply_shrinkage(self.shrinkage_rate)
                    # train score update via leaf ids (UpdateScore,
                    # gbdt.cpp:521)
                    with tracing.phase("boosting/update_score"):
                        leaf_vals = jnp.asarray(tree.leaf_value, jnp.float32)
                        lid = state.leaf_id
                        if self._num_processes > 1:
                            # scores are per-process row shards; pull this
                            # process's block of the global leaf ids
                            from ..parallel.multihost import local_rows
                            lid = jnp.asarray(local_rows(state.leaf_id))
                        self._score = self._score.at[cls].add(
                            leaf_vals[jnp.clip(lid, 0,
                                               tree.num_leaves - 1)])

            if tree.num_leaves > 1:
                could_split_any = True
                self._update_valid_scores(cls, tree)
                # fold boost-from-average into the tree AFTER the score
                # update (scores were bumped at init): gbdt.cpp:445-447
                if abs(getattr(self, "_pending_bias", 0.0)) > _K_EPSILON:
                    tree.add_bias(self._pending_bias)
                    self._pending_bias = 0.0
                    self.init_score_bias = 0.0
            self.models.append(tree)

        return self._finish_iter(could_split_any)

    def _train_one_iter_pipelined(self, grad, hess, row_weight,
                                  probe=None, qscales=None) -> bool:
        """Serial-learner iteration with the tree fetch pipelined one
        iteration behind the device dispatch (see __init__ note). The
        stop/rollback decision therefore lags one iteration: a
        non-splitting tree is detected when it is materialized, its
        iteration is rolled back (its score delta was already zero on
        device, _grow_and_update_impl's `grew` guard), and the one extra
        dispatched iteration — which cannot split either — is discarded
        by finalize_training()."""
        import jax.numpy as jnp

        from .. import tracing
        from ..learner.grow import FMETA_KEYS

        if getattr(self, "_stopped", False):
            # report the pending stop ONCE, then drop the latch: the
            # reference retries every TrainOneIter call (a fresh bag can
            # open splits the previous one closed), so a later call must
            # be allowed to train again (ADVICE.md round 5 #1)
            self._stopped = False
            return True
        mask = self._feature_mask()
        with tracing.phase("tree/grow"):
            self._score, small = _grow_and_update(
                self._score, self._binned, grad[0], hess[0],
                row_weight, jnp.asarray(mask), self.shrinkage_rate,
                self._n, [self._fmeta[key] for key in FMETA_KEYS], 0,
                self._grower_cfg,
                qscale=None if qscales is None else qscales[0])
        # fetch + build the PREVIOUS tree while this one runs on device
        ok_prev = self._flush_pending()
        # stash the DISPATCH-TIME shrinkage (a learning-rate schedule
        # changes self.shrinkage_rate before the flush happens one
        # iteration later) and the dispatch-time non-finite probe and
        # iteration index, fetched together with the small tree arrays
        self._pending_small = (small, self.shrinkage_rate, probe, self.iter_)
        self.iter_ += 1
        if not ok_prev:
            # previous iteration produced no split: unwind the
            # speculative iteration just dispatched. Under bagging it may
            # HAVE split (a fresh bag can open splits the previous one
            # closed) and its leaf values are already in the device
            # score, so roll it back the way rollback_one_iter does —
            # materialize and subtract its traversal values — instead of
            # assuming the delta was zero.
            small, shrink, probe, it = self._pending_small
            self._pending_small = None
            self._raise_if_nonfinite(probe, it)
            self.iter_ -= 1
            tree = self._materialize_small(small, shrink, fold_bias=False)
            if tree.num_leaves > 1:
                neg = copy.deepcopy(tree)
                neg.leaf_value = -neg.leaf_value
                self._score = self._score.at[0].add(
                    predict_value_binned(neg.to_device(), self._binned))
            # the stop is reported by THIS return — disarm the latch so
            # the next call trains again (the latch only needs to carry
            # a stop detected by an out-of-band drain, e.g. an eval's
            # finalize_training, to the next train_one_iter)
            self._stopped = False
            return True
        return False

    def _materialize_small(self, small, shrink, fold_bias=True):
        """Device small-state -> host Tree (+ shrinkage and, for kept
        trees, the one-time boost-from-average bias fold) — the single
        copy both the pipelined flush and its rollback path use."""
        import jax

        from .. import tracing
        with tracing.phase("tree/extract"):
            host_state = _HostState(jax.device_get(small))
            tree = Tree.from_grower_state(host_state, self.train_data)
        if tree.num_leaves > 1:
            tree.apply_shrinkage(shrink)
            if fold_bias and \
                    abs(getattr(self, "_pending_bias", 0.0)) > _K_EPSILON:
                tree.add_bias(self._pending_bias)
                self._pending_bias = 0.0
                self.init_score_bias = 0.0
        self._log_pass_economics(host_state)
        return tree

    def _log_pass_economics(self, host_state) -> None:
        """Schedule observability (scripts/profile_train.py + PARITY.md +
        bench.py): append (passes, table high-water, rows fed to histogram
        contractions, per-device collective elements) per tree —
        rows_contracted is the compaction economics headline (full passes
        report ~passes * N), comm_elems the histogram-merge volume the
        scatter schedule exists to shrink."""
        from .. import tracing
        if not hasattr(self, "pass_log"):
            self.pass_log = []
        rows_contracted = float(getattr(host_state, "rows_contracted", 0.0))
        comm_elems = float(getattr(host_state, "comm_elems", 0.0))
        # element count -> wire bytes: every exchanged histogram element
        # is 4 bytes (f32, or the exact int32 domain under
        # tpu_hist_quantize — where the constant-hessian channel elision
        # already shrank comm_elems itself by red_ch/3)
        comm_bytes = comm_elems * 4.0
        self.pass_log.append((int(host_state.num_passes),
                              int(host_state.next_free),
                              rows_contracted, comm_elems, comm_bytes))
        tracing.counter("tree/num_passes", int(host_state.num_passes))
        tracing.counter("tree/rows_contracted", rows_contracted)
        tracing.counter("tree/comm_elems", comm_elems)
        tracing.counter("tree/comm_bytes", comm_bytes)

    def _flush_pending(self) -> bool:
        """Materialize the pipelined tree, if any. Returns False when the
        tree could not split (its iteration is rolled back here)."""
        if self._pending_small is None:
            return True
        small, shrink, probe, it = self._pending_small
        self._pending_small = None
        self._raise_if_nonfinite(probe, it)
        tree = self._materialize_small(small, shrink)
        if tree.num_leaves > 1:
            self.models.append(tree)
            self._bump_model_version()
            # a splitting tree clears any stale stop latch: the latch
            # exists to carry a pending stop across a drain, not to
            # poison later successful iterations (a fresh bag can open
            # splits a previous bag closed — ADVICE.md round 5 #1)
            self._stopped = False
            return True
        self.iter_ -= 1
        # latch the stop so a drain from finalize_training (e.g. a
        # training-metric eval mid-loop) cannot swallow it — the next
        # train_one_iter must still report termination
        self._stopped = True
        log.warning("Stopped training because there are no more leaves "
                    "that meet the split requirements")
        return False

    def finalize_training(self) -> None:
        """Drain the async pipeline (engine.train calls this after the
        boosting loop; model/prediction readers call it defensively)."""
        self._flush_pending()

    # ------------------------------------------------------------------
    # model-version bookkeeping (serving/forest.py): EVERY ensemble
    # mutation — tree append, rollback, model load, checkpoint restore,
    # continued training, DART re-normalization — must route through
    # here so device-resident stacked forests can never serve a stale
    # model. The version only ever increases.
    def _bump_model_version(self) -> None:
        self._compiled_forest.invalidate()
        for listener in list(getattr(self, "_version_listeners", ())):
            try:
                listener(self._compiled_forest.version)
            except Exception:  # a broken observer must not poison training
                log.warning("model-version listener raised (ignored)")

    def add_version_listener(self, fn) -> None:
        """Publish hook: `fn(version)` fires after every ensemble
        mutation (the registry uses it to refresh budget accounting and
        swap-visibility gauges)."""
        self._version_listeners.append(fn)

    def remove_version_listener(self, fn) -> None:
        try:
            self._version_listeners.remove(fn)
        except ValueError:
            pass

    def compiled_stack_bytes(self) -> int:
        """Device bytes currently held by this booster's compiled
        forest stacks (the registry's budget unit)."""
        return self._compiled_forest.device_bytes()

    def model_version(self) -> int:
        """Monotonic counter identifying the current ensemble contents
        (drains the async tree pipeline first, like num_trees(): a
        pending tree is part of the model the next predict serves)."""
        self.finalize_training()
        return self._compiled_forest.version

    # ------------------------------------------------------------------
    # NaN/Inf gradient guard
    def _nonfinite_probe(self, grad, hess):
        """Lazily-fetched device flag; None when the guard is disabled
        (tpu_guard_nonfinite=false)."""
        if not self.config.boosting.tpu_guard_nonfinite:
            return None
        return _nonfinite_probe_device(grad, hess)

    def _raise_if_nonfinite(self, probe, iteration: int) -> None:
        """A NaN/Inf gradient would not crash anything downstream — the
        histogram sums just absorb it and every later tree fits garbage
        residuals — so fail loudly, naming the objective and iteration,
        instead of silently degrading the whole remaining run."""
        if probe is None or not bool(probe):
            return
        name = self.objective.name if self.objective is not None \
            else "custom (fobj)"
        raise log.LightGBMError(
            "Objective '%s' produced non-finite gradients/hessians at "
            "iteration %d. This usually means the labels/init_score "
            "contain NaN/Inf, the learning rate diverged the scores, or "
            "a custom objective overflowed; set tpu_guard_nonfinite="
            "false to disable this check." % (name, iteration))

    def _tree_values_device(self, dtree, binned, raw):
        """Per-row values of one device tree over a binned matrix.
        Constant-leaf trees gather leaf_value from the binned traversal;
        linear trees additionally need the RAW (inner-space) matrix for
        the leaf-gathered coeff . x term — predict_value_binned refuses
        them by design (ops/predict.py)."""
        import jax.numpy as jnp

        from ..ops.predict import linear_leaf_addend
        if dtree.leaf_coeff is None or dtree.leaf_coeff.shape[-1] == 0:
            return predict_value_binned(dtree, binned)
        if raw is None:
            raise log.LightGBMError(
                "linear_tree score replay needs raw feature values for "
                "this dataset: construct it with keep_raw=true")
        lid = predict_leaf_binned(dtree, binned)
        return dtree.leaf_value[lid].astype(jnp.float32) \
            + linear_leaf_addend(dtree.leaf_coeff, dtree.leaf_feat, lid,
                                 raw)

    def _update_valid_scores(self, cls: int, tree) -> None:
        from .. import tracing
        with tracing.phase("boosting/update_valid_score"):
            dtree = tree.to_device() if self.valid_sets else None
            vraws = getattr(self, "_valid_raw", None)
            for vi in range(len(self.valid_sets)):
                self._valid_score[vi] = \
                    self._valid_score[vi].at[cls].add(
                        self._tree_values_device(
                            dtree, self._valid_binned[vi],
                            vraws[vi] if vraws else None))

    def _finish_iter(self, could_split_any: bool) -> bool:
        """Advance the iteration counter, rolling the whole iteration
        back when no class tree could split (gbdt.cpp:466-472)."""
        # trees were appended (or are about to be popped) either way
        self._bump_model_version()
        self.iter_ += 1
        if not could_split_any:
            for _ in range(self.num_tree_per_iteration):
                self.models.pop()
            self.iter_ -= 1
            log.warning("Stopped training because there are no more leaves "
                        "that meet the split requirements")
            return True
        # sync-path iterations that split clear the pipelined stop latch
        # (same rationale as in _flush_pending)
        self._stopped = False
        return False

    def _train_one_iter_multi(self, grad, hess, row_weight,
                              qscales=None) -> bool:
        """All num_class trees of one iteration as ONE device program
        (serial learner; see _grow_and_update_multi_impl)."""
        import jax
        import jax.numpy as jnp

        from .. import tracing
        from ..learner.grow import FMETA_KEYS

        k = self.num_tree_per_iteration
        masks = np.stack([self._feature_mask() for _ in range(k)])
        with tracing.phase("tree/grow"):
            self._score, small = _grow_and_update_multi(
                self._score, self._binned, grad, hess, row_weight,
                jnp.asarray(masks), self.shrinkage_rate, self._n,
                [self._fmeta[key] for key in FMETA_KEYS],
                self._grower_cfg, qscales=qscales)
        with tracing.phase("tree/extract"):
            host = jax.device_get(small)
        could_split_any = False
        for cls in range(k):
            host_state = _HostState({key: v[cls] for key, v in host.items()})
            tree = Tree.from_grower_state(host_state, self.train_data)
            if tree.num_leaves > 1:
                could_split_any = True
                tree.apply_shrinkage(self.shrinkage_rate)
                self._update_valid_scores(cls, tree)
            self.models.append(tree)

        return self._finish_iter(could_split_any)

    def rollback_one_iter(self) -> None:
        """Reference: GBDT::RollbackOneIter, gbdt.cpp:476-492."""
        import jax.numpy as jnp
        self.finalize_training()
        self._stopped = False
        if self.iter_ <= 0:
            return
        k = self.num_tree_per_iteration
        for cls in reversed(range(k)):
            tree = self.models.pop()
            if tree.num_leaves > 1:
                neg = copy.deepcopy(tree)
                neg.leaf_value = -neg.leaf_value
                neg.leaf_coeff = -neg.leaf_coeff
                dtree = neg.to_device()
                vraws = getattr(self, "_valid_raw", None)
                self._score = self._score.at[cls].add(
                    self._tree_values_device(dtree, self._binned,
                                             getattr(self, "_raw", None)))
                for vi in range(len(self.valid_sets)):
                    self._valid_score[vi] = self._valid_score[vi].at[cls].add(
                        self._tree_values_device(
                            dtree, self._valid_binned[vi],
                            vraws[vi] if vraws else None))
        self.iter_ -= 1
        self._bump_model_version()

    # ------------------------------------------------------------------
    def eval_once(self) -> List[Tuple[str, str, float, bool]]:
        """Evaluate all metrics; returns (data_name, metric_name, value,
        is_bigger_better) tuples (reference: GBDT::OutputMetric,
        gbdt.cpp:575-632)."""
        out = []
        self.finalize_training()
        if self.metrics and self.config.metric.is_provide_training_metric:
            train_score = self._train_score_unpadded()
            for m in self.metrics:
                for name, val in m.eval(train_score, self.objective):
                    out.append(("training", name, val, m.is_bigger_better))
        for vi, ms in enumerate(self.valid_metrics):
            vscore = np.asarray(self._valid_score[vi], np.float64).reshape(-1)
            for m in ms:
                for name, val in m.eval(vscore, self.objective):
                    out.append((self.valid_names[vi], name, val, m.is_bigger_better))
        return out

    def _train_score_unpadded(self) -> np.ndarray:
        s = np.asarray(self._score, np.float64)
        return s[:, :self._n].reshape(-1)

    # ------------------------------------------------------------------
    def num_trees(self) -> int:
        self.finalize_training()
        return len(self.models)

    def current_iteration(self) -> int:
        # drain the async pipeline like num_trees(): mid-pipeline the
        # counter could name an iteration whose tree later fails to
        # split and is rolled back (non-monotonic, inconsistent with
        # num_trees — ADVICE.md round 5 #2)
        self.finalize_training()
        return self.iter_

    # ------------------------------------------------------------------
    # prediction (reference: gbdt_prediction.cpp + Predictor)

    # rows per device dispatch. The WALK path (categorical models) keeps
    # small batches: large forests over >=500k-row walk dispatches
    # reproducibly fault the relay-attached TPU worker. The matmul path
    # takes much larger batches — per-chunk upload+dispatch overhead
    # dominated at 2^17 (measured 14s -> 5.5s for 500k x 100 trees).
    _PREDICT_ROW_CHUNK = 1 << 17
    _PREDICT_ROW_CHUNK_MATMUL = 1 << 19

    def _capped_total(self, num_iteration: int) -> int:
        """Trees used under a num_iteration cap (shared by the value,
        leaf, and early-stop prediction routes — they used to slice
        `self.models` independently)."""
        total = len(self.models)
        if num_iteration > 0:
            total = min(total, num_iteration * self.num_tree_per_iteration)
        return total

    def _forest_cache(self):
        """The CompiledForest cache with its enable bit refreshed from
        config (tpu_predict_cache=false reproduces the per-call-restack
        seed behavior for A/B timing)."""
        self._compiled_forest.enabled = bool(self.config.io.tpu_predict_cache)
        return self._compiled_forest

    def _predict_chunk_rows(self, default: int) -> int:
        c = int(self.config.io.tpu_predict_chunk)
        return c if c > 0 else default

    # ------------------------------------------------------------------
    # quantized serving layouts (tpu_predict_quantize, serving/forest.py)
    # calibration rows for the accuracy-delta gate: enough to exercise
    # every split region of a realistic forest without making the first
    # quantized predict pay a second full-batch evaluation
    _QUANT_CALIB_ROWS = 256

    def _quantize_mode(self) -> str:
        mode = str(self.config.io.tpu_predict_quantize or "none").lower()
        from ..serving.forest import QUANTIZE_MODES
        if mode not in QUANTIZE_MODES:  # config validates; double belt
            raise log.LightGBMError(
                "tpu_predict_quantize must be one of %s (got %r)"
                % (QUANTIZE_MODES, mode))
        return mode

    def _class_stack_dev(self, entry, dj, mode):
        """Dispatch one class's stacked forest on a padded chunk."""
        if mode == "int8":
            qf, st = entry
            if qf is not None:
                return _jit_forest_quant(qf, dj)
            return _jit_forest_raw(st, dj) if st is not None else None
        mf, st = entry
        if mf is not None:
            return _jit_forest_f16(mf, dj) if mode == "f16" \
                else _jit_forest_raw_matmul(mf, dj)
        return _jit_forest_raw(st, dj) if st is not None else None

    def _quant_gate(self, cache, mode, k, total, q_stacks, data) -> None:
        """Build-time accuracy gate: on the first predict of a freshly
        stacked quantized layout, evaluate it AND the f32 stack on a
        calibration batch (the head of the incoming data) and refuse to
        serve if the worst raw-score delta exceeds
        `tpu_predict_quantize_tol` (relative to the batch's raw-score
        scale, floored at 1). The measured delta is cached per
        (layout, model version), so steady-state requests only compare
        a float against the tolerance — and a later call with a
        tightened tolerance re-judges the same measurement instead of
        re-running the comparison."""
        import jax.numpy as jnp

        from .. import tracing
        from ..serving.forest import pad_rows
        key = ("value", total, k, mode)
        delta = cache.gate_delta(key)
        if delta is None and getattr(self, "_quant_gate_defer", False):
            # warmup traffic (synthetic all-zeros rows) must not become
            # the cached calibration measurement — defer to the first
            # real batch (serving/predictor.warmup sets the flag)
            return
        if delta is None:
            n_cal = min(data.shape[0], self._QUANT_CALIB_ROWS)
            calib = np.asarray(data[:n_cal], np.float32)
            bucket = self._bucket_size(n_cal, self._PREDICT_ROW_CHUNK)
            dj = jnp.asarray(pad_rows(calib, bucket))
            f32_stacks = cache.value_stacks(self.models, k, total)
            delta = 0.0
            scale = 1.0
            for cls in range(k):
                fr = self._class_stack_dev(f32_stacks[cls], dj, "none")
                qr = self._class_stack_dev(q_stacks[cls], dj, mode)
                if fr is None or qr is None:
                    continue
                fr = np.asarray(fr, np.float64)[:n_cal]
                qr = np.asarray(qr, np.float64)[:n_cal]
                delta = max(delta, float(np.max(np.abs(fr - qr)))
                            if n_cal else 0.0)
                scale = max(scale, float(np.max(np.abs(fr)))
                            if n_cal else 1.0)
            delta = delta / scale
            cache.record_gate(key, delta)
            from .. import telemetry
            telemetry.gauge_set("serving/quantize_gate_delta", delta)
            tracing.counter("predict/quant_gate_runs", 1)
            log.debug("Quantize gate (%s, %d trees): relative raw-score "
                      "delta %.3g on %d calibration rows", mode, total,
                      delta, n_cal)
        tol = float(self.config.io.tpu_predict_quantize_tol)
        if delta > tol:
            raise log.LightGBMError(
                "tpu_predict_quantize=%s refused: max raw-score delta "
                "%.3g vs the f32 stack exceeds tpu_predict_quantize_tol"
                "=%.3g (relative to the calibration batch's score "
                "scale). Raise the tolerance or serve with "
                "tpu_predict_quantize=none." % (mode, delta, tol))

    def _bucket_size(self, nrows: int, cap: int) -> int:
        from ..serving.forest import bucket_rows
        return bucket_rows(nrows, int(self.config.io.tpu_predict_bucket_min),
                           cap)

    def _pipelined_chunks(self, data: np.ndarray, chunk: int,
                          dispatch, fetch) -> None:
        """Double-buffered row-chunk loop: dispatch chunk k+1 BEFORE
        fetching chunk k, so chunk k's D2H fetch overlaps chunk k+1's
        H2D/compute instead of serializing with it (jax dispatch is
        async; the blocking call is the fetch). Each chunk's row count
        is padded up the bucket ladder so the remainder chunk reuses a
        compiled program instead of retracing — every prediction kernel
        is row-independent, so the padding is sliced off at fetch with
        bit-identical results. `dispatch(dj)` returns unfetched device
        value(s); `fetch(sl, nrows, dev)` materializes them."""
        import jax.numpy as jnp

        from .. import tracing
        from ..serving.forest import pad_rows
        n = data.shape[0]
        pipeline = bool(self.config.io.tpu_predict_pipeline)
        pending = None
        for i in range(0, n, chunk):
            nrows = min(chunk, n - i)
            bucket = self._bucket_size(nrows, chunk)
            dj = jnp.asarray(pad_rows(data[i:i + nrows], bucket))
            tracing.counter("predict/chunks", 1)
            dev = dispatch(dj)
            if pending is not None:
                fetch(*pending)
            pending = (slice(i, i + nrows), nrows, dev)
            if not pipeline:
                fetch(*pending)
                pending = None
        if pending is not None:
            fetch(*pending)

    def _predict_raw_matrix(self, data: np.ndarray,
                            num_iteration: int = -1,
                            pred_early_stop: bool = False,
                            pred_early_stop_freq: int = 10,
                            pred_early_stop_margin: float = 10.0,
                            transform=None) -> np.ndarray:
        """Raw scores [num_data, num_tree_per_iteration] from raw features.

        Steady-state serving shape: the stacked forest comes from the
        device-resident CompiledForest cache (stacked/transferred once
        per model version, not per call), rows dispatch through the
        bucket ladder, and the chunk loop is pipelined — see
        _pipelined_chunks. Only the row axis is chunked (large forests
        over >=500k-row single walk dispatches reproducibly fault the
        relay-attached TPU worker)."""
        data = np.asarray(data, np.float32)
        self.finalize_training()
        n = data.shape[0]
        k = self.num_tree_per_iteration
        total = self._capped_total(num_iteration)
        out = np.zeros((k, n), np.float64)
        # margin-based prediction early stop (predictor.hpp:34-60: binary
        # and multiclass objectives only)
        use_es = (pred_early_stop and total > 0
                  and (k > 1 or (self.objective is not None
                                 and self.objective.name == "binary")))
        cache = self._forest_cache()
        # quantized serving layouts (serving/forest.py): raw-score value
        # prediction only — pred_leaf stays exact by contract and the
        # early-stop route keeps its f32 [K, T] walk
        mode = self._quantize_mode() if not use_es else "none"
        stacked_kt = None
        class_stacks = []
        if use_es:
            stacked_kt = cache.early_stop_stacks(self.models, k, total // k)
        elif total > 0:
            # gather-free MXU path (ops/predict.MatmulForest), including
            # categorical models via the one-hot category expansion;
            # only over-budget forests take the walk
            from ..ops.predict import QuantRefused
            try:
                class_stacks = cache.value_stacks(self.models, k, total,
                                                  quantize=mode)
            except QuantRefused as exc:
                raise log.LightGBMError(
                    "tpu_predict_quantize=%s refused for this model: %s"
                    % (mode, exc)) from exc
            if mode != "none" and n > 0:
                self._quant_gate(cache, mode, k, total, class_stacks, data)

        c = self._predict_chunk_rows(
            self._PREDICT_ROW_CHUNK_MATMUL
            if (not use_es and class_stacks
                and all(mf is not None for mf, _ in class_stacks))
            else self._PREDICT_ROW_CHUNK)

        def dispatch(dj):
            if use_es:
                # [K, bucket] device array, fetched as ONE D2H transfer
                # (a per-class slice fetch would pay k blocking relay
                # round trips per chunk)
                return _jit_forest_es(stacked_kt, dj,
                                      float(pred_early_stop_margin),
                                      int(pred_early_stop_freq))
            devs = []
            for entry in class_stacks:
                raw = self._class_stack_dev(entry, dj, mode)
                if raw is not None and transform is not None:
                    # output transform fused on device: ONE f32 fetch
                    # instead of fetch-raw + re-upload + fetch-converted
                    # (each blocking relay fetch of a 500k-row f64
                    # vector measured ~1.3 s — more than the forest
                    # compute itself)
                    raw = transform(raw)
                devs.append(raw)
            return devs

        def fetch(sl, nrows, devs):
            if not isinstance(devs, list):       # early-stop [K, bucket]
                out[:, sl] = np.asarray(devs, np.float64)[:, :nrows]
                return
            for cls, dev in enumerate(devs):
                if dev is not None:
                    out[cls, sl] = np.asarray(dev, np.float64)[:nrows]

        if use_es or class_stacks:
            self._pipelined_chunks(data, c, dispatch, fetch)
        if transform is None:
            if self.average_output and total > 0:
                out /= max(total // k, 1)
            out += self.init_score_bias
        return out.T

    def predict(self, data: np.ndarray, num_iteration: int = -1,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False,
                pred_early_stop: bool = False,
                pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0) -> np.ndarray:
        import jax.numpy as jnp
        self.finalize_training()
        if pred_leaf:
            data = np.asarray(data, np.float32)
            n = data.shape[0]
            total = self._capped_total(num_iteration)
            if total == 0:
                return np.zeros((n, 0), np.int32)
            # same cache/cap/layout route as the value path (the two
            # used to slice self.models and pick matmul-vs-walk
            # independently)
            mf, st = self._forest_cache().leaf_stacks(self.models, total)
            c = self._predict_chunk_rows(
                self._PREDICT_ROW_CHUNK_MATMUL if mf is not None
                else self._PREDICT_ROW_CHUNK)
            out = np.zeros((n, total), np.int32)

            def dispatch(dj):
                return _jit_forest_leaf_matmul(mf, dj) if mf is not None \
                    else _jit_forest_leaf_raw(st, dj)

            def fetch(sl, nrows, dev):
                out[sl] = np.asarray(dev)[:nrows]

            self._pipelined_chunks(data, c, dispatch, fetch)
            return out
        if pred_contrib:
            from ..shap import predict_contrib
            return predict_contrib(self, np.asarray(data, np.float64), num_iteration)
        k = self.num_tree_per_iteration
        total_cap = self._capped_total(num_iteration)
        if (not raw_score and self.objective is not None and k == 1
                and not pred_early_stop and total_cap > 0):
            # single-class fast path: bias/averaging + the objective's
            # output transform run on device before the single fetch.
            # Zero-tree models fall through to the slow path, which
            # returns the transformed bias prior; the averaging
            # denominator honors the num_iteration cap.
            obj = self.objective
            denom = float(max(total_cap // k, 1)) \
                if self.average_output else 1.0
            bias = float(self.init_score_bias)
            if getattr(self, "_fused_convert", None) is None:
                import jax

                def _conv(r, d, b):
                    return obj.convert_output(r / d + b)

                self._fused_convert = jax.jit(_conv)
            tr = lambda r: self._fused_convert(
                r, jnp.float32(denom), jnp.float32(bias))
            raw = self._predict_raw_matrix(data, num_iteration, transform=tr)
            return raw[:, 0]
        raw = self._predict_raw_matrix(
            data, num_iteration, pred_early_stop=pred_early_stop,
            pred_early_stop_freq=pred_early_stop_freq,
            pred_early_stop_margin=pred_early_stop_margin)
        if raw_score or self.objective is None:
            return raw[:, 0] if raw.shape[1] == 1 else raw
        conv = np.asarray(self.objective.convert_output(
            jnp.asarray(raw.T.reshape(-1), jnp.float32)), np.float64)
        if k == 1:
            return conv
        return conv.reshape(k, -1).T

    # ------------------------------------------------------------------
    # model text IO (reference: gbdt_model.cpp:170-370)
    def model_name(self) -> str:
        return "tree"

    def save_model_to_string(self, num_iteration: int = -1) -> str:
        self.finalize_training()
        out = [self.model_name()]
        out.append("version=v2_tpu")
        out.append(f"num_class={self.num_class}")
        out.append(f"num_tree_per_iteration={self.num_tree_per_iteration}")
        out.append("label_index=0")
        out.append(f"max_feature_idx={self.max_feature_idx}")
        if self.objective is not None:
            out.append(f"objective={self.objective.to_string()}")
        if self.average_output:
            out.append("average_output")
        out.append("feature_names=" + " ".join(self.feature_names))
        out.append("feature_infos=" + " ".join(
            getattr(self, "feature_infos_", None)
            or ["none"] * (self.max_feature_idx + 1)))
        if self.init_score_bias != 0.0:
            # only reachable for models loaded from old-format files; new
            # models carry the bias inside the first tree (AddBias)
            out.append(f"init_score_bias={self.init_score_bias}")
        out.extend(self._extra_model_header(num_iteration))
        out.append("")
        total = len(self.models)
        if num_iteration > 0:
            total = min(total, num_iteration * self.num_tree_per_iteration)
        for i in range(total):
            out.append(f"Tree={i}")
            out.append(self.models[i].to_string())
        out.append("end of trees")
        out.append("")
        imp = self.feature_importance("split")
        pairs = sorted(((v, self.feature_names[i]) for i, v in enumerate(imp) if v > 0),
                       reverse=True)
        out.append("feature importances:")
        for v, name in pairs:
            out.append(f"{name}={int(v)}")
        return "\n".join(out) + "\n"

    def _extra_model_header(self, num_iteration: int = -1) -> List[str]:
        """Subclass hook for extra `key=value` header lines (DART's drop
        ledger); emitted before the tree blocks, ignored by loaders that
        don't know them."""
        return []

    def save_model(self, filename: str, num_iteration: int = -1) -> None:
        # atomic (tmp + fsync + rename): a preemption mid-save must never
        # leave a truncated file that still parses as a shorter model
        ckpt.atomic_write_text(filename,
                               self.save_model_to_string(num_iteration))
        log.info("Saved model to %s", filename)

    def load_model_from_string(self, text: str) -> None:
        """Reference: GBDT::LoadModelFromString, gbdt_model.cpp:247-330."""
        lines = text.splitlines()
        kv = {}
        tree_blocks: List[List[str]] = []
        cur: Optional[List[str]] = None
        for line in lines:
            ls = line.strip()
            if ls.startswith("Tree="):
                if cur is not None:
                    tree_blocks.append(cur)
                cur = []
                continue
            if ls == "end of trees":
                if cur is not None:
                    tree_blocks.append(cur)
                cur = None
                continue
            if cur is not None:
                if ls:
                    cur.append(ls)
            elif "=" in ls:
                k, v = ls.split("=", 1)
                kv[k] = v
            elif ls == "average_output":
                kv["average_output"] = "1"
        if cur:
            tree_blocks.append(cur)
        self.num_class = int(kv.get("num_class", 1))
        self.num_tree_per_iteration = int(kv.get("num_tree_per_iteration", self.num_class))
        self.max_feature_idx = int(kv.get("max_feature_idx", 0))
        self.feature_names = kv.get("feature_names", "").split()
        self.feature_infos_ = kv.get("feature_infos", "").split()
        self.init_score_bias = float(kv.get("init_score_bias", 0.0))
        self.average_output = "average_output" in kv
        self.models = [Tree.from_string("\n".join(b)) for b in tree_blocks]
        self.iter_ = len(self.models) // max(self.num_tree_per_iteration, 1)
        self._bump_model_version()

    # ------------------------------------------------------------------
    # checkpoint/resume (lightgbm_tpu/checkpoint.py drives this through
    # engine.train; the contract is bit-identical restart: everything the
    # next train_one_iter reads must round-trip EXACTLY)
    def _checkpoint_extra(self) -> dict:
        """Subclass hook for boosting-variant state (DART's drop ledger +
        drop RNG). GOSS and bagging need nothing here: their row masks
        are pure functions of (seed, iteration) via jax fold_in."""
        return {}

    def _restore_extra(self, extra: dict) -> None:
        return None

    def checkpoint_state(self) -> dict:
        """Full JSON-serializable training state EXCLUDING the model
        string (the snapshot payload carries that separately so tooling
        can extract a plain model from any checkpoint). Scores are the
        exact f32 device arrays: replaying trees would re-sum their
        contributions in a different order and break bit-identity."""
        self.finalize_training()
        state = {
            "iter": int(self.iter_),
            "shrinkage_rate": float(self.shrinkage_rate),
            "init_score_bias": float(self.init_score_bias),
            "pending_bias": float(getattr(self, "_pending_bias", 0.0)),
            "stopped": bool(self._stopped),
            "score": ckpt.encode_array(np.asarray(self._score)),
            "valid_scores": [ckpt.encode_array(np.asarray(v))
                             for v in getattr(self, "_valid_score", [])],
            "feature_rng": ckpt.encode_rng(self._feature_rng),
            "best_iter": {k: int(v) for k, v in self.best_iter.items()},
            "best_score": {k: dict(v) for k, v in self.best_score.items()},
            "eval_history": list(self._eval_history),
            "extra": self._checkpoint_extra(),
            # world-size metadata (elastic resume, checkpoint.py): how
            # many real rows the score block covers, which global rows
            # they are, and the world this snapshot was taken under —
            # what a different-sized cohort needs to re-shard it
            "num_data": int(self._n),
            "world": {
                "processes": int(self._num_processes),
                "rank": int(self._process_rank),
                "devices": int(self._num_shards),
                "n_pad": int(self._n_pad),
            },
        }
        n_global = getattr(self.train_data, "num_global_rows", None)
        if n_global:
            state["num_data_global"] = int(n_global)
        if self._num_processes > 1:
            # the partition is identical across a run's snapshots, but
            # each snapshot must stay SELF-CONTAINED: resume falls back
            # past corrupt/rotated files to any older snapshot, and a
            # sidecar partition file would re-introduce a second thing
            # that can be lost/corrupt independently. The cost is
            # ~10.7 B64-bytes/row per snapshot, bounded by keep-last-K
            row_index = getattr(self.train_data, "used_row_indices", None)
            if row_index is not None and len(row_index) == self._n:
                state["row_index"] = ckpt.encode_array(
                    np.asarray(row_index, np.int64))
        return state

    def restore_state(self, state: dict, model_str: str) -> None:
        """Inverse of checkpoint_state, applied to a freshly-init()'d
        booster (same dataset, same config — the engine verifies the
        config fingerprint before calling this)."""
        import jax.numpy as jnp
        self.finalize_training()
        self.load_model_from_string(model_str)
        for tree in self.models:
            # our text carries complete bin/group metadata, so loaded
            # trees are device-ready as-is; only legacy/reference text
            # needs re-derivation (which is NOT bit-exactness-critical:
            # such models never came from a checkpoint of this build)
            if tree.num_leaves > 1 and not tree.has_bin_metadata:
                tree.attach_bin_metadata(self.train_data)
        # metadata attach mutates the trees after the load's bump
        self._bump_model_version()
        self.iter_ = int(state["iter"])
        self.shrinkage_rate = float(state["shrinkage_rate"])
        self.init_score_bias = float(state["init_score_bias"])
        self._pending_bias = float(state["pending_bias"])
        self._stopped = bool(state["stopped"])
        score = ckpt.decode_array(state["score"])
        have_shape = tuple(np.asarray(self._score).shape)
        if tuple(score.shape) == have_shape:
            self._score = jnp.asarray(score)
        else:
            # world-size-elastic resume: a snapshot taken at a different
            # device/process count pads (or shards) its score block
            # differently. The REAL rows' exact f32 values carry over
            # unchanged; the padding region keeps this init's values —
            # padded rows are weight-0 in every histogram and never read
            # by eval, so trees stay byte-identical (the same argument
            # that makes trees bit-identical across device counts,
            # tests/test_scatter_reduce.py)
            elastic_ok = bool(getattr(self.config.io, "tpu_elastic_resume",
                                      True))
            old_n = state.get("num_data")
            if (elastic_ok and old_n is not None
                    and int(old_n) == int(self._n)
                    and score.shape[0] == have_shape[0]
                    and score.shape[1] >= int(self._n)):
                log.info(
                    "Elastic resume: re-padding checkpoint scores from "
                    "%s to %s (%d real rows; snapshot world %s, now %d "
                    "device(s) x %d process(es))",
                    tuple(score.shape), have_shape, int(self._n),
                    state.get("world"), self._num_shards,
                    self._num_processes)
                fresh = np.asarray(self._score).copy()
                fresh[:, :int(self._n)] = score[:, :int(self._n)]
                self._score = jnp.asarray(fresh)
            else:
                raise log.LightGBMError(
                    "Checkpoint score shape %s does not match this "
                    "training setup %s — the dataset differs from the "
                    "checkpointed run%s"
                    % (score.shape, have_shape,
                       "" if elastic_ok else
                       " (tpu_elastic_resume=false refuses world-size "
                       "changes)"))
        valid_encs = state.get("valid_scores", [])
        have = getattr(self, "_valid_score", [])
        if len(valid_encs) != len(have):
            raise log.LightGBMError(
                "Checkpoint carries %d validation-score arrays but %d "
                "validation sets are attached; resume with the same "
                "valid_sets as the original run"
                % (len(valid_encs), len(have)))
        for vi, enc in enumerate(valid_encs):
            vs = ckpt.decode_array(enc)
            if tuple(vs.shape) != tuple(np.asarray(have[vi]).shape):
                raise log.LightGBMError(
                    "Checkpoint valid set %d score shape %s != %s — "
                    "validation data differs from the checkpointed run"
                    % (vi, vs.shape, np.asarray(have[vi]).shape))
            self._valid_score[vi] = jnp.asarray(vs)
        self._feature_rng = ckpt.decode_rng(state["feature_rng"])
        self.best_iter = {k: int(v)
                          for k, v in state.get("best_iter", {}).items()}
        self.best_score = {k: dict(v)
                           for k, v in state.get("best_score", {}).items()}
        self._eval_history = list(state.get("eval_history", []))
        # derived per-iteration caches must not leak across the restore
        self._pending_small = None
        if hasattr(self, "_bag_cache"):
            del self._bag_cache
        self._restore_extra(state.get("extra", {}))

    # ------------------------------------------------------------------
    def feature_importance(self, importance_type: str = "split",
                           num_iteration: int = -1) -> np.ndarray:
        """Reference: GBDT::FeatureImportance (gbdt_model.cpp:335-370)."""
        self.finalize_training()
        nf = self.max_feature_idx + 1
        imp = np.zeros(nf, np.float64)
        total = len(self.models)
        if num_iteration > 0:
            total = min(total, num_iteration * self.num_tree_per_iteration)
        for i in range(total):
            t = self.models[i]
            m = t.num_leaves - 1
            for j in range(m):
                if importance_type == "split":
                    imp[t.split_feature[j]] += 1
                else:
                    imp[t.split_feature[j]] += max(t.split_gain[j], 0.0)
        return imp

    def dump_model(self, num_iteration: int = -1) -> dict:
        self.finalize_training()
        total = len(self.models)
        if num_iteration > 0:
            total = min(total, num_iteration * self.num_tree_per_iteration)
        return {
            "name": "tree",
            "version": "v2_tpu",
            "num_class": self.num_class,
            "num_tree_per_iteration": self.num_tree_per_iteration,
            "label_index": 0,
            "max_feature_idx": self.max_feature_idx,
            "feature_names": self.feature_names,
            "tree_info": [t.to_json() for t in self.models[:total]],
        }
