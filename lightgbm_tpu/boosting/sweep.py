"""Many-model sweep training: K boosters, one compiled program, lockstep.

Host half of the vmapped sweep (device half: learner/sweep.SweepGrower).
`engine.train_sweep` drives this:

- `validate_sweep_params` checks up front that every param dict agrees
  on every knob that is not on the per-model allowlist — the
  shape-affecting ones (max_bin, num_leaves, max_depth, bundling, ...)
  decide the compiled program's shapes, so a divergence must surface as
  a LightGBMError naming the key, not as an XLA shape failure half a
  compile later.
- `SweepTrainer` builds ONE device-resident dataset + grower schedule
  (through a lead GBDT init), stacks the per-model knobs into traced
  [K] arrays, and steps all K boosting loops in lockstep with one
  dispatch per iteration and ZERO host syncs in the loop (small tree
  states stay on device until `finish()`).
- `finish()` materializes each model's trees, applies the serial stop
  rule per model (training truncates at the first iteration where no
  class tree could split — later lockstep iterations are discarded, so
  the ensemble matches what `engine.train` would have kept), folds the
  boost-from-average bias into each model's first splitting tree, and
  returns real `Booster` objects built through the model-text path (the
  loaded-booster invariants are test-enforced; tree text round-trips
  exactly).

Every model's trees are BYTE-IDENTICAL to training that config alone
(tests/test_sweep.py asserts `model_to_string()` equality, including
bagging/GOSS sampling, multiclass, and heterogeneous learning rates).
"""
from __future__ import annotations

from typing import Any, Dict, List, Sequence

import numpy as np

from .. import log, tracing
from ..config import Config, key_alias_transform
from ..learner.grow import GrowParams
from ..learner.sweep import (MODE_BAGGING, MODE_GOSS, MODE_PLAIN,
                             SweepGrower, SweepModelParams)
from ..objectives import create_objective
from ..tree import Tree
from . import create_boosting
from .gbdt import (_SMALL_STATE_KEYS, _HostState, _K_EPSILON,
                   feature_fraction_mask)

# knobs that may differ across the models of one sweep: they enter the
# compiled program as TRACED per-model values (learner/grow.GrowParams,
# shrinkage, sampling seeds/rates) or as host-side per-model state
# (feature_fraction masks). Everything else must agree — most of the
# rest is shape-affecting (max_bin, num_leaves, max_depth, bundling,
# num_class, bagging_freq, ...) or changes the shared dataset/binning.
SWEEP_VARIABLE_PARAMS = frozenset({
    "learning_rate",
    "lambda_l1", "lambda_l2", "min_gain_to_split",
    "min_data_in_leaf", "min_sum_hessian_in_leaf",
    "bagging_fraction", "bagging_seed",
    "feature_fraction", "feature_fraction_seed",
    "top_rate", "other_rate",
    # cosmetic / sweep-bookkeeping: never reaches the compiled program
    "verbosity",
})

_MISSING = object()


def _agreement_key(v):
    """Type-tolerant comparison key: 255 and 255.0 (or "255") are the
    same effective config value — Config.from_params parses them
    identically — so they must not be refused as divergent. Booleans
    stay distinct from their numeric forms."""
    if v is _MISSING:
        return ("missing",)
    if isinstance(v, bool):
        return ("bool", v)
    try:
        return ("num", float(v))
    except (TypeError, ValueError):
        return ("str", str(v))


def validate_sweep_params(params_list: Sequence[Dict[str, Any]]
                          ) -> List[Dict[str, Any]]:
    """Alias-canonicalize the K param dicts and verify they agree on
    every non-allowlisted key. Raises LightGBMError NAMING the first
    divergent key (sorted order, deterministic) instead of letting the
    divergence surface as an XLA shape error. Returns the canonical
    dicts."""
    if not params_list:
        raise log.LightGBMError("train_sweep needs at least one param dict")
    canon = [key_alias_transform(dict(p)) for p in params_list]
    if len(canon) == 1:
        return canon
    all_keys = sorted(set().union(*[set(p) for p in canon]))
    for key in all_keys:
        if key in SWEEP_VARIABLE_PARAMS:
            continue
        vals = [p.get(key, _MISSING) for p in canon]
        ref = vals[0]
        for ki, v in enumerate(vals[1:], start=1):
            if _agreement_key(v) != _agreement_key(ref):
                raise log.LightGBMError(
                    "Sweep configs disagree on %r (model 0: %s, model %d: "
                    "%s). A vmapped sweep shares one compiled program, so "
                    "every knob outside the per-model set %s must agree — "
                    "shape-affecting ones (max_bin, num_leaves, max_depth, "
                    "enable_bundle, num_class, bagging_freq, ...) "
                    "especially. Set it identically in every config, or "
                    "drop it everywhere."
                    % (key,
                       "<unset>" if ref is _MISSING else repr(ref), ki,
                       "<unset>" if v is _MISSING else repr(v),
                       sorted(SWEEP_VARIABLE_PARAMS)))
    return canon


class SweepTrainer:
    """Train K boosters in lockstep inside one XLA program per iteration.

    Built by engine.train_sweep; not a public API surface of its own.
    The LEAD config (index 0) decides everything shared: the dataset is
    bound/binned once under it, and its GBDT init derives the padded row
    layout, feature metadata, and grower schedule for the whole sweep.
    """

    def __init__(self, params_list: Sequence[Dict[str, Any]], train_set,
                 num_boost_round: int):
        import jax
        import jax.numpy as jnp

        canon = validate_sweep_params(params_list)
        self.params_list = [dict(p) for p in canon]
        # num_iterations is part of the lockstep contract (validated
        # shared above); pop it off like engine.train does
        rounds = [int(p.pop("num_iterations", num_boost_round))
                  for p in canon]
        self.num_boost_round = rounds[0]
        self.configs = [Config.from_params(dict(p)) for p in canon]
        lead_cfg = self.configs[0]
        K = len(self.configs)
        self.num_models = K

        if lead_cfg.tree_learner != "serial":
            raise log.LightGBMError(
                "train_sweep supports tree_learner=serial only (got %r); "
                "the model axis and the device mesh are separate batching "
                "dimensions" % lead_cfg.tree_learner)
        if lead_cfg.boosting_type not in ("gbdt", "goss"):
            raise log.LightGBMError(
                "train_sweep supports boosting_type gbdt or goss (got "
                "%r); dart/rf keep host-side per-iteration state that "
                "cannot run branch-free in lockstep"
                % lead_cfg.boosting_type)
        declared = int(lead_cfg.io.tpu_sweep_size)
        if declared > 0 and declared != K:
            raise log.LightGBMError(
                "tpu_sweep_size=%d but %d param dict(s) were given; the "
                "declared sweep width must match the sweep"
                % (declared, K))
        if jax.process_count() > 1:
            raise log.LightGBMError(
                "train_sweep is single-process (multi-host sweeps would "
                "need the model axis laid out over the mesh)")

        # ---- shared device state via the lead booster's init ----------
        train_set._update_params(dict(self.params_list[0]))
        inner = train_set._lazy_init()
        objective = create_objective(lead_cfg)
        if objective is None:
            raise log.LightGBMError(
                "train_sweep requires a built-in objective (custom fobj "
                "would need one gradient callback per model per step)")
        self.lead = create_boosting(lead_cfg.boosting_type, lead_cfg)
        self.lead.init(inner, objective, ())
        gb = self.lead
        self.kc = gb.num_tree_per_iteration
        self.n, self.n_pad = gb._n, gb._n_pad

        # ---- sweep grower schedule ------------------------------------
        # the sweep keeps the lead's auto-selected schedule VERBATIM:
        # subtraction and compaction reorder f32 partial sums, so
        # matching the serial counterpart's schedule exactly is what
        # makes model k's trees byte-identical to training it alone.
        # (Under the model-axis vmap the compaction cond batches — both
        # kernels run every pass and a select keeps each model's own
        # branch result: correct, merely slower. batch_k/table_mult are
        # bit-transparent by the grower's hard guarantee.) The one
        # override: K per-model subtraction caches multiply the memory
        # budget, so re-check it at K x and drop subtraction — with the
        # byte-identity caveat logged — only when it cannot fit.
        self.cfg = gb._grower_cfg
        if self.cfg.hist_subtract:
            from .gbdt import _SUBTRACT_CACHE_BUDGET
            g_cnt = max(1, int(gb.train_data.num_groups))
            slot_bytes = self.kc * g_cnt * gb._max_bins * 3 * 4
            slots = self.cfg.table_mult * lead_cfg.tree.num_leaves + 52
            if slots * slot_bytes * K > _SUBTRACT_CACHE_BUDGET:
                log.warning(
                    "Sweep: %d sibling-subtraction caches exceed the "
                    "device budget; disabling subtraction for the sweep. "
                    "Trees then match serial training only up to f32 "
                    "summation order (set tpu_hist_subtract=false on the "
                    "serial side for strict byte comparisons).", K)
                self.cfg = self.cfg._replace(hist_subtract=False)

        mode = MODE_PLAIN
        bag_freq = int(lead_cfg.boosting.bagging_freq)
        if lead_cfg.boosting_type == "goss":
            mode = MODE_GOSS
            for ki, c in enumerate(self.configs):
                if c.boosting.top_rate <= 0 or c.boosting.other_rate <= 0:
                    raise log.LightGBMError(
                        "GOSS sweep model %d requires top_rate > 0 and "
                        "other_rate > 0" % ki)
                # the serial GOSS ctor fatals on bagging (goss.py); a
                # non-lead model must be refused HERE, before the
                # lockstep run, not at finish() when its shell is built
                if bag_freq > 0 and c.boosting.bagging_fraction != 1.0:
                    raise log.LightGBMError(
                        "GOSS sweep model %d sets bagging_fraction=%g "
                        "with bagging_freq>0; cannot use bagging in "
                        "GOSS" % (ki, c.boosting.bagging_fraction))
        elif bag_freq > 0 and any(c.boosting.bagging_fraction < 1.0
                                  for c in self.configs):
            mode = MODE_BAGGING
        self.mode = mode

        # ---- per-model traced arrays ----------------------------------
        # every scalar below is computed with the serial path's exact
        # host expressions (gbdt._bagging_mask_impl / goss._goss_impl
        # derivations) so the traced values match the serial constants
        # bit-for-bit
        n = self.n
        f32 = np.float32
        self._lrs = [float(c.boosting.learning_rate) for c in self.configs]
        goss_top_k, goss_rest_p, goss_mult, goss_start = [], [], [], []
        for c in self.configs:
            b = c.boosting
            top_k = max(1, int(n * b.top_rate))
            other_k = max(1, int(n * b.other_rate))
            goss_top_k.append(top_k)
            goss_rest_p.append(f32(other_k / max(1, n - top_k)))
            goss_mult.append(f32((n - top_k) / other_k))
            goss_start.append(int(1.0 / max(b.learning_rate, 1e-12)))
        self._pm = SweepModelParams(
            grow=GrowParams(
                lambda_l1=jnp.asarray(
                    [c.tree.lambda_l1 for c in self.configs], f32),
                lambda_l2=jnp.asarray(
                    [c.tree.lambda_l2 for c in self.configs], f32),
                min_gain_to_split=jnp.asarray(
                    [c.tree.min_gain_to_split for c in self.configs], f32),
                min_data_in_leaf=jnp.asarray(
                    [c.tree.min_data_in_leaf for c in self.configs],
                    np.int32),
                min_sum_hessian_in_leaf=jnp.asarray(
                    [c.tree.min_sum_hessian_in_leaf for c in self.configs],
                    f32)),
            shrinkage=jnp.asarray(self._lrs, f32),
            bag_seed=jnp.asarray(
                [c.boosting.bagging_seed for c in self.configs], np.int32),
            bag_fraction=jnp.asarray(
                [c.boosting.bagging_fraction for c in self.configs], f32),
            goss_start=jnp.asarray(goss_start, np.int32),
            goss_top_k=jnp.asarray(goss_top_k, np.int32),
            goss_rest_p=jnp.asarray(goss_rest_p, f32),
            goss_multiply=jnp.asarray(goss_mult, f32),
        )

        # per-model feature_fraction host RNGs (exact serial draw order:
        # one RandomState per model, one draw per class tree). With no
        # fraction below 1.0 anywhere the masks are a constant all-ones
        # block — build it once and skip the per-iteration host stack +
        # upload entirely
        self._feature_rngs = [
            np.random.RandomState(c.tree.feature_fraction_seed)
            for c in self.configs]
        self._feature_fracs = [float(c.tree.feature_fraction)
                               for c in self.configs]
        self._static_masks = None
        if all(frac >= 1.0 for frac in self._feature_fracs):
            self._static_masks = jnp.ones(
                (K, self.kc, gb._num_features_padded), bool)

        from ..learner.grow import FMETA_KEYS
        self.grower = SweepGrower(
            self.cfg, objective, kc=self.kc, n=self.n, n_pad=self.n_pad,
            mode=mode, bag_freq=bag_freq,
            fmeta_args=tuple(gb._fmeta[k] for k in FMETA_KEYS),
            small_keys=_SMALL_STATE_KEYS,
            # quantized-gradient statics from the lead init (the gate
            # already ran inside lead.init; data_random_seed and the
            # hess_const-deciding params are sweep-SHARED by the
            # variable-params whitelist, so the lead's values hold for
            # every member)
            quant_seed=getattr(gb, "_quant_seed", 0),
            quant_hess_const=getattr(gb, "_quant_hess_const", False))

        # all K models start from the lead's initial score (same
        # objective + dataset => same init_score / boost-from-average)
        self._score = jnp.repeat(gb._score[None], K, axis=0)
        self._pending_bias = float(getattr(gb, "_pending_bias", 0.0))
        self._base_w = gb._base_weight
        self._smalls: List[Dict[str, Any]] = []
        self._it = 0
        log.info("Sweep: %d models x %d class tree(s), mode=%s, one "
                 "compiled program per iteration", K, self.kc, mode)

    # ------------------------------------------------------------------
    def _feature_mask(self, ki: int) -> np.ndarray:
        """Model ki's per-tree feature_fraction sample: the SHARED
        serial sampling code (gbdt.feature_fraction_mask), driven by
        the model's own RNG stream."""
        gb = self.lead
        return feature_fraction_mask(
            self._feature_rngs[ki], self._feature_fracs[ki],
            gb.train_data.num_features, gb._num_features_padded)

    def step(self) -> None:
        """One lockstep boosting iteration for all K models: ONE device
        dispatch, zero host syncs (tree states stay on device)."""
        import jax.numpy as jnp
        if self._static_masks is not None:
            masks = self._static_masks
        else:
            masks = jnp.asarray(np.stack([
                np.stack([self._feature_mask(ki) for _ in range(self.kc)])
                for ki in range(self.num_models)]))
        self._score, small = self.grower.step(
            self._score, self.lead._binned, self._it, self._pm,
            self._base_w, masks)
        self._smalls.append(small)
        self._it += 1
        tracing.counter("sweep/iterations", 1)

    # ------------------------------------------------------------------
    def finish(self) -> List[Any]:
        """Materialize the K Boosters: fetch every iteration's small
        tree state in one go, build host trees, apply the serial
        per-model stop rule, and wrap each model through the (exact)
        model-text load path."""
        import jax

        from ..basic import Booster
        with tracing.phase("sweep/materialize"):
            hosts = jax.device_get(self._smalls)
        gb = self.lead
        kc = self.kc
        boosters = []
        num_passes = 0  # accumulated across ALL models for the counter
        for ki in range(self.num_models):
            trees: List[Tree] = []
            pending_bias = self._pending_bias
            for host in hosts:
                iter_trees = []
                any_split = False
                for ci in range(kc):
                    hs = _HostState({key: np.asarray(v[ki][ci])
                                     for key, v in host.items()})
                    tree = Tree.from_grower_state(hs, gb.train_data)
                    num_passes += int(hs.num_passes)
                    if tree.num_leaves > 1:
                        any_split = True
                        tree.apply_shrinkage(self._lrs[ki])
                    iter_trees.append(tree)
                if not any_split:
                    # the serial engine rolls this iteration back and
                    # stops training — every later lockstep iteration
                    # belongs to models that are still running
                    break
                if abs(pending_bias) > _K_EPSILON:
                    for tree in iter_trees:
                        if tree.num_leaves > 1:
                            tree.add_bias(pending_bias)
                            pending_bias = 0.0
                            break
                trees.extend(iter_trees)

            shell = create_boosting(self.configs[ki].boosting_type,
                                    self.configs[ki])
            shell.objective = create_objective(self.configs[ki])
            shell.num_class = gb.num_class
            shell.num_tree_per_iteration = kc
            shell.max_feature_idx = gb.max_feature_idx
            shell.feature_names = list(gb.feature_names)
            shell.feature_infos_ = list(gb.feature_infos_)
            shell.models = trees
            shell.iter_ = len(trees) // max(kc, 1)
            # an unfolded bias (model never split) rides the header the
            # way legacy models carry it; folded bias lives in tree 0
            shell.init_score_bias = pending_bias
            booster = Booster(params=dict(self.params_list[ki]),
                              model_str=shell.save_model_to_string())
            boosters.append(booster)
            tracing.counter("sweep/trees", len(trees))
        tracing.counter("sweep/models", self.num_models)
        tracing.counter("sweep/passes", num_passes)
        return boosters
