from .gbdt import GBDT
from .dart import DART
from .goss import GOSS
from .rf import RF

from .. import log


def create_boosting(boosting_type: str, config):
    """Factory (reference: Boosting::CreateBoosting, boosting.cpp:29-76)."""
    if boosting_type == "gbdt":
        return GBDT(config)
    if boosting_type == "dart":
        return DART(config)
    if boosting_type == "goss":
        return GOSS(config)
    if boosting_type in ("rf", "random_forest"):
        return RF(config)
    log.fatal("Unknown boosting type %s" % boosting_type)
