"""Random forest mode.

Reference: `src/boosting/rf.hpp` — bagged trees with no shrinkage;
gradients are computed ONCE from the zero score (rf.hpp:83-89), every
iteration refits against them on a fresh bag, and the ensemble output is
the average over iterations (average_output_, rf.hpp:22 + score updates at
:120-140). Requires bagging and feature_fraction < 1.
"""
from __future__ import annotations

import numpy as np

from .. import log
from .gbdt import GBDT


class RF(GBDT):
    def __init__(self, config):
        super().__init__(config)
        cfg = config.boosting
        if not (cfg.bagging_freq > 0 and 0.0 < cfg.bagging_fraction < 1.0):
            log.fatal("RF mode requires bagging "
                      "(bagging_freq > 0 and bagging_fraction in (0,1))")
        if not (0.0 < config.tree.feature_fraction < 1.0):
            log.fatal("RF mode requires feature_fraction in (0, 1)")
        self.average_output = True

    def model_name(self) -> str:
        return "tree"  # reference RF also serializes as 'tree' with average_output

    def init(self, train_data, objective, metric_names=()):
        super().init(train_data, objective, metric_names)
        self.shrinkage_rate = 1.0
        if objective is None:
            log.fatal("RF mode requires an objective function")
        # RF fits against gradients of the ZERO score (rf.hpp:83-89); undo
        # any boost_from_average the base init applied so the averaged
        # ensemble output is not offset by bias/T
        if self.init_score_bias != 0.0:
            self._score = self._score - self.init_score_bias
            self.init_score_bias = 0.0
        self._pending_bias = 0.0
        # gradients from the zero score, fixed for all iterations
        import jax.numpy as jnp
        k = self.num_tree_per_iteration
        zero = jnp.zeros((k, self._n_pad), jnp.float32)
        g, h = self.objective.get_gradients(zero.reshape(-1))
        # RF never recomputes gradients, so a NaN label would poison
        # EVERY tree — check the one batch that matters up front
        self._raise_if_nonfinite(self._nonfinite_probe(g, h), 0)
        self._rf_grad = g
        self._rf_hess = h

    def _checkpoint_extra(self) -> dict:
        """RF needs no extra checkpoint state: `_rf_grad`/`_rf_hess` are
        rebuilt bit-identically by init() (gradients of the zero score),
        and its bagging masks are stateless like the base class's."""
        return {}

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        from ..testing import faults
        faults.inject("backend.grow")
        import jax.numpy as jnp
        k = self.num_tree_per_iteration
        n_pad = self._n_pad
        grad = self._rf_grad.reshape(k, n_pad)
        hess = self._rf_hess.reshape(k, n_pad)

        bag = self._bagging_weights(self.iter_, grad, hess)
        row_weight = self._row_weight_from_bag(bag)

        from .. import tracing
        from ..tree import Tree
        from ..ops.predict import predict_value_binned
        could_split_any = False
        t_before = float(self.iter_)
        for cls in range(k):
            mask = self._feature_mask()
            # phase spans match the base class's so RF iterations show
            # up under the same tree/grow..tree/extract accounting
            with tracing.phase("tree/grow"):
                state = self._grow(grad[cls], hess[cls], row_weight, mask)
            with tracing.phase("tree/extract"):
                tree = Tree.from_grower_state(state, self.train_data)
            if tree.num_leaves > 1:
                could_split_any = True
                # running average: score_{t+1} = (score_t * t + tree) / (t+1)
                leaf_vals = jnp.asarray(tree.leaf_value, jnp.float32)
                contrib = leaf_vals[jnp.clip(state.leaf_id, 0, tree.num_leaves - 1)]
                self._score = self._score.at[cls].set(
                    (self._score[cls] * t_before + contrib) / (t_before + 1.0))
                dtree = tree.to_device()
                for vi in range(len(self.valid_sets)):
                    vadd = predict_value_binned(dtree, self._valid_binned[vi])
                    self._valid_score[vi] = self._valid_score[vi].at[cls].set(
                        (self._valid_score[vi][cls] * t_before + vadd) / (t_before + 1.0))
            self.models.append(tree)
        self._bump_model_version()
        self.iter_ += 1
        if not could_split_any:
            for _ in range(k):
                self.models.pop()
            self.iter_ -= 1
            log.warning("Stopped training: no more valid splits")
            return True
        return False
