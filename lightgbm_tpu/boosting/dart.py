"""DART: Dropouts meet Multiple Additive Regression Trees.

Reference: `src/boosting/dart.hpp` — per iteration a random subset of
existing trees is dropped (weight-proportional unless uniform_drop), the
new tree is fit against the score without them, and dropped trees are
re-weighted to k/(k+1) (or the xgboost_dart_mode variant) so the ensemble
stays normalized (DroppingTrees dart.hpp:85-130, Normalize :140-180).
"""
from __future__ import annotations

import copy
from typing import List

import numpy as np

from .. import checkpoint as ckpt
from .gbdt import GBDT
from ..ops.predict import predict_value_binned


class DART(GBDT):
    def __init__(self, config):
        super().__init__(config)
        # DART reads back the CURRENT iteration's tree (normalization,
        # dart.hpp:85-130), so the base class's one-behind async tree
        # pipeline cannot apply
        self._supports_pipeline = False
        self.tree_weight: List[float] = []
        self.sum_weight = 0.0
        self._drop_rng = np.random.RandomState(config.boosting.drop_seed)
        self.drop_index: List[int] = []

    def model_name(self) -> str:
        return "dart"

    # ------------------------------------------------------------------
    # DART owns mutable cross-iteration state the base class doesn't:
    # the per-tree weight ledger (future drop probabilities are weight-
    # proportional) and the host drop RNG. Both must survive checkpoint/
    # resume and model-text round-trips or a restarted run diverges.
    def _checkpoint_extra(self) -> dict:
        return {
            "tree_weight": [float(w) for w in self.tree_weight],
            "sum_weight": float(self.sum_weight),
            "drop_rng": ckpt.encode_rng(self._drop_rng),
        }

    def _restore_extra(self, extra: dict) -> None:
        self.tree_weight = [float(w) for w in extra.get("tree_weight", [])]
        self.sum_weight = float(extra.get("sum_weight", 0.0))
        if "drop_rng" in extra:
            self._drop_rng = ckpt.decode_rng(extra["drop_rng"])
        self.drop_index = []

    def _extra_model_header(self, num_iteration: int = -1):
        # the drop ledger rides in the model text too (reference DART
        # cannot continue-train a loaded model for exactly this reason —
        # dart.hpp keeps the ledger in memory only); repr() round-trips
        # the doubles exactly. Truncated saves truncate the ledger.
        weights = self.tree_weight
        sum_weight = self.sum_weight
        if 0 < num_iteration < len(weights):
            weights = weights[:num_iteration]
            sum_weight = float(sum(weights))
        if not weights:
            return []
        # full saves emit the exact RUNNING sum (maintained incrementally
        # through _normalize; recomputing would change the f64 rounding)
        return ["tpu_dart_tree_weights=" + " ".join(
                    repr(float(w)) for w in weights),
                "tpu_dart_sum_weight=" + repr(float(sum_weight))]

    def load_model_from_string(self, text: str) -> None:
        super().load_model_from_string(text)
        self.tree_weight = []
        self.sum_weight = 0.0
        self.drop_index = []
        for line in text.splitlines():
            ls = line.strip()
            if ls.startswith("tpu_dart_tree_weights="):
                self.tree_weight = [float(w)
                                    for w in ls.split("=", 1)[1].split()]
            elif ls.startswith("tpu_dart_sum_weight="):
                self.sum_weight = float(ls.split("=", 1)[1])
            elif ls.startswith("Tree="):
                break

    def _tree_contribution(self, it: int, sign: float, on_valid: bool):
        """Add sign * tree(it) to train (and optionally valid) scores."""
        import jax.numpy as jnp
        k = self.num_tree_per_iteration
        for cls in range(k):
            tree = self.models[it * k + cls]
            if tree.num_leaves <= 1:
                continue
            t = copy.deepcopy(tree)
            t.leaf_value = t.leaf_value * sign
            dt = t.to_device()
            if not on_valid:
                self._score = self._score.at[cls].add(
                    predict_value_binned(dt, self._binned))
            else:
                for vi in range(len(self.valid_sets)):
                    self._valid_score[vi] = self._valid_score[vi].at[cls].add(
                        predict_value_binned(dt, self._valid_binned[vi]))

    def _dropping_trees(self):
        """Select and remove dropped trees from the train score
        (dart.hpp:85-130)."""
        cfg = self.config.boosting
        self.drop_index = []
        if self._drop_rng.rand() >= cfg.skip_drop:
            drop_rate = cfg.drop_rate
            if not cfg.uniform_drop:
                inv_avg = len(self.tree_weight) / self.sum_weight \
                    if self.sum_weight > 0 else 0.0
                if cfg.max_drop > 0 and self.sum_weight > 0:
                    drop_rate = min(drop_rate, cfg.max_drop * inv_avg / self.sum_weight)
                for i in range(self.iter_):
                    if self._drop_rng.rand() < drop_rate * self.tree_weight[i] * inv_avg:
                        self.drop_index.append(i)
            else:
                if cfg.max_drop > 0 and self.iter_ > 0:
                    drop_rate = min(drop_rate, cfg.max_drop / self.iter_)
                for i in range(self.iter_):
                    if self._drop_rng.rand() < drop_rate:
                        self.drop_index.append(i)
        for i in self.drop_index:
            self._tree_contribution(i, -1.0, on_valid=False)
        kdrop = len(self.drop_index)
        # drop activity in the run log / counters: a DART run whose
        # ledger drifted is diagnosed from dropped-per-iteration deltas
        from .. import tracing
        tracing.counter("boosting/dart_dropped_trees", kdrop)
        if not cfg.xgboost_dart_mode:
            self.shrinkage_rate = cfg.learning_rate / (1.0 + kdrop)
        else:
            self.shrinkage_rate = cfg.learning_rate if kdrop == 0 else \
                cfg.learning_rate / (cfg.learning_rate + kdrop)

    def _normalize(self):
        """Re-weight dropped trees (dart.hpp:140-180)."""
        cfg = self.config.boosting
        kdrop = float(len(self.drop_index))
        for i in self.drop_index:
            if not cfg.xgboost_dart_mode:
                factor = kdrop / (kdrop + 1.0)
            else:
                factor = kdrop / (kdrop + cfg.learning_rate)
            # valid scores still hold the full tree: adjust by (factor-1)
            k = self.num_tree_per_iteration
            for cls in range(k):
                tree = self.models[i * k + cls]
                tree.leaf_value = tree.leaf_value * factor
                tree.internal_value = tree.internal_value * factor
            self._tree_contribution_scaled(i, (factor - 1.0) / factor, on_valid=True)
            # train score had the tree fully removed: add back factor*tree
            self._tree_contribution(i, 1.0, on_valid=False)
            if not cfg.uniform_drop:
                if not cfg.xgboost_dart_mode:
                    self.sum_weight -= self.tree_weight[i] / (kdrop + 1.0)
                else:
                    self.sum_weight -= self.tree_weight[i] / (kdrop + cfg.learning_rate)
                self.tree_weight[i] *= factor

    def _tree_contribution_scaled(self, it: int, rel_sign: float, on_valid: bool):
        """Add rel_sign * current-tree-values to valid scores (used after the
        tree's stored values were already rescaled)."""
        import jax.numpy as jnp
        k = self.num_tree_per_iteration
        for cls in range(k):
            tree = self.models[it * k + cls]
            if tree.num_leaves <= 1:
                continue
            t = copy.deepcopy(tree)
            t.leaf_value = t.leaf_value * rel_sign
            dt = t.to_device()
            for vi in range(len(self.valid_sets)):
                self._valid_score[vi] = self._valid_score[vi].at[cls].add(
                    predict_value_binned(dt, self._valid_binned[vi]))

    def train_one_iter(self, gradients=None, hessians=None) -> bool:
        self._dropping_trees()
        stop = super().train_one_iter(gradients, hessians)
        if not stop:
            self.tree_weight.append(self.shrinkage_rate)
            self.sum_weight += self.shrinkage_rate
            self._normalize()
            # _normalize rescales EXISTING trees' leaf values in place —
            # a stacked forest cached after the append would be stale
            self._bump_model_version()
        else:
            # restore dropped trees to the train score
            for i in self.drop_index:
                self._tree_contribution(i, 1.0, on_valid=False)
        return stop
