"""GOSS: Gradient-based One-Side Sampling.

Reference: `src/boosting/goss.hpp` — keep the top_rate fraction of rows by
|grad*hess|, sample other_rate of the rest, and amplify the sampled rows'
gradients and hessians by (1-top_rate)/other_rate (BaggingHelper,
goss.hpp:87-131). Sampling starts after 1/learning_rate iterations
(goss.hpp:135-138). In the leaf-id design the amplification folds into the
per-row weight channel fed to the histogram kernel.
"""
from __future__ import annotations

from .. import log
from .gbdt import GBDT


class GOSS(GBDT):
    def __init__(self, config):
        super().__init__(config)
        if config.boosting.top_rate <= 0 or config.boosting.other_rate <= 0:
            log.fatal("GOSS requires top_rate > 0 and other_rate > 0")
        if config.boosting.bagging_freq > 0 and config.boosting.bagging_fraction != 1.0:
            log.fatal("Cannot use bagging in GOSS")
        log.info("Using GOSS")

    def model_name(self) -> str:
        return "goss"

    def _checkpoint_extra(self) -> dict:
        """GOSS needs NO extra checkpoint state: its subsample RNG is
        stateless — the row weights are a pure function of
        (bagging_seed, iteration) via jax.random.fold_in, and the top-k
        threshold derives from the (restored) score's gradients. Resume
        at iteration k therefore reproduces the exact masks of the
        uninterrupted run (asserted in tests/test_checkpoint.py)."""
        return {}

    def _bagging_weights(self, iter_idx, grad=None, hess=None):
        """GOSS row weights built ON DEVICE (no per-iteration [N]
        argsort on host / H2D upload): the top_rate threshold comes from
        a device sort of |grad*hess| (the partial-selection analogue of
        the reference's ArgMaxAtK, array_args.h), and the "other" rows
        are Bernoulli-sampled at other_k/(n-top_k) with the jax PRNG —
        the reference's own per-block `rand < prob` scheme
        (goss.hpp:87-131) rather than exact without-replacement draws."""
        cfg = self.config.boosting
        n = self._n
        # no subsampling for the first 1/lr iterations (goss.hpp:137)
        if iter_idx < int(1.0 / max(cfg.learning_rate, 1e-12)) or grad is None:
            return None
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))
        from .. import tracing
        tracing.counter("boosting/goss_sampled_iters", 1)
        return _goss_weights_device(
            grad, hess, cfg.bagging_seed, iter_idx,
            self.num_tree_per_iteration, n, self._n_pad, top_k, other_k)


def _goss_impl(g, h, it, *, seed, k, n, n_pad, top_k, other_k):
    import jax
    import jax.numpy as jnp

    # per-class |g*h| summed over classes (goss.hpp:91 accumulates
    # fabs(grad*hess) per class — abs BEFORE the class sum, so
    # opposite-signed class gradients don't cancel a row's magnitude)
    mag = jnp.abs(g.reshape(k, n_pad) * h.reshape(k, n_pad)).sum(axis=0)
    real = jnp.arange(n_pad, dtype=jnp.int32) < n
    mag = jnp.where(real, mag, -jnp.inf)
    # threshold = top_k-th largest magnitude
    thresh = -jnp.sort(-mag)[top_k - 1]
    is_top = mag >= thresh
    key = jax.random.fold_in(jax.random.PRNGKey(seed), it)
    # (n,) then pad, like the bagging mask in gbdt.py: a (n_pad,) draw
    # would tie the sample to the padded row count (a function of the
    # device count — threefry is not prefix-stable across shapes) and
    # break cross-world-size training bit-identity
    u = jnp.pad(jax.random.uniform(key, (n,)), (0, n_pad - n),
                constant_values=1.0)
    rest_p = other_k / max(1, n - top_k)
    multiply = (n - top_k) / other_k
    w = jnp.where(is_top, 1.0,
                  jnp.where(u < rest_p, multiply, 0.0))
    return jnp.where(real, w, 0.0).astype(jnp.float32)


_goss_jit = None


def _goss_weights_device(grad, hess, seed, iter_idx, k, n, n_pad,
                         top_k, other_k):
    import jax
    import jax.numpy as jnp
    global _goss_jit
    if _goss_jit is None:
        _goss_jit = jax.jit(_goss_impl, static_argnames=(
            "seed", "k", "n", "n_pad", "top_k", "other_k"))
    return _goss_jit(grad, hess, jnp.int32(iter_idx), seed=seed, k=k, n=n,
                     n_pad=n_pad, top_k=top_k, other_k=other_k)
