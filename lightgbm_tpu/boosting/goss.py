"""GOSS: Gradient-based One-Side Sampling.

Reference: `src/boosting/goss.hpp` — keep the top_rate fraction of rows by
|grad*hess|, sample other_rate of the rest, and amplify the sampled rows'
gradients and hessians by (1-top_rate)/other_rate (BaggingHelper,
goss.hpp:87-131). Sampling starts after 1/learning_rate iterations
(goss.hpp:135-138). In the leaf-id design the amplification folds into the
per-row weight channel fed to the histogram kernel.
"""
from __future__ import annotations

import numpy as np

from .. import log
from .gbdt import GBDT


class GOSS(GBDT):
    def __init__(self, config):
        super().__init__(config)
        if config.boosting.top_rate <= 0 or config.boosting.other_rate <= 0:
            log.fatal("GOSS requires top_rate > 0 and other_rate > 0")
        if config.boosting.bagging_freq > 0 and config.boosting.bagging_fraction != 1.0:
            log.fatal("Cannot use bagging in GOSS")
        log.info("Using GOSS")
        self._goss_rng = np.random.RandomState(config.boosting.bagging_seed)

    def model_name(self) -> str:
        return "goss"

    def _bagging_weights(self, iter_idx, grad=None, hess=None):
        cfg = self.config.boosting
        n = self._n
        # no subsampling for the first 1/lr iterations (goss.hpp:137)
        if iter_idx < int(1.0 / max(cfg.learning_rate, 1e-12)) or grad is None:
            return None
        g = np.asarray(grad, np.float64).reshape(self.num_tree_per_iteration, -1)[:, :n]
        h = np.asarray(hess, np.float64).reshape(self.num_tree_per_iteration, -1)[:, :n]
        mag = np.abs(g * h).sum(axis=0)
        top_k = max(1, int(n * cfg.top_rate))
        other_k = max(1, int(n * cfg.other_rate))
        order = np.argsort(-mag, kind="stable")
        top_idx = order[:top_k]
        rest_idx = order[top_k:]
        multiply = (n - top_k) / other_k
        w = np.zeros(n, np.float32)
        w[top_idx] = 1.0
        if len(rest_idx) > 0:
            sampled = self._goss_rng.choice(
                rest_idx, size=min(other_k, len(rest_idx)), replace=False)
            w[sampled] = multiply
        return w
