"""lightgbm_tpu — a TPU-native gradient boosting framework.

A from-scratch JAX/XLA re-design of the LightGBM feature set: leaf-wise
histogram GBDT with data/feature/voting-parallel distributed training over
`jax.sharding.Mesh` collectives, objectives/metrics for regression, binary,
multiclass and lambdarank, DART/GOSS/RF variants, and a LightGBM-compatible
Python API and text model format.
"""
import os as _os

# Persistent XLA compilation cache (VERDICT r2 item 6: a first 2M-row
# train paid ~2 min of compile before iteration 1 on every process).
# Re-runs of any already-seen (shape, config) signature now load from
# disk. Opt out with LIGHTGBM_TPU_COMPILE_CACHE=0; redirect with
# LIGHTGBM_TPU_COMPILE_CACHE_DIR. jax.config.update is safe pre-backend
# and does not initialize XLA.
if _os.environ.get("LIGHTGBM_TPU_COMPILE_CACHE", "1") != "0":
    try:
        import jax as _jax
        _jax.config.update(
            "jax_compilation_cache_dir",
            _os.environ.get(
                "LIGHTGBM_TPU_COMPILE_CACHE_DIR",
                _os.path.join(_os.path.expanduser("~"), ".cache",
                              "lightgbm_tpu", "jax_cache")))
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # pragma: no cover — cache is best-effort
        pass

from .basic import Booster, Dataset  # noqa: F401
from .engine import cv, train  # noqa: F401
from . import log  # noqa: F401

try:
    from .sklearn import (LGBMClassifier, LGBMModel,  # noqa: F401
                          LGBMRanker, LGBMRegressor)
except ImportError:  # sklearn not installed
    pass

__version__ = "0.1.0"
