"""lightgbm_tpu — a TPU-native gradient boosting framework.

A from-scratch JAX/XLA re-design of the LightGBM feature set: leaf-wise
histogram GBDT with data/feature/voting-parallel distributed training over
`jax.sharding.Mesh` collectives, objectives/metrics for regression, binary,
multiclass and lambdarank, DART/GOSS/RF variants, and a LightGBM-compatible
Python API and text model format.
"""
import os as _os

# Persistent XLA compilation cache (VERDICT r2 item 6: a first 2M-row
# train paid ~2 min of compile before iteration 1 on every process).
# Re-runs of any already-seen (shape, config) signature now load from
# disk. Opt out with LIGHTGBM_TPU_COMPILE_CACHE=0; redirect with
# LIGHTGBM_TPU_COMPILE_CACHE_DIR. jax.config.update is safe pre-backend
# and does not initialize XLA.
if _os.environ.get("LIGHTGBM_TPU_COMPILE_CACHE", "1") != "0":
    try:
        import jax as _jax
        _jax.config.update(
            "jax_compilation_cache_dir",
            _os.environ.get(
                "LIGHTGBM_TPU_COMPILE_CACHE_DIR",
                _os.path.join(_os.path.expanduser("~"), ".cache",
                              "lightgbm_tpu", "jax_cache")))
        _jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        _jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:  # pragma: no cover — cache is best-effort
        pass

# The public names below resolve lazily (PEP 562).  Training-free serving
# replicas import `lightgbm_tpu.export.runtime` with the trainer modules
# (boosting/, learner/, ingest/, parallel/) absent or import-blocked; an
# eager `from .basic import ...` here would drag the whole training stack
# into every child process and defeat the export subsystem's isolation.
_LAZY_ATTRS = {
    "Booster": ("lightgbm_tpu.basic", "Booster"),
    "Dataset": ("lightgbm_tpu.basic", "Dataset"),
    "cv": ("lightgbm_tpu.engine", "cv"),
    "train": ("lightgbm_tpu.engine", "train"),
    "log": ("lightgbm_tpu.log", None),
    "LGBMClassifier": ("lightgbm_tpu.sklearn", "LGBMClassifier"),
    "LGBMModel": ("lightgbm_tpu.sklearn", "LGBMModel"),
    "LGBMRanker": ("lightgbm_tpu.sklearn", "LGBMRanker"),
    "LGBMRegressor": ("lightgbm_tpu.sklearn", "LGBMRegressor"),
}


def __getattr__(name):
    try:
        module_name, attr = _LAZY_ATTRS[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib
    try:
        module = importlib.import_module(module_name)
    except ImportError as exc:
        # sklearn wrappers are optional; surface the same AttributeError a
        # missing eager import used to.
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r} "
            f"(importing {module_name} failed: {exc})") from None
    value = module if attr is None else getattr(module, attr)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY_ATTRS))


__version__ = "0.1.0"
