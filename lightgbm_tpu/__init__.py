"""lightgbm_tpu — a TPU-native gradient boosting framework.

A from-scratch JAX/XLA re-design of the LightGBM feature set: leaf-wise
histogram GBDT with data/feature/voting-parallel distributed training over
`jax.sharding.Mesh` collectives, objectives/metrics for regression, binary,
multiclass and lambdarank, DART/GOSS/RF variants, and a LightGBM-compatible
Python API and text model format.
"""
from .basic import Booster, Dataset  # noqa: F401
from .engine import cv, train  # noqa: F401
from . import log  # noqa: F401

try:
    from .sklearn import (LGBMClassifier, LGBMModel,  # noqa: F401
                          LGBMRanker, LGBMRegressor)
except ImportError:  # sklearn not installed
    pass

__version__ = "0.1.0"
