"""Command-line application: train / predict / convert_model.

Re-implements the reference CLI (`src/main.cpp:4-22`,
`src/application/application.cpp:30-258`): `python -m lightgbm_tpu
config=train.conf [key=value ...]` with the same config-file format
(key=value lines, '#' comments), task dispatch, data/validation loading
(label/weight/query sidecar files), model output and prediction-result
files — so the reference's `examples/*/train.conf` run unmodified.
"""
from __future__ import annotations

import os
import sys
from typing import Dict, List

import numpy as np

from . import log
from .basic import Booster, Dataset
from .config import Config, key_alias_transform
from .engine import train
from .io.parser import (load_data_file, load_query_file, load_weight_file)
from .metrics import default_metric_for_objective


def load_config_file(path: str) -> Dict[str, str]:
    """Reference: Application::LoadParameters config-file branch
    (application.cpp:48-104)."""
    out: Dict[str, str] = {}
    with open(path) as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip().strip('"')
    return out


def parse_cli_params(argv: List[str]) -> Dict[str, str]:
    params: Dict[str, str] = {}
    for tok in argv:
        if "=" not in tok:
            log.warning("Unknown CLI token (ignored): %s", tok)
            continue
        k, v = tok.split("=", 1)
        params[k.strip()] = v.strip()
    # config file params have LOWER priority than CLI (application.cpp:75-90);
    # both sides are alias-canonicalized before merging so "num_trees=3" on
    # the CLI overrides "num_iterations=50" in the file
    params = key_alias_transform(params)
    cfg_path = params.get("config_file")
    if cfg_path:
        file_params = key_alias_transform(load_config_file(cfg_path))
        for k, v in file_params.items():
            params.setdefault(k, v)
    return params


def _check_binary_dataset(path: str):
    """Binary-dataset fast path (reference: CheckCanLoadFromBin,
    dataset_loader.cpp:240-263 — `file` or `file.bin` with the magic
    token loads without re-parsing/re-binning). Recognizes both the v2
    ingest cache and the legacy v1 artifact."""
    from .dataset import _BINARY_MAGIC
    from .ingest import CACHE_MAGIC
    probe = max(len(_BINARY_MAGIC), len(CACHE_MAGIC))
    for cand in (path, path + ".bin"):
        if not os.path.exists(cand):
            continue
        with open(cand, "rb") as fh:
            head = fh.read(probe)
        if head.startswith(_BINARY_MAGIC) or head.startswith(CACHE_MAGIC):
            return cand
    return None


def _cache_fingerprint(data_path: str, cfg: Config):
    """The (source identity, binning params) fingerprint a cache built
    from `data_path` under `cfg` must carry. None when the data file is
    gone (a cache shipped without its source can't be source-verified)."""
    from .ingest import (binning_params_fingerprint_fields,
                         ingest_fingerprint)
    params = binning_params_fingerprint_fields(
        max_bin=cfg.io.max_bin, min_data_in_bin=cfg.io.min_data_in_bin,
        bin_construct_sample_cnt=cfg.io.bin_construct_sample_cnt,
        data_random_seed=cfg.io.data_random_seed,
        use_missing=cfg.io.use_missing,
        zero_as_missing=cfg.io.zero_as_missing,
        enable_bundle=cfg.io.enable_bundle,
        max_conflict_rate=cfg.io.max_conflict_rate,
        sparse_threshold=cfg.io.sparse_threshold)
    params["categorical_column"] = cfg.io.categorical_column
    params["has_header"] = cfg.io.has_header
    if not os.path.exists(data_path):
        return None
    from .ingest import FileSource
    try:
        source = FileSource(
            data_path, has_header=cfg.io.has_header).describe()
    except ValueError:  # libsvm: no streamed identity to pin
        return None
    return ingest_fingerprint(source, params)


def _build_dataset(path: str, params: Dict, cfg: Config,
                   reference: Dataset = None) -> Dataset:
    has_header = cfg.io.has_header
    # multi-process training: this process loads its row partition with
    # globally-consistent distributed bin finding (reference:
    # dataset_loader.cpp:159-217 + 737-817); pre-partitioned files keep
    # all their rows but still sync mappers
    import jax
    nproc = jax.process_count()
    if nproc > 1 and reference is None:
        from .parallel.loader import jax_process_allgather, two_round_load
        rank = jax.process_index()
        log.info("Rank %d/%d loading %s (pre_partition=%s)", rank, nproc,
                 path, cfg.io.is_pre_partition)
        inner = two_round_load(
            path, max_bin=cfg.io.max_bin,
            min_data_in_bin=cfg.io.min_data_in_bin,
            bin_construct_sample_cnt=cfg.io.bin_construct_sample_cnt,
            has_header=has_header, seed=cfg.io.data_random_seed,
            rank=rank, num_machines=nproc, comm=jax_process_allgather,
            shard_rows=not cfg.io.is_pre_partition,
            use_missing=cfg.io.use_missing,
            zero_as_missing=cfg.io.zero_as_missing,
            # EFB grouping is derived from local row samples and could
            # diverge across ranks, which would misalign the stored
            # histogram layout — keep features unbundled under multi-host
            enable_bundle=False,
            max_conflict_rate=cfg.io.max_conflict_rate,
            sparse_threshold=cfg.io.sparse_threshold)
        ds = Dataset._from_inner(inner)
        return _load_sidecars(ds, path, inner.used_row_indices,
                              num_global_rows=inner.num_global_rows)
    bin_path = _check_binary_dataset(path) \
        if cfg.io.enable_load_from_binary_file else None
    ds = None
    if bin_path is not None and reference is None:
        from .dataset import Dataset as InnerDataset
        from .ingest import CacheCorrupt, CacheMismatch
        expected = _cache_fingerprint(path, cfg) \
            if bin_path != path else None
        if expected is None and bin_path != path:
            log.warning("Cannot verify %s against its source (data file "
                        "unreadable); trusting the cache", bin_path)
        log.info("Loading binary dataset from %s (binning params come "
                 "from the cache; enable_load_from_binary_file=false "
                 "re-bins)", bin_path)
        try:
            inner = InnerDataset.load_binary(
                bin_path, expected_fingerprint=expected)
            ds = Dataset._from_inner(inner)
        except CacheMismatch as exc:
            log.fatal(str(exc))
        except CacheCorrupt as exc:
            # the corrupt file is already quarantined (*.corrupt); with a
            # source file present we can re-bin, otherwise there is
            # nothing to rebuild from
            if bin_path == path:
                log.fatal(str(exc))
            log.warning("%s — rebuilding from %s", exc, path)
            bin_path = None
    if ds is not None:
        pass
    elif cfg.io.use_two_round_loading and reference is None:
        from .parallel.loader import two_round_load
        log.info("Two-round loading %s", path)
        inner = two_round_load(
            path, max_bin=cfg.io.max_bin,
            min_data_in_bin=cfg.io.min_data_in_bin,
            bin_construct_sample_cnt=cfg.io.bin_construct_sample_cnt,
            has_header=has_header, seed=cfg.io.data_random_seed,
            use_missing=cfg.io.use_missing,
            zero_as_missing=cfg.io.zero_as_missing,
            enable_bundle=cfg.io.enable_bundle,
            max_conflict_rate=cfg.io.max_conflict_rate,
            sparse_threshold=cfg.io.sparse_threshold)
        ds = Dataset._from_inner(inner)
    else:
        # lazy wrapper: construction streams through the ingest
        # subsystem (chunked two-pass binning — the raw float matrix
        # never materializes; tpu_ingest=false restores the old path)
        ds = Dataset(path, params=dict(params), reference=reference)
    ds = _load_sidecars(ds, path, None)
    if cfg.io.is_save_binary_file and bin_path is None:
        ds.construct()
        fp = _cache_fingerprint(path, cfg)
        ds._inner.save_binary(path + ".bin", fingerprint=fp or "")
    return ds


def _load_sidecars(ds: Dataset, path: str, row_idx,
                   num_global_rows: int = 0) -> Dataset:
    """Attach .weight/.query/.init files. Under multi-process sharding
    `row_idx` holds the global rows this rank owns; sidecar arrays cover
    ALL global rows and are sliced to the local partition (queries are
    already query-atomically assigned by the loader, which set the group
    itself — reference: dataset_loader.cpp:159-217)."""
    weights = load_weight_file(path)
    if weights is not None:
        ds.set_weight(weights if row_idx is None else weights[row_idx])
    inner = getattr(ds, "_inner", None)
    already_grouped = (inner is not None and
                       inner.metadata.query_boundaries is not None)
    if not already_grouped:
        query = load_query_file(path)
        if query is not None:
            ds.set_group(query)
    init_path = path + ".init"
    if os.path.exists(init_path):
        with open(init_path) as fh:
            scores = np.asarray([float(x) for x in fh.read().split()])
        if row_idx is not None:
            # multiclass .init holds n_global*k values, class-major
            # ([k, n] flattened — gbdt.py init_score layout); slice each
            # class's column to the local rows
            n = num_global_rows
            if n and scores.size % n == 0 and scores.size != n:
                scores = scores.reshape(-1, n)[:, row_idx].ravel()
            else:
                scores = scores[row_idx]
        ds.set_init_score(scores)
    return ds


def run_train(params: Dict, cfg: Config) -> None:
    """Reference: Application::InitTrain + Train (application.cpp:190-234)."""
    if not cfg.data:
        log.fatal("No training data specified (data=...)")
    if cfg.io.tpu_telemetry_dir or cfg.io.tpu_telemetry:
        # armed BEFORE the dataset build so the ingest phase (pass 1/2
        # spans, rows/bytes/chunks counters, cache hits) lands in the
        # registry the run log snapshots
        from . import telemetry
        telemetry.enable(True)
        telemetry.install_observer()
    if cfg.network.tpu_collective_timeout_s > 0 \
            or cfg.network.tpu_heartbeat_dir:
        # armed BEFORE the dataset build: distributed bin finding and
        # the pre-partition sample merge are collectives too — a rank
        # that dies while its peers are still LOADING must produce the
        # same clean RC_RANK_FAILURE exit as one that dies mid-training
        # (GBDT.init re-arms with the rank once the backend is up)
        from .parallel import watchdog
        watchdog.configure(
            timeout_s=cfg.network.tpu_collective_timeout_s,
            failure_dir=cfg.network.tpu_heartbeat_dir or None,
            lease_s=cfg.network.tpu_heartbeat_lease_s
            if cfg.network.tpu_heartbeat_dir else None)
    log.info("Loading train data from %s", cfg.data)
    train_set = _build_dataset(cfg.data, params, cfg)
    valid_sets, valid_names = [], []
    for vpath in cfg.valid_data:
        log.info("Loading validation data from %s", vpath)
        valid_sets.append(_build_dataset(vpath, params, cfg, reference=train_set))
        valid_names.append(os.path.basename(vpath))

    if cfg.io.tpu_telemetry_dir:
        # engine.train opens the run log; named here so operators know
        # where the trail will be before the (possibly hours-long) run
        log.info("Telemetry armed: JSONL run log + Prometheus dump under "
                 "%s (scripts/telemetry_report.py renders it)",
                 cfg.io.tpu_telemetry_dir)
    if cfg.io.tpu_checkpoint_dir:
        # engine.train resumes from / writes to this directory; surfaced
        # here so operators see preemption tolerance is armed before the
        # (possibly hours-long) run starts
        log.info("Preemption-tolerant training: full-state checkpoint "
                 "every %d iteration(s) to %s (keep last %d); rerun this "
                 "exact command after a preemption to resume "
                 "bit-identically", max(1, cfg.io.tpu_checkpoint_interval),
                 cfg.io.tpu_checkpoint_dir, cfg.io.tpu_checkpoint_keep)

    callbacks = []
    if cfg.io.snapshot_freq > 0:
        # periodic model snapshots (reference: GBDT::Train, gbdt.cpp:349-353
        # — writes <output_model>.snapshot_iter_N every snapshot_freq iters)
        freq, out = cfg.io.snapshot_freq, cfg.io.output_model

        def _snapshot(env):
            it = env.iteration + 1
            if it % freq == 0:
                path = f"{out}.snapshot_iter_{it}"
                env.model.save_model(path)
                log.info("Saved snapshot to %s", path)

        callbacks.append(_snapshot)

    booster = train(params, train_set,
                    num_boost_round=cfg.boosting.num_iterations,
                    valid_sets=valid_sets, valid_names=valid_names,
                    verbose_eval=cfg.metric.metric_freq
                    if cfg.io.verbosity >= 1 else False,
                    early_stopping_rounds=cfg.boosting.early_stopping_round
                    or None,
                    callbacks=callbacks)
    booster.save_model(cfg.io.output_model)
    log.info("Finished training, model saved to %s", cfg.io.output_model)


def run_predict(params: Dict, cfg: Config) -> None:
    """Reference: Application::Predict (application.cpp:236-249) +
    Predictor (predictor.hpp:24-205)."""
    if not cfg.io.input_model:
        log.fatal("No input model specified (input_model=...)")
    if not cfg.data:
        log.fatal("No prediction data specified (data=...)")
    if cfg.io.tpu_telemetry_dir:
        # serving-side observability: collect predict/serving counters +
        # latency histograms for this invocation and dump them as
        # Prometheus text exposition on exit
        from . import telemetry
        telemetry.enable(True)
        telemetry.install_observer()
    data, _ = load_data_file(cfg.data, has_header=cfg.io.has_header)
    from . import export as export_mod
    from .serving import Predictor
    predictor_kwargs = dict(
        num_iteration=cfg.io.num_iteration_predict,
        raw_score=cfg.io.is_predict_raw_score,
        pred_leaf=cfg.io.is_predict_leaf_index,
        pred_contrib=cfg.io.is_predict_contrib,
        pred_early_stop=cfg.io.pred_early_stop,
        pred_early_stop_freq=cfg.io.pred_early_stop_freq,
        pred_early_stop_margin=cfg.io.pred_early_stop_margin)
    if export_mod.is_artifact(cfg.io.input_model):
        # input_model is an exported-forest artifact: serve it without
        # constructing a Booster (no training stack, no tree re-parse,
        # zero Python retracing of the forest)
        model = export_mod.load_artifact(cfg.io.input_model,
                                         params=dict(params))
        predictor = Predictor(model, **predictor_kwargs)
    else:
        booster = Booster(model_file=cfg.io.input_model,
                          params=dict(params))
        # serving front end (lightgbm_tpu/serving): device-resident
        # compiled forest + bucketed, pipelined dispatch; its counters
        # are the CLI's throughput report
        predictor = booster.serving_predictor(**predictor_kwargs)
    if cfg.io.tpu_predict_quantize != "none":
        # the accuracy-delta gate aborts (loudly) on the first batch if
        # the quantized stacks drift past the tolerance
        log.info("Serving with quantized forest layout '%s' (accuracy "
                 "gate tolerance %g)", cfg.io.tpu_predict_quantize,
                 cfg.io.tpu_predict_quantize_tol)
    if cfg.io.tpu_serving_deadline_ms > 0 or cfg.io.tpu_serving_max_queue \
            or cfg.io.tpu_serving_max_inflight:
        log.info("Serving admission armed: deadline=%gms max_queue=%d "
                 "max_inflight=%d (refusals raise structured retriable "
                 "errors)", cfg.io.tpu_serving_deadline_ms,
                 cfg.io.tpu_serving_max_queue,
                 cfg.io.tpu_serving_max_inflight)
    result = predictor.predict(data)
    stats = predictor.stats()
    if stats.get("mean_latency_ms"):
        secs = stats["mean_latency_ms"] / 1e3
        log.info("Predicted %d rows in %.3fs (%.0f rows/s, %d forest "
                 "restack(s))", data.shape[0], secs,
                 data.shape[0] / max(secs, 1e-9),
                 stats.get("stack_restacks", 0))
    adm = stats.get("admission", {})
    if adm.get("rejected"):
        log.warning("Admission rejected %d request(s) this run: %s",
                    adm["rejected"],
                    {k: v for k, v in adm.items()
                     if k in ("shed", "deadline_expired", "queue_full",
                              "inflight_full", "compile_wait") and v})
    result = np.atleast_1d(np.asarray(result))
    with open(cfg.io.output_result, "w") as fh:
        # vectorized formatting (np.char.mod runs the %-format in C): a
        # per-row python f-string loop cost ~1s at 500k rows
        if result.ndim <= 1:
            fh.write("\n".join(np.char.mod("%.9g", result)) + "\n")
        else:
            rows = np.char.mod("%.9g", result)
            fh.write("\n".join("\t".join(r) for r in rows) + "\n")
    log.info("Finished prediction, results saved to %s", cfg.io.output_result)
    if cfg.io.tpu_telemetry_dir and cfg.io.tpu_telemetry_prometheus:
        from .telemetry import export
        rank = 0
        try:
            import jax
            rank = jax.process_index()
        except Exception:
            pass
        path = os.path.join(cfg.io.tpu_telemetry_dir,
                            f"metrics_predict_r{rank}.prom")
        os.makedirs(cfg.io.tpu_telemetry_dir, exist_ok=True)
        export.write_prometheus(path, extra_labels={"rank": str(rank),
                                                    "task": "predict"})
        log.info("Serving metrics written to %s", path)


def run_export(params: Dict, cfg: Config) -> None:
    """task=export: pack input_model into a forest artifact under
    tpu_export_dir (optionally gating quantized layouts on `data` as
    the calibration batch)."""
    if not cfg.io.input_model:
        log.fatal("No input model specified (input_model=...)")
    from . import export as export_mod
    booster = Booster(model_file=cfg.io.input_model, params=dict(params))
    calibration = None
    if cfg.data:
        calibration, _ = load_data_file(cfg.data,
                                        has_header=cfg.io.has_header)
    path = os.path.join(cfg.io.tpu_export_dir or ".",
                        export_mod.DEFAULT_NAME)
    info = booster.export_forest(
        path, num_iteration=cfg.io.num_iteration_predict,
        calibration=calibration)
    log.info("Export finished: %s (%d bytes, %d sections)",
             info["path"], info["bytes"], info["sections"])


def run_convert_model(params: Dict, cfg: Config) -> None:
    """Reference: kConvertModel task (application.cpp:251-258 +
    gbdt_model.cpp ModelToIfElse) — emits standalone C++ if-else code."""
    from .io.convert_model import model_to_if_else
    booster = Booster(model_file=cfg.io.input_model, params=dict(params))
    code = model_to_if_else(booster._inner)
    with open(cfg.io.convert_model, "w") as fh:
        fh.write(code)
    log.info("Model converted to C++ code at %s", cfg.io.convert_model)


def main(argv: List[str] = None) -> int:
    argv = argv if argv is not None else sys.argv[1:]
    params = parse_cli_params(argv)
    cfg = Config.from_params(params)
    if cfg.io.verbosity < 0:
        log.set_level(log.WARNING)
    elif cfg.io.verbosity >= 2:
        log.set_level(log.DEBUG)

    # multi-host mesh (reference: Application::InitTrain's Network::Init,
    # application.cpp:190-224 — here jax.distributed over the machine list)
    if cfg.network.num_machines > 1:
        from .parallel.multihost import init_distributed
        up = init_distributed(
            num_processes=cfg.network.num_machines,
            machine_list_filename=cfg.network.machine_list_filename,
            local_listen_port=cfg.network.local_listen_port)
        if not up:
            log.fatal(
                "num_machines=%d but no distributed runtime could be "
                "initialized: set LGBM_TPU_COORDINATOR / "
                "LGBM_TPU_NUM_MACHINES / LGBM_TPU_RANK or provide "
                "machine_list_file" % cfg.network.num_machines)

    task = cfg.task
    if task == "train":
        run_train(params, cfg)
    elif task in ("predict", "prediction", "test"):
        run_predict(params, cfg)
    elif task == "export":
        run_export(params, cfg)
    elif task == "convert_model":
        run_convert_model(params, cfg)
    else:
        log.fatal("Unknown task: %s" % task)
    return 0


if __name__ == "__main__":
    sys.exit(main())
