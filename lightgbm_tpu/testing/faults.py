"""Fault-injection harness for preemption/IO/distributed robustness.

TPU pods are preemptible: a long boosting run can die at any iteration,
an NFS checkpoint write can fail halfway, a collective can be severed by
a restarting worker — or simply WEDGE when a peer stops answering. This
module simulates those failures deterministically so the
checkpoint/resume subsystem (`lightgbm_tpu/checkpoint.py`) and the
collective watchdogs (`lightgbm_tpu/parallel/watchdog.py`) can be tested
at tier-1 speed:

- `active(kill_at_iteration=23)` — raise `SimulatedPreemption` when the
  training loop reaches iteration 23 (after 23 completed iterations),
  mimicking a SIGKILL between iterations.
- `active(fail={"checkpoint.write": 2})` — the next 2 calls that pass
  through the named injection site raise `InjectedFault`; sites are
  instrumented in checkpoint IO (`checkpoint.write`, `checkpoint.rename`,
  `checkpoint.read`), the boosting backend (`backend.grow`), the
  distributed learners (`collective.call`) and the multihost collectives
  (`multihost.allgather`, `multihost.agree`).
- distributed fault shapes (ISSUE 11): `kill_rank(rank, at_iteration)`
  preempts only the named rank; `wedge_collective(site, seconds)` makes
  the next call through `site` BLOCK for `seconds` (the "peer stopped
  answering" shape the collective watchdog must convert into a clean
  `RC_RANK_FAILURE` exit); `fail_next_collective(n)` fails the next n
  grower dispatches.
- serving fault shapes (ISSUE 12): `slow_predict(seconds)` makes EVERY
  predict dispatch take `seconds` (a saturated/slow device — the shape
  the admission layer's shedding must degrade gracefully under, so
  unlike `wedge` it does not pop after one call); `fail_predict(n)`
  fails the next n predict dispatches (trips the registry's per-model
  circuit breaker); `compile_storm(seconds)` wedges every cold-bucket
  FIRST compile (the single-flight leader) for `seconds`, so tests can
  prove N concurrent cold requests pay exactly one compile.
- storage fault shapes (ISSUE 18): `enospc(n)` / `eio_write(n)` make
  the next n calls through a durable-IO site raise a REAL `OSError`
  (`InjectedIOError`) carrying the errno, so `lightgbm_tpu/durable.py`
  handles injected and genuine disk faults through the same
  except-OSError path; `slow_io(site, seconds)` makes every write
  through the site stall (NFS brown-out); `torn_write(site)` makes the
  next publish write HALF its payload to the tmp file and die before
  the rename — the shape atomic publication must make invisible.
  Injection sites live inside the durable layer (`<site>.write`,
  `<site>.rename`, plus the torn probe between body and fsync).
- `corrupt_file` / `truncate_file` — bit-flip or cut a checkpoint on
  disk to exercise the checksum-validation / fall-back-to-previous path.

Child processes arm plans through the `LGBM_TPU_FAULT_PLAN` env var — a
JSON object with the same fields as `FaultPlan`
(`{"kill_at_iteration": 5, "wedge": {"collective.call": 30},
"fail": {...}, "kill_rank": [1, 5],
"io_fail": {"checkpoint.write": ["ENOSPC", 2]}, "torn": {...}}`) —
which is how the supervisors (`scripts/elastic_smoke.py`,
`scripts/storage_chaos_smoke.py`) inject failures into ranks they
launch.

Instrumented code calls `inject(site)` which is a no-op (one `is None`
check) unless a plan is active, so production runs pay nothing.
"""
from __future__ import annotations

import contextlib
import errno as _errno
import json
import os
import time
from typing import Dict, List, Optional, Tuple


class InjectedFault(RuntimeError):
    """Raised by an armed injection site (stands in for an IOError /
    severed collective / backend dispatch failure)."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at site '{site}'")
        self.site = site


class InjectedIOError(OSError):
    """An armed storage fault: a real OSError with a real errno, so the
    durable-IO retry loop (`lightgbm_tpu/durable.py`) cannot tell an
    injected ENOSPC/EIO from a genuine one — by design."""

    def __init__(self, site: str, errname: str):
        code = getattr(_errno, errname)
        super().__init__(code, f"injected {errname} at site '{site}'")
        self.site = site


class SimulatedPreemption(Exception):
    """Raised to emulate the process being preempted mid-training."""

    def __init__(self, iteration: int):
        super().__init__(f"simulated preemption at iteration {iteration}")
        self.iteration = iteration


class FaultPlan:
    """One active injection schedule (install via `active()` or the
    module-level distributed-shape helpers)."""

    def __init__(self, kill_at_iteration: Optional[int] = None,
                 fail: Optional[Dict[str, int]] = None,
                 wedge: Optional[Dict[str, float]] = None,
                 kill_rank: Optional[Tuple[int, int]] = None,
                 slow: Optional[Dict[str, float]] = None,
                 io_fail: Optional[Dict[str, Tuple[str, int]]] = None,
                 torn: Optional[Dict[str, int]] = None):
        self.kill_at_iteration = kill_at_iteration
        self.fail = dict(fail or {})
        # site -> seconds: the next call through the site sleeps (once)
        self.wedge = {k: float(v) for k, v in (wedge or {}).items()}
        # site -> seconds: EVERY call through the site sleeps (sustained
        # slowness, the overload shape — wedge is for one-shot hangs)
        self.slow = {k: float(v) for k, v in (slow or {}).items()}
        # site -> [errno-name, count]: the next `count` calls through
        # the site raise InjectedIOError with that errno (storage shape)
        self.io_fail = {k: [str(v[0]), int(v[1])]
                        for k, v in (io_fail or {}).items()}
        # site -> count: the next `count` durable publishes through the
        # site write half their payload then die before the rename
        self.torn = {k: int(v) for k, v in (torn or {}).items()}
        # (rank, at_iteration): preempt only that rank
        self.kill_rank = tuple(kill_rank) if kill_rank else None
        self.fired: List[str] = []   # audit log of injected faults


_plan: Optional[FaultPlan] = None
_env_checked = False

FAULT_PLAN_ENV = "LGBM_TPU_FAULT_PLAN"


def _current_rank() -> int:
    # one source of truth for rank discovery (env var, configured rank,
    # live-runtime probe): the collective watchdog's
    from ..parallel.watchdog import current_rank
    return current_rank()


def _load_env_plan() -> None:
    """Install a persistent plan from LGBM_TPU_FAULT_PLAN (checked once,
    on the first inject call with no in-process plan armed)."""
    global _plan, _env_checked
    _env_checked = True
    spec = os.environ.get(FAULT_PLAN_ENV, "")
    if not spec:
        return
    try:
        d = json.loads(spec)
        _plan = FaultPlan(
            kill_at_iteration=d.get("kill_at_iteration"),
            fail=d.get("fail"),
            wedge=d.get("wedge"),
            kill_rank=d.get("kill_rank"),
            slow=d.get("slow"),
            io_fail=d.get("io_fail"),
            torn=d.get("torn"))
    except (ValueError, TypeError) as exc:
        raise ValueError(
            f"Unparseable {FAULT_PLAN_ENV}: {spec!r} ({exc})") from exc


def _active_plan() -> Optional[FaultPlan]:
    """The armed plan, loading LGBM_TPU_FAULT_PLAN on first probe."""
    if _plan is None:
        if _env_checked:
            return None
        _load_env_plan()
    return _plan


def inject(site: str, iteration: Optional[int] = None) -> None:
    """Injection point. Called from instrumented production code; no-op
    unless a plan is active. `iteration` is only consulted by the
    `train.iteration` site (the engine loop's preemption point)."""
    # snapshot: a serving test's main thread may reset() while a
    # batcher thread is mid-sleep inside a slow/wedge injection — the
    # rest of this call must keep operating on the plan it started with
    plan = _active_plan()
    if plan is None:
        return
    if site == "train.iteration" and iteration is not None:
        if (plan.kill_at_iteration is not None
                and iteration >= plan.kill_at_iteration):
            plan.fired.append(f"kill@{iteration}")
            raise SimulatedPreemption(iteration)
        if (plan.kill_rank is not None
                and iteration >= plan.kill_rank[1]
                and _current_rank() == plan.kill_rank[0]):
            plan.fired.append(
                f"kill_rank{plan.kill_rank[0]}@{iteration}")
            raise SimulatedPreemption(iteration)
    secs = plan.wedge.pop(site, None)
    if secs is not None:
        # the wedge shape: the call BLOCKS (peer stopped answering) —
        # one-shot, so a watchdog-less run eventually continues and a
        # watchdog-armed run has exactly one deadline violation to catch
        plan.fired.append(f"wedge@{site}")
        time.sleep(secs)
    secs = plan.slow.get(site)
    if secs is not None:
        # sustained slowness: EVERY call pays it (a saturated device /
        # a long compile) — the overload harness's capacity knob
        plan.fired.append(f"slow@{site}")
        time.sleep(secs)
    remaining = plan.fail.get(site, 0)
    if remaining > 0:
        plan.fail[site] = remaining - 1
        plan.fired.append(site)
        raise InjectedFault(site)
    spec = plan.io_fail.get(site)
    if spec is not None and spec[1] > 0:
        spec[1] -= 1
        plan.fired.append(f"{spec[0].lower()}@{site}")
        raise InjectedIOError(site, spec[0])


def take_torn(site: str) -> bool:
    """Probe consumed by the durable layer between body-write and fsync:
    True means this publish must tear (write half, die pre-rename)."""
    plan = _active_plan()
    if plan is None:
        return False
    n = plan.torn.get(site, 0)
    if n <= 0:
        return False
    plan.torn[site] = n - 1
    plan.fired.append(f"torn@{site}")
    return True


@contextlib.contextmanager
def active(kill_at_iteration: Optional[int] = None,
           fail: Optional[Dict[str, int]] = None,
           wedge: Optional[Dict[str, float]] = None,
           kill_rank: Optional[Tuple[int, int]] = None,
           slow: Optional[Dict[str, float]] = None,
           io_fail: Optional[Dict[str, Tuple[str, int]]] = None,
           torn: Optional[Dict[str, int]] = None):
    """Arm a fault plan for the duration of the with-block."""
    global _plan
    prev = _plan
    _plan = FaultPlan(kill_at_iteration=kill_at_iteration, fail=fail,
                      wedge=wedge, kill_rank=kill_rank, slow=slow,
                      io_fail=io_fail, torn=torn)
    try:
        yield _plan
    finally:
        _plan = prev


def _ensure_plan() -> FaultPlan:
    global _plan
    if _plan is None:
        _plan = FaultPlan()
    return _plan


def kill_rank(rank: int, at_iteration: int) -> FaultPlan:
    """Preempt ONLY the named rank when its training loop reaches
    `at_iteration` (other ranks keep running — and block in their next
    collective, which is what the watchdog exists to catch)."""
    plan = _ensure_plan()
    plan.kill_rank = (int(rank), int(at_iteration))
    return plan


def wedge_collective(site: str, seconds: float) -> FaultPlan:
    """Make the next call through `site` block for `seconds` (e.g.
    "collective.call" for the grower dispatch, "multihost.allgather" /
    "multihost.agree" for the host-level collectives)."""
    plan = _ensure_plan()
    plan.wedge[str(site)] = float(seconds)
    return plan


def fail_next_collective(n: int) -> FaultPlan:
    """Fail the next `n` grower collective dispatches."""
    plan = _ensure_plan()
    plan.fail["collective.call"] = plan.fail.get("collective.call", 0) + int(n)
    return plan


# ---------------------------------------------------------------------------
# serving fault shapes (ISSUE 12)
# ---------------------------------------------------------------------------
def slow_predict(seconds: float) -> FaultPlan:
    """Make EVERY serving predict dispatch take `seconds` — the
    saturated-device shape driving the overload gate (capacity becomes
    a knob: micro_batch rows / `seconds` per dispatch)."""
    plan = _ensure_plan()
    plan.slow["serving.predict"] = float(seconds)
    return plan


def fail_predict(n: int) -> FaultPlan:
    """Fail the next `n` serving predict dispatches (the repeated-
    failure shape the registry's per-model circuit breaker trips on)."""
    plan = _ensure_plan()
    plan.fail["serving.predict"] = plan.fail.get("serving.predict", 0) \
        + int(n)
    return plan


def compile_storm(seconds: float = 0.25) -> FaultPlan:
    """Wedge every cold-bucket FIRST compile (the single-flight leader
    in serving/predictor.py) for `seconds`: N concurrent first requests
    on an unseen shape bucket then demonstrably pay ONE simulated
    trace, while the followers wait under their deadlines or shed."""
    plan = _ensure_plan()
    plan.slow["serving.compile"] = float(seconds)
    return plan


# ---------------------------------------------------------------------------
# storage fault shapes (ISSUE 18) — sites live inside lightgbm_tpu/durable.py
# ---------------------------------------------------------------------------
def enospc(n: int = 1, site: str = "checkpoint.write") -> FaultPlan:
    """The next `n` writes through `site` fail with a real ENOSPC (disk
    full) — the shape the checkpoint manager's oldest-snapshot eviction
    escape hatch exists for."""
    plan = _ensure_plan()
    plan.io_fail[str(site)] = ["ENOSPC", int(n)]
    return plan


def eio_write(n: int = 1, site: str = "checkpoint.write") -> FaultPlan:
    """The next `n` writes through `site` fail with a real EIO (the
    transient-NFS-hiccup shape the retry/backoff policy absorbs)."""
    plan = _ensure_plan()
    plan.io_fail[str(site)] = ["EIO", int(n)]
    return plan


def slow_io(site: str, seconds: float) -> FaultPlan:
    """EVERY write through `site` stalls for `seconds` (storage
    brown-out) — the per-write deadline's reason to exist."""
    plan = _ensure_plan()
    plan.slow[str(site)] = float(seconds)
    return plan


def torn_write(site: str = "checkpoint", n: int = 1) -> FaultPlan:
    """The next `n` durable publishes through `site` write HALF their
    payload to the tmp file and die before the rename. The atomic
    publish must leave no partial target visible."""
    plan = _ensure_plan()
    plan.torn[str(site)] = int(n)
    return plan


def reset() -> None:
    global _plan, _env_checked
    _plan = None
    _env_checked = True  # an explicit reset also disarms the env plan


# ---------------------------------------------------------------------------
# on-disk corruption (no plan needed; mutates files directly)
# ---------------------------------------------------------------------------
def corrupt_file(path: str, offset: Optional[int] = None,
                 nbytes: int = 8) -> None:
    """Flip bits in `nbytes` bytes of the file (default: mid-file, which
    lands in the checkpoint payload and must trip the checksum)."""
    size = os.path.getsize(path)
    if size == 0:
        return
    if offset is None:
        offset = size // 2
    offset = min(offset, size - 1)
    with open(path, "r+b") as fh:
        fh.seek(offset)
        chunk = fh.read(min(nbytes, size - offset))
        fh.seek(offset)
        fh.write(bytes(b ^ 0xFF for b in chunk))


def truncate_file(path: str, frac: float = 0.5) -> None:
    """Cut the file to `frac` of its size (a crash mid-write on a
    filesystem without atomic rename would look like this)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(max(0, int(size * frac)))
