"""Fault-injection harness for preemption/IO robustness testing.

TPU pods are preemptible: a long boosting run can die at any iteration,
an NFS checkpoint write can fail halfway, a collective can be severed by
a restarting worker. This module simulates those failures
deterministically so the checkpoint/resume subsystem
(`lightgbm_tpu/checkpoint.py`) can be tested at tier-1 speed:

- `active(kill_at_iteration=23)` — raise `SimulatedPreemption` when the
  training loop reaches iteration 23 (after 23 completed iterations),
  mimicking a SIGKILL between iterations.
- `active(fail={"checkpoint.write": 2})` — the next 2 calls that pass
  through the named injection site raise `InjectedFault`; sites are
  instrumented in checkpoint IO (`checkpoint.write`, `checkpoint.rename`,
  `checkpoint.read`), the boosting backend (`backend.grow`) and the
  distributed learners (`collective.call`).
- `corrupt_file` / `truncate_file` — bit-flip or cut a checkpoint on
  disk to exercise the checksum-validation / fall-back-to-previous path.

Instrumented code calls `inject(site)` which is a no-op (one `is None`
check) unless a plan is active, so production runs pay nothing.
"""
from __future__ import annotations

import contextlib
import os
from typing import Dict, List, Optional


class InjectedFault(RuntimeError):
    """Raised by an armed injection site (stands in for an IOError /
    severed collective / backend dispatch failure)."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at site '{site}'")
        self.site = site


class SimulatedPreemption(Exception):
    """Raised to emulate the process being preempted mid-training."""

    def __init__(self, iteration: int):
        super().__init__(f"simulated preemption at iteration {iteration}")
        self.iteration = iteration


class FaultPlan:
    """One active injection schedule (install via `active()`)."""

    def __init__(self, kill_at_iteration: Optional[int] = None,
                 fail: Optional[Dict[str, int]] = None):
        self.kill_at_iteration = kill_at_iteration
        self.fail = dict(fail or {})
        self.fired: List[str] = []   # audit log of injected faults


_plan: Optional[FaultPlan] = None


def inject(site: str, iteration: Optional[int] = None) -> None:
    """Injection point. Called from instrumented production code; no-op
    unless a plan is active. `iteration` is only consulted by the
    `train.iteration` site (the engine loop's preemption point)."""
    if _plan is None:
        return
    if (site == "train.iteration"
            and _plan.kill_at_iteration is not None
            and iteration is not None
            and iteration >= _plan.kill_at_iteration):
        _plan.fired.append(f"kill@{iteration}")
        raise SimulatedPreemption(iteration)
    remaining = _plan.fail.get(site, 0)
    if remaining > 0:
        _plan.fail[site] = remaining - 1
        _plan.fired.append(site)
        raise InjectedFault(site)


@contextlib.contextmanager
def active(kill_at_iteration: Optional[int] = None,
           fail: Optional[Dict[str, int]] = None):
    """Arm a fault plan for the duration of the with-block."""
    global _plan
    prev = _plan
    _plan = FaultPlan(kill_at_iteration=kill_at_iteration, fail=fail)
    try:
        yield _plan
    finally:
        _plan = prev


def reset() -> None:
    global _plan
    _plan = None


# ---------------------------------------------------------------------------
# on-disk corruption (no plan needed; mutates files directly)
# ---------------------------------------------------------------------------
def corrupt_file(path: str, offset: Optional[int] = None,
                 nbytes: int = 8) -> None:
    """Flip bits in `nbytes` bytes of the file (default: mid-file, which
    lands in the checkpoint payload and must trip the checksum)."""
    size = os.path.getsize(path)
    if size == 0:
        return
    if offset is None:
        offset = size // 2
    offset = min(offset, size - 1)
    with open(path, "r+b") as fh:
        fh.seek(offset)
        chunk = fh.read(min(nbytes, size - offset))
        fh.seek(offset)
        fh.write(bytes(b ^ 0xFF for b in chunk))


def truncate_file(path: str, frac: float = 0.5) -> None:
    """Cut the file to `frac` of its size (a crash mid-write on a
    filesystem without atomic rename would look like this)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as fh:
        fh.truncate(max(0, int(size * frac)))
