"""Plotting utilities (matplotlib / graphviz).

Mirrors the reference python-package plotting module
(`python-package/lightgbm/plotting.py`): plot_importance, plot_metric,
plot_tree / create_tree_digraph. Matplotlib/graphviz are imported lazily so
the core package has no hard dependency.
"""
from __future__ import annotations

import numpy as np

from .basic import Booster, LightGBMError
from .sklearn import LGBMModel


def _check_not_tuple_of_2_elements(obj, obj_name="obj"):
    if not isinstance(obj, tuple) or len(obj) != 2:
        raise TypeError(f"{obj_name} must be a tuple of 2 elements.")


def plot_importance(booster, ax=None, height=0.2, xlim=None, ylim=None,
                    title="Feature importance", xlabel="Feature importance",
                    ylabel="Features", importance_type="split",
                    max_num_features=None, ignore_zero=True, figsize=None,
                    grid=True, **kwargs):
    """Reference: plotting.py plot_importance."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib for plot_importance")

    if isinstance(booster, LGBMModel):
        booster = booster.booster_
    if not isinstance(booster, Booster):
        raise TypeError("booster must be Booster or LGBMModel")

    importance = booster.feature_importance(importance_type=importance_type)
    feature_names = booster.feature_name()
    tuples = sorted(zip(feature_names, importance), key=lambda x: x[1])
    if ignore_zero:
        tuples = [x for x in tuples if x[1] > 0]
    if max_num_features is not None and max_num_features > 0:
        tuples = tuples[-max_num_features:]
    if not tuples:
        raise ValueError("Booster's feature_importance is empty")
    labels, values = zip(*tuples)

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    ylocs = np.arange(len(values))
    ax.barh(ylocs, values, align="center", height=height, **kwargs)
    for x, y in zip(values, ylocs):
        ax.text(x + 1, y, str(int(x) if float(x).is_integer() else round(x, 2)),
                va="center")
    ax.set_yticks(ylocs)
    ax.set_yticklabels(labels)
    if xlim is not None:
        _check_not_tuple_of_2_elements(xlim, "xlim")
        ax.set_xlim(xlim)
    if ylim is not None:
        _check_not_tuple_of_2_elements(ylim, "ylim")
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def plot_metric(booster, metric=None, dataset_names=None, ax=None, xlim=None,
                ylim=None, title="Metric during training", xlabel="Iterations",
                ylabel="auto", figsize=None, grid=True):
    """Reference: plotting.py plot_metric (takes evals_result dict or
    LGBMModel)."""
    try:
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib for plot_metric")

    if isinstance(booster, LGBMModel):
        eval_results = dict(booster.evals_result_)
    elif isinstance(booster, dict):
        eval_results = dict(booster)
    else:
        raise TypeError("booster must be dict or LGBMModel")
    if not eval_results:
        raise ValueError("eval results cannot be empty")

    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)

    if dataset_names is None:
        dataset_names = iter(eval_results.keys())
    name = None
    for dataset_name in dataset_names:
        metrics = eval_results.get(dataset_name)
        if not metrics:
            continue
        if metric is None:
            name, results = list(metrics.items())[0]
        else:
            name, results = metric, metrics[metric]
        ax.plot(range(len(results)), results, label=dataset_name)
    ax.legend(loc="best")
    if xlim is not None:
        ax.set_xlim(xlim)
    if ylim is not None:
        ax.set_ylim(ylim)
    if title:
        ax.set_title(title)
    if xlabel:
        ax.set_xlabel(xlabel)
    if ylabel == "auto":
        ylabel = name
    if ylabel:
        ax.set_ylabel(ylabel)
    ax.grid(grid)
    return ax


def create_tree_digraph(booster, tree_index=0, show_info=None, name=None,
                        comment=None, **kwargs):
    """Reference: plotting.py create_tree_digraph (graphviz)."""
    try:
        from graphviz import Digraph
    except ImportError:
        raise ImportError("You must install graphviz for plot_tree")

    if isinstance(booster, LGBMModel):
        booster = booster.booster_
    if not isinstance(booster, Booster):
        raise TypeError("booster must be Booster or LGBMModel")
    if any(getattr(t, "is_linear", False)
           for t in getattr(booster._inner, "models", ())):
        raise LightGBMError(
            "create_tree_digraph/plot_tree do not render linear_tree "
            "models: leaf nodes carry per-leaf regressions, not the "
            "single constant the digraph labels show; dump_model() "
            "exposes the leaf_features/leaf_coeff tables instead")
    model = booster.dump_model()
    tree_infos = model["tree_info"]
    if tree_index >= len(tree_infos):
        raise IndexError("tree_index is out of range.")
    tree_info = tree_infos[tree_index]
    show_info = show_info or []

    graph = Digraph(name=name, comment=comment, **kwargs)

    def add(node, parent=None, decision=None):
        if "split_index" in node:
            nid = f"split{node['split_index']}"
            label = f"split_feature_index: {node['split_feature']}"
            label += f"\\nthreshold: {node['threshold']:.6g}"
            for info in show_info:
                if info in node:
                    label += f"\\n{info}: {node[info]:.6g}" \
                        if isinstance(node[info], float) else f"\\n{info}: {node[info]}"
            graph.node(nid, label=label)
            add(node["left_child"], nid, node.get("decision_type", "<="))
            add(node["right_child"], nid, ">")
        else:
            nid = f"leaf{node['leaf_index']}"
            label = f"leaf_index: {node['leaf_index']}"
            label += f"\\nleaf_value: {node['leaf_value']:.6g}"
            if "leaf_count" in show_info and "leaf_count" in node:
                label += f"\\nleaf_count: {node['leaf_count']}"
            graph.node(nid, label=label)
        if parent is not None:
            graph.edge(parent, nid, decision)

    add(tree_info["tree_structure"])
    return graph


def plot_tree(booster, ax=None, tree_index=0, figsize=None, show_info=None,
              **kwargs):
    """Reference: plotting.py plot_tree (renders the digraph into an axes)."""
    try:
        import matplotlib.image as image
        import matplotlib.pyplot as plt
    except ImportError:
        raise ImportError("You must install matplotlib for plot_tree")
    if ax is None:
        _, ax = plt.subplots(1, 1, figsize=figsize)
    graph = create_tree_digraph(booster=booster, tree_index=tree_index,
                                show_info=show_info, **kwargs)
    import io
    s = io.BytesIO(graph.pipe(format="png"))
    img = image.imread(s)
    ax.imshow(img)
    ax.axis("off")
    return ax
