"""Per-phase timers + profiler hooks (SURVEY.md §5.1).

TPU-native equivalent of the reference's compile-time TIMETAG accumulators
(`gbdt.cpp:22-30,53-62`, `serial_tree_learner.cpp:10-17,29-37`): named
wall-clock accumulators around the boosting phases, dumped on demand or at
interpreter exit when `LGBM_TPU_TIMETAG=1`. Device work is asynchronous
under JAX, so phases that must attribute device time call `block()` on
their outputs (only when timing is enabled — timers are zero-cost when
off).

For kernel-level traces, `trace_to(dir)` wraps `jax.profiler.trace`; the
resulting xplane protobuf is the artifact to inspect with
`jax.profiler.ProfileData` (see scripts/profile_train.py).
"""
from __future__ import annotations

import atexit
import contextlib
import os
import time
from collections import defaultdict
from typing import Dict, Tuple

from . import log

_totals: Dict[str, float] = defaultdict(float)
_counts: Dict[str, int] = defaultdict(int)
_counters: Dict[str, float] = defaultdict(float)
_counter_events: Dict[str, int] = defaultdict(int)
_enabled = os.environ.get("LGBM_TPU_TIMETAG", "") not in ("", "0", "false")


def enable(on: bool = True) -> None:
    global _enabled
    _enabled = on


def enabled() -> bool:
    return _enabled


def reset() -> None:
    _totals.clear()
    _counts.clear()
    _counters.clear()
    _counter_events.clear()


def totals() -> Dict[str, Tuple[float, int]]:
    return {k: (_totals[k], _counts[k]) for k in _totals}


def counter(name: str, value: float) -> None:
    """Accumulate a numeric event counter (e.g. histogram passes, rows
    contracted) next to the phase timers; dumped with them. Zero-cost
    when tracing is disabled."""
    if _enabled:
        _counters[name] += float(value)
        _counter_events[name] += 1


def counters() -> Dict[str, Tuple[float, int]]:
    return {k: (_counters[k], _counter_events[k]) for k in _counters}


@contextlib.contextmanager
def phase(name: str, block=None):
    """Accumulate wall time under `name`. `block` is an optional array (or
    pytree) to block_until_ready on before stopping the clock, so async
    device work is charged to the right phase."""
    if not _enabled:
        yield
        return
    t0 = time.perf_counter()
    try:
        yield
    finally:
        if block is not None:
            import jax
            jax.block_until_ready(block)
        _totals[name] += time.perf_counter() - t0
        _counts[name] += 1


def block(x):
    """Block on device values inside an open phase (when enabled)."""
    if _enabled and x is not None:
        import jax
        jax.block_until_ready(x)
    return x


def dump() -> None:
    """Log accumulated phase times (reference: the TIMETAG destructor
    printout, gbdt.cpp:53-62)."""
    if not _totals and not _counters:
        return
    if _totals:
        log.info("=== phase timers ===")
        for name in sorted(_totals, key=_totals.get, reverse=True):
            log.info("%-28s %8.3f s  x%d", name, _totals[name],
                     _counts[name])
    if _counters:
        log.info("=== counters ===")
        for name in sorted(_counters, key=_counters.get, reverse=True):
            log.info("%-28s %12.0f  x%d", name, _counters[name],
                     _counter_events[name])


@contextlib.contextmanager
def trace_to(trace_dir: str):
    """jax.profiler trace wrapper; writes an xplane.pb artifact."""
    import jax
    with jax.profiler.trace(trace_dir):
        yield


@atexit.register
def _dump_at_exit() -> None:
    if _enabled:
        dump()
