"""Back-compat shim over `lightgbm_tpu.telemetry` (the old flat timers).

This module used to hold the TIMETAG-style global accumulators
(reference `gbdt.cpp:53-62`); the real implementation now lives in
`lightgbm_tpu/telemetry/` (labeled registry, run log, compile observer,
Prometheus export). Every historical entry point keeps its exact
signature and semantics:

- `phase(name, block=...)` — span-scoped wall timer (block_until_ready
  on `block` before the clock stops)
- `counter(name, value)` / `counters()` — accumulate / read
  `{name: (total, events)}`
- `totals()` — `{phase: (seconds, count)}`
- `enable/enabled/reset/dump/block` — as before; `LGBM_TPU_TIMETAG=1`
  still enables at import and dumps at exit
- `trace_to(dir)` — jax.profiler xplane trace wrapper

New code should import `lightgbm_tpu.telemetry` directly.
"""
from __future__ import annotations

import atexit
import contextlib
from typing import Dict, Tuple

from . import telemetry as _t

enable = _t.enable
enabled = _t.enabled
reset = _t.reset
block = _t.block
dump = _t.dump


def totals() -> Dict[str, Tuple[float, int]]:
    return {name: (acc.total, acc.count)
            for name, acc in _t.registry().phases.items()}


def counter(name: str, value: float) -> None:
    """Accumulate a numeric event counter; zero-cost when disabled."""
    _t.counter_add(name, value)


def counters() -> Dict[str, Tuple[float, int]]:
    out: Dict[str, Tuple[float, int]] = {}
    for c in _t.registry().counters.values():
        if not c.labels:
            out[c.name] = (c.value, c.events)
    return out


def phase(name: str, block=None):
    """Accumulate wall time under `name` (telemetry.span)."""
    return _t.span(name, block=block)


@contextlib.contextmanager
def trace_to(trace_dir: str):
    """jax.profiler trace wrapper; writes an xplane.pb artifact."""
    import jax
    with jax.profiler.trace(trace_dir):
        yield


@atexit.register
def _dump_at_exit() -> None:
    if _t.enabled():
        _t.dump()
