"""Training callbacks.

Mirrors the reference python-package callback protocol
(`python-package/lightgbm/callback.py`): callbacks receive a CallbackEnv
namedtuple before/after each iteration; `EarlyStopException` unwinds the
training loop (engine.py:216-218 in the reference).
"""
from __future__ import annotations

import collections
from typing import Callable, List

CallbackEnv = collections.namedtuple(
    "CallbackEnv",
    ["model", "params", "iteration", "begin_iteration", "end_iteration",
     "evaluation_result_list"])


class EarlyStopException(Exception):
    def __init__(self, best_iteration: int, best_score):
        super().__init__()
        self.best_iteration = best_iteration
        self.best_score = best_score


def print_evaluation(period: int = 1, show_stdv: bool = True) -> Callable:
    """Reference: callback.py print_evaluation."""
    def _callback(env: CallbackEnv) -> None:
        if period > 0 and env.evaluation_result_list \
                and (env.iteration + 1) % period == 0:
            result = "\t".join(
                [_format_eval_result(x, show_stdv) for x in env.evaluation_result_list])
            from . import log
            log.info("[%d]\t%s", env.iteration + 1, result)
    _callback.order = 10
    return _callback


def _format_eval_result(value, show_stdv: bool = True) -> str:
    if len(value) == 4:
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    if len(value) == 5:
        if show_stdv:
            return f"{value[0]}'s {value[1]}: {value[2]:g} + {value[4]:g}"
        return f"{value[0]}'s {value[1]}: {value[2]:g}"
    raise ValueError("Wrong metric value")


def record_evaluation(eval_result: dict) -> Callable:
    """Reference: callback.py record_evaluation."""
    if not isinstance(eval_result, dict):
        raise TypeError("eval_result should be a dict")
    eval_result.clear()

    def _init(env: CallbackEnv) -> None:
        for data_name, eval_name, _, _ in env.evaluation_result_list:
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])

    def _callback(env: CallbackEnv) -> None:
        if not eval_result:
            _init(env)
        for data_name, eval_name, result, _ in env.evaluation_result_list:
            eval_result.setdefault(data_name, collections.OrderedDict())
            eval_result[data_name].setdefault(eval_name, [])
            eval_result[data_name][eval_name].append(result)

    # checkpoint/resume protocol: a resumed run must re-enter the loop
    # with the recorded history of the interrupted one, or the user's
    # evals_result dict restarts mid-run with a hole in every series
    def _state() -> dict:
        return {d: {m: list(v) for m, v in metrics.items()}
                for d, metrics in eval_result.items()}

    def _restore(state: dict) -> None:
        eval_result.clear()
        for d, metrics in state.items():
            eval_result[d] = collections.OrderedDict(
                (m, [float(x) for x in v]) for m, v in metrics.items())

    _callback.order = 20
    _callback.checkpoint_key = "record_evaluation"
    _callback.checkpoint_state = _state
    _callback.restore_state = _restore
    return _callback


def reset_parameter(**kwargs) -> Callable:
    """Reference: callback.py reset_parameter (supports learning_rate
    schedules as list or callable)."""
    def _callback(env: CallbackEnv) -> None:
        new_params = {}
        for key, value in kwargs.items():
            if isinstance(value, list):
                if len(value) != env.end_iteration - env.begin_iteration:
                    raise ValueError(f"Length of list {key} has to equal num_boost_round")
                new_params[key] = value[env.iteration - env.begin_iteration]
            elif callable(value):
                new_params[key] = value(env.iteration - env.begin_iteration)
        if new_params:
            if "learning_rate" in new_params:
                env.model._inner.shrinkage_rate = float(new_params["learning_rate"])
            env.params.update(new_params)
    _callback.before_iteration = True
    _callback.order = 10
    return _callback


def checkpoint(save_fn: Callable, interval: int = 1) -> Callable:
    """Periodic full-state snapshot (preemption tolerance). `save_fn(env)`
    builds and writes the snapshot — `lightgbm_tpu.engine` wires it to a
    `checkpoint.CheckpointManager`. Runs AFTER early_stopping (order 40)
    so a restored snapshot carries the patience state of its own
    iteration, not the previous one.

    A failed WRITE is logged and training continues: losing one snapshot
    (the previous one still restores) is strictly better than killing a
    long run over a transient filesystem error. Only IO-shaped errors
    are swallowed — anything else (e.g. the non-finite-gradient guard
    firing inside the state capture's pipeline flush) is a training
    error and must propagate."""
    def _callback(env: CallbackEnv) -> None:
        if interval > 0 and (env.iteration + 1) % interval == 0:
            from . import tracing
            from .checkpoint import CheckpointError
            from .testing.faults import InjectedFault
            try:
                # timed as its own phase: snapshots drain the async tree
                # pipeline, so their cost must not masquerade as tree/grow
                with tracing.phase("checkpoint/save"):
                    save_fn(env)
            except (OSError, CheckpointError, InjectedFault) as exc:
                # deliberately NOT RuntimeError: jax backend failures
                # (XlaRuntimeError) during the state capture's pipeline
                # flush mean the training state itself is suspect
                from . import log
                log.warning("Checkpoint write failed at iteration %d "
                            "(%s: %s); continuing without it",
                            env.iteration + 1, type(exc).__name__, exc)
    _callback.order = 40
    return _callback


def early_stopping(stopping_rounds: int, verbose: bool = True) -> Callable:
    """Reference: callback.py early_stopping."""
    best_score: List[float] = []
    best_iter: List[int] = []
    best_score_list: List = []
    cmp_op: List[Callable] = []
    higher_better: List[bool] = []

    def _init(env: CallbackEnv) -> None:
        if not env.evaluation_result_list:
            raise ValueError("For early stopping, at least one dataset and "
                             "eval metric is required for evaluation")
        for _ in env.evaluation_result_list:
            best_iter.append(0)
            best_score_list.append(None)

        for _, _, _, is_higher_better in env.evaluation_result_list:
            higher_better.append(bool(is_higher_better))
            if is_higher_better:
                best_score.append(float("-inf"))
                cmp_op.append(lambda a, b: a > b)
            else:
                best_score.append(float("inf"))
                cmp_op.append(lambda a, b: a < b)

    def _callback(env: CallbackEnv) -> None:
        if not cmp_op:
            _init(env)
        for i, (data_name, eval_name, score, _) in enumerate(env.evaluation_result_list):
            if best_score_list[i] is None or cmp_op[i](score, best_score[i]):
                best_score[i] = score
                best_iter[i] = env.iteration
                best_score_list[i] = env.evaluation_result_list
            elif env.iteration - best_iter[i] >= stopping_rounds:
                if verbose:
                    from . import log
                    log.info("Early stopping, best iteration is: [%d]",
                             best_iter[i] + 1)
                raise EarlyStopException(best_iter[i], best_score_list[i])

    # checkpoint/resume protocol: without the best-score history a
    # resumed run would reset its patience counter and stop late (or,
    # with a restarted best_score baseline, stop on the wrong iteration)
    def _state() -> dict:
        return {
            "best_score": list(best_score),
            "best_iter": [int(x) for x in best_iter],
            "best_score_list": [
                None if lst is None else [[d, m, float(v), bool(b)]
                                          for d, m, v, b in lst]
                for lst in best_score_list],
            "higher_better": list(higher_better),
        }

    def _restore(state: dict) -> None:
        best_score[:] = [float(x) for x in state["best_score"]]
        best_iter[:] = [int(x) for x in state["best_iter"]]
        best_score_list[:] = [
            None if lst is None else [(d, m, float(v), bool(b))
                                      for d, m, v, b in lst]
            for lst in state["best_score_list"]]
        higher_better[:] = [bool(x) for x in state["higher_better"]]
        cmp_op[:] = [(lambda a, b: a > b) if hb else (lambda a, b: a < b)
                     for hb in higher_better]

    _callback.order = 30
    _callback.checkpoint_key = "early_stopping"
    _callback.checkpoint_state = _state
    _callback.restore_state = _restore
    return _callback
