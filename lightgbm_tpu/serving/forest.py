"""Device-resident compiled forest cache + shape-bucketed dispatch plan.

The reference builds its prediction closures once per booster
(`Predictor::Predictor`, predictor.hpp:24-78: the `predict_fun_`
lambdas capture the iterated-over trees) and GBDT inference
accelerators keep the packed forest resident across requests
(arXiv:2011.02022). The TPU analogue: stacking/padding/transferring
the host `Tree` objects into a `MatmulForest`/`DeviceTree` is O(forest)
host work and an H2D transfer of the whole ensemble — paying it per
`predict` call makes steady-state serving host-bound. `CompiledForest`
caches every stacked layout keyed by `(layout, trees-used, model
version)`; the monotonically increasing model version is bumped by the
owning `GBDT` on EVERY ensemble mutation (tree append, rollback,
continued training, checkpoint restore, model load, DART
re-normalization), so a stale stack is structurally impossible: old
versions can never be looked up again.

Quantized layouts (`tpu_predict_quantize={f16,int8}`) are additional
cache entries keyed by the quantize mode, so the f32 stack and its
quantized siblings coexist per model version — the accuracy gate
compares them on a calibration batch and the registry budgets them
together. `f16` keeps the MatmulForest/DeviceTree algorithm with f16
leaf values (+ bf16 path/category tables); `int8` is the fixed-point
bin-code layout (`ops/predict.QuantForest`). Split decisions stay
bit-exact in both; only the leaf-value storage is lossy, and
`gate_delta` records the measured worst-case raw-score delta so
`boosting/gbdt.py` can refuse a layout exceeding
`tpu_predict_quantize_tol` instead of silently serving it.

Shape buckets: `jax` compiles one program per input shape. Serving
traffic has arbitrary batch sizes, so the row axis is padded up a
power-of-two ladder (`bucket_rows`) — arbitrary sizes then hit a
handful of compiled programs instead of retracing per shape. Every
prediction kernel in ops/predict.py is row-independent (per-row
gathers / per-row matmul contractions; the traversal while_loops only
extend their trip count), so padded rows change nothing for the real
rows: predictions stay bit-identical and the padding is sliced off
after the fetch.
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

# stacked layouts kept per model version: one per distinct
# (num_iteration cap, layout kind) seen — enough for a serving process
# that predicts at a couple of caps without letting an iteration sweep
# (e.g. a learning-curve plot) pin every prefix of the forest on device
_MAX_ENTRIES = 8

# default power-of-two ladder floor: a single row pads to 16, which
# costs nothing on a 128-lane machine and keeps the ladder short
DEFAULT_BUCKET_MIN = 16

QUANTIZE_MODES = ("none", "f16", "int8")


def bucket_rows(n: int, bucket_min: int = DEFAULT_BUCKET_MIN,
                cap: int = 1 << 19) -> int:
    """Smallest ladder size >= n: power-of-two steps from bucket_min up
    to cap (chunking splits anything larger). bucket_min <= 0 disables
    bucketing (every size compiles its own program — the seed
    behavior)."""
    if bucket_min <= 0 or n >= cap:
        return min(n, cap) if n > 0 else n
    b = max(1, int(bucket_min))
    while b < n:
        b <<= 1
    return min(b, cap)


def bucket_ladder(bucket_min: int, cap: int) -> List[int]:
    """All bucket sizes warmup() should compile, smallest first. The
    top entry rounds cap UP to the next ladder step — real requests
    dispatch through bucket_rows, which only ever produces power-of-two
    multiples of bucket_min, so a raw non-power-of-two cap would warm a
    program no request ever uses."""
    if bucket_min <= 0:
        return []
    out = []
    b = max(1, int(bucket_min))
    while b < cap:
        out.append(b)
        b <<= 1
    out.append(b)
    return out


def pad_rows(arr: np.ndarray, size: int) -> np.ndarray:
    """Zero-pad the row axis to `size` (no-op when already there)."""
    if arr.shape[0] >= size:
        return arr
    pad = np.zeros((size - arr.shape[0],) + arr.shape[1:], arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def _tree_bytes(value) -> int:
    """Device bytes held by a cache entry (stacked NamedTuples, lists,
    tuples — anything jax.tree can walk)."""
    import jax
    total = 0
    for leaf in jax.tree.leaves(value):
        nbytes = getattr(leaf, "nbytes", None)
        if nbytes is not None:
            total += int(nbytes)
    return total


class CompiledForest:
    """Per-booster cache of device-resident stacked forests.

    Owned by `GBDT`; every ensemble mutation calls `invalidate()`,
    which bumps the model version and drops all entries. Lookups key on
    the CURRENT version, so even an entry that somehow survived a clear
    could never be returned for a newer model. `enabled=False` (the
    `tpu_predict_cache=false` escape hatch) makes every lookup rebuild,
    reproducing the per-call-restack seed behavior for A/B timing.

    `evict_entries()` drops the cached stacks WITHOUT bumping the model
    version — the registry's device-memory budget reclaims idle models'
    stacks this way; the next predict restacks from the host trees and
    versioned lookups stay correct throughout."""

    def __init__(self):
        self._version = 0
        self._cache: "OrderedDict[Tuple, Any]" = OrderedDict()
        self._entry_bytes: Dict[Tuple, int] = {}
        # accuracy-gate ledger: (layout key) -> measured max raw-score
        # delta vs the f32 stack on the calibration batch
        self._gate_delta: Dict[Tuple, float] = {}
        self.enabled = True
        # the Predictor serves concurrent requests (micro-batcher thread
        # + caller threads); the lock covers lookup AND build so two
        # simultaneous misses cannot stack/transfer the forest twice
        # (which would break the one-restack-per-version invariant)
        self._lock = threading.RLock()
        self.stats: Dict[str, int] = {
            "restacks": 0, "hits": 0, "invalidations": 0, "evictions": 0,
            "bytes": 0}

    @property
    def version(self) -> int:
        return self._version

    def invalidate(self) -> None:
        with self._lock:
            self._version += 1
            if self._cache:
                self.stats["invalidations"] += 1
            self._drop_all()

    def evict_entries(self) -> int:
        """Drop every cached stack (registry memory reclaim; the model
        version is NOT bumped). Returns the bytes freed."""
        with self._lock:
            freed = self.stats["bytes"]
            if self._cache:
                self.stats["evictions"] += 1
            self._drop_all()
            return freed

    def _drop_all(self) -> None:
        self._cache.clear()
        self._entry_bytes.clear()
        self._gate_delta.clear()
        self.stats["bytes"] = 0

    def device_bytes(self) -> int:
        """Current device memory held by cached stacks."""
        with self._lock:
            return self.stats["bytes"]

    def _get(self, key: Tuple, build: Callable[[], Any]) -> Any:
        from .. import tracing
        with self._lock:
            key = key + (self._version,)
            if self.enabled:
                hit = self._cache.get(key)
                if hit is not None:
                    self._cache.move_to_end(key)
                    self.stats["hits"] += 1
                    tracing.counter("predict/stack_cache_hit", 1)
                    # serving/* mirror: the hit-rate series the export
                    # surfaces next to the latency histogram
                    tracing.counter("serving/stack_cache_hit", 1)
                    return hit
            value = build()
            self.stats["restacks"] += 1
            tracing.counter("predict/restack", 1)
            tracing.counter("serving/restack", 1)
            if self.enabled:
                self._cache[key] = value
                self._entry_bytes[key] = _tree_bytes(value)
                self.stats["bytes"] += self._entry_bytes[key]
                while len(self._cache) > _MAX_ENTRIES:
                    old_key, _ = self._cache.popitem(last=False)
                    self.stats["bytes"] -= self._entry_bytes.pop(old_key, 0)
            return value

    # ------------------------------------------------------------------
    # accuracy-gate ledger (boosting/gbdt.py runs the comparison; the
    # ledger lives here so it drops with the entries it describes)
    def gate_delta(self, key: Tuple) -> Optional[float]:
        with self._lock:
            return self._gate_delta.get(key + (self._version,))

    def record_gate(self, key: Tuple, delta: float) -> None:
        with self._lock:
            self._gate_delta[key + (self._version,)] = float(delta)

    # ------------------------------------------------------------------
    # stacked layouts (each build counts as ONE restack regardless of
    # class count — the unit the invalidation tests probe)
    def value_stacks(self, models, k: int, total: int,
                     quantize: str = "none"):
        """Per-class stacks for raw-score prediction.

        quantize="none": [(MatmulForest|None, DeviceTree|None)] — the
        layout choice of GBDT._predict_raw_matrix (gather-free MXU path
        when the path tensor fits, walk otherwise), bit-identical.
        quantize="f16": same structure with f16 leaf values and bf16
        path/category tables. quantize="int8": [QuantForest] fixed-point
        layout (raises ops.predict.QuantRefused when the forest cannot
        be coded). Distinct cache keys, so all three coexist."""
        if quantize == "int8":
            def build_q():
                import jax.numpy as jnp

                from ..ops.predict import stack_trees_quant, stack_trees_raw
                stacks = []
                for cls in range(k):
                    class_trees = [models[i] for i in range(cls, total, k)]
                    qf = stack_trees_quant(class_trees) \
                        if class_trees else None
                    st = None
                    if class_trees and qf is None:
                        # over the path/cat budgets: walk layout with
                        # f16 leaves (same quantized-leaf contract)
                        st = stack_trees_raw(class_trees)
                        st = st._replace(
                            leaf_value=st.leaf_value.astype(jnp.float16))
                    stacks.append((qf, st))
                return stacks
            return self._get(("value", total, k, "int8"), build_q)

        def build():
            from ..ops.predict import stack_trees_matmul, stack_trees_raw
            stacks = []
            for cls in range(k):
                class_trees = [models[i] for i in range(cls, total, k)]
                mf = stack_trees_matmul(class_trees) if class_trees else None
                st = stack_trees_raw(class_trees) \
                    if class_trees and mf is None else None
                stacks.append((mf, st))
            if quantize == "f16":
                return [_stacks_to_f16(mf, st) for mf, st in stacks]
            return stacks
        return self._get(("value", total, k, quantize), build)

    def leaf_stacks(self, models, total: int):
        """(MatmulForest|None, DeviceTree|None) over ALL trees for
        pred_leaf — the same cap/layout choice as the value path, so
        both routes share one stacking implementation. Always f32:
        leaf indices are exact by contract, quantize never routes
        here."""
        def build():
            from ..ops.predict import stack_trees_matmul, stack_trees_raw
            mf = stack_trees_matmul(models[:total])
            st = stack_trees_raw(models[:total]) if mf is None else None
            return (mf, st)
        return self._get(("leaf", total), build)

    def early_stop_stacks(self, models, k: int, t_iters: int):
        """[K, T, ...] DeviceTree for margin-based prediction early stop
        (ops/predict.predict_forest_raw_early_stop)."""
        def build():
            import jax
            import jax.numpy as jnp
            from ..ops.predict import stack_trees_raw
            stacked = stack_trees_raw(models[:t_iters * k])
            return jax.tree.map(
                lambda a: jnp.swapaxes(
                    a.reshape((t_iters, k) + a.shape[1:]), 0, 1), stacked)
        return self._get(("early_stop", t_iters, k), build)


class SingleFlightExpired(Exception):
    """A follower's bounded wait for the leader's build ran out (the
    caller converts this into its deadline/shed rejection)."""


class SingleFlight:
    """Cold-start-storm protection: N concurrent first requests on an
    unseen key (a shape bucket about to pay its first trace) run
    exactly ONE build — the leader proceeds and everyone else waits for
    its program, bounded by their own deadlines.

    Without this, a freshly restarted replica taking a traffic burst
    compiles the same 29-81s wide-shape program once PER CONCURRENT
    REQUEST (jit caches the result, but the storm of identical traces
    races in before the first one lands). `begin(key)` returns True for
    exactly one caller per unseen key; followers block until the leader
    `finish()`es (success marks the key done forever) or their timeout
    expires (`SingleFlightExpired` — shed under the deadline instead of
    queueing on a compile). A FAILED leader wakes the followers and the
    next one through becomes the new leader, so one poisoned build
    cannot wedge the key."""

    def __init__(self):
        self._lock = threading.Lock()
        self._done: set = set()
        self._leading: Dict[Any, threading.Event] = {}
        self.counts: Dict[str, int] = {"leads": 0, "waits": 0,
                                       "expired": 0}

    def seen(self, key) -> bool:
        with self._lock:
            return key in self._done

    def mark(self, key) -> None:
        """Record a key as already-built (warmup marks its whole
        ladder so warmed traffic never enters the flight path)."""
        with self._lock:
            self._done.add(key)

    def begin(self, key, timeout: Optional[float] = None) -> bool:
        """True = caller is the leader and MUST call finish(). False =
        a leader already built the key (possibly after a wait)."""
        from .. import tracing
        deadline = None if timeout is None \
            else time.monotonic() + max(0.0, timeout)
        while True:
            with self._lock:
                if key in self._done:
                    return False
                ev = self._leading.get(key)
                if ev is None:
                    self._leading[key] = threading.Event()
                    self.counts["leads"] += 1
                    tracing.counter("serving/single_flight_leads", 1)
                    return True
                self.counts["waits"] += 1
            tracing.counter("serving/single_flight_waits", 1)
            remaining = None if deadline is None \
                else deadline - time.monotonic()
            if remaining is not None and remaining <= 0:
                with self._lock:
                    self.counts["expired"] += 1
                tracing.counter("serving/single_flight_expired", 1)
                raise SingleFlightExpired(key)
            if not ev.wait(timeout=remaining):
                with self._lock:
                    self.counts["expired"] += 1
                tracing.counter("serving/single_flight_expired", 1)
                raise SingleFlightExpired(key)
            # woken: either the leader succeeded (key in done -> return
            # False) or it failed (loop; first caller back in becomes
            # the new leader)

    def finish(self, key, ok: bool) -> None:
        with self._lock:
            if ok:
                self._done.add(key)
            ev = self._leading.pop(key, None)
        if ev is not None:
            ev.set()


_COMPILE_CACHE_ARMED: Optional[str] = None
_COMPILE_CACHE_LOCK = threading.Lock()


def enable_compile_cache(path: str) -> bool:
    """Point JAX's persistent compilation cache at `path`
    (`tpu_compile_cache_dir`): every program the shape-bucket ladder
    compiles is written to disk, and a RESTARTED replica's warmup()
    loads the same ladder back instead of re-tracing it — the
    29-81s wide-shape cold start becomes a file read. Thresholds are
    dropped to zero so even small bucket programs persist (the default
    1s floor would skip exactly the small-batch programs a serving
    replica warms first). Idempotent per path; returns False when the
    cache could not be armed (best-effort, serving proceeds without
    it)."""
    global _COMPILE_CACHE_ARMED
    path = os.path.abspath(path)
    with _COMPILE_CACHE_LOCK:
        if _COMPILE_CACHE_ARMED == path:
            return True
        if _COMPILE_CACHE_ARMED is not None:
            # the cache is PROCESS-GLOBAL (one jax config): two
            # resident models naming different dirs cannot each get
            # their own — the flip is honored but loudly, because the
            # earlier model's future compiles now persist to the new
            # path and its restarted replicas will find a cold cache
            from .. import log
            log.warning(
                "tpu_compile_cache_dir is process-global: re-pointing "
                "the persistent compile cache from %s to %s (programs "
                "compiled from now on land in the new dir)",
                _COMPILE_CACHE_ARMED, path)
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", path)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
            try:
                # a cache already initialized at another dir (the
                # package-level default) must be re-pointed, not ignored
                from jax._src import compilation_cache as _cc
                _cc.reset_cache()
            except Exception:  # pragma: no cover — jax-version-specific
                pass
        except Exception as exc:  # pragma: no cover — cache best-effort
            from .. import log
            log.warning("tpu_compile_cache_dir=%s could not be armed: %s",
                        path, exc)
            return False
        _COMPILE_CACHE_ARMED = path
    return True


def _stacks_to_f16(mf, st):
    """The f16 quantized layout: identical algorithm, leaf values
    stored f16 (upcast to f32 inside the kernels before accumulation)
    and the big ±1/0 tensors stored bf16 (exact — they hold only
    -1/0/+1). Split thresholds stay f32: decisions remain bit-exact,
    only leaf storage is lossy. When no NUMERIC node carries a missing
    type, `missing` is nulled out so the eval kernel
    (ops/predict.predict_forest_f16) skips the NaN-mask selection
    einsum and missing-resolution chain outright — categorical nodes
    resolve NaN through the block expansion regardless.

    linear_tree forests refuse (QuantRefused, surfaced by the gbdt
    accuracy-gate wrapper as a named LightGBMError): coefficient tables
    have no designed f16 storage contract yet, and silently truncating
    slopes would break the train/serve agreement."""
    import jax.numpy as jnp
    from ..ops.predict import QuantRefused
    if any(x is not None and x.leaf_coeff is not None
           and x.leaf_coeff.shape[-1] > 0 for x in (mf, st)):
        raise QuantRefused(
            "linear_tree leaf coefficients have no f16 layout; "
            "predict linear forests with tpu_predict_quantize=none (f32)")
    if mf is not None:
        numeric_missing = np.asarray(mf.missing)[~np.asarray(mf.is_cat)]
        clean = not numeric_missing.any()
        mf = mf._replace(
            leaf_value=mf.leaf_value.astype(jnp.float16),
            path=mf.path.astype(jnp.bfloat16),
            cat_table=mf.cat_table.astype(jnp.bfloat16),
            missing=None if clean else mf.missing)
    if st is not None:
        st = st._replace(leaf_value=st.leaf_value.astype(jnp.float16))
    return (mf, st)
