"""Device-resident compiled forest cache + shape-bucketed dispatch plan.

The reference builds its prediction closures once per booster
(`Predictor::Predictor`, predictor.hpp:24-78: the `predict_fun_`
lambdas capture the iterated-over trees) and GBDT inference
accelerators keep the packed forest resident across requests
(arXiv:2011.02022). The TPU analogue: stacking/padding/transferring
the host `Tree` objects into a `MatmulForest`/`DeviceTree` is O(forest)
host work and an H2D transfer of the whole ensemble — paying it per
`predict` call makes steady-state serving host-bound. `CompiledForest`
caches every stacked layout keyed by `(layout, trees-used, model
version)`; the monotonically increasing model version is bumped by the
owning `GBDT` on EVERY ensemble mutation (tree append, rollback,
continued training, checkpoint restore, model load, DART
re-normalization), so a stale stack is structurally impossible: old
versions can never be looked up again.

Shape buckets: `jax` compiles one program per input shape. Serving
traffic has arbitrary batch sizes, so the row axis is padded up a
power-of-two ladder (`bucket_rows`) — arbitrary sizes then hit a
handful of compiled programs instead of retracing per shape. Every
prediction kernel in ops/predict.py is row-independent (per-row
gathers / per-row matmul contractions; the traversal while_loops only
extend their trip count), so padded rows change nothing for the real
rows: predictions stay bit-identical and the padding is sliced off
after the fetch.
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

# stacked layouts kept per model version: one per distinct
# (num_iteration cap, layout kind) seen — enough for a serving process
# that predicts at a couple of caps without letting an iteration sweep
# (e.g. a learning-curve plot) pin every prefix of the forest on device
_MAX_ENTRIES = 8

# default power-of-two ladder floor: a single row pads to 16, which
# costs nothing on a 128-lane machine and keeps the ladder short
DEFAULT_BUCKET_MIN = 16


def bucket_rows(n: int, bucket_min: int = DEFAULT_BUCKET_MIN,
                cap: int = 1 << 19) -> int:
    """Smallest ladder size >= n: power-of-two steps from bucket_min up
    to cap (chunking splits anything larger). bucket_min <= 0 disables
    bucketing (every size compiles its own program — the seed
    behavior)."""
    if bucket_min <= 0 or n >= cap:
        return min(n, cap) if n > 0 else n
    b = max(1, int(bucket_min))
    while b < n:
        b <<= 1
    return min(b, cap)


def bucket_ladder(bucket_min: int, cap: int) -> List[int]:
    """All bucket sizes warmup() should compile, smallest first. The
    top entry rounds cap UP to the next ladder step — real requests
    dispatch through bucket_rows, which only ever produces power-of-two
    multiples of bucket_min, so a raw non-power-of-two cap would warm a
    program no request ever uses."""
    if bucket_min <= 0:
        return []
    out = []
    b = max(1, int(bucket_min))
    while b < cap:
        out.append(b)
        b <<= 1
    out.append(b)
    return out


def pad_rows(arr: np.ndarray, size: int) -> np.ndarray:
    """Zero-pad the row axis to `size` (no-op when already there)."""
    if arr.shape[0] >= size:
        return arr
    pad = np.zeros((size - arr.shape[0],) + arr.shape[1:], arr.dtype)
    return np.concatenate([arr, pad], axis=0)


class CompiledForest:
    """Per-booster cache of device-resident stacked forests.

    Owned by `GBDT`; every ensemble mutation calls `invalidate()`,
    which bumps the model version and drops all entries. Lookups key on
    the CURRENT version, so even an entry that somehow survived a clear
    could never be returned for a newer model. `enabled=False` (the
    `tpu_predict_cache=false` escape hatch) makes every lookup rebuild,
    reproducing the per-call-restack seed behavior for A/B timing."""

    def __init__(self):
        self._version = 0
        self._cache: "OrderedDict[Tuple, Any]" = OrderedDict()
        self.enabled = True
        # the Predictor serves concurrent requests (micro-batcher thread
        # + caller threads); the lock covers lookup AND build so two
        # simultaneous misses cannot stack/transfer the forest twice
        # (which would break the one-restack-per-version invariant)
        self._lock = threading.RLock()
        self.stats: Dict[str, int] = {
            "restacks": 0, "hits": 0, "invalidations": 0}

    @property
    def version(self) -> int:
        return self._version

    def invalidate(self) -> None:
        with self._lock:
            self._version += 1
            if self._cache:
                self.stats["invalidations"] += 1
            self._cache.clear()

    def _get(self, key: Tuple, build: Callable[[], Any]) -> Any:
        from .. import tracing
        with self._lock:
            key = key + (self._version,)
            if self.enabled:
                hit = self._cache.get(key)
                if hit is not None:
                    self._cache.move_to_end(key)
                    self.stats["hits"] += 1
                    tracing.counter("predict/stack_cache_hit", 1)
                    # serving/* mirror: the hit-rate series the export
                    # surfaces next to the latency histogram
                    tracing.counter("serving/stack_cache_hit", 1)
                    return hit
            value = build()
            self.stats["restacks"] += 1
            tracing.counter("predict/restack", 1)
            tracing.counter("serving/restack", 1)
            if self.enabled:
                self._cache[key] = value
                while len(self._cache) > _MAX_ENTRIES:
                    self._cache.popitem(last=False)
            return value

    # ------------------------------------------------------------------
    # stacked layouts (each build counts as ONE restack regardless of
    # class count — the unit the invalidation tests probe)
    def value_stacks(self, models, k: int, total: int):
        """Per-class [(MatmulForest|None, DeviceTree|None)] for raw-score
        prediction (the layout choice of GBDT._predict_raw_matrix:
        gather-free MXU path when the path tensor fits, walk
        otherwise)."""
        def build():
            from ..ops.predict import stack_trees_matmul, stack_trees_raw
            stacks = []
            for cls in range(k):
                class_trees = [models[i] for i in range(cls, total, k)]
                mf = stack_trees_matmul(class_trees) if class_trees else None
                st = stack_trees_raw(class_trees) \
                    if class_trees and mf is None else None
                stacks.append((mf, st))
            return stacks
        return self._get(("value", total, k), build)

    def leaf_stacks(self, models, total: int):
        """(MatmulForest|None, DeviceTree|None) over ALL trees for
        pred_leaf — the same cap/layout choice as the value path, so
        both routes share one stacking implementation."""
        def build():
            from ..ops.predict import stack_trees_matmul, stack_trees_raw
            mf = stack_trees_matmul(models[:total])
            st = stack_trees_raw(models[:total]) if mf is None else None
            return (mf, st)
        return self._get(("leaf", total), build)

    def early_stop_stacks(self, models, k: int, t_iters: int):
        """[K, T, ...] DeviceTree for margin-based prediction early stop
        (ops/predict.predict_forest_raw_early_stop)."""
        def build():
            import jax
            import jax.numpy as jnp
            from ..ops.predict import stack_trees_raw
            stacked = stack_trees_raw(models[:t_iters * k])
            return jax.tree.map(
                lambda a: jnp.swapaxes(
                    a.reshape((t_iters, k) + a.shape[1:]), 0, 1), stacked)
        return self._get(("early_stop", t_iters, k), build)
