"""Admission control for the serving tier: bounded queues, deadlines,
EWMA-based load shedding, per-model rate limits, and circuit breakers.

PR 10's open-loop bench shows the failure mode this module exists to
prevent: past saturation an unbounded `submit()` queue accepts every
request and answers all of them LATE — p99 grows without bound, callers
retry, and the retry storm compounds the overload. A resilient tier
degrades instead of collapsing: it answers the requests it can answer
on time and refuses the rest IMMEDIATELY with a structured, retriable
error, so callers back off against a clear signal instead of timing out
against a silent queue.

Four cooperating pieces (reference points: the shed/deadline discipline
of production RPC stacks, ported onto PR 11's robustness idiom of
structured failure evidence):

- `ServingOverload` / `DeadlineExceeded` — the rejection contract.
  Every refused request gets one of these, with a machine-readable
  `reason`, `retriable=True`, and a `retry_after_s` hint. Shedding
  changes *whether* a request is answered, never *what* is answered —
  admitted requests stay bit-identical to an unloaded serve.
- `AdmissionController` — per-predictor queue-depth / in-flight caps
  plus the EWMA shed policy: it tracks the exponentially-weighted
  queue wait and starts refusing new work when the estimated wait
  already exceeds the request's deadline (the request would expire in
  the queue; rejecting it now costs nothing and tells the caller the
  truth `deadline_ms` earlier).
- `TokenBucket` — per-model QPS isolation for the registry: one hot
  model exhausts its OWN budget and sheds, instead of queueing into
  the shared device and starving every other resident model.
- `CircuitBreaker` — per-model failure isolation: repeated predict
  failures trip the breaker open (requests are refused without
  touching the model), and after a backoff window it half-opens for a
  single probe — success closes it, failure re-opens with exponential
  backoff. Overload rejections are NOT failures and never trip it.

All counters live on the objects themselves (stats() must work with
global telemetry off) and are mirrored into `serving/*` registry
counters so the Prometheus export carries them with cross-rank
aggregation, PR 7 style. The first shed also lands a structured
`serving_overload` run-log event through `telemetry.active_recorder()`
— the serving-side mirror of PR 11's `rank_failure` evidence idiom.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional

from .. import log, telemetry, tracing


class ServingOverload(log.LightGBMError):
    """A request refused by admission control. Always retriable: the
    refusal is about the server's CURRENT load, not about the request.

    `reason` is machine-readable: "queue_full", "inflight_full",
    "shed" (EWMA queue wait already exceeds the deadline),
    "rate_limited" (per-model token bucket), "breaker_open",
    "shutdown" (predictor closing; retry against the current entry /
    another replica), "compile_wait" (cold-bucket single-flight wait
    exceeded the deadline)."""

    retriable = True

    def __init__(self, message: str, reason: str = "overload",
                 retry_after_s: Optional[float] = None,
                 model: Optional[str] = None):
        super().__init__(message)
        self.reason = str(reason)
        self.retry_after_s = retry_after_s
        self.model = model


class DeadlineExceeded(ServingOverload):
    """The request's deadline expired before device dispatch (it would
    have been answered late; failing it in the queue burns no device
    time and unblocks the caller's retry immediately)."""

    def __init__(self, message: str, deadline_ms: Optional[float] = None,
                 waited_ms: Optional[float] = None):
        super().__init__(message, reason="deadline")
        self.deadline_ms = deadline_ms
        self.waited_ms = waited_ms


class PredictorShutdown(ServingOverload):
    """The predictor is closed (hot swap drained it, or the process is
    shutting down). The message contains "closed" by contract: the
    registry's swap-retry path keys on it to re-route the request to
    the entry that replaced this predictor."""

    def __init__(self, message: str = "Predictor is closed "
                 "(shutting down; retry against the current model)"):
        super().__init__(message, reason="shutdown")


class AdmissionController:
    """Per-predictor admission decisions: caps, deadlines, EWMA shed.

    `max_queue` bounds the micro-batch queue depth, `max_inflight`
    bounds concurrent synchronous predicts, `deadline_s` is the default
    request deadline (0 = none; per-call overrides ride on the request).
    All three are 0-disabled so the pre-existing unbounded behavior is
    exactly reproduced by the defaults."""

    # EWMA weight for queue-wait observations: 0.2 ~ the last ~10
    # dispatches dominate, fast enough to track a saturation edge and
    # smooth enough not to shed on one slow dispatch
    EWMA_ALPHA = 0.2
    # serving_overload run-log events: first rejection + every Nth
    EVENT_EVERY = 1000

    def __init__(self, max_queue: int = 0, max_inflight: int = 0,
                 deadline_s: float = 0.0):
        self.max_queue = max(0, int(max_queue))
        self.max_inflight = max(0, int(max_inflight))
        self.deadline_s = max(0.0, float(deadline_s))
        self._lock = threading.Lock()
        self._ewma_wait_s: Optional[float] = None
        self._ewma_service_s: Optional[float] = None
        self.inflight = 0
        self.counts: Dict[str, int] = {
            "admitted": 0, "shed": 0, "deadline_expired": 0,
            "queue_full": 0, "inflight_full": 0, "compile_wait": 0,
            "rejected": 0}

    # ------------------------------------------------------------------
    def deadline_for(self, deadline_ms: Optional[float]) -> Optional[float]:
        """Absolute deadline (perf_counter clock) for a request arriving
        now, honoring a per-call override (ms; <=0 = no deadline)."""
        d = self.deadline_s if deadline_ms is None \
            else max(0.0, float(deadline_ms)) / 1e3
        return (time.perf_counter() + d) if d > 0 else None

    def observe_wait(self, wait_s: float) -> None:
        """Fold one queue-wait observation (enqueue -> dispatch) into
        the EWMA the shed policy reads."""
        with self._lock:
            prev = self._ewma_wait_s
            self._ewma_wait_s = wait_s if prev is None else \
                (1 - self.EWMA_ALPHA) * prev + self.EWMA_ALPHA * wait_s
        telemetry.gauge_set("serving/queue_wait_ewma_ms",
                            round(self._ewma_wait_s * 1e3, 4))

    def observe_service(self, service_s: float) -> None:
        with self._lock:
            prev = self._ewma_service_s
            self._ewma_service_s = service_s if prev is None else \
                (1 - self.EWMA_ALPHA) * prev + self.EWMA_ALPHA * service_s

    @property
    def ewma_wait_s(self) -> float:
        with self._lock:
            return self._ewma_wait_s or 0.0

    @property
    def ewma_service_s(self) -> float:
        with self._lock:
            return self._ewma_service_s or 0.0

    # ------------------------------------------------------------------
    def _reject(self, kind: str, exc: ServingOverload) -> ServingOverload:
        with self._lock:
            self.counts[kind] += 1
            self.counts["rejected"] += 1
            total = self.counts["rejected"]
        tracing.counter("serving/" + kind, 1)
        tracing.counter("serving/rejected", 1)
        if total == 1 or total % self.EVENT_EVERY == 0:
            self._overload_event(kind, total)
        return exc

    def _overload_event(self, kind: str, total: int) -> None:
        """Structured overload evidence in the run log (PR 11's
        `rank_failure` idiom): an operator reading the trail of a
        degraded replica sees WHEN shedding started and what the
        controller believed about its queue at that moment."""
        rec = telemetry.active_recorder()
        if rec is None:
            return
        with self._lock:
            counts = dict(self.counts)
            ewma = self._ewma_wait_s
        rec.event("serving_overload", reason=kind,
                  rejected_total=int(total),
                  queue_wait_ewma_ms=None if ewma is None
                  else round(ewma * 1e3, 3),
                  deadline_ms=round(self.deadline_s * 1e3, 3),
                  max_queue=self.max_queue,
                  max_inflight=self.max_inflight, counts=counts)

    # ------------------------------------------------------------------
    def admit_queued(self, queue_depth: int,
                     deadline_abs: Optional[float]) -> None:
        """Admission decision for one submit(): queue cap, then the
        EWMA shed policy. Raises ServingOverload on refusal."""
        if self.max_queue > 0 and queue_depth >= self.max_queue:
            raise self._reject("queue_full", ServingOverload(
                "Serving queue is full (%d queued >= tpu_serving_max_queue"
                "=%d); retriable" % (queue_depth, self.max_queue),
                reason="queue_full",
                retry_after_s=max(self.ewma_wait_s, 0.001)))
        if deadline_abs is not None:
            remaining = deadline_abs - time.perf_counter()
            # the EWMA only updates when queued items are POPPED, so it
            # can hold a stale overload-era value after the burst ends;
            # shedding into an EMPTY queue on that stale estimate would
            # refuse traffic forever (nothing enqueued -> nothing
            # popped -> estimate never corrects). An empty queue admits
            # on the wait estimate — the pop-time deadline check still
            # expires anything that genuinely waits too long, and its
            # observe_wait drags the EWMA back down
            est = self.ewma_wait_s if queue_depth > 0 else 0.0
            if remaining <= 0 or est > remaining:
                raise self._reject("shed", ServingOverload(
                    "Shedding: estimated queue wait %.1fms exceeds the "
                    "request deadline (%.1fms remaining); retriable"
                    % (est * 1e3, max(remaining, 0.0) * 1e3),
                    reason="shed", retry_after_s=max(est, 0.001)))
        with self._lock:
            self.counts["admitted"] += 1
        tracing.counter("serving/admitted", 1)

    def admit_sync(self, deadline_abs: Optional[float]) -> None:
        """Admission for one synchronous predict(): in-flight cap plus
        the deadline pre-check (estimated service time vs remaining
        budget — refuse BEFORE burning device time). Check and
        increment happen under ONE lock hold: a check-then-increment
        race would let K concurrent callers exceed the cap by K-1."""
        refusal = None
        with self._lock:
            if self.max_inflight > 0 and self.inflight >= self.max_inflight:
                refusal = ("inflight_full", ServingOverload(
                    "Too many in-flight predicts (%d >= tpu_serving_max_"
                    "inflight=%d); retriable"
                    % (self.inflight, self.max_inflight),
                    reason="inflight_full",
                    retry_after_s=max(self._ewma_service_s or 0.0, 0.001)))
            elif deadline_abs is not None:
                remaining = deadline_abs - time.perf_counter()
                # same staleness guard as the queue path: the service
                # EWMA only corrects when something DISPATCHES, so
                # shedding an idle predictor on a stale estimate (a
                # past slow-device period) would refuse deadline-
                # bearing traffic forever. With work in flight the
                # estimate is live evidence; idle, the request runs
                # immediately and its measurement re-anchors the EWMA
                est = (self._ewma_service_s or 0.0) \
                    if self.inflight > 0 else 0.0
                if remaining <= 0 or est > remaining:
                    refusal = ("shed", ServingOverload(
                        "Shedding: estimated service time %.1fms exceeds "
                        "the request deadline (%.1fms remaining); "
                        "retriable" % (est * 1e3, max(remaining, 0.0) * 1e3),
                        reason="shed", retry_after_s=max(est, 0.001)))
            if refusal is None:
                self.counts["admitted"] += 1
                self.inflight += 1
        if refusal is not None:
            # _reject re-takes the lock, so it must run OUTSIDE it
            raise self._reject(*refusal)
        tracing.counter("serving/admitted", 1)

    def release_sync(self) -> None:
        with self._lock:
            self.inflight -= 1

    def expire(self, waited_s: float,
               deadline_abs: float) -> DeadlineExceeded:
        """Build + count the rejection for a queued request whose
        deadline passed before dispatch."""
        with self._lock:
            self.counts["deadline_expired"] += 1
            self.counts["rejected"] += 1
            total = self.counts["rejected"]
        tracing.counter("serving/deadline_expired", 1)
        tracing.counter("serving/rejected", 1)
        if total == 1 or total % self.EVENT_EVERY == 0:
            self._overload_event("deadline_expired", total)
        over_ms = (time.perf_counter() - deadline_abs) * 1e3
        return DeadlineExceeded(
            "Request deadline expired in the serving queue (waited "
            "%.1fms, %.1fms past deadline); retriable"
            % (waited_s * 1e3, over_ms),
            waited_ms=round(waited_s * 1e3, 3))

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = dict(self.counts)
            out["inflight"] = self.inflight
            if self._ewma_wait_s is not None:
                out["queue_wait_ewma_ms"] = round(self._ewma_wait_s * 1e3, 4)
            if self._ewma_service_s is not None:
                out["service_ewma_ms"] = round(self._ewma_service_s * 1e3, 4)
        out["max_queue"] = self.max_queue
        out["max_inflight"] = self.max_inflight
        out["deadline_ms"] = round(self.deadline_s * 1e3, 3)
        return out


class TokenBucket:
    """Per-model QPS isolation (registry): `rate` tokens/s refill, burst
    of `burst` tokens (default: one second's worth). `take()` is a
    non-blocking admission decision — a drained bucket REFUSES (the
    caller sheds with "rate_limited") instead of queueing, so a hot
    model's backlog can never occupy the shared device at another
    model's expense."""

    def __init__(self, rate: float, burst: Optional[float] = None):
        self.rate = float(rate)
        self.burst = float(burst) if burst is not None \
            else max(1.0, self.rate)
        self._tokens = self.burst
        self._t = time.monotonic()
        self._lock = threading.Lock()

    def take(self, n: float = 1.0) -> bool:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(self.burst,
                               self._tokens + (now - self._t) * self.rate)
            self._t = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def retry_after_s(self, n: float = 1.0) -> float:
        with self._lock:
            missing = max(0.0, n - self._tokens)
        return missing / self.rate if self.rate > 0 else 1.0


class CircuitBreaker:
    """Per-model failure isolation: `failures` CONSECUTIVE predict
    failures trip the breaker open for `reset_s`; it then half-opens
    for a single probe. Probe success closes it (and resets the
    backoff); probe failure re-opens with exponential backoff capped at
    `backoff_cap_s`. Overload rejections never count: shedding a
    request says nothing about the model's health."""

    CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"

    def __init__(self, failures: int = 5, reset_s: float = 5.0,
                 backoff_cap_s: float = 60.0):
        self.failures = max(1, int(failures))
        self.reset_s = max(0.001, float(reset_s))
        self.backoff_cap_s = float(backoff_cap_s)
        self._lock = threading.Lock()
        self._state = self.CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._backoff = self.reset_s
        self._probing = False
        self.counts: Dict[str, int] = {"trips": 0, "rejected": 0,
                                       "recoveries": 0}

    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self) -> None:
        if self._state == self.OPEN and \
                time.monotonic() - self._opened_at >= self._backoff:
            self._state = self.HALF_OPEN
            self._probing = False

    def allow(self) -> bool:
        """True = the request may proceed. In half-open exactly ONE
        caller gets through as the probe; everyone else is refused
        until the probe reports."""
        with self._lock:
            self._maybe_half_open()
            if self._state == self.CLOSED:
                return True
            if self._state == self.HALF_OPEN and not self._probing:
                self._probing = True
                return True
            self.counts["rejected"] += 1
            return False

    def retry_after_s(self) -> float:
        with self._lock:
            if self._state != self.OPEN:
                return 0.0
            return max(0.0, self._backoff
                       - (time.monotonic() - self._opened_at))

    def release_probe(self) -> None:
        """The half-open probe produced NO evidence about the model —
        it was shed upstream, failed client-side, or was cancelled.
        Free the slot so the NEXT request can probe; without this, a
        rejected probe would leave the breaker half-open-and-probing
        forever (no success to close it, no failure to re-open it)."""
        with self._lock:
            if self._state == self.HALF_OPEN:
                self._probing = False

    def record_success(self) -> None:
        with self._lock:
            if self._state == self.OPEN:
                # stale evidence: a pre-trip request (e.g. a queued
                # micro-batch future) that resolved after the trip.
                # Only the half-open PROBE may close an open breaker —
                # otherwise a trickle of old successes would defeat the
                # reset window and keep hammering a failing model
                return
            recovered = self._state == self.HALF_OPEN
            if recovered:
                self.counts["recoveries"] += 1
            self._state = self.CLOSED
            self._consecutive = 0
            self._probing = False
            self._backoff = self.reset_s
        if recovered:
            tracing.counter("serving/breaker_recoveries", 1)

    def record_failure(self) -> None:
        tripped = False
        with self._lock:
            if self._state == self.HALF_OPEN:
                # failed probe: back off harder before the next one
                self._state = self.OPEN
                self._opened_at = time.monotonic()
                self._backoff = min(self._backoff * 2, self.backoff_cap_s)
                self._probing = False
                self.counts["trips"] += 1
                tripped = True
            else:
                self._consecutive += 1
                if self._state == self.CLOSED \
                        and self._consecutive >= self.failures:
                    self._state = self.OPEN
                    self._opened_at = time.monotonic()
                    self._backoff = self.reset_s
                    self.counts["trips"] += 1
                    tripped = True
        if tripped:
            tracing.counter("serving/breaker_trips", 1)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            self._maybe_half_open()
            return {"state": self._state, **self.counts,
                    "consecutive_failures": self._consecutive,
                    "backoff_s": round(self._backoff, 3)}
