"""Multi-model serving registry: many boosters behind one front end.

The heavy-traffic tier the ROADMAP's serving item names: a process
serving millions of users runs MANY models (per-surface, per-cohort,
canaries) on one accelerator, retrains them, and swaps new versions in
without dropping traffic. The reference has no analogue (its Predictor
is built once per booster per process); production GBDT servers grow
exactly this shape around it.

- **Registry**: named models, each behind its own `serving.Predictor`
  (micro-batching, bucket-ladder warmup). Predictors share the compiled
  bucket programs — the jit cache is keyed by stack/input shapes, so
  same-shape models reuse each other's XLA programs and a swap compiles
  nothing new.
- **Device-memory budget**: compiled stacks across all resident models
  are accounted against `tpu_serving_budget_mb` (`CompiledForest`
  tracks per-entry bytes). Past budget, the least-recently-used models'
  stacks are evicted — the HOST trees stay, so an evicted model's next
  request restacks instead of failing, and versioned lookups stay
  correct throughout (eviction never bumps the model version).
- **Atomic hot swap**: `publish(name, booster)` warms the incoming
  predictor over the bucket ladder FIRST, swaps the entry under the
  registry lock, then drains the outgoing predictor's micro-batch
  queue. In-flight `submit()` futures complete on the model they were
  accepted under; requests racing the swap retry onto the new entry —
  zero dropped, zero misrouted (gated by
  scripts/predict_latency_smoke.py and the sustained-load bench).
- **Per-model QPS isolation + circuit breaking** (ISSUE 12,
  serving/admission.py): each published model gets its own token
  bucket (`tpu_serving_model_qps`) — a hot model drains its OWN budget
  and sheds with a structured retriable "rate_limited" error instead
  of queueing into the shared device and starving the other residents
  — and its own circuit breaker: repeated predict failures trip it
  open (requests refused without touching the model), and it
  half-opens after a backoff for a single probe. Overload rejections
  (shed/deadline/queue-full) never count as breaker failures: shedding
  says nothing about the model's health — crucially, a swap-in model
  arriving while the tier is shedding starts with a clean breaker.
- **Telemetry**: resident-model count, stack bytes vs budget, eviction
  and publish counts, and per-model request counters are mirrored into
  `serving/registry_*` gauges on the hot paths themselves, so the
  Prometheus export carries the tier without a stats() caller in the
  loop.
"""
from __future__ import annotations

import threading
import time
from collections import OrderedDict
from concurrent.futures import CancelledError as FutureCancelledError
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

from .. import log, telemetry
from .admission import CircuitBreaker, ServingOverload, TokenBucket
from .predictor import Predictor


class _Entry:
    __slots__ = ("name", "booster", "gbdt", "predictor", "publish_version",
                 "requests", "published_at", "listener", "bucket",
                 "breaker")

    def __init__(self, name, booster, gbdt, predictor, publish_version,
                 bucket=None, breaker=None):
        self.name = name
        self.booster = booster
        self.gbdt = gbdt
        self.predictor = predictor
        self.publish_version = publish_version
        self.requests = 0
        self.published_at = time.time()
        self.listener = None
        # per-model QPS token bucket (None = unlimited) + circuit
        # breaker: fresh per publish — a swap-in model never inherits
        # the outgoing version's failure history
        self.bucket = bucket
        self.breaker = breaker


class ModelRegistry:
    """Named boosters behind one serving front end with a shared
    device-memory budget and atomic hot swap.

    `budget_mb` overrides `tpu_serving_budget_mb` (0 = unlimited).
    `predictor_kwargs` fix the per-model Predictor defaults
    (num_iteration, raw_score, ...). `warmup_rows` caps the publish-time
    bucket-ladder warmup (None = each model's
    `tpu_predict_warmup_rows`; 0 skips warmup)."""

    def __init__(self, budget_mb: Optional[float] = None,
                 warmup_rows: Optional[int] = None,
                 model_qps: Optional[float] = None,
                 breaker_failures: Optional[int] = None,
                 breaker_reset_s: Optional[float] = None,
                 **predictor_kwargs):
        self._lock = threading.RLock()
        self._models: "OrderedDict[str, _Entry]" = OrderedDict()
        self._budget_mb = budget_mb
        self._warmup_rows = warmup_rows
        # None = read each model's config at publish time (the params
        # path); explicit ctor values override for embedding callers
        self._model_qps = model_qps
        self._breaker_failures = breaker_failures
        self._breaker_reset_s = breaker_reset_s
        self._predictor_kwargs = dict(predictor_kwargs)
        self._closed = False
        # budget recomputed on publish/unpublish, read per request: the
        # no-budget default must cost nothing on the submit hot path
        self._budget_cached = 0
        self.stats_counts: Dict[str, int] = {
            "publishes": 0, "swaps": 0, "evictions": 0, "requests": 0,
            "rate_limited": 0, "breaker_rejected": 0}

    # ------------------------------------------------------------------
    @staticmethod
    def _gbdt_of(booster):
        return getattr(booster, "_inner", booster)

    def _compute_budget_bytes(self) -> int:
        if self._budget_mb is not None:
            return int(self._budget_mb * (1 << 20))
        for entry in self._models.values():
            mb = float(entry.gbdt.config.io.tpu_serving_budget_mb)
            if mb > 0:
                return int(mb * (1 << 20))
        return 0

    def _budget_bytes(self) -> int:
        return self._budget_cached

    # ------------------------------------------------------------------
    def publish(self, name: str, booster, warmup_rows: Optional[int] = None
                ) -> Dict[str, Any]:
        """Atomically (re)bind `name` to `booster`. Returns the publish
        record (per-name publish version + the booster's model version).

        The incoming predictor is warmed BEFORE the swap so already-seen
        bucket shapes compile nothing afterwards; the outgoing
        predictor's micro-batch queue is drained after the swap, so
        every accepted future resolves on the model it was accepted
        under. Publishing the same booster again is a cheap no-op swap
        (fresh publish version, same stacks)."""
        record = self._publish_one(name, booster, warmup_rows)
        self._enforce_budget()
        self._mirror_gauges()
        return record

    def publish_from_artifact(self, name: str, path: str,
                              params: Optional[Dict[str, Any]] = None,
                              warmup_rows: Optional[int] = None,
                              expect_fingerprint: Optional[str] = None
                              ) -> Dict[str, Any]:
        """Publish an exported forest artifact (lightgbm_tpu/export)
        under `name` — the horizontal scale-out path: a replica that
        never imports the training stack loads the artifact and gets
        the same warm-then-swap, budget-accounted treatment as a live
        booster. The loaded model's deserialized executables live in a
        real CompiledForest, so the registry's byte budget evicts them
        exactly like compiled stacks, and re-admission reloads from
        `path` instead of retracing."""
        from ..export.loader import load_artifact
        model = load_artifact(path, params=params,
                              expect_fingerprint=expect_fingerprint)
        record = self.publish(name, model, warmup_rows=warmup_rows)
        record["artifact_path"] = model._path
        record["artifact_fingerprint"] = model.fingerprint
        telemetry.counter_add("serving/registry_artifact_publishes", 1)
        recorder = telemetry.active_recorder()
        if recorder is not None:
            recorder.event("artifact_published", name=name,
                           path=model._path,
                           fingerprint=model.fingerprint)
        return record

    def publish_many(self, boosters, warmup_rows: Optional[int] = None
                     ) -> List[Dict[str, Any]]:
        """Publish a batch of models — a finished sweep's fleet
        (engine.train_sweep) — under ONE shared budget/eviction pass.

        `boosters` is a mapping name -> booster or an iterable of
        (name, booster) pairs. Each model gets the same warm-then-swap
        treatment as publish(), but the device-memory budget sweep and
        the gauge mirror run ONCE at the end instead of K times: a
        K-model sweep whose stacks jointly exceed the budget evicts the
        coldest residents in one LRU pass rather than churning evict/
        restack K times mid-batch. Returns the publish records in
        order."""
        items = list(boosters.items()) if hasattr(boosters, "items") \
            else list(boosters)
        records = []
        try:
            for name, booster in items:
                records.append(self._publish_one(name, booster,
                                                 warmup_rows))
        finally:
            # a mid-batch failure must not leave the already-swapped
            # models unaccounted: the budget sweep and gauge mirror run
            # over whatever part of the batch landed
            self._enforce_budget()
            self._mirror_gauges()
        return records

    def _publish_one(self, name: str, booster,
                     warmup_rows: Optional[int] = None) -> Dict[str, Any]:
        """One warm + atomic swap + outgoing drain, WITHOUT the budget/
        gauge pass (the public entries run it after their batch)."""
        with self._lock:
            if self._closed:
                raise log.LightGBMError("ModelRegistry is closed")
        gbdt = self._gbdt_of(booster)
        predictor = Predictor(booster, **self._predictor_kwargs)
        rows = warmup_rows if warmup_rows is not None else self._warmup_rows
        if rows != 0:
            predictor.warmup(max_rows=rows)

        def _on_version(_v, _name=name):
            # publish hook (boosting/gbdt.py): keep budget/visibility
            # gauges fresh when the resident model itself mutates
            # (continued training on a published booster)
            self._mirror_gauges()

        old = None
        with self._lock:
            if self._closed:
                # close() ran while we warmed up: do not resurrect a
                # model into a closed registry
                predictor.close()
                raise log.LightGBMError("ModelRegistry is closed")
            prev = self._models.pop(name, None)
            version = (prev.publish_version + 1) if prev else 1
            io = gbdt.config.io
            qps = self._model_qps if self._model_qps is not None \
                else float(getattr(io, "tpu_serving_model_qps", 0.0))
            fails = self._breaker_failures \
                if self._breaker_failures is not None \
                else int(getattr(io, "tpu_serving_breaker_failures", 0))
            reset = self._breaker_reset_s \
                if self._breaker_reset_s is not None \
                else float(getattr(io, "tpu_serving_breaker_reset_s", 5.0))
            entry = _Entry(
                name, booster, gbdt, predictor, version,
                bucket=TokenBucket(qps) if qps > 0 else None,
                breaker=CircuitBreaker(fails, reset) if fails > 0
                else None)
            entry.listener = _on_version
            # listener registered BEFORE the entry becomes visible: a
            # racing publish/unpublish of the same name can then always
            # pair its remove_version_listener with this add
            gbdt.add_version_listener(_on_version)
            self._models[name] = entry          # most-recently-used end
            self._budget_cached = self._compute_budget_bytes()
            self.stats_counts["publishes"] += 1
            if prev is not None:
                self.stats_counts["swaps"] += 1
                old = prev
        if old is not None:
            if old.listener is not None:
                old.gbdt.remove_version_listener(old.listener)
            # drain outside the lock: new requests already route to the
            # new entry; accepted futures on the old one complete here
            old.predictor.close()
        record = {"name": name, "publish_version": version,
                  "model_version": gbdt.model_version(),
                  "warmed_buckets": list(predictor._warmup_buckets)}
        telemetry.counter_add("serving/registry_publishes", 1)
        log.debug("Registry published %s v%d (model version %d)", name,
                  version, record["model_version"])
        return record

    def unpublish(self, name: str) -> bool:
        """Remove a model (drains its predictor). Returns False when
        absent."""
        with self._lock:
            entry = self._models.pop(name, None)
            self._budget_cached = self._compute_budget_bytes()
        if entry is None:
            return False
        if entry.listener is not None:
            entry.gbdt.remove_version_listener(entry.listener)
        entry.predictor.close()
        self._mirror_gauges()
        return True

    def models(self):
        with self._lock:
            return list(self._models)

    def _entry(self, name: str) -> _Entry:
        with self._lock:
            entry = self._models.get(name)
            if entry is None:
                raise log.LightGBMError(
                    "Model %r is not published (resident: %s)"
                    % (name, list(self._models)))
            self._models.move_to_end(name)      # LRU touch
            entry.requests += 1
            self.stats_counts["requests"] += 1
        telemetry.counter_add("serving/registry_requests", 1,
                              labels={"model": name})
        return entry

    # ------------------------------------------------------------------
    # request front end: thin name-routed wrappers over the entry's
    # Predictor. A request racing a hot swap may catch the outgoing
    # predictor mid-close; it retries against the current entry instead
    # of surfacing the internal state ("zero dropped or misrouted").
    _SWAP_RETRIES = 3

    def _admit_entry(self, entry: _Entry) -> None:
        """Per-model isolation gates: token bucket, then breaker. Both
        raise structured retriable errors — the caller gets a truthful
        "this model, right now" signal, and the other residents keep
        their full budget."""
        if entry.bucket is not None and not entry.bucket.take():
            with self._lock:
                self.stats_counts["rate_limited"] += 1
            telemetry.counter_add("serving/rate_limited", 1,
                                  labels={"model": entry.name})
            raise ServingOverload(
                "Model %r is over its QPS budget (%.1f/s); retriable"
                % (entry.name, entry.bucket.rate), reason="rate_limited",
                retry_after_s=entry.bucket.retry_after_s(),
                model=entry.name)
        if entry.breaker is not None and not entry.breaker.allow():
            with self._lock:
                self.stats_counts["breaker_rejected"] += 1
            telemetry.counter_add("serving/breaker_rejected", 1,
                                  labels={"model": entry.name})
            raise ServingOverload(
                "Model %r circuit breaker is %s after repeated predict "
                "failures; retriable" % (entry.name,
                                         entry.breaker.state()),
                reason="breaker_open",
                retry_after_s=entry.breaker.retry_after_s(),
                model=entry.name)

    @staticmethod
    def _record_outcome(entry: _Entry, exc: Optional[BaseException]) -> None:
        """Feed the model's breaker. Three outcomes:

        - success -> record_success (closes a half-open breaker);
        - server-side predict failure (device error, injected fault) ->
          record_failure — the only breaker evidence;
        - NO evidence: overload rejections (shedding says nothing about
          model health, so shed traffic during a hot swap cannot trip
          the incoming model's breaker), client/config errors
          (LightGBMError: wrong-width rows, bad overrides — the
          CALLER's fault), and cancelled futures (the model was never
          exercised) -> release a half-open probe slot so the next
          request can probe, but never move the state.

        The breaker-state gauge is refreshed on EVERY outcome — a
        recovery must flip the exported series back to closed, not
        leave the dashboard showing a breaker that no longer exists."""
        if entry.breaker is None:
            return
        if exc is None:
            entry.breaker.record_success()
        elif isinstance(exc, (log.LightGBMError, FutureCancelledError)):
            entry.breaker.release_probe()
        else:
            entry.breaker.record_failure()
        telemetry.gauge_set("serving/breaker_state",
                            {"closed": 0, "half_open": 1,
                             "open": 2}[entry.breaker.state()],
                            labels={"model": entry.name})

    def _with_predictor(self, name, fn, sync: bool = True):
        last = None
        for _ in range(self._SWAP_RETRIES):
            entry = self._entry(name)
            self._admit_entry(entry)
            try:
                result = fn(entry.predictor)
            except ServingOverload as exc:
                # no breaker evidence either way, but a half-open probe
                # slot must be released or the breaker wedges probing
                self._record_outcome(entry, exc)
                if exc.reason != "shutdown":
                    raise          # structured rejection: not a swap race
                last = exc         # racing a close(): retry current entry
                continue
            except log.LightGBMError as exc:
                self._record_outcome(entry, exc)
                if "closed" not in str(exc):
                    raise          # client/config error: caller's fault
                last = exc
                continue
            except Exception as exc:
                self._record_outcome(entry, exc)
                raise
            if sync:
                self._record_outcome(entry, None)
            else:
                # submit(): the outcome is async — record it into the
                # breaker of the entry that SERVED the future (a model
                # swapped out mid-flight keeps its own history; a
                # cancelled future records nothing)
                result.add_done_callback(
                    lambda f, e=entry: self._record_outcome(
                        e, FutureCancelledError() if f.cancelled()
                        else f.exception()))
            self._enforce_budget(exclude=name)
            return result
        raise last

    def predict(self, name: str, data, deadline_ms: Optional[float] = None,
                **overrides):
        return self._with_predictor(
            name,
            lambda p: p.predict(data, deadline_ms=deadline_ms, **overrides))

    def predict_one(self, name: str, row,
                    deadline_ms: Optional[float] = None, **overrides):
        return self._with_predictor(
            name,
            lambda p: p.predict_one(row, deadline_ms=deadline_ms,
                                    **overrides))

    def submit(self, name: str, row,
               deadline_ms: Optional[float] = None) -> Future:
        return self._with_predictor(
            name, lambda p: p.submit(row, deadline_ms=deadline_ms),
            sync=False)

    def predictor(self, name: str) -> Predictor:
        """The current Predictor for `name` (hot swaps rebind the name;
        holders of the old object keep a drained-but-valid predictor)."""
        return self._entry(name).predictor

    # ------------------------------------------------------------------
    def _stack_bytes(self) -> Dict[str, int]:
        with self._lock:
            entries = list(self._models.values())
        return {e.name: e.gbdt.compiled_stack_bytes() for e in entries}

    def _enforce_budget(self, exclude: Optional[str] = None) -> int:
        """LRU-evict resident models' compiled stacks until the total
        fits the budget. The most-recently-used model (and `exclude`)
        are never evicted — evicting the model being served would
        restack it on the very next request. Returns evictions made.

        Called per request because stack bytes GROW during requests
        (a restack on a previously evicted or invalidated model); with
        no budget configured (the default) this is one cached-int read,
        and with one it is a small per-model byte sweep — the
        documented cost of enforcement."""
        budget = self._budget_bytes()
        if budget <= 0:
            return 0
        per_model = self._stack_bytes()
        total = sum(per_model.values())
        if total <= budget:
            return 0
        evicted = 0
        with self._lock:
            names = list(self._models)          # LRU -> MRU
        for name in names[:-1] if len(names) > 1 else []:
            if total <= budget:
                break
            if name == exclude:
                continue
            with self._lock:
                entry = self._models.get(name)
            if entry is None:
                continue
            freed = entry.gbdt._compiled_forest.evict_entries()
            if freed <= 0:
                continue
            total -= freed
            evicted += 1
            with self._lock:  # shared counter: racing publishes also bump it
                self.stats_counts["evictions"] += 1
            telemetry.counter_add("serving/registry_evictions", 1,
                                  labels={"model": name})
            log.debug("Registry evicted %s stacks (%d bytes; total %d > "
                      "budget %d)", name, freed, total + freed, budget)
        self._mirror_gauges()
        return evicted

    # ------------------------------------------------------------------
    def _mirror_gauges(self) -> None:
        per_model = self._stack_bytes()
        telemetry.gauge_set("serving/registry_models", len(per_model))
        telemetry.gauge_set("serving/registry_stack_bytes",
                            sum(per_model.values()))
        telemetry.gauge_set("serving/registry_budget_bytes",
                            self._budget_bytes())
        telemetry.gauge_set("serving/registry_evictions_total",
                            self.stats_counts["evictions"])
        with self._lock:
            entries = list(self._models.values())
        for e in entries:
            telemetry.gauge_set("serving/registry_model_requests",
                                e.requests, labels={"model": e.name})
            telemetry.gauge_set("serving/registry_model_version",
                                e.publish_version,
                                labels={"model": e.name})

    def stats(self) -> Dict[str, Any]:
        """Registry-level counters + per-model snapshots (each model's
        Predictor.stats() under "models"). Mirrored into
        serving/registry_* gauges, which the hot paths also keep fresh
        between stats() calls."""
        per_model = self._stack_bytes()
        with self._lock:
            entries = list(self._models.values())
            counts = dict(self.stats_counts)
        out: Dict[str, Any] = dict(counts)
        out["resident_models"] = len(entries)
        out["stack_bytes"] = sum(per_model.values())
        out["budget_bytes"] = self._budget_bytes()
        out["models"] = {}
        for e in entries:
            ps = e.predictor.stats()
            ps["publish_version"] = e.publish_version
            ps["registry_requests"] = e.requests
            ps["stack_bytes"] = per_model.get(e.name, 0)
            if e.breaker is not None:
                ps["breaker"] = e.breaker.stats()
            if e.bucket is not None:
                ps["qps_limit"] = e.bucket.rate
            # artifact-backed entries (publish_from_artifact) carry
            # their provenance so operators can match a replica's
            # resident forest to the artifact it was packed from
            art = getattr(e.gbdt, "_path", None)
            if art is not None and getattr(e.gbdt, "fingerprint", None):
                ps["artifact_path"] = art
                ps["artifact_fingerprint"] = e.gbdt.fingerprint
            out["models"][e.name] = ps
        self._mirror_gauges()
        return out

    def close(self) -> None:
        """Drain and drop every resident model."""
        with self._lock:
            self._closed = True
            entries = list(self._models.values())
            self._models.clear()
        for e in entries:
            if e.listener is not None:
                e.gbdt.remove_version_listener(e.listener)
            e.predictor.close()
