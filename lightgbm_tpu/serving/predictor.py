"""Serving front end: warmup, low-latency small-batch path, optional
micro-batching, admission control, and throughput/latency counters.

The reference serves predictions through a per-model `Predictor`
(predictor.hpp:24-205) whose closures are built once and reused per
request; this is its TPU-shaped counterpart for the ROADMAP's
"heavy traffic from millions of users" north star. The heavy lifting —
device-resident stacked forests, shape-bucketed dispatch, the pipelined
chunk loop — lives in `GBDT` + `serving.forest.CompiledForest`; this
layer adds what a serving process needs around it:

- `warmup()` compiles the whole bucket ladder up front so the first
  real request never pays a trace (and the stacking happens exactly
  once, before traffic arrives); with `tpu_compile_cache_dir` set the
  ladder's programs persist to disk, so a RESTARTED replica's warmup
  loads them back instead of re-tracing;
- `predict()` / `predict_one()` time every request into a latency ring
  and tracing counters (`serving/requests`, `serving/rows`), the same
  surface as the training-side counters;
- `submit()` optionally coalesces concurrent single-row requests into
  one device dispatch (micro-batching): rows arriving within
  `tpu_predict_micro_batch_window_ms` of each other ride one bucketed
  program instead of one dispatch each;
- admission control (serving/admission.py): queue-depth / in-flight
  caps (`tpu_serving_max_queue` / `tpu_serving_max_inflight`),
  per-request deadlines (`tpu_serving_deadline_ms` + per-call
  `deadline_ms=` overrides), and the EWMA shed policy — past
  saturation, requests that would expire in the queue are refused
  IMMEDIATELY with a structured retriable `ServingOverload` /
  `DeadlineExceeded` instead of being answered late. Shedding changes
  *whether* a request is answered, never *what* is answered: admitted
  requests stay bit-identical to an unloaded serve;
- cold-start-storm protection: concurrent first requests on an unseen
  shape bucket run exactly one compile (`serving.forest.SingleFlight`);
  the others wait under their deadlines or shed.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Dict, List, Optional

import numpy as np

from .. import log, telemetry, tracing
from ..testing import faults
from .admission import (AdmissionController, DeadlineExceeded,
                        PredictorShutdown, ServingOverload)
from .forest import (SingleFlight, SingleFlightExpired, bucket_ladder,
                     bucket_rows, enable_compile_cache)

# latency histogram bounds: 10us..~20s exponential — a fixed-memory
# distribution replacing the old bounded ring, so p50/p95/p99 cover the
# predictor's WHOLE service life, not the last window
_LATENCY_BOUNDS = tuple(1e-5 * (2.0 ** i) for i in range(22))
# micro-batch size distribution (rows per coalesced dispatch)
_BATCH_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256)


class _QueueItem:
    """One queued submit(): the row, its future, and the admission
    evidence the batch loop needs to expire/time it."""
    __slots__ = ("arr", "fut", "enqueued", "deadline_abs")

    def __init__(self, arr, fut, enqueued, deadline_abs):
        self.arr = arr
        self.fut = fut
        self.enqueued = enqueued
        self.deadline_abs = deadline_abs


def _resolve(fut: Future, value) -> None:
    try:
        fut.set_result(value)
    except InvalidStateError:  # raced close()'s shutdown sweep
        pass


def _fail(fut: Future, exc: BaseException) -> None:
    try:
        fut.set_exception(exc)
    except InvalidStateError:
        pass


class Predictor:
    """Reference: class Predictor, predictor.hpp:24-205 — built once per
    booster, reused per request. Accepts a `basic.Booster` or a bare
    `boosting.GBDT`; per-request overrides ride on `predict(**kw)`."""

    def __init__(self, booster, num_iteration: int = -1,
                 raw_score: bool = False, pred_leaf: bool = False,
                 pred_contrib: bool = False, pred_early_stop: bool = False,
                 pred_early_stop_freq: int = 10,
                 pred_early_stop_margin: float = 10.0):
        self._gbdt = getattr(booster, "_inner", booster)
        self._kwargs = dict(
            num_iteration=num_iteration, raw_score=raw_score,
            pred_leaf=pred_leaf, pred_contrib=pred_contrib,
            pred_early_stop=pred_early_stop,
            pred_early_stop_freq=pred_early_stop_freq,
            pred_early_stop_margin=pred_early_stop_margin)
        io = self._gbdt.config.io
        self._micro_batch = max(0, int(io.tpu_predict_micro_batch))
        self._window_s = max(0.0, float(
            io.tpu_predict_micro_batch_window_ms)) / 1e3
        self._bucket_min = int(io.tpu_predict_bucket_min)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: List[_QueueItem] = []
        self._batcher: Optional[threading.Thread] = None
        self._closed = False
        # admission control: all caps default to 0 (= off), reproducing
        # the pre-admission unbounded behavior exactly
        self.admission = AdmissionController(
            max_queue=int(io.tpu_serving_max_queue),
            max_inflight=int(io.tpu_serving_max_inflight),
            deadline_s=max(0.0, float(io.tpu_serving_deadline_ms)) / 1e3)
        # cold-start-storm protection: one compile per unseen bucket
        self._single_flight = SingleFlight()
        if getattr(io, "tpu_compile_cache_dir", ""):
            enable_compile_cache(io.tpu_compile_cache_dir)
        # always-on local instruments (stats() must work with global
        # telemetry off), registered as SHARED registry instruments so
        # the Prometheus export reads the same series — one observe per
        # request, not a local copy plus a registry twin (a later
        # telemetry.reset() only drops them from export, never from
        # stats())
        self._latency_hist = telemetry.registry().register_histogram(
            telemetry.Histogram("serving/latency_seconds",
                                bounds=_LATENCY_BOUNDS))
        self._batch_hist = telemetry.registry().register_histogram(
            telemetry.Histogram("serving/micro_batch_rows",
                                bounds=_BATCH_BOUNDS))
        self._counts = {"requests": 0, "rows": 0,
                        "micro_batches": 0, "micro_rows": 0,
                        "batch_isolated_rows": 0}
        self._warmup_seconds: Optional[float] = None
        self._warmup_buckets: List[int] = []

    # ------------------------------------------------------------------
    def num_features(self) -> int:
        return self._gbdt.max_feature_idx + 1

    def _check_width(self, arr: np.ndarray) -> None:
        """Reject wrong-width rows up front with a clear error — before
        this check a mis-shaped row surfaced as an XLA shape failure at
        the dispatch site AND burned a spurious retrace for a program
        no valid request can ever reuse."""
        want = self.num_features()
        if arr.ndim != 2 or arr.shape[1] != want:
            raise log.LightGBMError(
                "Prediction input has %s feature column(s); this model "
                "expects %d (shape %s)"
                % (arr.shape[1] if arr.ndim == 2 else "a bad number of",
                   want, tuple(arr.shape)))

    def warmup(self, max_rows: Optional[int] = None) -> Dict[str, Any]:
        """Compile every bucket program up to `max_rows` (default
        `tpu_predict_warmup_rows`) and stack the forest once, so the
        first real request is pure device compute. Warmup traffic is
        NOT counted in the request/latency stats. With
        `tpu_compile_cache_dir` set the compiled programs also persist
        to disk, so the next replica's warmup is a cache read."""
        io = self._gbdt.config.io
        cap = int(max_rows if max_rows is not None
                  else io.tpu_predict_warmup_rows)
        ladder = bucket_ladder(int(io.tpu_predict_bucket_min), max(1, cap))
        f = self.num_features()
        t0 = time.perf_counter()
        # synthetic all-zeros rows compile/stack fine but are useless —
        # and dangerous — as quantize-gate calibration (16 identical
        # rows traverse one leaf per tree, freezing a near-zero delta
        # per model version): flag them so the gate defers to the first
        # REAL batch
        self._gbdt._quant_gate_defer = True
        try:
            for rows in ladder:
                self._predict_timed(np.zeros((rows, f), np.float32),
                                    count=False)
                self._single_flight.mark(rows)
        finally:
            self._gbdt._quant_gate_defer = False
        self._warmup_seconds = time.perf_counter() - t0
        self._warmup_buckets = ladder
        tracing.counter("serving/warmup_buckets", len(ladder))
        log.debug("Predictor warmup: %d bucket programs in %.3fs",
                  len(ladder), self._warmup_seconds)
        return {"buckets": ladder, "seconds": self._warmup_seconds}

    # ------------------------------------------------------------------
    def _request_bucket(self, nrows: int) -> Optional[int]:
        """The shape bucket a request of `nrows` rows dispatches
        through (the single-flight key). None when bucketing is off —
        every size then traces its own program and there is no shared
        bucket for a storm to pile onto. The row count is capped at the
        dispatch chunk EXACTLY like GBDT._pipelined_chunks caps it:
        two over-chunk requests of different sizes compile the same
        chunk-bucket program and must share one flight key (the walk
        default is used — for matmul layouts whose chunk is larger,
        over-chunk requests merely share a key early, which only
        widens the guard, never splits it)."""
        if self._bucket_min <= 0 or nrows <= 0:
            return None
        cap = self._gbdt._predict_chunk_rows(
            self._gbdt._PREDICT_ROW_CHUNK)
        return bucket_rows(min(nrows, cap), self._bucket_min, cap=cap)

    def _predict_timed(self, arr: np.ndarray, count: bool = True,
                       deadline_abs: Optional[float] = None, **overrides):
        """The timed dispatch body shared by predict(), the micro-batch
        loop, and warmup(). Admission decisions happen in the PUBLIC
        entry points — this layer only guards the cold-bucket compile
        (single flight) and feeds the latency instruments."""
        kw = dict(self._kwargs)
        kw.update(overrides)
        t0 = time.perf_counter()
        bucket = self._request_bucket(arr.shape[0])
        lead = False
        cold = bucket is not None and not self._single_flight.seen(bucket)
        if cold:
            timeout = None if deadline_abs is None \
                else deadline_abs - time.perf_counter()
            try:
                lead = self._single_flight.begin(bucket, timeout=timeout)
            except SingleFlightExpired:
                raise self.admission._reject("compile_wait", ServingOverload(
                    "Deadline expired while waiting for bucket %d's "
                    "first compile (single-flight); retriable" % bucket,
                    reason="compile_wait"))
        ok = False
        try:
            if lead:
                # test seam: compile_storm() wedges the leader here,
                # simulating the 29-81s trace the followers must NOT
                # replicate
                faults.inject("serving.compile")
            faults.inject("serving.predict")
            out = self._gbdt.predict(arr, **kw)
            ok = True
        finally:
            if lead:
                self._single_flight.finish(bucket, ok)
        dt = time.perf_counter() - t0
        if count and not cold:
            # compile time is NOT service-time evidence: a cold-bucket
            # request (the single-flight leader pays the trace, its
            # followers pay the wait) or a slow warmup would otherwise
            # prime the EWMA at compile scale — ~30s on wide shapes —
            # and the shed policy would then refuse every deadline-
            # bearing request forever (shed requests never dispatch, so
            # nothing would ever correct the estimate)
            self.admission.observe_service(dt)
        if count:
            with self._lock:
                self._counts["requests"] += 1
                self._counts["rows"] += int(arr.shape[0])
            self._latency_hist.observe(dt)
            tracing.counter("serving/requests", 1)
            tracing.counter("serving/rows", int(arr.shape[0]))
        return out

    def predict(self, data, deadline_ms: Optional[float] = None,
                **overrides):
        """Timed predict over a [N, F] batch (rows also accepted as a
        single 1-D row, returned as a 1-row result — use predict_one()
        for the squeezed scalar path). `deadline_ms` overrides
        `tpu_serving_deadline_ms` for this call: a request whose
        estimated service time already exceeds it is refused with a
        structured retriable error BEFORE any device work."""
        # TreeSHAP walks raw f64 thresholds (shap._decision_vec): an f32
        # cast here can flip a hot/cold path for values straddling an
        # f32-rounded threshold, so contrib keeps the caller's dtype
        # (_predict_timed does the full kwargs merge for the dispatch)
        contrib = overrides.get("pred_contrib",
                                self._kwargs["pred_contrib"])
        arr = np.asarray(data) if contrib \
            else np.asarray(data, np.float32)
        if arr.ndim == 1:
            arr = arr.reshape(1, -1)
        self._check_width(arr)
        deadline_abs = self.admission.deadline_for(deadline_ms)
        self.admission.admit_sync(deadline_abs)
        try:
            return self._predict_timed(arr, deadline_abs=deadline_abs,
                                       **overrides)
        finally:
            self.admission.release_sync()

    def predict_one(self, row, deadline_ms: Optional[float] = None,
                    **overrides):
        """Single-row fast path: pads to the smallest bucket on one
        resident compiled program; returns the row's prediction with
        the batch axis squeezed."""
        return self.predict(np.asarray(row, np.float32).reshape(1, -1),
                            deadline_ms=deadline_ms, **overrides)[0]

    # ------------------------------------------------------------------
    # micro-batching: coalesce concurrent single-row requests
    def submit(self, row, deadline_ms: Optional[float] = None) -> Future:
        """Enqueue one row; resolves to its prediction. With
        `tpu_predict_micro_batch` 0 this degenerates to a synchronous
        predict_one; otherwise rows arriving within the window share
        one device dispatch. Refusals (queue full, shed, closed) raise
        `ServingOverload` HERE — an accepted Future either resolves to
        a prediction or fails with a structured error (deadline expiry,
        shutdown, a predict failure); it is never silently dropped."""
        arr = np.asarray(row, np.float32).reshape(-1)
        # validate BEFORE enqueueing: a wrong-width row must fail its
        # caller, not poison the whole coalesced batch it would ride in
        self._check_width(arr.reshape(1, -1))
        deadline_abs = self.admission.deadline_for(deadline_ms)
        fut: Future = Future()
        if self._micro_batch <= 0:
            self.admission.admit_sync(deadline_abs)
            try:
                _resolve(fut, self._predict_timed(
                    arr.reshape(1, -1), deadline_abs=deadline_abs)[0])
            except Exception as exc:  # surface through the future
                _fail(fut, exc)
            finally:
                self.admission.release_sync()
            return fut
        with self._cv:
            if self._closed:
                raise PredictorShutdown()
            # queue cap + EWMA shed under the lock: the depth the
            # decision reads is the depth the enqueue appends to
            self.admission.admit_queued(len(self._queue), deadline_abs)
            if self._batcher is None:
                self._batcher = threading.Thread(
                    target=self._batch_loop, name="lgbm-tpu-microbatch",
                    daemon=True)
                self._batcher.start()
            self._queue.append(_QueueItem(arr, fut, time.perf_counter(),
                                          deadline_abs))
            telemetry.gauge_set("serving/queue_depth", len(self._queue))
            self._cv.notify()
        return fut

    def _batch_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if self._closed and not self._queue:
                    return
                # collect up to micro_batch rows arriving within the window
                deadline = time.perf_counter() + self._window_s
                while len(self._queue) < self._micro_batch:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0 or self._closed:
                        break
                    self._cv.wait(timeout=remaining)
                batch = self._queue[:self._micro_batch]
                del self._queue[:len(batch)]
                telemetry.gauge_set("serving/queue_depth", len(self._queue))
            now = time.perf_counter()
            live = []
            for item in batch:
                self.admission.observe_wait(now - item.enqueued)
                # claim each future; a client may have cancel()ed while
                # its row sat in the window (request-timeout pattern) —
                # resolving a cancelled future raises and would kill
                # this thread
                if not item.fut.set_running_or_notify_cancel():
                    continue
                if item.deadline_abs is not None and now > item.deadline_abs:
                    # expired in the queue: prompt structured rejection
                    # BEFORE burning device time on a row whose answer
                    # nobody is waiting for anymore
                    _fail(item.fut, self.admission.expire(
                        now - item.enqueued, item.deadline_abs))
                    continue
                live.append(item)
            if not live:
                continue
            rows = np.stack([item.arr for item in live])
            # the batch inherits its TIGHTEST member deadline so a
            # cold-bucket compile (single-flight wait) cannot answer
            # deadline-bearing futures tens of seconds late; if the
            # dispatch sheds on it, the per-row isolation pass below
            # re-runs each row under its OWN deadline (a no-deadline
            # row then waits the compile out instead of failing)
            deadlines = [item.deadline_abs for item in live
                         if item.deadline_abs is not None]
            try:
                res = self._predict_timed(
                    rows, deadline_abs=min(deadlines) if deadlines
                    else None)
            except Exception as exc:
                self._isolate_batch_failure(live, exc)
                continue
            with self._lock:
                self._counts["micro_batches"] += 1
                self._counts["micro_rows"] += len(live)
            self._batch_hist.observe(len(live))
            tracing.counter("serving/micro_batches", 1)
            for i, item in enumerate(live):
                _resolve(item.fut, res[i])

    def _isolate_batch_failure(self, live: List[_QueueItem],
                               exc: BaseException) -> None:
        """A predict failure inside a coalesced batch must fail only
        the rows that actually fail: re-run each row alone so one
        poisoned row (or one transient fault) cannot take down every
        co-riding future. Single-row batches skip the retry — the
        failure IS that row's answer. Each re-run honors its row's
        deadline: under overload the serialized per-row dispatches can
        outlive deadlines that were met at pop time, and an expired
        row must not burn device time nobody is waiting for."""
        if len(live) == 1:
            _fail(live[0].fut, exc)
            return
        tracing.counter("serving/batch_isolated", 1)
        with self._lock:
            self._counts["batch_isolated_rows"] += len(live)
        for item in live:
            now = time.perf_counter()
            if item.deadline_abs is not None and now > item.deadline_abs:
                _fail(item.fut, self.admission.expire(
                    now - item.enqueued, item.deadline_abs))
                continue
            try:
                out = self._predict_timed(item.arr.reshape(1, -1),
                                          count=False,
                                          deadline_abs=item.deadline_abs)
            except Exception as row_exc:
                _fail(item.fut, row_exc)
            else:
                _resolve(item.fut, out[0])

    def close(self, timeout: float = 5.0) -> None:
        """Stop the micro-batcher. Queued requests are drained (they
        complete on this model — the registry's hot-swap contract);
        anything the batcher fails to drain within `timeout` (a wedged
        device, a dead thread) is failed with a structured
        `PredictorShutdown` instead of leaking an unresolved Future."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
            # read (don't clear) the batcher under the lock: EVERY
            # racing close() must wait out the same drain window —
            # Thread.join is multi-caller-safe, whereas clearing here
            # would let a second closer skip straight to the sweep and
            # fail futures the batcher was actively draining. Join
            # OUTSIDE the lock — the batcher takes it to drain
            batcher = self._batcher
        if batcher is not None:
            batcher.join(timeout=timeout)
            with self._cv:
                if self._batcher is batcher:
                    self._batcher = None
        # shutdown sweep: after the drain window nothing may stay
        # pending forever — a leaked Future is an indefinitely blocked
        # caller, the one outcome the overload contract forbids
        with self._cv:
            leftovers = self._queue[:]
            del self._queue[:]
            telemetry.gauge_set("serving/queue_depth", 0)
        for item in leftovers:
            if item.fut.set_running_or_notify_cancel():
                _fail(item.fut, PredictorShutdown())
                tracing.counter("serving/shutdown_failed_futures", 1)

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Counters in the same spirit as tracing's training counters:
        request/row totals, service-lifetime latency percentiles (from
        the bucketed telemetry histogram — bucket-resolution estimates,
        not a bounded recent-window sort), throughput, admission /
        shed / single-flight counters, and the forest cache's restack
        economics. The aggregates are also mirrored into `serving/*`
        registry gauges so the Prometheus export carries them without a
        stats() caller in the loop."""
        with self._lock:
            counts = dict(self._counts)
        hist = self._latency_hist.snapshot()
        out: Dict[str, Any] = dict(counts)
        out["model_version"] = int(self._gbdt._compiled_forest.version)
        stack = self._gbdt._compiled_forest.stats
        out.update({f"stack_{k}": int(v) for k, v in stack.items()})
        out["quantize"] = str(self._gbdt.config.io.tpu_predict_quantize)
        out["warmup_seconds"] = self._warmup_seconds
        out["warmup_buckets"] = list(self._warmup_buckets)
        out["admission"] = self.admission.stats()
        out["single_flight"] = dict(self._single_flight.counts)
        if hist["count"]:
            out["p50_latency_ms"] = round(
                self._latency_hist.quantile(0.50) * 1e3, 4)
            out["p95_latency_ms"] = round(
                self._latency_hist.quantile(0.95) * 1e3, 4)
            out["p99_latency_ms"] = round(
                self._latency_hist.quantile(0.99) * 1e3, 4)
            out["mean_latency_ms"] = round(
                hist["sum"] / hist["count"] * 1e3, 4)
            out["max_latency_ms"] = round(hist["max"] * 1e3, 4)
            if hist["sum"] > 0:
                out["rows_per_second"] = round(counts["rows"] / hist["sum"],
                                               2)
        if self._micro_batch > 0:
            with self._cv:
                out["queue_depth"] = len(self._queue)
            batch = self._batch_hist.snapshot()
            if batch["count"]:
                out["mean_micro_batch_rows"] = round(
                    batch["sum"] / batch["count"], 2)
        # cache hit/miss + latency mirrors for the file exporter
        telemetry.gauge_set("serving/stack_restacks", stack["restacks"])
        telemetry.gauge_set("serving/stack_hits", stack["hits"])
        telemetry.gauge_set("serving/stack_bytes", stack["bytes"])
        telemetry.gauge_set("serving/stack_evictions", stack["evictions"])
        telemetry.gauge_set("serving/model_version", out["model_version"])
        if hist["count"]:
            telemetry.gauge_set("serving/p99_latency_ms",
                                out["p99_latency_ms"])
        return out
