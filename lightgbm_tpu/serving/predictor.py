"""Serving front end: warmup, low-latency small-batch path, optional
micro-batching, and throughput/latency counters.

The reference serves predictions through a per-model `Predictor`
(predictor.hpp:24-205) whose closures are built once and reused per
request; this is its TPU-shaped counterpart for the ROADMAP's
"heavy traffic from millions of users" north star. The heavy lifting —
device-resident stacked forests, shape-bucketed dispatch, the pipelined
chunk loop — lives in `GBDT` + `serving.forest.CompiledForest`; this
layer adds what a serving process needs around it:

- `warmup()` compiles the whole bucket ladder up front so the first
  real request never pays a trace (and the stacking happens exactly
  once, before traffic arrives);
- `predict()` / `predict_one()` time every request into a latency ring
  and tracing counters (`serving/requests`, `serving/rows`), the same
  surface as the training-side counters;
- `submit()` optionally coalesces concurrent single-row requests into
  one device dispatch (micro-batching): rows arriving within
  `tpu_predict_micro_batch_window_ms` of each other ride one bucketed
  program instead of one dispatch each.
"""
from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Any, Dict, List, Optional

import numpy as np

from .. import log, telemetry, tracing
from .forest import bucket_ladder

# latency histogram bounds: 10us..~20s exponential — a fixed-memory
# distribution replacing the old bounded ring, so p50/p95/p99 cover the
# predictor's WHOLE service life, not the last window
_LATENCY_BOUNDS = tuple(1e-5 * (2.0 ** i) for i in range(22))
# micro-batch size distribution (rows per coalesced dispatch)
_BATCH_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256)


class Predictor:
    """Reference: class Predictor, predictor.hpp:24-205 — built once per
    booster, reused per request. Accepts a `basic.Booster` or a bare
    `boosting.GBDT`; per-request overrides ride on `predict(**kw)`."""

    def __init__(self, booster, num_iteration: int = -1,
                 raw_score: bool = False, pred_leaf: bool = False,
                 pred_contrib: bool = False, pred_early_stop: bool = False,
                 pred_early_stop_freq: int = 10,
                 pred_early_stop_margin: float = 10.0):
        self._gbdt = getattr(booster, "_inner", booster)
        self._kwargs = dict(
            num_iteration=num_iteration, raw_score=raw_score,
            pred_leaf=pred_leaf, pred_contrib=pred_contrib,
            pred_early_stop=pred_early_stop,
            pred_early_stop_freq=pred_early_stop_freq,
            pred_early_stop_margin=pred_early_stop_margin)
        io = self._gbdt.config.io
        self._micro_batch = max(0, int(io.tpu_predict_micro_batch))
        self._window_s = max(0.0, float(
            io.tpu_predict_micro_batch_window_ms)) / 1e3
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: List = []
        self._batcher: Optional[threading.Thread] = None
        self._closed = False
        # always-on local instruments (stats() must work with global
        # telemetry off), registered as SHARED registry instruments so
        # the Prometheus export reads the same series — one observe per
        # request, not a local copy plus a registry twin (a later
        # telemetry.reset() only drops them from export, never from
        # stats())
        self._latency_hist = telemetry.registry().register_histogram(
            telemetry.Histogram("serving/latency_seconds",
                                bounds=_LATENCY_BOUNDS))
        self._batch_hist = telemetry.registry().register_histogram(
            telemetry.Histogram("serving/micro_batch_rows",
                                bounds=_BATCH_BOUNDS))
        self._counts = {"requests": 0, "rows": 0,
                        "micro_batches": 0, "micro_rows": 0}
        self._warmup_seconds: Optional[float] = None
        self._warmup_buckets: List[int] = []

    # ------------------------------------------------------------------
    def num_features(self) -> int:
        return self._gbdt.max_feature_idx + 1

    def _check_width(self, arr: np.ndarray) -> None:
        """Reject wrong-width rows up front with a clear error — before
        this check a mis-shaped row surfaced as an XLA shape failure at
        the dispatch site AND burned a spurious retrace for a program
        no valid request can ever reuse."""
        want = self.num_features()
        if arr.ndim != 2 or arr.shape[1] != want:
            raise log.LightGBMError(
                "Prediction input has %s feature column(s); this model "
                "expects %d (shape %s)"
                % (arr.shape[1] if arr.ndim == 2 else "a bad number of",
                   want, tuple(arr.shape)))

    def warmup(self, max_rows: Optional[int] = None) -> Dict[str, Any]:
        """Compile every bucket program up to `max_rows` (default
        `tpu_predict_warmup_rows`) and stack the forest once, so the
        first real request is pure device compute. Warmup traffic is
        NOT counted in the request/latency stats."""
        io = self._gbdt.config.io
        cap = int(max_rows if max_rows is not None
                  else io.tpu_predict_warmup_rows)
        ladder = bucket_ladder(int(io.tpu_predict_bucket_min), max(1, cap))
        f = self.num_features()
        t0 = time.perf_counter()
        # synthetic all-zeros rows compile/stack fine but are useless —
        # and dangerous — as quantize-gate calibration (16 identical
        # rows traverse one leaf per tree, freezing a near-zero delta
        # per model version): flag them so the gate defers to the first
        # REAL batch
        self._gbdt._quant_gate_defer = True
        try:
            for rows in ladder:
                self._predict_inner(np.zeros((rows, f), np.float32))
        finally:
            self._gbdt._quant_gate_defer = False
        self._warmup_seconds = time.perf_counter() - t0
        self._warmup_buckets = ladder
        tracing.counter("serving/warmup_buckets", len(ladder))
        log.debug("Predictor warmup: %d bucket programs in %.3fs",
                  len(ladder), self._warmup_seconds)
        return {"buckets": ladder, "seconds": self._warmup_seconds}

    # ------------------------------------------------------------------
    def _predict_inner(self, arr: np.ndarray, **overrides):
        kw = dict(self._kwargs)
        kw.update(overrides)
        return self._gbdt.predict(arr, **kw)

    def predict(self, data, **overrides):
        """Timed predict over a [N, F] batch (rows also accepted as a
        single 1-D row, returned as a 1-row result — use predict_one()
        for the squeezed scalar path)."""
        kw = dict(self._kwargs)
        kw.update(overrides)
        # TreeSHAP walks raw f64 thresholds (shap._decision_vec): an f32
        # cast here can flip a hot/cold path for values straddling an
        # f32-rounded threshold, so contrib keeps the caller's dtype
        arr = np.asarray(data) if kw.get("pred_contrib") \
            else np.asarray(data, np.float32)
        if arr.ndim == 1:
            arr = arr.reshape(1, -1)
        self._check_width(arr)
        t0 = time.perf_counter()
        out = self._gbdt.predict(arr, **kw)
        dt = time.perf_counter() - t0
        with self._lock:
            self._counts["requests"] += 1
            self._counts["rows"] += int(arr.shape[0])
        self._latency_hist.observe(dt)
        tracing.counter("serving/requests", 1)
        tracing.counter("serving/rows", int(arr.shape[0]))
        return out

    def predict_one(self, row, **overrides):
        """Single-row fast path: pads to the smallest bucket on one
        resident compiled program; returns the row's prediction with
        the batch axis squeezed."""
        return self.predict(np.asarray(row, np.float32).reshape(1, -1),
                            **overrides)[0]

    # ------------------------------------------------------------------
    # micro-batching: coalesce concurrent single-row requests
    def submit(self, row) -> Future:
        """Enqueue one row; resolves to its prediction. With
        `tpu_predict_micro_batch` 0 this degenerates to a synchronous
        predict_one; otherwise rows arriving within the window share
        one device dispatch."""
        arr = np.asarray(row, np.float32).reshape(-1)
        # validate BEFORE enqueueing: a wrong-width row must fail its
        # caller, not poison the whole coalesced batch it would ride in
        self._check_width(arr.reshape(1, -1))
        fut: Future = Future()
        if self._micro_batch <= 0:
            try:
                fut.set_result(self.predict_one(arr))
            except Exception as exc:  # surface through the future
                fut.set_exception(exc)
            return fut
        with self._cv:
            if self._closed:
                raise log.LightGBMError("Predictor is closed")
            if self._batcher is None:
                self._batcher = threading.Thread(
                    target=self._batch_loop, name="lgbm-tpu-microbatch",
                    daemon=True)
                self._batcher.start()
            self._queue.append((arr, fut))
            telemetry.gauge_set("serving/queue_depth", len(self._queue))
            self._cv.notify()
        return fut

    def _batch_loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if self._closed and not self._queue:
                    return
                # collect up to micro_batch rows arriving within the window
                deadline = time.perf_counter() + self._window_s
                while len(self._queue) < self._micro_batch:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0 or self._closed:
                        break
                    self._cv.wait(timeout=remaining)
                batch = self._queue[:self._micro_batch]
                del self._queue[:len(batch)]
                telemetry.gauge_set("serving/queue_depth", len(self._queue))
            # claim each future; a client may have cancel()ed while its
            # row sat in the window (request-timeout pattern) — resolving
            # a cancelled future raises and would kill this thread
            live = [(r, f) for r, f in batch
                    if f.set_running_or_notify_cancel()]
            if not live:
                continue
            rows = np.stack([r for r, _ in live])
            try:
                res = self.predict(rows)
            except Exception as exc:
                for _, fut in live:
                    fut.set_exception(exc)
                continue
            with self._lock:
                self._counts["micro_batches"] += 1
                self._counts["micro_rows"] += len(live)
            self._batch_hist.observe(len(live))
            tracing.counter("serving/micro_batches", 1)
            for i, (_, fut) in enumerate(live):
                fut.set_result(res[i])

    def close(self) -> None:
        """Stop the micro-batcher (pending requests still complete)."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._batcher is not None:
            self._batcher.join(timeout=5.0)
            self._batcher = None

    # ------------------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Counters in the same spirit as tracing's training counters:
        request/row totals, service-lifetime latency percentiles (from
        the bucketed telemetry histogram — bucket-resolution estimates,
        not a bounded recent-window sort), throughput, and the forest
        cache's restack economics. The aggregates are also mirrored into
        `serving/*` registry gauges so the Prometheus export carries
        them without a stats() caller in the loop."""
        with self._lock:
            counts = dict(self._counts)
        hist = self._latency_hist.snapshot()
        out: Dict[str, Any] = dict(counts)
        out["model_version"] = int(self._gbdt._compiled_forest.version)
        stack = self._gbdt._compiled_forest.stats
        out.update({f"stack_{k}": int(v) for k, v in stack.items()})
        out["quantize"] = str(self._gbdt.config.io.tpu_predict_quantize)
        out["warmup_seconds"] = self._warmup_seconds
        out["warmup_buckets"] = list(self._warmup_buckets)
        if hist["count"]:
            out["p50_latency_ms"] = round(
                self._latency_hist.quantile(0.50) * 1e3, 4)
            out["p95_latency_ms"] = round(
                self._latency_hist.quantile(0.95) * 1e3, 4)
            out["p99_latency_ms"] = round(
                self._latency_hist.quantile(0.99) * 1e3, 4)
            out["mean_latency_ms"] = round(
                hist["sum"] / hist["count"] * 1e3, 4)
            out["max_latency_ms"] = round(hist["max"] * 1e3, 4)
            if hist["sum"] > 0:
                out["rows_per_second"] = round(counts["rows"] / hist["sum"],
                                               2)
        if self._micro_batch > 0:
            with self._cv:
                out["queue_depth"] = len(self._queue)
            batch = self._batch_hist.snapshot()
            if batch["count"]:
                out["mean_micro_batch_rows"] = round(
                    batch["sum"] / batch["count"], 2)
        # cache hit/miss + latency mirrors for the file exporter
        telemetry.gauge_set("serving/stack_restacks", stack["restacks"])
        telemetry.gauge_set("serving/stack_hits", stack["hits"])
        telemetry.gauge_set("serving/stack_bytes", stack["bytes"])
        telemetry.gauge_set("serving/stack_evictions", stack["evictions"])
        telemetry.gauge_set("serving/model_version", out["model_version"])
        if hist["count"]:
            telemetry.gauge_set("serving/p99_latency_ms",
                                out["p99_latency_ms"])
        return out
