"""Serving-grade prediction engine.

`CompiledForest` (forest.py) keeps the stacked/padded forest device-
resident across `predict` calls with model-version invalidation, in
f32 and quantized (`tpu_predict_quantize=f16/int8`) layouts that
coexist per model version; `Predictor` (predictor.py) is the
request-facing front end: bucket-ladder warmup, a low-latency
small-batch path, optional micro-batching of concurrent requests,
row-width validation, and throughput/latency/cache counters;
`ModelRegistry` (registry.py) serves many named boosters behind one
front end with a shared device-memory budget (LRU stack eviction) and
atomic zero-drop hot swap. The reference analogue is `Predictor`
(predictor.hpp:24-205), whose prediction closures are likewise built
once per booster, not per call; the registry/quantization tier follows
the GBDT inference accelerator literature (arXiv:2011.02022).

Overload resilience (admission.py, ISSUE 12): queue/in-flight caps,
per-request deadlines and EWMA load shedding on the `Predictor`;
per-model token-bucket QPS isolation and circuit breakers in the
`ModelRegistry`; cold-start-storm protection (`SingleFlight` — one
compile per unseen shape bucket) plus the persistent compile cache
(`tpu_compile_cache_dir`) in forest.py. Refused requests always get a
structured, retriable `ServingOverload` / `DeadlineExceeded`; admitted
requests stay bit-identical to an unloaded serve.
"""
from .admission import (AdmissionController, CircuitBreaker,
                        DeadlineExceeded, PredictorShutdown,
                        ServingOverload, TokenBucket)
from .forest import (QUANTIZE_MODES, CompiledForest, SingleFlight,
                     bucket_ladder, bucket_rows, enable_compile_cache,
                     pad_rows)
from .predictor import Predictor
from .registry import ModelRegistry

__all__ = ["AdmissionController", "CircuitBreaker", "CompiledForest",
           "DeadlineExceeded", "ModelRegistry", "Predictor",
           "PredictorShutdown", "QUANTIZE_MODES", "ServingOverload",
           "SingleFlight", "TokenBucket", "bucket_ladder", "bucket_rows",
           "enable_compile_cache", "pad_rows"]
