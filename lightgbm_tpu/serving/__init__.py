"""Serving-grade prediction engine.

`CompiledForest` (forest.py) keeps the stacked/padded forest device-
resident across `predict` calls with model-version invalidation, in
f32 and quantized (`tpu_predict_quantize=f16/int8`) layouts that
coexist per model version; `Predictor` (predictor.py) is the
request-facing front end: bucket-ladder warmup, a low-latency
small-batch path, optional micro-batching of concurrent requests,
row-width validation, and throughput/latency/cache counters;
`ModelRegistry` (registry.py) serves many named boosters behind one
front end with a shared device-memory budget (LRU stack eviction) and
atomic zero-drop hot swap. The reference analogue is `Predictor`
(predictor.hpp:24-205), whose prediction closures are likewise built
once per booster, not per call; the registry/quantization tier follows
the GBDT inference accelerator literature (arXiv:2011.02022).
"""
from .forest import (QUANTIZE_MODES, CompiledForest, bucket_ladder,
                     bucket_rows, pad_rows)
from .predictor import Predictor
from .registry import ModelRegistry

__all__ = ["CompiledForest", "ModelRegistry", "Predictor",
           "QUANTIZE_MODES", "bucket_ladder", "bucket_rows", "pad_rows"]
