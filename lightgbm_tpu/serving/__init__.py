"""Serving-grade prediction engine.

`CompiledForest` (forest.py) keeps the stacked/padded forest device-
resident across `predict` calls with model-version invalidation;
`Predictor` (predictor.py) is the request-facing front end: bucket-
ladder warmup, a low-latency small-batch path, optional micro-batching
of concurrent requests, and throughput/latency/cache counters. The
reference analogue is `Predictor` (predictor.hpp:24-205), whose
prediction closures are likewise built once per booster, not per call.
"""
from .forest import CompiledForest, bucket_ladder, bucket_rows, pad_rows
from .predictor import Predictor

__all__ = ["CompiledForest", "Predictor", "bucket_ladder", "bucket_rows",
           "pad_rows"]
