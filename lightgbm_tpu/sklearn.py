"""scikit-learn API wrappers.

Mirrors the reference sklearn interface
(`python-package/lightgbm/sklearn.py:584-759`): LGBMModel base +
LGBMRegressor / LGBMClassifier / LGBMRanker, supporting get_params/
set_params/clone, fit with eval sets and early stopping, custom objective
callables, and joblib persistence via Booster string round-trip.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from . import log
from .basic import Booster, Dataset, LightGBMError
from .engine import train


def _objective_decorator(func: Callable) -> Callable:
    """Wrap sklearn-style fobj(y_true, y_pred) -> (grad, hess)
    (reference: sklearn.py:23-76)."""
    def inner(preds, dataset):
        labels = dataset.get_label()
        argc = func.__code__.co_argcount
        if argc == 2:
            grad, hess = func(labels, preds)
        elif argc == 3:
            grad, hess = func(labels, preds, dataset.get_group())
        else:
            raise TypeError(f"Self-defined objective should have 2 or 3 arguments, got {argc}")
        return grad, hess
    return inner


def _eval_decorator(func: Callable) -> Callable:
    def inner(preds, dataset):
        labels = dataset.get_label()
        argc = func.__code__.co_argcount
        if argc == 2:
            return func(labels, preds)
        if argc == 3:
            return func(labels, preds, dataset.get_weight())
        if argc == 4:
            return func(labels, preds, dataset.get_weight(), dataset.get_group())
        raise TypeError(f"Self-defined eval function should have 2-4 arguments, got {argc}")
    return inner


try:
    from sklearn.base import (BaseEstimator as _SKBase,
                              ClassifierMixin as _SKClassifierMixin,
                              RegressorMixin as _SKRegressorMixin)
except ImportError:  # sklearn optional
    class _SKBase:
        pass

    class _SKClassifierMixin:
        pass

    class _SKRegressorMixin:
        pass


class LGBMModel(_SKBase):
    """Reference: sklearn.py:96-583 (LGBMModel)."""

    def __init__(self, boosting_type: str = "gbdt", num_leaves: int = 31,
                 max_depth: int = -1, learning_rate: float = 0.1,
                 n_estimators: int = 100, max_bin: int = 255,
                 subsample_for_bin: int = 200000, objective: Optional[str] = None,
                 min_split_gain: float = 0.0, min_child_weight: float = 1e-3,
                 min_child_samples: int = 20, subsample: float = 1.0,
                 subsample_freq: int = 0, colsample_bytree: float = 1.0,
                 reg_alpha: float = 0.0, reg_lambda: float = 0.0,
                 linear_tree: bool = False, linear_lambda: float = 0.0,
                 random_state: Optional[int] = None, n_jobs: int = -1,
                 silent: bool = True, **kwargs):
        self.boosting_type = boosting_type
        self.num_leaves = num_leaves
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_estimators = n_estimators
        self.max_bin = max_bin
        self.subsample_for_bin = subsample_for_bin
        self.objective = objective
        self.min_split_gain = min_split_gain
        self.min_child_weight = min_child_weight
        self.min_child_samples = min_child_samples
        self.subsample = subsample
        self.subsample_freq = subsample_freq
        self.colsample_bytree = colsample_bytree
        self.reg_alpha = reg_alpha
        self.reg_lambda = reg_lambda
        self.linear_tree = linear_tree
        self.linear_lambda = linear_lambda
        self.random_state = random_state
        self.n_jobs = n_jobs
        self.silent = silent
        self._other_params: Dict = dict(kwargs)
        self._Booster: Optional[Booster] = None
        self._evals_result: Dict = {}
        self._best_iteration = -1
        self._n_features = 0
        self._classes = None
        self._n_classes = 1

    # -- sklearn protocol -------------------------------------------------
    def get_params(self, deep: bool = True) -> Dict:
        params = {
            "boosting_type": self.boosting_type, "num_leaves": self.num_leaves,
            "max_depth": self.max_depth, "learning_rate": self.learning_rate,
            "n_estimators": self.n_estimators, "max_bin": self.max_bin,
            "subsample_for_bin": self.subsample_for_bin, "objective": self.objective,
            "min_split_gain": self.min_split_gain,
            "min_child_weight": self.min_child_weight,
            "min_child_samples": self.min_child_samples,
            "subsample": self.subsample, "subsample_freq": self.subsample_freq,
            "colsample_bytree": self.colsample_bytree,
            "reg_alpha": self.reg_alpha, "reg_lambda": self.reg_lambda,
            "linear_tree": self.linear_tree,
            "linear_lambda": self.linear_lambda,
            "random_state": self.random_state, "n_jobs": self.n_jobs,
            "silent": self.silent,
        }
        params.update(self._other_params)
        return params

    def set_params(self, **params) -> "LGBMModel":
        for key, value in params.items():
            if hasattr(self, key) and not key.startswith("_"):
                setattr(self, key, value)
            else:
                self._other_params[key] = value
        return self

    # ---------------------------------------------------------------------
    def _default_objective(self) -> str:
        return "regression"

    def _train_params(self) -> Dict:
        params = {
            "boosting_type": self.boosting_type,
            "num_leaves": self.num_leaves,
            "max_depth": self.max_depth,
            "learning_rate": self.learning_rate,
            "max_bin": self.max_bin,
            "bin_construct_sample_cnt": self.subsample_for_bin,
            "min_gain_to_split": self.min_split_gain,
            "min_sum_hessian_in_leaf": self.min_child_weight,
            "min_data_in_leaf": self.min_child_samples,
            "bagging_fraction": self.subsample,
            "bagging_freq": self.subsample_freq,
            "feature_fraction": self.colsample_bytree,
            "lambda_l1": self.reg_alpha,
            "lambda_l2": self.reg_lambda,
            "linear_tree": self.linear_tree,
            "linear_lambda": self.linear_lambda,
            "verbose": -1 if self.silent else 1,
        }
        if self.random_state is not None:
            params["seed"] = self.random_state
        obj = self.objective if isinstance(self.objective, str) and self.objective \
            else self._default_objective()
        params["objective"] = obj
        params.update(self._other_params)
        return params

    def fit(self, X, y, sample_weight=None, init_score=None, group=None,
            eval_set=None, eval_names=None, eval_sample_weight=None,
            eval_init_score=None, eval_group=None, eval_metric=None,
            early_stopping_rounds=None, verbose: bool = False,
            feature_name: str = "auto", categorical_feature: str = "auto",
            callbacks=None) -> "LGBMModel":
        params = self._train_params()
        fobj = None
        if callable(self.objective):
            fobj = _objective_decorator(self.objective)
            params["objective"] = "none"
        if eval_metric is not None and isinstance(eval_metric, str):
            params["metric"] = eval_metric
        feval = _eval_decorator(eval_metric) if callable(eval_metric) else None

        X = np.asarray(X, np.float64) if not hasattr(X, "dtypes") else X
        train_set = Dataset(X, label=y, weight=sample_weight, group=group,
                            init_score=init_score, params=params,
                            feature_name=feature_name,
                            categorical_feature=categorical_feature)
        valid_sets = []
        valid_names = []
        if eval_set is not None:
            if isinstance(eval_set, tuple):
                eval_set = [eval_set]
            for i, (vx, vy) in enumerate(eval_set):
                vw = eval_sample_weight[i] if eval_sample_weight else None
                vg = eval_group[i] if eval_group else None
                vi = eval_init_score[i] if eval_init_score else None
                if np.asarray(vx).shape == np.asarray(X).shape and \
                        np.array_equal(np.asarray(vx, np.float64), np.asarray(X, np.float64)):
                    valid_sets.append(train_set)
                else:
                    valid_sets.append(train_set.create_valid(
                        vx, label=vy, weight=vw, group=vg, init_score=vi))
                valid_names.append(eval_names[i] if eval_names else f"valid_{i}")

        self._evals_result = {}
        self._Booster = train(
            params, train_set, num_boost_round=self.n_estimators,
            valid_sets=valid_sets, valid_names=valid_names,
            fobj=fobj, feval=feval,
            early_stopping_rounds=early_stopping_rounds,
            evals_result=self._evals_result, verbose_eval=verbose,
            callbacks=callbacks)
        self._best_iteration = self._Booster.best_iteration
        self._n_features = self._Booster.num_feature()
        return self

    def predict(self, X, raw_score: bool = False, num_iteration: int = -1,
                pred_leaf: bool = False, pred_contrib: bool = False):
        # routed through the booster's shared serving Predictor
        # (lightgbm_tpu/serving): device-resident compiled forest,
        # bucketed dispatch, request counters
        if self._Booster is None:
            raise LightGBMError("Estimator not fitted, call fit before predict")
        return self._Booster.predict(X, num_iteration=num_iteration,
                                     raw_score=raw_score, pred_leaf=pred_leaf,
                                     pred_contrib=pred_contrib)

    def serving_predictor(self, **kwargs):
        """Serving front end over the fitted booster (warmup over the
        bucket ladder, micro-batching, latency/throughput counters) —
        see `lightgbm_tpu.serving.Predictor`."""
        return self.booster_.serving_predictor(**kwargs)

    @property
    def booster_(self) -> Booster:
        if self._Booster is None:
            raise LightGBMError("No booster found; call fit first")
        return self._Booster

    @property
    def evals_result_(self) -> Dict:
        return self._evals_result

    @property
    def best_iteration_(self) -> int:
        return self._best_iteration

    @property
    def feature_importances_(self) -> np.ndarray:
        return self.booster_.feature_importance()

    @property
    def n_features_(self) -> int:
        return self._n_features

    # joblib / pickle use default __getstate__ (Booster pickles via string)


class LGBMRegressor(_SKRegressorMixin, LGBMModel):
    def _default_objective(self) -> str:
        return "regression"


class LGBMClassifier(_SKClassifierMixin, LGBMModel):
    def _default_objective(self) -> str:
        return "multiclass" if self._n_classes > 2 else "binary"

    def fit(self, X, y, **kwargs):
        y = np.asarray(y).ravel()
        self._classes, y_enc = np.unique(y, return_inverse=True)
        self._n_classes = len(self._classes)
        if self._n_classes > 2 and not (isinstance(self.objective, str) and self.objective):
            self._other_params.setdefault("num_class", self._n_classes)
        super().fit(X, y_enc, **kwargs)
        return self

    @property
    def classes_(self):
        return self._classes

    @property
    def n_classes_(self) -> int:
        return self._n_classes

    def predict(self, X, raw_score: bool = False, num_iteration: int = -1,
                pred_leaf: bool = False, pred_contrib: bool = False):
        result = self.predict_proba(X, raw_score, num_iteration,
                                    pred_leaf, pred_contrib)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if result.ndim == 1:  # binary probabilities
            idx = (result > 0.5).astype(int)
        else:
            idx = np.argmax(result, axis=1)
        return self._classes[idx]

    def predict_proba(self, X, raw_score: bool = False, num_iteration: int = -1,
                      pred_leaf: bool = False, pred_contrib: bool = False):
        result = super().predict(X, raw_score, num_iteration, pred_leaf, pred_contrib)
        if raw_score or pred_leaf or pred_contrib:
            return result
        if self._n_classes <= 2 and result.ndim == 1:
            return np.vstack([1.0 - result, result]).T
        return result

    def predict_proba_raw(self, X):
        return super().predict(X, raw_score=True)


class LGBMRanker(LGBMModel):
    def _default_objective(self) -> str:
        return "lambdarank"

    def fit(self, X, y, group=None, **kwargs):
        if group is None:
            raise ValueError("Should set group for ranking task")
        super().fit(X, y, group=group, **kwargs)
        return self
