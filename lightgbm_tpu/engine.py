"""train() / cv() — the training entrypoints.

Mirrors the reference python-package engine
(`python-package/lightgbm/engine.py` — train at :18, cv at :310) including
the callback protocol (before/after iteration, engine.py:190-226) and
EarlyStopException unwinding (engine.py:216-218).
"""
from __future__ import annotations

import collections
import copy
import math
from typing import Any, Dict, List, Optional

import numpy as np

from . import callback as callback_mod
from . import checkpoint as checkpoint_mod
from . import log
from . import telemetry as telemetry_mod
from .basic import Booster, Dataset, LightGBMError
from .config import key_alias_transform
from .testing import faults


def train(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
          valid_sets=None, valid_names=None, fobj=None, feval=None,
          init_model=None, feature_name: str = "auto",
          categorical_feature: str = "auto", early_stopping_rounds: Optional[int] = None,
          evals_result: Optional[dict] = None, verbose_eval=True,
          learning_rates=None, keep_training_booster: bool = False,
          callbacks: Optional[List] = None) -> Booster:
    """Train one model (reference: engine.py:18-230)."""
    params = key_alias_transform(dict(params))
    num_boost_round = int(params.pop("num_iterations", num_boost_round))
    if "early_stopping_round" in params:
        early_stopping_rounds = int(params.pop("early_stopping_round"))
    if fobj is not None:
        params["objective"] = "none"
    if feature_name != "auto":
        train_set.feature_name = feature_name
    if categorical_feature != "auto":
        train_set.categorical_feature = categorical_feature

    valid_sets = valid_sets or []
    if isinstance(valid_sets, Dataset):
        valid_sets = [valid_sets]
    valid_sets = list(valid_sets)

    # binning params given at train time reach the lazy datasets
    # (reference: engine.py / basic.py Dataset._update_params)
    train_set._update_params(params)
    for vs in valid_sets:
        vs._update_params(params)

    booster = Booster(params=params, train_set=train_set)
    if init_model is not None:
        init_booster = init_model if isinstance(init_model, Booster) \
            else Booster(model_file=init_model, params=params)
        # continued training: seed scores with the loaded model's predictions
        _continue_from(booster, init_booster, train_set)

    valid_names = valid_names or [f"valid_{i}" for i in range(len(valid_sets))]
    is_valid_contain_train = False
    train_data_name = "training"
    for i, vs in enumerate(valid_sets):
        if vs is train_set:
            is_valid_contain_train = True
            train_data_name = valid_names[i]
            continue
        booster.add_valid(vs, valid_names[i])
    if is_valid_contain_train:
        booster._inner.config.metric.is_provide_training_metric = True
        booster.set_train_data_name(train_data_name)

    # assemble callbacks (engine.py:150-188)
    callbacks = list(callbacks or [])
    if verbose_eval is True:
        callbacks.append(callback_mod.print_evaluation())
    elif isinstance(verbose_eval, int) and verbose_eval and verbose_eval is not False:
        callbacks.append(callback_mod.print_evaluation(int(verbose_eval)))
    if early_stopping_rounds is not None and early_stopping_rounds > 0:
        callbacks.append(callback_mod.early_stopping(
            early_stopping_rounds, verbose=bool(verbose_eval)))
    if learning_rates is not None:
        callbacks.append(callback_mod.reset_parameter(learning_rate=learning_rates))
    if evals_result is not None:
        callbacks.append(callback_mod.record_evaluation(evals_result))
    # preemption-tolerant checkpointing (lightgbm_tpu/checkpoint.py):
    # resume from the newest valid snapshot, then snapshot every
    # tpu_checkpoint_interval iterations through the checkpoint callback
    recorder_ref: Dict[str, Any] = {"r": None}
    start_iter = _setup_checkpointing(booster, callbacks, recorder_ref)
    # observability (lightgbm_tpu/telemetry/): armed AFTER a possible
    # resume so the run-log header names the true start iteration; the
    # recorder is None when telemetry is off and costs nothing then
    recorder = telemetry_mod.start_run(booster._inner, params)
    recorder_ref["r"] = recorder
    # out-of-band reporters (the collective watchdog's rank_failure
    # path) reach the run log through the active-recorder registry
    telemetry_mod.set_active_recorder(recorder)
    if recorder is not None and start_iter > 0:
        elastic_info = getattr(booster, "_elastic_resume_info", None)
        if elastic_info:
            recorder.event("elastic_resume", **elastic_info)
        recorder.event("resume", iteration=start_iter)
    if recorder is not None:
        # dataset-construction trail: the ingest subsystem's counters
        # (rows/bytes/chunks, cache hits) and phase walls accumulated
        # BEFORE this recorder's baseline — surfaced as one event so the
        # run log says how the training data came to be (a cache hit
        # shows cache_hit>0 with no pass1/pass2 spans)
        reg = telemetry_mod.registry()
        ingest_counters = {
            c.name.split("/", 1)[1]: c.value
            for c in reg.counters.values()
            if c.name.startswith("ingest/") and not c.labels and c.value}
        ingest_phases = {
            name.split("/", 1)[1]: round(acc.total, 6)
            for name, acc in reg.phases.items()
            if name.startswith("ingest/") and acc.count}
        if ingest_counters or ingest_phases:
            recorder.event("ingest", counters=ingest_counters,
                           phase_seconds=ingest_phases)

    callbacks_before = [cb for cb in callbacks if getattr(cb, "before_iteration", False)]
    callbacks_after = [cb for cb in callbacks if not getattr(cb, "before_iteration", False)]
    callbacks_before.sort(key=lambda cb: getattr(cb, "order", 0))
    callbacks_after.sort(key=lambda cb: getattr(cb, "order", 0))

    # main loop (engine.py:190-226)
    finished_iter = num_boost_round
    try:
        for i in range(start_iter, num_boost_round):
            # preemption point for the fault-injection harness
            # (lightgbm_tpu/testing/faults.py): "the pod died after i
            # completed iterations"
            faults.inject("train.iteration", iteration=i)
            for cb in callbacks_before:
                cb(callback_mod.CallbackEnv(model=booster, params=params,
                                            iteration=i, begin_iteration=0,
                                            end_iteration=num_boost_round,
                                            evaluation_result_list=None))
            stop = booster.update(fobj=fobj)
            if stop:
                if recorder is not None:
                    recorder.event("stop", iteration=i,
                                   reason="no_more_splits")
                finished_iter = i
                break
            evaluation_result_list = []
            if is_valid_contain_train:
                evaluation_result_list.extend(booster.eval_train(feval))
            if valid_sets:
                evaluation_result_list.extend(booster.eval_valid(feval))
            if evaluation_result_list:
                _check_eval_finite(booster, evaluation_result_list, i)
                booster._inner._eval_history.append(
                    [[d, m, float(v), bool(b)]
                     for d, m, v, b in evaluation_result_list])
            try:
                for cb in callbacks_after:
                    cb(callback_mod.CallbackEnv(model=booster, params=params,
                                                iteration=i, begin_iteration=0,
                                                end_iteration=num_boost_round,
                                                evaluation_result_list=evaluation_result_list))
            except callback_mod.EarlyStopException as e:
                booster.best_iteration = e.best_iteration + 1
                finished_iter = booster.best_iteration
                for data_name, eval_name, score, _ in e.best_score:
                    booster.best_score.setdefault(data_name, collections.OrderedDict())
                    booster.best_score[data_name][eval_name] = score
                if recorder is not None:
                    recorder.iteration(i, evaluation_result_list)
                    recorder.event("early_stop", iteration=i,
                                   best_iteration=e.best_iteration)
                break
            if recorder is not None:
                recorder.iteration(i, evaluation_result_list)
            else:
                # watchdog heartbeat (LGBM_TPU_HEARTBEAT_FILE) stays armed
                # even without a recorder; no-op when the env var is unset
                telemetry_mod.heartbeat(i)
        # exported-forest artifact (lightgbm_tpu/export): with
        # tpu_export_dir set, a completed run ends by packing the
        # training-stack-free serving artifact; the run log records the
        # publish so a fleet rollout can key on it
        io_cfg = booster._inner.config.io
        if getattr(io_cfg, "tpu_export_dir", ""):
            import os

            from . import export as export_mod
            booster._inner.finalize_training()
            info = booster.export_forest(os.path.join(
                io_cfg.tpu_export_dir, export_mod.DEFAULT_NAME))
            if recorder is not None:
                recorder.event("artifact_published", **info)
    except KeyboardInterrupt:
        raise
    finally:
        # drain the async tree pipeline (boosting/gbdt.py) so models are
        # materialized before anyone reads booster internals
        try:
            booster._inner.finalize_training()
        finally:
            try:
                if recorder is not None:
                    import sys
                    exc = sys.exc_info()[1]
                    recorder.close(
                        status="finished" if exc is None else
                        f"error: {type(exc).__name__}")
            finally:
                # cleared AFTER close: the end-of-run aggregate is a
                # collective that can wedge on a dead peer, and the
                # watchdog's rank_failure event must still reach the
                # run log through the active-recorder registry
                telemetry_mod.set_active_recorder(None)
    return booster


def train_sweep(params_list, train_set: Dataset, num_boost_round: int = 100,
                names=None, registry=None,
                warmup_rows: Optional[int] = None) -> List[Booster]:
    """Train K boosters over ONE shared dataset in lockstep, inside one
    compiled XLA program per boosting iteration (the many-model tier:
    hyperparameter sweeps, per-segment fleets of small models).

    `params_list` holds one param dict per model. They may differ only
    in per-model knobs (regularization, learning rate, sampling seeds
    and fractions — boosting/sweep.SWEEP_VARIABLE_PARAMS); every other
    key must agree and a divergence raises a LightGBMError naming it.
    Every model's trees are byte-identical to training that config alone
    with `train()` (tests/test_sweep.py).

    When `registry` (a serving.ModelRegistry) is given, the finished
    boosters are published under `names` — default
    `<tpu_sweep_name_prefix>/<k>` — through one shared
    `publish_many` budget/eviction pass. Returns the K Boosters in
    param order."""
    from .boosting.sweep import SweepTrainer

    if registry is not None and names is not None \
            and len(names) != len(params_list):
        # fail BEFORE the (potentially long) lockstep run, not after
        raise LightGBMError(
            "train_sweep got %d names for %d models"
            % (len(names), len(params_list)))
    trainer = SweepTrainer(params_list, train_set, num_boost_round)
    telemetry_mod.heartbeat(0, phase="sweep_init")
    try:
        for i in range(trainer.num_boost_round):
            # the same preemption point engine.train exposes, so fault
            # harnesses can kill a sweep "after i completed iterations"
            faults.inject("train.iteration", iteration=i)
            trainer.step()
        boosters = trainer.finish()
    finally:
        telemetry_mod.heartbeat(trainer._it, phase="sweep_done")
    if registry is not None:
        if names is None:
            prefix = trainer.configs[0].io.tpu_sweep_name_prefix
            names = [f"{prefix}/{k}" for k in range(len(boosters))]
        registry.publish_many(list(zip(names, boosters)),
                              warmup_rows=warmup_rows)
    return boosters


def _check_eval_finite(booster: Booster, results, iteration: int) -> None:
    """A NaN metric means the scores (or the metric's own inputs) went
    bad; every later iteration would train against the same garbage, so
    stop with a named, located error instead (tpu_guard_nonfinite)."""
    if not booster._inner.config.boosting.tpu_guard_nonfinite:
        return
    for data_name, eval_name, val, _ in results:
        if not math.isfinite(val):
            raise LightGBMError(
                "Metric '%s' on '%s' evaluated to %r at iteration %d; "
                "the model scores or metric inputs are no longer finite "
                "(set tpu_guard_nonfinite=false to disable this check)"
                % (eval_name, data_name, val, iteration))


def _setup_checkpointing(booster: Booster, callbacks: List,
                         recorder_ref: Optional[Dict[str, Any]] = None) -> int:
    """When tpu_checkpoint_dir is set: resume the booster (and any
    stateful callbacks) from the newest valid snapshot, register the
    periodic checkpoint callback, and return the iteration to restart
    the loop from. Returns 0 (fresh start) when checkpointing is off.

    Corrupt/truncated snapshots are skipped to the previous good one
    (CheckpointManager.load_latest); a snapshot whose config fingerprint
    differs from this run's is REFUSED loudly — restoring RNG/score
    state into different training semantics would produce a model that
    matches neither configuration. Under multi-host training every rank
    restores its own row-shard snapshot and all ranks agree on the
    minimum common iteration."""
    inner = booster._inner
    cfg = inner.config
    if not cfg.io.tpu_checkpoint_dir:
        return 0
    # fingerprint on GLOBAL rows: the local shard size is a function of
    # the world size, and a snapshot taken at W ranks must be accepted
    # at W' ranks (world-size-elastic resume, ISSUE 11)
    n_fp = int(getattr(inner.train_data, "num_global_rows", 0)
               or inner._n)
    fingerprint = checkpoint_mod.config_fingerprint(
        cfg.raw_params, n_fp, inner.max_feature_idx + 1,
        cfg.boosting_type)
    manager = checkpoint_mod.CheckpointManager(
        cfg.io.tpu_checkpoint_dir, keep_last=cfg.io.tpu_checkpoint_keep)
    stateful = [cb for cb in callbacks if hasattr(cb, "checkpoint_state")]
    elastic_ok = bool(cfg.io.tpu_elastic_resume)

    start_iter = 0
    found = manager.load_latest()
    if found is None and elastic_ok:
        # no series for THIS rank (the cohort grew past the original
        # world size, or a single process is adopting a multi-rank
        # directory): start from the newest snapshot any rank wrote
        found = manager.load_latest_any_rank()
    payload = found[0] if found else None
    candidate = int(payload["iteration"]) if payload else 0
    # world payloads already decoded on this path (iteration -> {rank:
    # payload}); the repartition reassembly below reuses them instead
    # of re-reading + re-checksumming every rank's snapshot
    world_cache: Dict[int, Dict[int, Any]] = {}
    if inner._num_processes > 1:
        from .parallel.multihost import agree_on_iteration
        target = agree_on_iteration(candidate)
        if target <= 0:
            payload = None  # some rank has no usable snapshot
        elif target != candidate:
            try:
                payload = manager.load_iteration(target)
            except (checkpoint_mod.CheckpointError, OSError) as exc:
                # this rank has no snapshot at the agreed iteration —
                # either the series drifted further apart than
                # keep-last-K retains, or this rank is NEW (a grown
                # cohort adopting another rank's series). Elastic
                # resume can still proceed from any ORIGINAL rank's
                # payload at that iteration (the repartition path below
                # reassembles the scores world-wide); without one,
                # silently diverging (this rank fresh, others restored)
                # would be far worse than stopping, so make the
                # operator decide
                payload = None
                if elastic_ok:
                    # corrupt peer files are skipped inside
                    # load_world_iteration — any readable original
                    # payload is enough to anchor the reassembly below
                    at_target = manager.load_world_iteration(target)
                    if at_target:
                        world_cache[int(target)] = at_target
                        payload = at_target.get(manager.rank,
                                                at_target[min(at_target)])
                if payload is None:
                    raise LightGBMError(
                        "Multi-host resume: the ranks agreed on "
                        "iteration %d but this rank cannot load it "
                        "(%s). Clear %s on all hosts to restart from "
                        "scratch, or restore the missing snapshot "
                        "files." % (target, exc, manager.directory))
    if payload is not None:
        path = manager.path_for(int(payload["iteration"]))
        if payload.get("fingerprint") != fingerprint:
            raise LightGBMError(
                "Refusing to resume from %s: its config fingerprint does "
                "not match this run (parameters, dataset shape or "
                "boosting type changed since the checkpoint was "
                "written). Restore the original configuration or point "
                "tpu_checkpoint_dir at a fresh directory."
                % path)
        # world-size-elastic resume: the snapshot's row partition
        # differs from this run's (different process count, or this
        # rank adopting another rank's series) — reassemble the global
        # score matrix from EVERY original rank's snapshot and slice
        # this rank's new partition out of it (checkpoint.py)
        snap_world = checkpoint_mod.payload_world(payload)
        snap_procs = int(snap_world.get("processes", 1))
        repartition = (snap_procs != inner._num_processes
                       or int(snap_world.get("rank", manager.rank))
                       != manager.rank)
        if repartition:
            if not elastic_ok:
                raise LightGBMError(
                    "Snapshot %s was taken at world size %d (rank %s) "
                    "but this run has %d process(es); set "
                    "tpu_elastic_resume=true to re-shard it or restore "
                    "the original world size."
                    % (path, snap_procs, snap_world.get("rank"),
                       inner._num_processes))
            it = int(payload["iteration"])
            try:
                payloads = world_cache.get(it)
                if payloads is not None and not any(
                        r not in payloads for r in range(snap_procs)):
                    # membership, not count: a stale extra-rank file in
                    # the cache could mask a MISSING original rank
                    payloads = {r: p for r, p in payloads.items()
                                if r < snap_procs}
                else:
                    payloads = manager.load_world_iteration(
                        it, expected_ranks=snap_procs)
            except checkpoint_mod.CheckpointError as exc:
                # a dying rank leaves the series SKEWED (rank 0 wrote
                # iteration k, rank 1 only reached k-1): fall back to
                # the newest iteration the whole original world can
                # reassemble instead of refusing the resume outright
                fallback = manager.latest_complete_iteration(
                    snap_procs, before=it)
                if fallback is None:
                    raise
                fb_iter, payloads = fallback
                log.warning(
                    "Elastic resume: iteration %d is incomplete across "
                    "the original ranks (%s); falling back to the "
                    "newest complete iteration %d", it, exc, fb_iter)
                payload = payloads.get(manager.rank,
                                       payloads[min(payloads)])
            # EVERY merged payload must carry this run's fingerprint,
            # not just the anchor: a stale rank file left over from a
            # differently-configured run in the same directory would
            # otherwise blend silently into the reassembled scores —
            # the exact blend the fingerprint contract exists to refuse
            stale = {r: p.get("fingerprint")
                     for r, p in payloads.items()
                     if p.get("fingerprint") != fingerprint}
            if stale:
                raise LightGBMError(
                    "Refusing elastic resume from iteration %d in %s: "
                    "rank file(s) %s carry a different config "
                    "fingerprint (leftovers from another run?). Clear "
                    "the directory or restore the original "
                    "configuration."
                    % (int(payload["iteration"]), manager.directory,
                       sorted(stale)))
            row_index = getattr(inner.train_data, "used_row_indices", None)
            if row_index is None or len(row_index) != inner._n:
                row_index = np.arange(inner._n, dtype=np.int64)
            state = checkpoint_mod.elastic_local_state(
                payloads, row_index, base_rank=manager.rank)
            payload = dict(payload, state=state)
            log.info(
                "Elastic resume: re-sharded a %d-rank snapshot set at "
                "iteration %d onto rank %d of %d process(es)",
                snap_procs, int(payload["iteration"]), manager.rank,
                inner._num_processes)
            booster._elastic_resume_info = {
                "from_processes": snap_procs,
                "to_processes": int(inner._num_processes),
                "iteration": int(payload["iteration"]),
            }
        booster.restore_state(payload)
        cb_states = payload.get("callbacks", {})
        for idx, cb in enumerate(stateful):
            state = cb_states.get(f"{getattr(cb, 'checkpoint_key', 'cb')}:{idx}")
            if state is not None:
                cb.restore_state(state)
        start_iter = int(payload["iteration"])
        log.info("Resumed training from checkpoint %s at iteration %d",
                 path, start_iter)

    def _save(env):
        snapshot = env.model.checkpoint_state()
        snapshot["fingerprint"] = fingerprint
        snapshot["callbacks"] = {
            f"{getattr(cb, 'checkpoint_key', 'cb')}:{idx}":
                cb.checkpoint_state()
            for idx, cb in enumerate(stateful)}
        path = manager.save(snapshot, snapshot["iteration"])
        # narrate the save into the run log (telemetry recorder is
        # created after this closure — read it through the shared ref)
        recorder = (recorder_ref or {}).get("r")
        if recorder is not None:
            recorder.event("checkpoint_saved",
                           iteration=int(snapshot["iteration"]), path=path)

    callbacks.append(callback_mod.checkpoint(
        _save, interval=max(1, cfg.io.tpu_checkpoint_interval)))
    return start_iter


def _continue_from(booster: Booster, init_booster: Booster, train_set: Dataset):
    """Seed a new booster's state from a loaded model (reference:
    boosting.cpp:29-62 + application.cpp:112-116 init-score path)."""
    inner = booster._inner
    init_inner = init_booster._inner
    inner.models = copy.deepcopy(init_inner.models)
    inner.iter_ = init_inner.iter_
    # the ensemble was swapped wholesale — stale compiled forests must
    # not survive into the continued run's predictions
    inner._bump_model_version()
    # carry over best-iteration / eval history when the init model has
    # them (a Booster handed over from a previous train() call): the
    # continued run starts from the loaded run's record instead of
    # forgetting where its best model was
    if getattr(init_booster, "best_iteration", -1) > 0:
        booster.best_iteration = init_booster.best_iteration
        booster.best_score = copy.deepcopy(init_booster.best_score)
    inner.best_iter = dict(getattr(init_inner, "best_iter", {}))
    inner.best_score = copy.deepcopy(getattr(init_inner, "best_score", {}))
    inner._eval_history = list(getattr(init_inner, "_eval_history", []))
    # DART: the drop ledger travels with the model (model text carries
    # tpu_dart_tree_weights); without it every pre-existing tree would
    # re-enter drop selection with no weight
    if hasattr(inner, "tree_weight") and hasattr(init_inner, "tree_weight"):
        inner.tree_weight = list(init_inner.tree_weight)
        inner.sum_weight = float(init_inner.sum_weight)
    # the fresh booster's own boost_from_average must be undone — the loaded
    # model's trees (plus its recorded bias) already carry the base score
    if inner.init_score_bias != 0.0:
        inner._score = inner._score - inner.init_score_bias
    inner.init_score_bias = init_inner.init_score_bias
    # the loaded trees already carry any boost-from-average bias (AddBias
    # folds it into the first tree) — nothing further to fold
    inner._pending_bias = 0.0
    # rebuild bin-space metadata from the TRAINING dataset's mappers
    # before binned replay: text-loaded trees used to keep their zeroed
    # group locators here (silently replaying every split through group
    # 0 on unbundled datasets), and even complete locators only describe
    # the binning of the dataset the init model was trained on — which
    # is this one only when the same constructed Dataset is reused
    same_data = getattr(init_inner, "train_data", None) is inner.train_data
    for tree in inner.models:
        if tree.num_leaves > 1 and (not tree.has_bin_metadata
                                    or not same_data):
            tree.attach_bin_metadata(inner.train_data)
    from .boosting.gbdt import _jit_forest_binned
    from .ops.predict import stack_trees
    k = inner.num_tree_per_iteration
    inner._score = inner._score + init_inner.init_score_bias
    for cls in range(k):
        class_trees = [t for i, t in enumerate(inner.models) if i % k == cls
                       and t.num_leaves > 1]
        if not class_trees:
            continue
        if any(t.is_linear for t in class_trees):
            # linear trees cannot replay through the stacked binned-only
            # path (coeff . x needs raw values); replay per tree via the
            # leaf + raw route, which needs the booster's raw landing
            if getattr(inner, "_raw", None) is None:
                raise LightGBMError(
                    "Continued training from a linear_tree init_model "
                    "requires linear_tree=true in the continuing params "
                    "(the score replay needs the raw feature matrix)")
            for t in class_trees:
                inner._score = inner._score.at[cls].add(
                    inner._tree_values_device(t.to_device(),
                                              inner._binned, inner._raw))
        else:
            inner._score = inner._score.at[cls].add(
                _jit_forest_binned(stack_trees(class_trees), inner._binned))


def cv(params: Dict[str, Any], train_set: Dataset, num_boost_round: int = 100,
       folds=None, nfold: int = 5, stratified: bool = True, shuffle: bool = True,
       metrics=None, fobj=None, feval=None, init_model=None,
       feature_name: str = "auto", categorical_feature: str = "auto",
       early_stopping_rounds: Optional[int] = None, fpreproc=None,
       verbose_eval=None, show_stdv: bool = True, seed: int = 0,
       callbacks: Optional[List] = None) -> Dict[str, List[float]]:
    """K-fold cross-validation (reference: engine.py:310-464)."""
    params = key_alias_transform(dict(params))
    num_boost_round = int(params.pop("num_iterations", num_boost_round))
    if metrics is not None:
        params["metric"] = metrics
    inner_full = train_set._lazy_init()
    n = inner_full.num_data
    label = np.asarray(inner_full.metadata.label)

    rng = np.random.RandomState(seed)
    if folds is None:
        idx = np.arange(n)
        if shuffle:
            rng.shuffle(idx)
        if stratified and params.get("objective", "").startswith(("binary", "multiclass")):
            # stratified assignment by label
            folds_idx = [[] for _ in range(nfold)]
            for lab in np.unique(label):
                lab_idx = idx[label[idx] == lab]
                for i, r in enumerate(lab_idx):
                    folds_idx[i % nfold].append(r)
            folds = [(np.setdiff1d(idx, np.asarray(f)), np.asarray(sorted(f)))
                     for f in folds_idx]
        else:
            splits = np.array_split(idx, nfold)
            folds = [(np.setdiff1d(idx, s), np.sort(s)) for s in splits]

    boosters = []
    for train_idx, test_idx in folds:
        tr = train_set.subset(train_idx)
        te = train_set.subset(test_idx)
        if fpreproc is not None:
            tr, te, params = fpreproc(tr, te, params.copy())
        b = Booster(params=dict(params), train_set=tr)
        te.set_reference(tr)
        b.add_valid(te, "valid")
        boosters.append(b)

    results = collections.defaultdict(list)
    try:
        for i in range(num_boost_round):
            agg: Dict[str, List[float]] = collections.defaultdict(list)
            bigger: Dict[str, bool] = {}
            for b in boosters:
                b.update(fobj=fobj)
                for _, name, val, ib in b.eval_valid(feval):
                    agg[name].append(val)
                    bigger[name] = ib
            for name, vals in agg.items():
                results[name + "-mean"].append(float(np.mean(vals)))
                results[name + "-stdv"].append(float(np.std(vals)))
            if verbose_eval:
                msg = "\t".join(f"cv_agg {k}: {v[-1]:g}" for k, v in results.items()
                                if k.endswith("-mean"))
                log.info("[%d]\t%s", i + 1, msg)
            if early_stopping_rounds and i >= early_stopping_rounds:
                keys = [k for k in results if k.endswith("-mean")]
                stop = True
                first_best = None
                for k in keys:
                    hist = results[k]
                    base = k[:-5]
                    if bigger.get(base, False):
                        best = int(np.argmax(hist))
                    else:
                        best = int(np.argmin(hist))
                    if first_best is None:
                        first_best = best  # first metric anchors truncation
                    if i - best < early_stopping_rounds:
                        stop = False
                if stop:
                    # truncate every history at the FIRST metric's best
                    # iteration (consistent with the callback-based early
                    # stopping, which tracks the first metric)
                    for k in list(results.keys()):
                        results[k] = results[k][:first_best + 1]
                    break
    except callback_mod.EarlyStopException:
        pass
    return dict(results)
