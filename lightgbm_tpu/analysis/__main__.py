"""graftlint CLI: `python -m lightgbm_tpu.analysis [paths...]`.

Exit code 0 iff no unsuppressed findings. `--json` emits the
machine-readable report (schema graftlint/1) on stdout for CI gates
(scripts/lint_report.py wraps this into the committed LINT artifact).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from . import all_rules, run

DEFAULT_BASELINE = "graftlint_baseline.json"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m lightgbm_tpu.analysis",
        description="graftlint: project-native static analysis "
                    "enforcing the repo's TPU-hazard invariants")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to scan (default: "
                        "lightgbm_tpu scripts, resolved against the "
                        "repo root this package lives in)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable report on stdout")
    p.add_argument("--baseline", default=None,
                   help="baseline file for grandfathered findings "
                        "(default: ./%s if present; every entry needs "
                        "a reason)" % DEFAULT_BASELINE)
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any baseline file")
    p.add_argument("--rules", default=None,
                   help="comma-separated rule subset to run")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.name}: {r.description}")
        return 0

    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    paths = args.paths
    if not paths:
        paths = [p for p in (os.path.join(repo, "lightgbm_tpu"),
                             os.path.join(repo, "scripts"))
                 if os.path.isdir(p)]

    baseline = None
    if not args.no_baseline:
        baseline = args.baseline
        if baseline is None:
            # cwd first (a scanned subtree may carry its own), then the
            # repo root the default scan paths anchor to — running the
            # CLI from a subdirectory must not silently drop the
            # committed baseline
            for cand in (DEFAULT_BASELINE,
                         os.path.join(repo, DEFAULT_BASELINE)):
                if os.path.exists(cand):
                    baseline = cand
                    break

    rule_names = None
    if args.rules:
        rule_names = [r.strip() for r in args.rules.split(",")
                      if r.strip()]
    try:
        report = run(paths, rule_names=rule_names, baseline_path=baseline)
    except ValueError as exc:  # unknown rule name
        print(f"graftlint: {exc}", file=sys.stderr)
        return 2

    if args.as_json:
        print(json.dumps(report.as_dict(), indent=2, sort_keys=True))
        return report.exit_code

    for f in report.findings:
        print(f.render())
    n_sup = len(report.suppressions)
    print("graftlint: %d file(s), %d finding(s), %d suppressed%s"
          % (report.files_scanned, len(report.findings), n_sup,
             "" if not report.stale_baseline
             else ", %d STALE baseline entr%s (prune them)"
             % (len(report.stale_baseline),
                "y" if len(report.stale_baseline) == 1 else "ies")))
    return report.exit_code


if __name__ == "__main__":
    sys.exit(main())
