"""graftlint: project-native static analysis for the TPU-hazard
invariants this repo keeps re-learning the hard way.

The hardest shipped bugs were violations of UNWRITTEN project
invariants: PR 11's bagging/GOSS masks drawn over the padded row count
(in-bag selection silently depended on the device count), PR 12's
check-then-act races on serving counters, and the hand-maintained
`tpu_*` param <-> docs <-> checkpoint-fingerprint triangle. Accelerator
GBDTs win by guaranteeing bit-level reproducibility across device
layouts; enforcing that only with after-the-fact bit-identity tests
means every new subsystem can re-introduce the same bug classes. This
package makes the invariants machine-checked at the source level.

Usage::

    python -m lightgbm_tpu.analysis lightgbm_tpu scripts          # text
    python -m lightgbm_tpu.analysis --json lightgbm_tpu scripts   # CI
    python -m lightgbm_tpu.analysis --list-rules

Suppressing a finding requires a WRITTEN reason, inline::

    risky()  # graftlint: disable=<rule>  <why the rule does not apply>

or a baseline entry (graftlint_baseline.json) with a `reason` field.
Reasonless pragmas, unknown rule names in pragmas, and reasonless
baseline entries are themselves findings. The pass runs as a tier-1
pytest (tests/test_static_analysis.py): zero unsuppressed findings
over `lightgbm_tpu/` and `scripts/` is a merge gate.

Rules live in `lightgbm_tpu/analysis/rules/` — one module per bug
class, each pinned by positive/negative fixtures under
tests/analysis_fixtures/. See README "Static analysis" for how to add
one.
"""
from __future__ import annotations

from .core import (Finding, Report, Rule, SourceFile, Suppression,  # noqa: F401
                   iter_python_files, run)
from .rules import RULE_CLASSES, all_rules  # noqa: F401

__all__ = ["Finding", "Report", "Rule", "SourceFile", "Suppression",
           "iter_python_files", "run", "all_rules", "RULE_CLASSES"]
