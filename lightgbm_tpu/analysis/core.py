"""graftlint core: finding model, pragma suppression, baseline, walker.

The engine is deliberately small: rules are AST visitors over parsed
source (never imported, never executed — a broken module is itself a
finding), findings are suppressible only with a WRITTEN reason (inline
pragma or baseline entry), and the whole pass is a tier-1 pytest so the
invariants it encodes are enforced on every run, not re-learned from
the next production incident.

Suppression contract:

- inline pragma, same line as the finding::

      risky_call()  # graftlint: disable=<rule>[,<rule2>]  <reason>

  The reason is MANDATORY — a pragma without one is itself a finding
  (rule ``pragma-missing-reason``), and naming a rule the engine does
  not know is a finding too (``pragma-unknown-rule``), so suppressions
  cannot rot silently when a rule is renamed.
- baseline file (``graftlint_baseline.json``) for grandfathered
  findings: entries match on (rule, path, message) and must carry a
  non-empty ``reason``. Entries that no longer match anything are
  reported as stale so the baseline shrinks monotonically.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import io
import json
import os
import tokenize
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SCHEMA = "graftlint/1"

# directories the file walker never descends into: bytecode caches and
# tool/VCS state are not source (satellite: no __pycache__ may ever be
# scanned OR committed — .gitignore handles the committing half)
SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", ".mypy_cache",
             ".ruff_cache", "node_modules", ".ipynb_checkpoints"}

PRAGMA_RULES = ("pragma-missing-reason", "pragma-unknown-rule",
                "baseline-missing-reason", "parse-error")


@dataclasses.dataclass
class Finding:
    """One rule violation at one source location."""
    rule: str
    path: str           # repo-relative (or as-given) posix path
    line: int
    col: int
    message: str

    @property
    def key(self) -> str:
        """Stable identity for baseline matching: deliberately excludes
        line/col so unrelated edits above a grandfathered finding do not
        un-suppress it."""
        h = hashlib.sha256(
            f"{self.rule}|{self.path}|{self.message}".encode()).hexdigest()
        return h[:16]

    def as_dict(self) -> Dict[str, object]:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message, "key": self.key}

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"


@dataclasses.dataclass
class Suppression:
    finding: Finding
    via: str            # "pragma" | "baseline"
    reason: str

    def as_dict(self) -> Dict[str, object]:
        d = self.finding.as_dict()
        d["via"] = self.via
        d["reason"] = self.reason
        return d


@dataclasses.dataclass
class Pragma:
    line: int
    rules: Tuple[str, ...]
    reason: str
    col: int = 0


class SourceFile:
    """One parsed source file handed to every rule: path, text, AST,
    and the pragma table. Parse failures surface as findings instead of
    crashing the pass (a module that cannot parse cannot be checked —
    and cannot run either)."""

    def __init__(self, path: str, display_path: str, text: str):
        self.path = path
        self.display_path = display_path
        self.text = text
        self.lines = text.splitlines()
        self.tree: Optional[ast.AST] = None
        self.parse_error: Optional[str] = None
        try:
            self.tree = ast.parse(text)
        except SyntaxError as exc:
            self.parse_error = f"{exc.msg} (line {exc.lineno})"
        self.pragmas: List[Pragma] = _collect_pragmas(text)

    def finding(self, rule: str, node, message: str) -> Finding:
        line = getattr(node, "lineno", 0) if node is not None else 0
        col = getattr(node, "col_offset", 0) if node is not None else 0
        return Finding(rule=rule, path=self.display_path, line=line,
                       col=col, message=message)


def _collect_pragmas(text: str) -> List[Pragma]:
    """Pragmas ride COMMENT tokens (tokenize, not regex-over-lines, so a
    '# graftlint:' inside a string literal is never misread)."""
    out: List[Pragma] = []
    try:
        tokens = tokenize.generate_tokens(io.StringIO(text).readline)
        comments = [(t.start[0], t.start[1], t.string) for t in tokens
                    if t.type == tokenize.COMMENT]
    except (tokenize.TokenError, SyntaxError, IndentationError):
        return out
    for line, col, comment in comments:
        body = comment.lstrip("#").strip()
        if not body.startswith("graftlint:"):
            continue
        body = body[len("graftlint:"):].strip()
        if not body.startswith("disable="):
            continue
        rest = body[len("disable="):]
        # rule list runs to the first whitespace; everything after is
        # the mandatory reason
        parts = rest.split(None, 1)
        rules = tuple(r.strip() for r in parts[0].split(",") if r.strip())
        reason = parts[1].strip() if len(parts) > 1 else ""
        out.append(Pragma(line=line, rules=rules, reason=reason, col=col))
    return out


class Rule:
    """Base class. Subclasses set `name`/`description` and override
    `check_file` (per-file findings) and/or `check_project` (cross-file
    findings over the whole scanned set, e.g. config-hygiene)."""

    name: str = ""
    description: str = ""

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        return ()

    def check_project(self, files: Sequence[SourceFile]) -> Iterable[Finding]:
        return ()


# ---------------------------------------------------------------------------
# walker
# ---------------------------------------------------------------------------
def iter_python_files(paths: Sequence[str]) -> List[Tuple[str, str]]:
    """(abs_path, display_path) for every .py under `paths` (files or
    directories), skipping bytecode caches and VCS/tool state. Display
    paths stay relative to the common parent of the inputs so findings
    and baseline entries are machine-portable."""
    out: List[Tuple[str, str]] = []
    for p in paths:
        ap = os.path.abspath(p)
        base = os.path.dirname(ap)
        if os.path.isfile(ap):
            if ap.endswith(".py"):
                out.append((ap, _display_file(ap)))
            continue
        for root, dirs, names in os.walk(ap):
            dirs[:] = sorted(d for d in dirs
                             if d not in SKIP_DIRS and not d.startswith("."))
            for name in sorted(names):
                if name.endswith(".py"):
                    full = os.path.join(root, name)
                    out.append((full, _display(full, base)))
    seen = set()
    uniq = []
    for ap, disp in out:
        if ap not in seen:
            seen.add(ap)
            uniq.append((ap, disp))
    return uniq


def _display(path: str, base: str) -> str:
    try:
        rel = os.path.relpath(path, base)
    except ValueError:  # different drive (windows)
        rel = path
    return rel.replace(os.sep, "/")


def _display_file(path: str) -> str:
    """A bare FILE input must keep its directory context — path-scoped
    rules (stdout-print's `lightgbm_tpu` segment, serving-lock's
    `/serving/`) match on directory segments, and a bare basename would
    silently disable them. Use the cwd-relative path when the file is
    under the cwd (the `python -m lightgbm_tpu.analysis some/file.py`
    case), else the absolute path."""
    try:
        rel = os.path.relpath(path)
    except ValueError:  # different drive (windows)
        return path.replace(os.sep, "/")
    if rel.startswith(".."):
        return path.replace(os.sep, "/")
    return rel.replace(os.sep, "/")


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------
class BaselineError(ValueError):
    """The baseline file itself is malformed (bad JSON, missing
    reasons); surfaced as findings so CI fails loudly."""


def load_baseline(path: str) -> Tuple[List[Dict[str, str]], List[Finding]]:
    """Returns (entries, findings-about-the-baseline-itself)."""
    findings: List[Finding] = []
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except FileNotFoundError:
        return [], []
    except (OSError, json.JSONDecodeError) as exc:
        findings.append(Finding(
            rule="parse-error", path=path.replace(os.sep, "/"), line=0,
            col=0, message=f"unreadable baseline file: {exc}"))
        return [], findings
    entries = doc.get("entries", []) if isinstance(doc, dict) else []
    ok: List[Dict[str, str]] = []
    for i, e in enumerate(entries):
        if not isinstance(e, dict) or not e.get("rule") or not e.get("path"):
            findings.append(Finding(
                rule="parse-error", path=path.replace(os.sep, "/"),
                line=0, col=0,
                message=f"baseline entry {i} needs 'rule' and 'path'"))
            continue
        if not str(e.get("reason", "")).strip():
            findings.append(Finding(
                rule="baseline-missing-reason",
                path=path.replace(os.sep, "/"), line=0, col=0,
                message="baseline entry %d (%s @ %s) has no written "
                        "justification — every grandfathered finding "
                        "must say WHY it is allowed to stand"
                        % (i, e.get("rule"), e.get("path"))))
            continue
        ok.append(e)
    return ok, findings


def _baseline_matches(entry: Dict[str, str], finding: Finding) -> bool:
    if entry.get("rule") != finding.rule:
        return False
    if entry.get("path") != finding.path:
        return False
    if "key" in entry:
        return str(entry["key"]) == finding.key
    return str(entry.get("message", "")) == finding.message


# ---------------------------------------------------------------------------
# engine
# ---------------------------------------------------------------------------
@dataclasses.dataclass
class Report:
    paths: List[str]
    files_scanned: int
    findings: List[Finding]              # unsuppressed
    suppressions: List[Suppression]
    rule_counts: Dict[str, Dict[str, int]]
    baseline_path: Optional[str]
    baseline_entries: int
    stale_baseline: List[Dict[str, str]]

    @property
    def exit_code(self) -> int:
        return 1 if self.findings else 0

    def as_dict(self) -> Dict[str, object]:
        return {
            "schema": SCHEMA,
            "paths": list(self.paths),
            "files_scanned": self.files_scanned,
            "rules": self.rule_counts,
            "findings": [f.as_dict() for f in self.findings],
            "suppressions": [s.as_dict() for s in self.suppressions],
            "baseline": {
                "path": self.baseline_path,
                "entries": self.baseline_entries,
                "stale": list(self.stale_baseline),
            },
            "exit_code": self.exit_code,
        }


def run(paths: Sequence[str], rules: Optional[Sequence[Rule]] = None,
        rule_names: Optional[Sequence[str]] = None,
        baseline_path: Optional[str] = None) -> Report:
    """Run the pass. `rules` overrides the registry (tests); otherwise
    `rule_names` selects from it (None = all)."""
    if rules is None:
        from .rules import all_rules
        rules = all_rules(rule_names)
    known = {r.name for r in rules} | set(PRAGMA_RULES)
    # a pragma naming a REGISTERED rule stays valid when only a subset
    # runs (conftest's fail-fast stdout gate must not flag suppressions
    # aimed at the full tier-1 pass); truly unknown names still fail
    try:
        from .rules import RULE_CLASSES
        known |= {cls.name for cls in RULE_CLASSES}
    except ImportError:  # pragma: no cover - registry always importable
        pass

    file_pairs = iter_python_files(paths)
    files: List[SourceFile] = []
    raw: List[Finding] = []
    for abs_path, disp in file_pairs:
        try:
            with open(abs_path, encoding="utf-8", errors="replace") as fh:
                text = fh.read()
        except OSError as exc:
            raw.append(Finding(rule="parse-error", path=disp, line=0,
                               col=0, message=f"unreadable: {exc}"))
            continue
        src = SourceFile(abs_path, disp, text)
        files.append(src)
        if src.parse_error is not None:
            raw.append(src.finding(
                "parse-error", None,
                f"module does not parse: {src.parse_error}"))

    # pragma hygiene findings, independent of whether the pragma ends up
    # suppressing anything — a malformed suppression must not lurk
    for src in files:
        for pragma in src.pragmas:
            if not pragma.reason:
                raw.append(Finding(
                    rule="pragma-missing-reason", path=src.display_path,
                    line=pragma.line, col=pragma.col,
                    message="graftlint pragma has no reason — write WHY "
                            "the rule does not apply here (format: "
                            "# graftlint: disable=<rule>  <reason>)"))
            for r in pragma.rules:
                if r not in known:
                    raw.append(Finding(
                        rule="pragma-unknown-rule", path=src.display_path,
                        line=pragma.line, col=pragma.col,
                        message=f"pragma names unknown rule {r!r} "
                                f"(known: {', '.join(sorted(known))})"))

    for src in files:
        if src.tree is None:
            continue
        for rule in rules:
            for f in rule.check_file(src):
                raw.append(f)
    for rule in rules:
        for f in rule.check_project(files):
            raw.append(f)

    baseline_entries: List[Dict[str, str]] = []
    if baseline_path:
        baseline_entries, bfindings = load_baseline(baseline_path)
        raw.extend(bfindings)

    pragma_by_file = {src.display_path: src.pragmas for src in files}
    findings: List[Finding] = []
    suppressions: List[Suppression] = []
    matched_entries: set = set()
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        sup = _pragma_for(pragma_by_file.get(f.path, ()), f)
        if sup is not None:
            suppressions.append(Suppression(f, "pragma", sup.reason))
            continue
        matched = None
        for i, entry in enumerate(baseline_entries):
            if _baseline_matches(entry, f):
                matched = (i, entry)
                break
        if matched is not None:
            matched_entries.add(matched[0])
            suppressions.append(
                Suppression(f, "baseline", str(matched[1]["reason"])))
            continue
        findings.append(f)

    stale = [e for i, e in enumerate(baseline_entries)
             if i not in matched_entries]

    counts: Dict[str, Dict[str, int]] = {}
    for r in rules:
        counts[r.name] = {"description": r.description,  # type: ignore
                          "findings": 0, "suppressed": 0}
    for name in PRAGMA_RULES:
        counts.setdefault(name, {"description": "engine hygiene",
                                 "findings": 0, "suppressed": 0})
    for f in findings:
        counts.setdefault(f.rule, {"findings": 0, "suppressed": 0})
        counts[f.rule]["findings"] += 1
    for s in suppressions:
        counts.setdefault(s.finding.rule, {"findings": 0, "suppressed": 0})
        counts[s.finding.rule]["suppressed"] += 1

    return Report(paths=[str(p) for p in paths], files_scanned=len(files),
                  findings=findings, suppressions=suppressions,
                  rule_counts=counts, baseline_path=baseline_path,
                  baseline_entries=len(baseline_entries),
                  stale_baseline=stale)


def _pragma_for(pragmas: Sequence[Pragma], f: Finding) -> Optional[Pragma]:
    """A pragma suppresses a finding on its own line only, and only
    with a written reason (a reasonless pragma suppresses nothing — it
    is itself a finding). Pragma-hygiene findings are never
    self-suppressible."""
    if f.rule in ("pragma-missing-reason", "pragma-unknown-rule"):
        return None
    for p in pragmas:
        if p.line == f.line and f.rule in p.rules and p.reason:
            return p
    return None
