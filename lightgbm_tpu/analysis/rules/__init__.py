"""graftlint rule registry.

Each rule targets a bug class this repo has actually shipped (see the
per-rule docstrings for the incident that motivated it). Adding a rule:
subclass `core.Rule`, give it a kebab-case `name` + one-line
`description`, implement `check_file` (per parsed module) and/or
`check_project` (cross-file), register it here, and pin its semantics
with positive/negative fixtures under tests/analysis_fixtures/<name>/.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from ..core import Rule
from .padded_rng import PaddedRngRule
from .collectives import UnguardedCollectiveRule
from .host_sync import TracedHostSyncRule
from .config_hygiene import ConfigHygieneRule
from .serving_locks import FutureGuardRule, ServingLockRule
from .stdout_print import StdoutPrintRule
from .export_hygiene import ExportImportHygieneRule
from .durable_write import DurableWriteRule

RULE_CLASSES = (
    PaddedRngRule,
    UnguardedCollectiveRule,
    TracedHostSyncRule,
    ConfigHygieneRule,
    ServingLockRule,
    FutureGuardRule,
    StdoutPrintRule,
    ExportImportHygieneRule,
    DurableWriteRule,
)


def all_rules(names: Optional[Sequence[str]] = None) -> List[Rule]:
    rules = [cls() for cls in RULE_CLASSES]
    if names is None:
        return rules
    known = {r.name for r in rules}
    unknown = set(names) - known
    if unknown:
        raise ValueError("unknown rule(s): %s (known: %s)"
                         % (", ".join(sorted(unknown)),
                            ", ".join(sorted(known))))
    return [r for r in rules if r.name in set(names)]
