"""Rule `padded-rng`: prefix-unstable RNG draws over padded dimensions.

The PR 11 incident class: threefry is NOT prefix-stable across output
shapes, so `jax.random.uniform(key, (n_pad,))[:n]` differs from
`jax.random.uniform(key, (n,))`. Because the pad width is a function of
the DEVICE COUNT, a draw shaped by a padded dimension silently ties the
sampled values (bagging masks, GOSS keep-sets) to the world size and
breaks cross-world-size training bit-identity — exactly the latent
bagging/GOSS bug PR 11 shipped and later had to excavate.

The invariant: draw over the REAL extent `(n,)` and pad the RESULT
(`jnp.pad(jax.random.uniform(key, (n,)), (0, n_pad - n))`), making the
sample a pure function of (seed, iteration, n) at any world size.

The invariant EXTENDS TO THE MODEL AXIS (ISSUE 14's vmapped sweep,
learner/sweep.py): per-model draws must come from per-model keys at the
serial shape `(n,)` — a `(num_models, n)` batched draw makes model k's
sample a function of the SWEEP WIDTH K, the exact way a padded draw
makes it a function of the device count, and breaks the sweep's
byte-identity-to-serial contract. Draw `(n,)` under `jax.vmap` over
per-model keys instead.

Detection: a call to a `jax.random` sampling function whose ARGUMENT
expressions mention a padded-dimension identifier — any name or
attribute with a `pad`/`padded`/`bucket` component (`n_pad`,
`rows_padded`, `bucket_rows`, ...) — or a model-axis identifier (a
`models`/`sweep` component: `num_models`, `sweep_size`, ...). Padding
the draw's RESULT is fine: the padded identifier then sits outside the
sampling call's own argument list.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Finding, Rule, SourceFile
from ..astutil import ImportTable, call_target, identifiers_in

# value-producing samplers (key plumbing like split/fold_in is exempt:
# keys are shape-independent)
SAMPLING_FNS = {
    "uniform", "normal", "bernoulli", "randint", "bits", "exponential",
    "gamma", "beta", "cauchy", "dirichlet", "gumbel", "laplace",
    "logistic", "maxwell", "multivariate_normal", "pareto", "poisson",
    "rademacher", "rayleigh", "t", "truncated_normal", "weibull_min",
    "categorical", "choice", "permutation", "shuffle", "binomial",
    "geometric", "loggamma", "orthogonal", "triangular", "wald",
}

_PAD_COMPONENTS = {"pad", "padded", "npad", "bucket", "bucketed",
                   # model-axis components (the vmapped-sweep extension):
                   # a draw shaped by the sweep width ties model k's
                   # sample to K
                   "models", "sweep", "nmodels"}


def _padded_identifier(name: str) -> bool:
    return any(part in _PAD_COMPONENTS
               for part in name.lower().split("_") if part)


class PaddedRngRule(Rule):
    name = "padded-rng"
    description = ("jax.random draw shaped by a padded dimension "
                   "(device-count-dependent sample; draw (n,) and pad "
                   "the result)")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        out: List[Finding] = []
        imports = ImportTable(src.tree)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            target = call_target(node, imports)
            if target is None:
                continue
            parts = target.split(".")
            if parts[-1] not in SAMPLING_FNS:
                continue
            # must actually be jax.random.<fn> (possibly via alias /
            # from-import), not numpy.random or a local helper
            if "jax" not in parts or "random" not in parts:
                continue
            offenders = sorted(
                ident
                for arg in list(node.args) + [kw.value
                                              for kw in node.keywords]
                for ident in identifiers_in(arg)
                if _padded_identifier(ident))
            if offenders:
                out.append(src.finding(
                    self.name, node,
                    "RNG draw %s is shaped by padded/model-axis "
                    "dimension(s) %s — threefry is not prefix-stable "
                    "across shapes, so the sample depends on the device "
                    "count (padded dims) or the sweep width (model "
                    "axis); draw the real extent (n,) per key and pad "
                    "the result (the PR 11 bagging/GOSS bug class)"
                    % (parts[-1], ", ".join(offenders))))
        return out
