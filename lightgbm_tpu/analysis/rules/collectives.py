"""Rule `unguarded-collective`: collectives outside the watchdog contract.

PR 11's contract: the jax runtime has no per-collective timeout, so a
dead or wedged peer leaves every other rank blocked FOREVER inside the
next collective. Every HOST-LEVEL collective dispatch must therefore be
armed with `watchdog.deadline(site)` — on expiry the rank dumps stacks,
writes rank-failure evidence, and exits rc 113 instead of hanging.

What the rule checks:

- `jax.experimental.multihost_utils.process_allgather(...)` — the raw
  host collective — must sit lexically inside a `with
  watchdog.deadline(...)` block (or in a function whose every in-module
  call site does; see astutil.ModuleIndex.covered_functions).
- calling a shard_map-produced function (a local name assigned from
  `shard_map(...)` / `shard_map_compat(...)` / `jax.shard_map(...)`) is
  a host-level dispatch of a program whose collectives can block on a
  peer: same deadline requirement, same interprocedural coverage (the
  learners.py idiom — `__call__` arms the deadline, `_dispatch` runs
  the shard-mapped program).
- `jax.lax.psum` / `psum_scatter` / `all_gather` / `pmax` / `pmin` /
  `pmean` / `all_to_all` / `ppermute` are DEVICE-level collectives that
  are only legal while tracing; they must appear in a traced context
  (jit/shard_map-decorated or -wrapped function, or a helper reachable
  from one through the module-local call graph). Anywhere else they are
  a host-level dispatch with no watchdog — or a bug outright.

`multihost.allgather_bytes` / `agree_on_iteration` are exempt by
design: they arm the deadline INTERNALLY (that is the module's whole
point), so call sites need no second guard.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Set

from ..core import Finding, Rule, SourceFile
from .. import astutil
from ..astutil import ModuleIndex, call_target, dotted_name

LAX_COLLECTIVES = {"psum", "psum_scatter", "all_gather", "pmax", "pmin",
                   "pmean", "all_to_all", "ppermute", "pshuffle"}
HOST_COLLECTIVES = {"process_allgather"}
SHARD_MAP_MAKERS = {"shard_map", "shard_map_compat"}

# traced-only functions the AST cannot see get jitted: ops/predict.py's
# forest kernels are wrapped via jax.jit(getattr(predict_ops, name)) in
# boosting/gbdt.py (`_forest_jit`)
KNOWN_TRACED = (
    (r"ops/predict\.py$", r"^predict_forest_"),
)


class UnguardedCollectiveRule(Rule):
    name = "unguarded-collective"
    description = ("host-level collective dispatch outside a "
                   "watchdog.deadline() guard (hangs forever on a dead "
                   "peer), or a device collective outside traced code")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        out: List[Finding] = []
        idx = ModuleIndex(src.tree, src.display_path,
                          known_traced=KNOWN_TRACED)
        is_deadline = astutil.deadline_guard(idx.imports)
        covered = idx.covered_functions(is_deadline)
        traced = idx.traced_functions()

        # local names bound to shard_map-produced callables, per
        # enclosing function (run = shard_map_compat(f, ...); run(...))
        sharded_names: Set[ast.AST] = set()  # the Assign nodes
        shard_bound: dict = {}  # (enclosing_fn, name) -> assign node
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Assign):
                continue
            if not isinstance(node.value, ast.Call):
                continue
            target_fn = call_target(node.value, idx.imports)
            if target_fn is None or \
                    target_fn.split(".")[-1] not in SHARD_MAP_MAKERS:
                continue
            encs = astutil.enclosing_functions(node, idx.parents)
            enc = encs[0] if encs else None
            for t in node.targets:
                if isinstance(t, ast.Name):
                    shard_bound[(enc, t.id)] = node
                    sharded_names.add(node)

        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            target = call_target(node, idx.imports)
            tail = target.split(".")[-1] if target else None

            if tail in HOST_COLLECTIVES:
                if not idx.guarded(node, is_deadline, covered):
                    out.append(src.finding(
                        self.name, node,
                        "%s is a host-level collective and must run "
                        "under 'with watchdog.deadline(site)': a dead "
                        "peer otherwise blocks this rank forever "
                        "(PR 11 contract)" % tail))
                continue

            if tail in LAX_COLLECTIVES and target and \
                    ("lax" in target.split(".") or
                     target.split(".")[0] == "jax"):
                encs = astutil.enclosing_functions(node, idx.parents)
                if not any(f in traced for f in encs):
                    out.append(src.finding(
                        self.name, node,
                        "jax.lax.%s outside any traced (jit/shard_map) "
                        "context: device collectives only execute under "
                        "a trace, and the host dispatch that runs them "
                        "must be watchdog-armed" % tail))
                continue

            # dispatch of a shard_map-produced callable
            if isinstance(node.func, ast.Name):
                encs = astutil.enclosing_functions(node, idx.parents)
                enc = encs[0] if encs else None
                bound = shard_bound.get((enc, node.func.id))
                if bound is not None and \
                        not idx.guarded(node, is_deadline, covered):
                    out.append(src.finding(
                        self.name, node,
                        "dispatch of shard_map-produced %r outside "
                        "'with watchdog.deadline(site)': the program's "
                        "collectives block forever on a dead peer "
                        "(PR 11 contract)" % node.func.id))
        return out
