"""Rule `stdout-print`: stdout hygiene inside the package.

Migrated from the ad-hoc AST guard that used to live in
tests/conftest.py's `pytest_sessionstart` (PR 7): no `lightgbm_tpu/`
module may write to stdout via bare `print()` — everything routes
through `log` (stderr / registered callback) or telemetry sinks, so
CLI pipelines and the bench driver's JSON-per-line stdout contract
stay parseable.

Same semantics as the conftest gate, now with pragma/baseline support:

- allowlist: `cli.py` and `__main__.py` — the CLI entry points, whose
  stdout IS the product (this covers graftlint's own CLI too);
- prints explicitly directed at `sys.stderr` are fine;
- scope: files under a `lightgbm_tpu` package directory only; scripts
  and tests own their stdout contracts.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Finding, Rule, SourceFile

ALLOWED_BASENAMES = {"cli.py", "__main__.py"}
PACKAGE_SEGMENT = "lightgbm_tpu"


class StdoutPrintRule(Rule):
    name = "stdout-print"
    description = ("bare print() to stdout inside lightgbm_tpu/ "
                   "(route through log/telemetry; cli.py and "
                   "__main__.py are allowlisted)")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        out: List[Finding] = []
        parts = src.display_path.split("/")
        if PACKAGE_SEGMENT not in parts[:-1]:
            return out
        if parts[-1] in ALLOWED_BASENAMES:
            return out
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id == "print"):
                continue
            file_kw = next((kw.value for kw in node.keywords
                            if kw.arg == "file"), None)
            if isinstance(file_kw, ast.Attribute) \
                    and file_kw.attr == "stderr":
                continue
            out.append(src.finding(
                self.name, node,
                "bare print() to stdout inside lightgbm_tpu/: route "
                "through log (stderr) or telemetry sinks so the CLI / "
                "bench JSON stdout contracts stay parseable"))
        return out
