"""Rule `durable-write`: the atomic-publish idiom lives in durable.py.

The storage-fault-tolerance PR centralized every durable write (tmp +
fsync + os.replace) behind `lightgbm_tpu/durable.py`, which adds the
retry policy, the per-stream criticality split, the ENOSPC eviction
hatch, and the fault-injection sites. A raw re-implementation anywhere
else silently escapes ALL of that: it neither retries transient EIO nor
shows up in the chaos gate, so the next disk hiccup kills a run the
durable layer would have saved.

This rule freezes the invariant: the low-level publish primitives —
`os.replace`, `os.rename`, `os.fsync`, `tempfile.mkstemp`,
`tempfile.NamedTemporaryFile` — may not be called from `lightgbm_tpu/`
modules other than `durable.py` itself. Route the write through
`durable.atomic_write_bytes/_text/_via` (critical streams) or
`durable.best_effort_write_text` (narration/liveness streams) instead.

Scope: files under a `lightgbm_tpu` package directory. Scripts and
tests own their tmp-file hygiene (harness children intentionally
exercise raw IO); plain `open(..., "w")` stays legal everywhere — user
output files are not durable state.
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Finding, Rule, SourceFile

PACKAGE_SEGMENT = "lightgbm_tpu"
EXEMPT_BASENAMES = {"durable.py"}

#: module -> attribute names whose call is a raw publish primitive
_BANNED = {
    "os": {"replace", "rename", "fsync"},
    "tempfile": {"mkstemp", "NamedTemporaryFile"},
}


class DurableWriteRule(Rule):
    name = "durable-write"
    description = ("raw atomic-publish primitives (os.replace/os.rename/"
                   "os.fsync/tempfile.mkstemp) outside durable.py "
                   "(route through the durable layer)")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        out: List[Finding] = []
        parts = src.display_path.split("/")
        if PACKAGE_SEGMENT not in parts[:-1]:
            return out
        if parts[-1] in EXEMPT_BASENAMES:
            return out
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)):
                continue
            banned = _BANNED.get(func.value.id)
            if banned is None or func.attr not in banned:
                continue
            out.append(src.finding(
                self.name, node,
                "raw %s.%s inside lightgbm_tpu/: durable-state publishes "
                "must route through durable.atomic_write_* (retry policy, "
                "criticality split, ENOSPC hatch and fault-injection "
                "sites all live there)" % (func.value.id, func.attr)))
        return out
