"""Rule `traced-host-sync`: host synchronization on traced values.

The retrace/transfer hazard class the telemetry compile observer
(PR 7) can only catch AT RUNTIME, moved to the source level: inside a
jitted pass function, forcing a traced value to a Python scalar either
fails outright under trace (`.item()`, `float()`, `bool()`,
`np.asarray`) or — worse — silently works on concrete values in
op-by-op debugging and then breaks or retraces in production. Implicit
`if array:` truthiness has the same failure mode and additionally
makes Python control flow depend on device data.

Scope (documented, pinned by fixtures):

- traced contexts are classified by astutil.ModuleIndex: jit/shard_map
  decorated or wrapped functions, their lexically nested helpers, and
  module-local callees; ops/predict.py's `predict_forest_*` kernels
  (the serving dispatch path's compute, jitted via gbdt._forest_jit's
  getattr) are known-traced by configuration.
- `.item()` and `jax.device_get` / `np.asarray` / `np.array` /
  `float|int|bool` host conversions are flagged when applied to a bare
  parameter of a DIRECTLY-traced function that is not listed in its
  `static_argnames` (static params are Python values — converting them
  at trace time is legitimate constant folding, which is why derived
  locals are out of scope for the conversions: too many false constants).
- `.item()` is additionally flagged anywhere in a traced context — on
  any expression: there is no legitimate trace-time `.item()`.
- `if`/`while` on the BARE truthiness of a non-static parameter of a
  directly-traced function (`if mask:`) is flagged; `is None` /
  comparison tests stay legal (trace-time Python checks on optional
  arguments are idiomatic, e.g. grow_tree's `n_valid is None`).
"""
from __future__ import annotations

import ast
from typing import Iterable, List

from ..core import Finding, Rule, SourceFile
from .. import astutil
from ..astutil import ModuleIndex, call_target

from .collectives import KNOWN_TRACED

_CONVERTERS = {"float", "int", "bool"}
_HOST_FETCHERS = {"asarray", "array", "device_get"}


class TracedHostSyncRule(Rule):
    name = "traced-host-sync"
    description = ("host sync on a traced value inside a jitted pass "
                   "function (.item()/float()/np.asarray/if-array): "
                   "trace failure or silent retrace/transfer hazard")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        out: List[Finding] = []
        idx = ModuleIndex(src.tree, src.display_path,
                          known_traced=KNOWN_TRACED)
        traced = idx.traced_functions()
        if not traced:
            return out

        for fn in idx.functions:
            if fn not in traced:
                continue
            directly = idx.directly_traced(fn)
            params = idx.traced_params(fn) if directly else set()
            # shallow walk: nested defs are visited as their own traced
            # functions with their own parameter sets
            for node in astutil.walk_shallow(fn):
                if isinstance(node, ast.Call):
                    out.extend(self._check_call(src, idx, node, params))
                elif isinstance(node, (ast.If, ast.While)) and directly:
                    test = node.test
                    neg = isinstance(test, ast.UnaryOp) and \
                        isinstance(test.op, ast.Not)
                    probe = test.operand if neg else test
                    if isinstance(probe, ast.Name) and probe.id in params:
                        out.append(src.finding(
                            self.name, test,
                            "implicit truthiness of traced parameter "
                            "%r in a jitted function: Python control "
                            "flow on device data fails under trace "
                            "(use jnp.where / lax.cond, or mark the "
                            "argument static)" % probe.id))
        return out

    def _check_call(self, src: SourceFile, idx: ModuleIndex,
                    node: ast.Call, params) -> List[Finding]:
        out: List[Finding] = []
        # x.item() — no legitimate trace-time use
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "item" and not node.args:
            out.append(src.finding(
                self.name, node,
                ".item() inside a traced context forces a device->host "
                "sync and fails under jit; return the array and fetch "
                "it at the dispatch layer"))
            return out
        target = call_target(node, idx.imports)
        if target is None or not node.args:
            return out
        arg0 = node.args[0]
        if not (isinstance(arg0, ast.Name) and arg0.id in params):
            return out
        parts = target.split(".")
        if target in _CONVERTERS:
            out.append(src.finding(
                self.name, node,
                "%s(%s) on a traced parameter of a jitted function: "
                "concretization fails under trace (jnp ops keep it on "
                "device; static_argnames makes it a Python value)"
                % (target, arg0.id)))
        elif parts[-1] in _HOST_FETCHERS and \
                (parts[0] in ("numpy", "onp")
                 or target == "jax.device_get"):
            # jax.numpy.asarray/array are DEVICE ops and legal under
            # trace; only real numpy (host) and device_get sync
            out.append(src.finding(
                self.name, node,
                "%s on traced parameter %r inside a jitted function "
                "forces a host transfer (use jnp.asarray, or hoist the "
                "conversion to the dispatch layer)" % (target, arg0.id)))
        return out
