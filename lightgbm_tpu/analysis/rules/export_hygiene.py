"""Rule `export-import-hygiene`: the serving replica's import boundary.

The whole point of `lightgbm_tpu/export/` is that a serving replica
loads a forest artifact WITHOUT the training stack — the export smoke
gate proves it by import-blocking `boosting/`, `learner/`, `ingest/`,
and `parallel/` in a child process. One innocent-looking import (a
helper moved, a type hint "just for clarity") re-couples the replica to
the trainer and the gate only catches it at bench time. This rule turns
the boundary into a static invariant: any module under
`lightgbm_tpu/export/` whose imports (module-level OR function-local —
a lazy import still executes on the serving path) resolve into a
trainer package is a finding. The allowed surface is `ops/`, `serving/`,
`export/` itself, and the leaf utility modules (log, config, telemetry,
checkpoint, testing).

Front-door modules (`basic`, `engine`, `cli`, `sklearn`, `dataset`,
`objectives`, `shap`) are banned too: each imports a trainer package
transitively, so allowing them would make the direct ban decorative.
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from ..core import Finding, Rule, SourceFile

EXPORT_SEGMENT = "/export/"
_PACKAGE = "lightgbm_tpu"

#: trainer packages the ISSUE names, plus the front-door modules that
#: transitively import them
_BANNED = {
    "boosting": "the boosting trainer",
    "learner": "the tree learner",
    "ingest": "the streaming ingest stack",
    "parallel": "the distributed-training stack",
    "basic": "Booster/Dataset (imports boosting + learner)",
    "engine": "train()/cv() (imports the full trainer)",
    "cli": "the CLI front end (imports the full trainer)",
    "sklearn": "the sklearn wrappers (import engine)",
    "dataset": "the in-memory dataset builder (trainer-side)",
    "objectives": "objective functions (trainer-side; artifacts carry "
                  "the transform spec instead)",
    "shap": "TreeSHAP (walks trainer-side tree objects)",
}


def _in_scope(src: SourceFile) -> bool:
    return EXPORT_SEGMENT in "/" + src.display_path


def _export_pkg_depth(display_path: str) -> int:
    """How many package levels `display_path` sits below the package
    root (export/writer.py -> 2), for resolving relative imports.
    Anchored on the export/ segment so fixture trees that lack the
    lightgbm_tpu/ prefix resolve the same way as the real package."""
    tail = ("/" + display_path).rsplit(EXPORT_SEGMENT, 1)[-1]
    return 1 + len(tail.split("/"))


class ExportImportHygieneRule(Rule):
    name = "export-import-hygiene"
    description = ("a module under lightgbm_tpu/export/ imports the "
                   "training stack (boosting/, learner/, ingest/, "
                   "parallel/ or a front door to them): serving "
                   "replicas must load artifacts training-stack-free")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        out: List[Finding] = []
        if not _in_scope(src):
            return out
        depth = _export_pkg_depth(src.display_path)
        for node in ast.walk(src.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    hit = self._banned_module(alias.name)
                    if hit:
                        out.append(self._finding(src, node, alias.name,
                                                 hit))
            elif isinstance(node, ast.ImportFrom):
                module = self._absolute_module(node, depth)
                if module is None:
                    continue
                hit = self._banned_module(module)
                if hit:
                    out.append(self._finding(src, node, module, hit))
                    continue
                # `from lightgbm_tpu import boosting` / `from .. import
                # engine`: the banned name is the imported attribute
                if module == _PACKAGE:
                    for alias in node.names:
                        sub = "%s.%s" % (_PACKAGE, alias.name)
                        hit = self._banned_module(sub)
                        if hit:
                            out.append(self._finding(src, node, sub, hit))
        return out

    @staticmethod
    def _absolute_module(node: ast.ImportFrom, depth: int) -> Optional[str]:
        """Resolve a (possibly relative) ImportFrom to a dotted module
        path rooted at the package, or None for foreign imports."""
        if node.level == 0:
            return node.module
        # from . / .. / ... inside lightgbm_tpu/export/<file>: level 1 =
        # the export package, level 2 = lightgbm_tpu, deeper = outside
        up = depth - node.level
        if up < 0:
            return None
        parts = [_PACKAGE] + (["export"] if up >= 1 else [])
        if node.module:
            parts.append(node.module)
        return ".".join(parts)

    @staticmethod
    def _banned_module(module: Optional[str]) -> Optional[str]:
        if not module:
            return None
        parts = module.split(".")
        if parts[0] != _PACKAGE or len(parts) < 2:
            return None
        return _BANNED.get(parts[1])

    def _finding(self, src: SourceFile, node: ast.AST, module: str,
                 why: str) -> Finding:
        return src.finding(
            self.name, node,
            "export/ imports %s — %s. Serving replicas load artifacts "
            "with the training stack absent (the export smoke gate "
            "import-blocks it); keep export/ to ops/, serving/, "
            "export/ and leaf utility modules" % (module, why))
