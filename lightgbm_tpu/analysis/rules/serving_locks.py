"""Rules `serving-lock` and `future-guard`: the PR 12 review-fix classes.

`serving-lock` — check-then-act races on shared serving state. PR 12's
review found K racing `predict()` calls could exceed the in-flight cap
by K-1 because the check and the increment took the lock separately.
The source-level invariant: inside `lightgbm_tpu/serving/`, any
READ-MODIFY-WRITE of shared instance state — an augmented assignment on
an attribute (`self.inflight += 1`, `entry.requests += 1`) or a
subscript of an attribute (`self.counts[k] += 1`), or a plain
assignment whose right-hand side reads the attribute it writes — must
execute under a lock `with` (`with self._lock:` / `with self._cv:`),
either lexically or inside a function whose every in-module call site
holds the lock. The same applies to an `if` that tests an attribute
and writes that attribute in its body (the literal check-then-act
shape). `__init__`/`__new__` are exempt: no concurrent reader can hold
the object yet.

`future-guard` — future resolution without the InvalidStateError
guard. A client may `cancel()` a queued future (the request-timeout
pattern) or a shutdown sweep may have failed it already; a bare
`set_result`/`set_exception` then RAISES and kills the batcher thread
that every other queued request depends on. Resolution must go through
a try/except InvalidStateError (the predictor's `_resolve`/`_fail`
helpers).
"""
from __future__ import annotations

import ast
from typing import Iterable, List, Optional

from ..core import Finding, Rule, SourceFile
from .. import astutil
from ..astutil import ModuleIndex

SERVING_SEGMENT = "/serving/"
_INIT_EXEMPT = {"__init__", "__new__", "__init_subclass__"}


def _in_scope(src: SourceFile) -> bool:
    return SERVING_SEGMENT in "/" + src.display_path


def _attr_chain(node: ast.AST) -> Optional[str]:
    """'self.counts' for Attribute, 'self.counts[]' for Subscript of an
    attribute — a stable identity for the shared-state slot."""
    if isinstance(node, ast.Subscript):
        base = astutil.dotted_name(node.value)
        return base + "[]" if base else None
    return astutil.dotted_name(node)


def _is_shared(chain: Optional[str]) -> bool:
    """Only attribute state can be shared across threads; bare locals
    never are."""
    return chain is not None and "." in chain


class ServingLockRule(Rule):
    name = "serving-lock"
    description = ("check-then-act / read-modify-write on shared "
                   "serving state outside a lock hold (racy admission "
                   "counters, the PR 12 cap-overrun class)")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        out: List[Finding] = []
        if not _in_scope(src):
            return out
        idx = ModuleIndex(src.tree, src.display_path)
        covered = idx.covered_functions(astutil.lock_guard)

        def guarded(node: ast.AST) -> bool:
            return idx.guarded(node, astutil.lock_guard, covered)

        def exempt(node: ast.AST) -> bool:
            encs = astutil.enclosing_functions(node, idx.parents)
            return bool(encs) and encs[0].name in _INIT_EXEMPT

        for node in ast.walk(src.tree):
            if isinstance(node, ast.AugAssign):
                chain = _attr_chain(node.target)
                if _is_shared(chain) and not exempt(node) \
                        and not guarded(node):
                    out.append(src.finding(
                        self.name, node,
                        "read-modify-write of shared %s outside a lock "
                        "hold: concurrent requests lose updates or "
                        "overrun caps (take self._lock around check "
                        "AND act)" % chain))
            elif isinstance(node, ast.Assign):
                for target in node.targets:
                    chain = _attr_chain(target)
                    if not _is_shared(chain) or exempt(node) \
                            or guarded(node):
                        continue
                    reads = {_attr_chain(n) for n in ast.walk(node.value)
                             if isinstance(n, (ast.Attribute,
                                               ast.Subscript))}
                    if chain in reads:
                        out.append(src.finding(
                            self.name, node,
                            "read-modify-write of shared %s outside a "
                            "lock hold (value reads the slot it "
                            "writes)" % chain))
            elif isinstance(node, ast.If):
                if exempt(node) or guarded(node):
                    continue
                tested = {
                    _attr_chain(n) for n in ast.walk(node.test)
                    if isinstance(n, (ast.Attribute, ast.Subscript))}
                tested = {t for t in tested if _is_shared(t)}
                if not tested:
                    continue
                written = set()
                for stmt in node.body:
                    for n in ast.walk(stmt):
                        if isinstance(n, ast.AugAssign):
                            written.add(_attr_chain(n.target))
                        elif isinstance(n, ast.Assign):
                            written.update(_attr_chain(t)
                                           for t in n.targets)
                hits = sorted(x for x in tested & written if x)
                if hits:
                    out.append(src.finding(
                        self.name, node,
                        "check-then-act on shared %s outside a lock "
                        "hold: the state can change between the test "
                        "and the write" % ", ".join(hits)))
        return out


class FutureGuardRule(Rule):
    name = "future-guard"
    description = ("fut.set_result/set_exception without the "
                   "InvalidStateError guard: a raced cancel()/shutdown "
                   "sweep kills the batcher thread")

    def check_file(self, src: SourceFile) -> Iterable[Finding]:
        out: List[Finding] = []
        if not _in_scope(src):
            return out
        idx = ModuleIndex(src.tree, src.display_path)
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(node.func, ast.Attribute):
                continue
            if node.func.attr not in ("set_result", "set_exception"):
                continue
            if self._guarded(node, idx):
                continue
            out.append(src.finding(
                self.name, node,
                "%s() without an InvalidStateError guard: a future the "
                "client cancel()ed (or a shutdown sweep already "
                "failed) raises here and kills the resolving thread — "
                "use the _resolve/_fail helpers or wrap in "
                "try/except InvalidStateError" % node.func.attr))
        return out

    @staticmethod
    def _guarded(node: ast.AST, idx: ModuleIndex) -> bool:
        """Lexically inside the BODY of a try whose handlers name
        InvalidStateError (alone or in a tuple) — a resolution in the
        handler/else/finally suites is not protected by it."""
        child = node
        cur = idx.parents.get(node)
        while cur is not None and not isinstance(cur, astutil.FuncNode):
            if isinstance(cur, ast.Try) and child in cur.body:
                handler_types = []
                for handler in cur.handlers:
                    if handler.type is None:
                        continue
                    if isinstance(handler.type, ast.Tuple):
                        handler_types.extend(handler.type.elts)
                    else:
                        handler_types.append(handler.type)
                names = {astutil.dotted_name(t) for t in handler_types}
                if any(n and n.split(".")[-1] == "InvalidStateError"
                       for n in names):
                    return True
            child = cur
            cur = idx.parents.get(cur)
        return False
