"""Rule `config-hygiene`: the tpu_* param / docs / fingerprint triangle.

Every `tpu_*` parameter lives in three places that have historically
been hand-synchronized and have repeatedly drifted: the dataclass
declaration in `config.py`, the generated `docs/Parameters.md`, and
the checkpoint-fingerprint classification in `checkpoint.py` (a param
either participates in the resume fingerprint or is explicitly listed
as excluded — PR 3/8/11/12 each re-discovered a missing exclusion the
hard way). This rule makes the triangle machine-checked:

for every `tpu_*` field of a dataclass in a scanned `config.py`:

1. **validation** — the field must have an entry in config.py's
   `TPU_PARAM_SPEC` table (the declarative bounds/choices table
   `check_param_conflict` applies), so no tpu_* knob ships without a
   validation decision; stale spec entries naming no field are errors
   too.
2. **docs** — the field name must appear in `docs/Parameters.md`
   (sibling `docs/` of the package directory), i.e. the doc was
   regenerated after the param landed.
3. **fingerprint** — the field must appear in EXACTLY ONE of
   checkpoint.py's `_FINGERPRINT_EXCLUDE` (resume may legitimately
   differ) or `_FINGERPRINT_INCLUDED` (participates in the fingerprint;
   resume refuses on mismatch). Unclassified and double-classified are
   both errors, as are stale tpu_* names in either list.

Scope: fires only when a scanned file is a `config.py` declaring
dataclass fields named `tpu_*` — fixture trees mirror that layout.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..core import Finding, Rule, SourceFile

SPEC_TABLE = "TPU_PARAM_SPEC"
EXCLUDE_SET = "_FINGERPRINT_EXCLUDE"
INCLUDE_SET = "_FINGERPRINT_INCLUDED"


def _dataclass_tpu_fields(tree: ast.AST) -> Dict[str, ast.AST]:
    """tpu_* AnnAssign fields of @dataclass classes: name -> node."""
    out: Dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        deco_names = set()
        for d in node.decorator_list:
            target = d.func if isinstance(d, ast.Call) else d
            if isinstance(target, ast.Name):
                deco_names.add(target.id)
            elif isinstance(target, ast.Attribute):
                deco_names.add(target.attr)
        if "dataclass" not in deco_names:
            continue
        for stmt in node.body:
            if isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name) and \
                    stmt.target.id.startswith("tpu_"):
                out[stmt.target.id] = stmt
    return out


def _string_collection(tree: ast.AST, var_name: str) \
        -> Optional[Tuple[Set[str], ast.AST]]:
    """String elements of a module-level set/tuple/list/dict-keys
    assignment named `var_name`."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == var_name
                   for t in node.targets):
            continue
        value = node.value
        elems: Sequence[ast.AST]
        if isinstance(value, (ast.Set, ast.Tuple, ast.List)):
            elems = value.elts
        elif isinstance(value, ast.Dict):
            elems = [k for k in value.keys if k is not None]
        else:
            return set(), node
        return ({e.value for e in elems
                 if isinstance(e, ast.Constant)
                 and isinstance(e.value, str)}, node)
    return None


class ConfigHygieneRule(Rule):
    name = "config-hygiene"
    description = ("tpu_* param drift across config.py validation "
                   "spec, docs/Parameters.md, and checkpoint.py "
                   "fingerprint classification")

    def check_project(self, files: Sequence[SourceFile]) \
            -> Iterable[Finding]:
        out: List[Finding] = []
        for src in files:
            if src.tree is None or \
                    os.path.basename(src.path) != "config.py":
                continue
            fields = _dataclass_tpu_fields(src.tree)
            if not fields:
                continue
            out.extend(self._check_triangle(src, fields, files))
        return out

    def _check_triangle(self, cfg: SourceFile,
                        fields: Dict[str, ast.AST],
                        files: Sequence[SourceFile]) -> List[Finding]:
        out: List[Finding] = []
        pkg_dir = os.path.dirname(cfg.path)

        # 1. validation spec table in config.py itself
        spec = _string_collection(cfg.tree, SPEC_TABLE)
        if spec is None:
            out.append(cfg.finding(
                self.name, None,
                "config.py declares tpu_* params but has no %s table: "
                "every tpu_* knob needs a declarative validation entry "
                "(bounds, choices, or an explicit freeform kind)"
                % SPEC_TABLE))
            spec_names: Set[str] = set(fields)  # don't cascade
        else:
            spec_names = spec[0]
            for name, node in sorted(fields.items()):
                if name not in spec_names:
                    out.append(cfg.finding(
                        self.name, node,
                        "%s has no %s entry: declare its validation "
                        "(bounds/choices/kind) so check_param_conflict "
                        "enforces it" % (name, SPEC_TABLE)))
            for name in sorted(spec_names - set(fields)):
                if name.startswith("tpu_"):
                    out.append(cfg.finding(
                        self.name, spec[1],
                        "%s entry %r names no declared tpu_* field "
                        "(stale spec row)" % (SPEC_TABLE, name)))

        # 2. docs/Parameters.md regenerated with every param present
        doc_path = os.path.join(os.path.dirname(pkg_dir), "docs",
                                "Parameters.md")
        try:
            with open(doc_path, encoding="utf-8") as fh:
                doc_text = fh.read()
        except OSError:
            doc_text = None
            out.append(cfg.finding(
                self.name, None,
                "docs/Parameters.md not found next to the package — "
                "regenerate it (python scripts/gen_params_doc.py); "
                "tpu_* params must be documented"))
        if doc_text is not None:
            for name, node in sorted(fields.items()):
                # word-bounded match: a param that is a PREFIX of
                # another documented param (tpu_predict_quantize vs
                # ..._tol) must still be flagged when its own row is
                # missing
                if not re.search(r"(?<![\w])%s(?![\w])"
                                 % re.escape(name), doc_text):
                    out.append(cfg.finding(
                        self.name, node,
                        "%s is not documented in docs/Parameters.md — "
                        "regenerate it (python scripts/"
                        "gen_params_doc.py)" % name))

        # 3. fingerprint classification in sibling checkpoint.py
        ckpt = next((f for f in files
                     if os.path.dirname(f.path) == pkg_dir
                     and os.path.basename(f.path) == "checkpoint.py"
                     and f.tree is not None), None)
        if ckpt is None:
            out.append(cfg.finding(
                self.name, None,
                "no checkpoint.py alongside config.py in the scanned "
                "set: tpu_* params cannot be fingerprint-classified"))
            return out
        excl = _string_collection(ckpt.tree, EXCLUDE_SET)
        incl = _string_collection(ckpt.tree, INCLUDE_SET)
        excl_names = excl[0] if excl else set()
        incl_names = incl[0] if incl else set()
        if excl is None:
            out.append(ckpt.finding(
                self.name, None,
                "checkpoint.py has no %s set" % EXCLUDE_SET))
        if incl is None:
            out.append(ckpt.finding(
                self.name, None,
                "checkpoint.py has no %s classification (params that "
                "deliberately participate in the resume fingerprint)"
                % INCLUDE_SET))
        for name, node in sorted(fields.items()):
            in_e, in_i = name in excl_names, name in incl_names
            if in_e and in_i:
                out.append(cfg.finding(
                    self.name, node,
                    "%s is classified BOTH fingerprint-included and "
                    "excluded in checkpoint.py — pick one" % name))
            elif not in_e and not in_i and excl is not None \
                    and incl is not None:
                out.append(cfg.finding(
                    self.name, node,
                    "%s has no checkpoint-fingerprint classification: "
                    "add it to %s (resume may legitimately differ) or "
                    "%s (mismatch must refuse resume) in checkpoint.py"
                    % (name, EXCLUDE_SET, INCLUDE_SET)))
        for name in sorted((excl_names | incl_names) - set(fields)):
            if name.startswith("tpu_") and name not in fields:
                node = (excl[1] if excl and name in excl_names
                        else incl[1] if incl else None)
                out.append(ckpt.finding(
                    self.name, node,
                    "fingerprint classification names %r but config.py "
                    "declares no such tpu_* field (stale entry)" % name))
        return out
