"""Shared AST machinery for graftlint rules.

Three project-specific analyses several rules need:

- **dotted names** — resolve `jax.lax.psum` / `watchdog.deadline` style
  call targets to a dotted string, honoring per-module import aliases
  (``import jax.random as jr`` / ``from jax import random``).
- **traced contexts** — which functions' bodies execute under a jax
  trace. Seeds: functions decorated with ``jax.jit`` (directly or via
  ``functools.partial``) or ``shard_map``; functions passed by name to
  ``jax.jit(...)`` / ``shard_map(...)`` / ``shard_map_compat(...)``;
  plus rule-configured known-traced name patterns (for getattr-style
  wrapping the AST cannot see, e.g. ops/predict.py's forest kernels
  jitted through ``gbdt._forest_jit``). Tracedness propagates through
  the module-local call graph and lexical nesting: a helper called from
  a traced function runs at trace time and receives tracers.
- **guard coverage** — which statements run under a given ``with``
  guard (``watchdog.deadline(...)`` for collectives, ``self._lock`` for
  serving counters), including one-hop interprocedural coverage: a
  function counts as covered when it has in-module call sites and EVERY
  one of them is inside the guard (fixed point), which is exactly the
  ``__call__``-arms-the-deadline-then-calls-``_dispatch`` idiom in
  parallel/learners.py.

All of it is per-module and syntactic: this is a lint, not a verifier —
the rules document their scope and the fixture corpus pins it.
"""
from __future__ import annotations

import ast
import re
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Set, Tuple

FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)


def walk_shallow(fn: ast.AST) -> Iterable[ast.AST]:
    """Nodes lexically belonging to `fn`'s own body: descends through
    everything EXCEPT nested function defs (their bodies run in their
    own scope and are visited as their own functions). Lambda bodies
    stay included — they are not tracked as separate functions."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, FuncNode):
            # still yield the nested def's decorators/defaults (they
            # evaluate in the enclosing scope), but not its body
            stack.extend(node.decorator_list)
            stack.extend(d for d in node.args.defaults if d is not None)
            stack.extend(d for d in (node.args.kw_defaults or [])
                         if d is not None)
            continue
        stack.extend(ast.iter_child_nodes(node))


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    out: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            out[child] = node
    return out


def enclosing_functions(node: ast.AST,
                        parents: Dict[ast.AST, ast.AST]) -> List[ast.AST]:
    """Innermost-first chain of enclosing function defs."""
    out: List[ast.AST] = []
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, FuncNode):
            out.append(cur)
        cur = parents.get(cur)
    return out


# ---------------------------------------------------------------------------
# imports and dotted names
# ---------------------------------------------------------------------------
class ImportTable:
    """local name -> dotted module/object path, from this module's
    imports. `import jax.random as jr` maps jr -> jax.random;
    `from jax import random` maps random -> jax.random;
    `from jax.random import uniform` maps uniform -> jax.random.uniform."""

    def __init__(self, tree: ast.AST):
        self.names: Dict[str, str] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    local = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    self.names[local] = target
            elif isinstance(node, ast.ImportFrom) and node.module \
                    and node.level == 0:
                for alias in node.names:
                    local = alias.asname or alias.name
                    self.names[local] = f"{node.module}.{alias.name}"

    def resolve(self, dotted: str) -> str:
        """Rewrite the first component through the import table:
        jr.uniform -> jax.random.uniform."""
        head, _, rest = dotted.partition(".")
        base = self.names.get(head)
        if base is None:
            return dotted
        return f"{base}.{rest}" if rest else base


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return None


def call_target(call: ast.Call,
                imports: Optional[ImportTable] = None) -> Optional[str]:
    name = dotted_name(call.func)
    if name is None:
        return None
    return imports.resolve(name) if imports is not None else name


def identifiers_in(node: ast.AST) -> Set[str]:
    """Every Name id and Attribute attr appearing inside `node`."""
    out: Set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            out.add(n.id)
        elif isinstance(n, ast.Attribute):
            out.add(n.attr)
    return out


# ---------------------------------------------------------------------------
# traced-context classification
# ---------------------------------------------------------------------------
_JIT_NAMES = {"jax.jit", "jit", "jax.pjit", "pjit",
              "jax.experimental.pjit.pjit"}
_SHARD_MAP_NAMES = {"jax.shard_map", "shard_map", "shard_map_compat",
                    "jax.experimental.shard_map.shard_map"}


def _is_jit_expr(expr: ast.AST, imports: ImportTable) -> bool:
    """Does `expr` denote jit/shard_map — directly, or as
    functools.partial(jax.jit, ...)?"""
    name = dotted_name(expr)
    if name is not None:
        resolved = imports.resolve(name)
        if resolved in _JIT_NAMES or resolved in _SHARD_MAP_NAMES:
            return True
        # unresolved tail match: jax.jit spelled through an odd alias
        if resolved.endswith(".jit") or resolved.endswith("shard_map"):
            return True
        return False
    if isinstance(expr, ast.Call):
        fn = dotted_name(expr.func)
        if fn is not None and imports.resolve(fn).endswith("partial"):
            return any(_is_jit_expr(a, imports) for a in expr.args)
        return _is_jit_expr(expr.func, imports)
    return False


def static_argnames_of(call_or_deco: ast.AST) -> Set[str]:
    """static_argnames=(...) strings from a jit decorator/wrap call."""
    out: Set[str] = set()
    calls = [n for n in ast.walk(call_or_deco) if isinstance(n, ast.Call)]
    for call in calls:
        for kw in call.keywords:
            if kw.arg != "static_argnames":
                continue
            for n in ast.walk(kw.value):
                if isinstance(n, ast.Constant) and isinstance(n.value, str):
                    out.add(n.value)
    return out


class ModuleIndex:
    """Per-module function index: tracedness, guard coverage, call
    graph. Built once per (file, configuration) by rules that need it."""

    def __init__(self, src_tree: ast.AST, display_path: str,
                 known_traced: Sequence[Tuple[str, str]] = ()):
        self.tree = src_tree
        self.path = display_path
        self.imports = ImportTable(src_tree)
        self.parents = parent_map(src_tree)
        self.functions: List[ast.AST] = [
            n for n in ast.walk(src_tree) if isinstance(n, FuncNode)]
        self._known_traced = known_traced
        self._traced: Optional[Set[ast.AST]] = None
        self._static_args: Dict[ast.AST, Set[str]] = {}

    # -- tracedness --------------------------------------------------------
    def directly_traced(self, fn: ast.AST) -> bool:
        """Decorated with jit/shard_map, wrapped by name in a jit/
        shard_map call in this module, or matching a known-traced
        pattern for this file."""
        for deco in fn.decorator_list:
            if _is_jit_expr(deco, self.imports):
                self._static_args.setdefault(fn, set()).update(
                    static_argnames_of(deco))
                return True
        name = fn.name
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            if not _is_jit_expr(node.func, self.imports):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Name) and arg.id == name:
                    self._static_args.setdefault(fn, set()).update(
                        static_argnames_of(node))
                    return True
        for path_pat, name_pat in self._known_traced:
            if re.search(path_pat, self.path) and re.match(name_pat, name):
                return True
        return False

    def traced_functions(self) -> Set[ast.AST]:
        """Fixed point over direct seeds + lexical nesting + the
        module-local call graph (any traced caller taints the callee:
        its body runs at trace time and may receive tracers)."""
        if self._traced is not None:
            return self._traced
        traced: Set[ast.AST] = {f for f in self.functions
                                if self.directly_traced(f)}
        by_name: Dict[str, List[ast.AST]] = {}
        for f in self.functions:
            by_name.setdefault(f.name, []).append(f)
        changed = True
        while changed:
            changed = False
            for f in self.functions:
                if f in traced:
                    continue
                # nested inside a traced function
                if any(enc in traced
                       for enc in enclosing_functions(f, self.parents)):
                    traced.add(f)
                    changed = True
                    continue
            # call-graph propagation: look at every call inside traced fns
            for f in list(traced):
                for node in ast.walk(f):
                    if not isinstance(node, ast.Call):
                        continue
                    callee = None
                    if isinstance(node.func, ast.Name):
                        callee = node.func.id
                    elif isinstance(node.func, ast.Attribute) and \
                            isinstance(node.func.value, ast.Name) and \
                            node.func.value.id == "self":
                        callee = node.func.attr
                    if callee is None:
                        continue
                    for target in by_name.get(callee, ()):
                        if target not in traced:
                            traced.add(target)
                            changed = True
        self._traced = traced
        return traced

    def static_params(self, fn: ast.AST) -> Set[str]:
        """static_argnames recorded while classifying `fn` as directly
        traced (empty for propagated helpers)."""
        self.directly_traced(fn)
        return set(self._static_args.get(fn, ()))

    def traced_params(self, fn: ast.AST) -> Set[str]:
        """Parameter names of a directly-traced function that carry
        traced values (everything not named in static_argnames)."""
        names = [a.arg for a in (fn.args.posonlyargs + fn.args.args
                                 + fn.args.kwonlyargs)]
        return set(names) - self.static_params(fn) - {"self", "cls"}

    # -- guard coverage ----------------------------------------------------
    def in_guard_with(self, node: ast.AST,
                      is_guard: Callable[[ast.AST], bool]) -> bool:
        """Is `node` lexically inside a `with` whose context expression
        satisfies `is_guard`? Stops at function boundaries (a nested
        def's body does not inherit the enclosing with — it runs
        later)."""
        cur = self.parents.get(node)
        while cur is not None and not isinstance(cur, FuncNode):
            if isinstance(cur, (ast.With, ast.AsyncWith)):
                for item in cur.items:
                    if is_guard(item.context_expr):
                        return True
            cur = self.parents.get(cur)
        return False

    def covered_functions(
            self, is_guard: Callable[[ast.AST], bool]) -> Set[ast.AST]:
        """Functions whose EVERY in-module call site sits inside the
        guard (lexically, or inside an already-covered function) —
        fixed point. Functions with no visible call sites are NOT
        covered."""
        by_name: Dict[str, List[ast.AST]] = {}
        for f in self.functions:
            by_name.setdefault(f.name, []).append(f)
        # call sites: name -> [(site_node, enclosing_fn)]
        sites: Dict[str, List[Tuple[ast.AST, Optional[ast.AST]]]] = {}
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = None
            if isinstance(node.func, ast.Name):
                callee = node.func.id
            elif isinstance(node.func, ast.Attribute):
                callee = node.func.attr
            if callee is None or callee not in by_name:
                continue
            encs = enclosing_functions(node, self.parents)
            sites.setdefault(callee, []).append(
                (node, encs[0] if encs else None))
        covered: Set[ast.AST] = set()
        changed = True
        while changed:
            changed = False
            for f in self.functions:
                if f in covered:
                    continue
                f_sites = sites.get(f.name, [])
                if not f_sites:
                    continue
                if all(self.in_guard_with(site, is_guard)
                       or (enc is not None and enc in covered)
                       for site, enc in f_sites):
                    covered.add(f)
                    changed = True
        return covered

    def guarded(self, node: ast.AST,
                is_guard: Callable[[ast.AST], bool],
                covered: Optional[Set[ast.AST]] = None) -> bool:
        """Lexical guard, or enclosing function fully covered."""
        if self.in_guard_with(node, is_guard):
            return True
        if covered is None:
            covered = self.covered_functions(is_guard)
        return any(enc in covered
                   for enc in enclosing_functions(node, self.parents))


# ---------------------------------------------------------------------------
# common guard predicates
# ---------------------------------------------------------------------------
def deadline_guard(imports: ImportTable) -> Callable[[ast.AST], bool]:
    """`with watchdog.deadline(...)` / `with deadline(...)` context
    expressions (the PR 11 collective-watchdog contract)."""
    def is_guard(expr: ast.AST) -> bool:
        if not isinstance(expr, ast.Call):
            return False
        name = dotted_name(expr.func)
        return name is not None and \
            name.split(".")[-1] == "deadline"
    return is_guard


_LOCK_WORD = re.compile(r"(?:^|_)(?:lock|cv|cond|mutex|mu)$")


def lock_guard(expr: ast.AST) -> bool:
    """`with self._lock:` / `with self._cv:` style context expressions
    (bare lock attribute/name, or a Condition used as its lock)."""
    name = dotted_name(expr)
    if name is None:
        return False
    return bool(_LOCK_WORD.search(name.split(".")[-1]))
