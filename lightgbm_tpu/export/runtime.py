"""Minimal serving front end over a forest artifact.

This module is what a serving replica imports — nothing else. Its
module-level imports are deliberately restricted to `ops/`, `serving/`,
and `export/` (plus the leaf utility modules `log`/`telemetry`): the
training stack (`boosting/`, `learner/`, `ingest/`, `parallel/`) must
never be reachable from here, and the `export-import-hygiene` graftlint
rule turns any such import into a finding. A replica container can ship
with those packages deleted and `ArtifactServer` still serves.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

from . import ArtifactError, is_artifact
from .. import log, telemetry
from ..serving.predictor import Predictor
from .loader import ArtifactModel, load_artifact


class ArtifactServer:
    """predict/predict_one over an exported artifact, with the full
    serving armor (admission control, deadlines, single-flight compile
    guard, micro-batching) inherited from `serving.Predictor`.

        server = ArtifactServer("/models/forest.artifact")
        probs = server.predict(rows)

    `params` overrides the serving io knobs frozen at export (e.g.
    {"tpu_predict_quantize": "int8"}); `warmup_rows=0` skips the
    bucket-ladder warmup (default walks exactly the exported ladder)."""

    def __init__(self, path: str, params: Optional[Dict[str, Any]] = None,
                 warmup_rows: Optional[int] = None,
                 expect_fingerprint: Optional[str] = None) -> None:
        if not is_artifact(path):
            raise ArtifactError(
                "%s is not a forest artifact (expected the "
                "lightgbm_tpu.forest_artifact magic); train with "
                "tpu_export_dir= or call Booster.export_forest() to "
                "produce one" % path)
        self.model: ArtifactModel = load_artifact(
            path, params=params, expect_fingerprint=expect_fingerprint)
        self.predictor = Predictor(self.model)
        if warmup_rows is None or warmup_rows > 0:
            info = self.predictor.warmup(warmup_rows)
            telemetry.counter_add("export/warmup_buckets",
                                  len(info["buckets"]))

    def num_features(self) -> int:
        return self.predictor.num_features()

    def predict(self, data, deadline_ms: Optional[float] = None,
                **overrides) -> np.ndarray:
        return self.predictor.predict(data, deadline_ms=deadline_ms,
                                      **overrides)

    def predict_one(self, row, deadline_ms: Optional[float] = None,
                    **overrides):
        return self.predictor.predict_one(row, deadline_ms=deadline_ms,
                                          **overrides)

    def stats(self) -> Dict[str, Any]:
        out = self.predictor.stats()
        out["artifact_path"] = self.model._path
        out["artifact_fingerprint"] = self.model.fingerprint
        out["artifact_buckets"] = list(self.model._buckets)
        out["artifact_layouts"] = sorted(self.model._layouts)
        return out

    def close(self) -> None:
        self.predictor.close()

    def __enter__(self) -> "ArtifactServer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
