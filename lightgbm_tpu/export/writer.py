"""Pack a trained booster into a forest artifact (`jax.export`).

The writer owns the only `jax.export.export` call sites in the repo.
Bit-identity with the in-process predict path is structural, not
tested-into-existence: each (layout, bucket, class) pair is traced as
the SAME kernel dispatch `GBDT._class_stack_dev` performs (the jaxpr of
`fn(leaves, data) = kernel(unflatten(leaves), data)` is the jaxpr of
`jax.jit(kernel)(entry, data)` — pytree arguments flatten to the same
leaf list either way), and the k==1 fused output transform is traced
from the objective's own `convert_output`, mirroring the two-program
split of `GBDT.predict`. Kernels are row-independent, so the bucket
padding a replica slices off can never perturb real rows.

Import hygiene: this module runs against a live GBDT instance passed in
by the caller — it calls its methods but never imports `boosting/` (the
`export-import-hygiene` graftlint rule enforces that for the whole
package).
"""
from __future__ import annotations

import hashlib
import json
import os
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import MAGIC, FORMAT_VERSION, FORMAT_VERSION_LINEAR, ArtifactError
from .. import durable, log, telemetry
from ..serving.forest import bucket_ladder, bucket_rows, pad_rows

_ALIGN = 64

#: serving/predict io knobs frozen into the artifact so a replica
#: reproduces the exporting process's dispatch behavior without the
#: training config file (load-time `params=` overrides win)
_IO_PARAM_FIELDS = (
    "tpu_predict_cache", "tpu_predict_bucket_min", "tpu_predict_chunk",
    "tpu_predict_pipeline", "tpu_predict_quantize",
    "tpu_predict_quantize_tol", "tpu_predict_warmup_rows",
    "tpu_predict_micro_batch", "tpu_predict_micro_batch_window_ms",
    "tpu_serving_budget_mb", "tpu_serving_max_queue",
    "tpu_serving_max_inflight", "tpu_serving_deadline_ms",
    "tpu_serving_model_qps", "tpu_serving_breaker_failures",
    "tpu_serving_breaker_reset_s", "tpu_compile_cache_dir",
)

#: objective-name -> host output-transform spec for the k>1 path
#: (`GBDT.predict` applies `objective.convert_output` eagerly on host
#: fetch; the loader replays the spec with the identical jnp expression,
#: so the table below must stay in lockstep with objectives.py)
_TRANSFORM_BY_NAME = {
    "binary": "sigmoid_scaled",
    "multiclassova": "sigmoid_scaled",
    "multiclass": "softmax",
    "xentropy": "sigmoid",
    "xentlambda": "log1p_exp",
    "poisson": "exp",
}


def _transform_spec(obj) -> Optional[Dict[str, Any]]:
    """JSON-able spec of `obj.convert_output` (None = identity)."""
    if obj is None:
        return None
    kind = _TRANSFORM_BY_NAME.get(obj.name)
    if kind is None:
        # regression family and lambdarank inherit the identity
        # convert_output; a custom objective that overrides it without a
        # spec entry cannot be replayed training-stack-free
        base = type(obj).convert_output
        for klass in type(obj).__mro__:
            if klass.__name__ == "ObjectiveFunction":
                if base is not klass.convert_output:
                    raise ArtifactError(
                        "Objective %r overrides convert_output but has "
                        "no exportable transform spec; add it to "
                        "export/writer._TRANSFORM_BY_NAME" % obj.name)
                break
        return {"kind": "identity"}
    spec: Dict[str, Any] = {"kind": kind}
    if kind == "sigmoid_scaled":
        spec["scale"] = float(obj.sigmoid)
    elif kind == "softmax":
        spec["num_class"] = int(obj.num_class)
    return spec


def _entry_fn(treedef, mode: str):
    """The exported computation for one class's stacked forest: exactly
    the `GBDT._class_stack_dev` dispatch, closed over the entry's pytree
    structure so a replica calls it with a flat leaf list."""
    import jax

    from ..ops import predict as predict_ops

    def fn(leaves, data):
        entry = jax.tree.unflatten(treedef, leaves)
        if mode == "int8":
            qf, st = entry
            if qf is not None:
                return predict_ops.predict_forest_quant(qf, data)
            return predict_ops.predict_forest_raw(st, data)
        mf, st = entry
        if mf is not None:
            if mode == "f16":
                return predict_ops.predict_forest_f16(mf, data)
            return predict_ops.predict_forest_raw_matmul(mf, data)
        return predict_ops.predict_forest_raw(st, data)

    return fn


def _export_layouts(io, layouts: Optional[List[str]]) -> List[str]:
    from ..serving.forest import QUANTIZE_MODES
    if layouts is None:
        layouts = [s.strip() for s in
                   str(io.tpu_export_layouts or "none").split(",") if s.strip()]
    modes = ["none"]  # f32 is always packed: it is the gate reference
    for m in layouts:
        m = m.lower()
        if m not in QUANTIZE_MODES:
            raise ArtifactError(
                "tpu_export_layouts entry %r is not one of %s"
                % (m, QUANTIZE_MODES))
        if m not in modes:
            modes.append(m)
    return modes


def _export_buckets(io, buckets) -> Tuple[int, List[int]]:
    bucket_min = int(io.tpu_predict_bucket_min)
    if bucket_min <= 0:
        raise ArtifactError(
            "Exported artifacts require the bucket ladder "
            "(tpu_predict_bucket_min > 0): every packed function is "
            "compiled for one bucket shape")
    if buckets is None:
        steps = max(1, int(io.tpu_export_buckets))
        return bucket_min, bucket_ladder(bucket_min, bucket_min << (steps - 1))
    want = sorted({int(b) for b in buckets})
    ladder = bucket_ladder(bucket_min, max(want))
    if want != ladder:
        raise ArtifactError(
            "buckets=%s is not the power-of-two ladder from "
            "tpu_predict_bucket_min=%d (expected %s): request dispatch "
            "walks the ladder, so gaps would retrace at serve time"
            % (want, bucket_min, ladder))
    return bucket_min, ladder


def _crc(raw: bytes) -> int:
    return zlib.crc32(raw) & 0xFFFFFFFF


def _gate_deltas(gbdt, cache, modes, k, total, stacks_by_mode,
                 calibration) -> Dict[str, Optional[float]]:
    """Measured quantize-gate deltas per layout (the in-process
    `GBDT._quant_gate` measurement, run at pack time so a replica can
    enforce `tpu_predict_quantize_tol` without the f32 comparison)."""
    deltas: Dict[str, Optional[float]] = {}
    for mode in modes:
        if mode == "none":
            continue
        key = ("value", total, k, mode)
        delta = cache.gate_delta(key)
        if delta is None and calibration is not None \
                and calibration.shape[0] > 0:
            calib = np.asarray(calibration, np.float32)
            defer = getattr(gbdt, "_quant_gate_defer", False)
            gbdt._quant_gate_defer = False
            try:
                gbdt._quant_gate(cache, mode, k, total,
                                 stacks_by_mode[mode], calib)
            finally:
                gbdt._quant_gate_defer = defer
            delta = cache.gate_delta(key)
        deltas[mode] = None if delta is None else float(delta)
    return deltas


def write_artifact(booster, path: str, num_iteration: int = -1,
                   layouts: Optional[List[str]] = None,
                   buckets: Optional[List[int]] = None,
                   calibration: Optional[np.ndarray] = None
                   ) -> Dict[str, Any]:
    """Serialize `booster`'s compiled-forest layouts to `path`.

    Returns a summary dict {path, bytes, sections, layouts, buckets,
    fingerprint}. `calibration` (optional real feature rows) runs the
    quantize accuracy gate at pack time and freezes the measured deltas
    into the manifest.
    """
    import jax
    from jax import export as jax_export

    gbdt = getattr(booster, "_inner", booster)
    gbdt.finalize_training()
    io = gbdt.config.io
    modes = _export_layouts(io, layouts)
    bucket_min, ladder = _export_buckets(io, buckets)
    k = int(gbdt.num_tree_per_iteration)
    total = int(gbdt._capped_total(num_iteration))
    num_features = int(gbdt.max_feature_idx) + 1

    with telemetry.span("export/write"):
        model_text = gbdt.save_model_to_string(num_iteration)
        cache = gbdt._forest_cache()
        sections: List[Tuple[Dict[str, Any], bytes]] = []

        def add_section(name: str, kind: str, raw: bytes,
                        dtype: str = "", shape=()) -> None:
            sections.append(({"name": name, "kind": kind, "dtype": dtype,
                              "shape": list(shape), "offset": 0,
                              "nbytes": len(raw), "crc32": _crc(raw)}, raw))

        add_section("model_text", "text", model_text.encode("utf-8"))

        from ..ops.predict import QuantRefused
        stacks_by_mode: Dict[str, Any] = {}
        layout_meta: Dict[str, Any] = {}
        platforms: Optional[Tuple[str, ...]] = None
        ccv = None
        n_fns = 0
        for mode in modes:
            if total > 0:
                try:
                    class_stacks = cache.value_stacks(gbdt.models, k, total,
                                                      quantize=mode)
                except QuantRefused as exc:
                    raise ArtifactError(
                        "layout %r refused for this model: %s"
                        % (mode, exc)) from exc
            else:
                class_stacks = [(None, None)] * k
            stacks_by_mode[mode] = class_stacks
            classes = []
            for cls, entry in enumerate(class_stacks):
                leaves, treedef = jax.tree.flatten(entry)
                empty = all(x is None for x in entry)
                classes.append({"empty": empty, "num_leaves": len(leaves)})
                if empty:
                    continue
                for i, leaf in enumerate(leaves):
                    a = np.asarray(leaf)
                    add_section("leaves/%s/%d/%d" % (mode, cls, i), "array",
                                a.tobytes(), dtype=a.dtype.name,
                                shape=a.shape)
                leaf_specs = [jax.ShapeDtypeStruct(x.shape, x.dtype)
                              for x in leaves]
                fn = _entry_fn(treedef, mode)
                for b in ladder:
                    data_spec = jax.ShapeDtypeStruct((b, num_features),
                                                     np.float32)
                    exp = jax_export.export(jax.jit(fn))(leaf_specs,
                                                         data_spec)
                    platforms = tuple(exp.platforms)
                    ccv = int(exp.calling_convention_version)
                    add_section("fn/%s/b%d/c%d" % (mode, b, cls),
                                "exported", exp.serialize())
                    n_fns += 1
            layout_meta[mode] = {"classes": classes}

        # the k==1 fused output transform, traced from the objective's
        # own convert_output — the second half of GBDT.predict's
        # two-program fast path
        obj = gbdt.objective
        has_conv = bool(obj is not None and k == 1 and total > 0)
        if has_conv:
            def _conv(r, d, b):
                return obj.convert_output(r / d + b)

            for b in ladder:
                exp = jax_export.export(jax.jit(_conv))(
                    jax.ShapeDtypeStruct((b,), np.float32),
                    jax.ShapeDtypeStruct((), np.float32),
                    jax.ShapeDtypeStruct((), np.float32))
                platforms = tuple(exp.platforms)
                ccv = int(exp.calling_convention_version)
                add_section("conv/b%d" % b, "exported", exp.serialize())
                n_fns += 1

        gate_deltas = _gate_deltas(gbdt, cache, modes, k, total,
                                   stacks_by_mode, calibration)

        raw_params = dict(getattr(gbdt.config, "raw_params", {}) or {})
        n_fp = int(getattr(getattr(gbdt, "train_data", None),
                           "num_global_rows", 0)
                   or getattr(gbdt, "_n", 0) or 0)
        from .. import checkpoint
        fingerprint = checkpoint.config_fingerprint(
            raw_params, n_fp, num_features, gbdt.config.boosting_type)

        io_params = {f: getattr(io, f) for f in _IO_PARAM_FIELDS
                     if hasattr(io, f)}
        # a replica's warmup must walk exactly the exported ladder —
        # buckets past the artifact's top would retrace from scratch
        io_params["tpu_predict_warmup_rows"] = int(ladder[-1])
        io_params["tpu_predict_bucket_min"] = int(bucket_min)

        # linear forests carry coefficient tables a format-1 reader
        # would drop silently — bump the format ONLY for them so
        # constant-leaf artifacts stay loadable by older readers
        has_linear = any(getattr(t, "is_linear", False)
                         for t in gbdt.models[:total])
        manifest = {
            "format": FORMAT_VERSION_LINEAR if has_linear
            else FORMAT_VERSION,
            "jax_version": jax.__version__,
            "calling_convention_version": ccv,
            "platforms": list(platforms) if platforms else [],
            "fingerprint": fingerprint,
            "model_sha256": hashlib.sha256(
                model_text.encode("utf-8")).hexdigest(),
            "forest": {
                "num_class": int(gbdt.num_class),
                "num_tree_per_iteration": k,
                "total_trees": total,
                "num_iteration": int(num_iteration),
                "max_feature_idx": int(gbdt.max_feature_idx),
                "average_output": bool(gbdt.average_output),
                "init_score_bias": float(gbdt.init_score_bias),
                "objective": obj.to_string() if obj is not None else "",
                "objective_name": obj.name if obj is not None else "",
                "transform": _transform_spec(obj),
                "has_conv": has_conv,
                "linear_tree": has_linear,
                "feature_names": list(gbdt.feature_names),
            },
            "layouts": layout_meta,
            "buckets": ladder,
            "bucket_min": bucket_min,
            "gate_deltas": gate_deltas,
            "io_params": io_params,
        }

        def render(descs):
            return json.dumps({"manifest": manifest, "sections": descs},
                              sort_keys=True).encode()

        descs = [d for d, _ in sections]
        # measure the header with worst-case offset widths (an artifact
        # can carry hundreds of sections, so fixed slack would not
        # scale), then pad to that length after the real offsets land
        for d in descs:
            d["offset"] = 1 << 53
        hlen = len(render(descs)) + 64
        base = len(MAGIC) + 8 + hlen
        base = ((base + _ALIGN - 1) // _ALIGN) * _ALIGN
        off = base
        for d, raw in sections:
            d["offset"] = off
            off = ((off + len(raw) + _ALIGN - 1) // _ALIGN) * _ALIGN
        blob = render(descs)
        if len(blob) > hlen:  # pragma: no cover — measured width always fits
            raise ArtifactError("artifact header overflow")
        blob = blob + b" " * (hlen - len(blob))

        out_dir = os.path.dirname(os.path.abspath(path))
        os.makedirs(out_dir, exist_ok=True)

        def _body(fh):
            fh.write(MAGIC)
            fh.write(struct.pack("<q", hlen))
            fh.write(blob)
            for d, raw in sections:
                fh.seek(d["offset"])
                fh.write(raw)

        # critical stream: a serving replica about to load this artifact
        # must never observe a half-written file, and a transient IO
        # fault must not silently skip the export
        durable.atomic_write_via(path, _body, site="export.artifact")
        nbytes = os.path.getsize(path)

    telemetry.counter_add("export/artifact_bytes", nbytes)
    telemetry.counter_add("export/artifact_sections", len(sections))
    telemetry.counter_add("export/exported_fns", n_fns)
    log.info("Exported forest artifact to %s: %d bytes, %d sections, "
             "layouts %s, buckets %s, fingerprint %s", path, nbytes,
             len(sections), modes, ladder, fingerprint[:12])
    return {"path": path, "bytes": nbytes, "sections": len(sections),
            "layouts": modes, "buckets": ladder,
            "fingerprint": fingerprint}
