"""Load a forest artifact into a serving-ready model — no training stack.

`load_artifact` verifies the container (magic, format version, jax
calling-convention skew, per-section CRC) and returns an
`ArtifactModel`: a frozen, predict-only stand-in for the trainer's GBDT
that satisfies the whole serving surface (`serving.Predictor`,
`serving.ModelRegistry`) — `config`, `max_feature_idx`, `predict()`,
`_compiled_forest`, version listeners, budget accounting.

Zero Python retracing: every packed function deserializes straight from
StableHLO; `jax.jit(exported.call)` only traces the O(1) call wrapper
(never the forest computation), and after the warmup walk of the
exported bucket ladder, steady-state serving emits no trace or compile
events at all. The layout entries live in a real `CompiledForest`, so
`ModelRegistry`'s byte budget sees deserialized executables exactly
like compiled stacks — and an evicted entry re-admits by re-reading the
artifact file instead of silently retracing.
"""
from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from . import MAGIC, FORMAT_VERSION_LINEAR, ArtifactError
from .. import log, telemetry
from ..serving.forest import (CompiledForest, QUANTIZE_MODES, bucket_rows,
                              pad_rows)


def _resolve_dtype(name: str) -> np.dtype:
    """Dtype from its stored name; bfloat16/float8 live in ml_dtypes."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _read_header(path: str) -> Tuple[Dict[str, Any], Dict[str, Any]]:
    """(manifest, {section name: descriptor}) with container checks."""
    try:
        with open(path, "rb") as fh:
            magic = fh.read(len(MAGIC))
            if magic != MAGIC:
                raise ArtifactError(
                    "%s is not a lightgbm_tpu forest artifact" % path)
            head = fh.read(8)
            if len(head) < 8:
                raise ArtifactError(
                    "Forest artifact %s is truncated (header length)"
                    % path)
            (hlen,) = struct.unpack("<q", head)
            if not 0 < hlen < (1 << 31):
                raise ArtifactError(
                    "Forest artifact %s has a corrupt header length (%d)"
                    % (path, hlen))
            blob = fh.read(hlen)
    except OSError as exc:
        raise ArtifactError(
            "Cannot read forest artifact %s: %s" % (path, exc)) from exc
    if len(blob) < hlen:
        raise ArtifactError(
            "Forest artifact %s is truncated (manifest)" % path)
    try:
        header = json.loads(blob.decode("utf-8"))
        manifest = header["manifest"]
        sections = {d["name"]: d for d in header["sections"]}
    except (ValueError, KeyError, TypeError) as exc:
        raise ArtifactError(
            "Forest artifact %s has a corrupt manifest (%s); the file "
            "cannot be trusted — re-export it" % (path, exc)) from exc
    fmt = int(manifest.get("format", 0))
    if fmt > FORMAT_VERSION_LINEAR:
        raise ArtifactError(
            "Forest artifact %s has format version %d; this build "
            "supports <= %d (manifest section 'format'). Upgrade "
            "lightgbm_tpu or re-export with the older writer."
            % (path, fmt, FORMAT_VERSION_LINEAR))
    return manifest, sections


def _check_runtime_compat(path: str, manifest: Dict[str, Any]) -> None:
    """Refuse jax calling-convention / platform skew up front, with the
    versions named — never a deserialization traceback."""
    import jax
    from jax import export as jax_export
    ccv = int(manifest.get("calling_convention_version", -1))
    lo = int(jax_export.minimum_supported_calling_convention_version)
    hi = int(jax_export.maximum_supported_calling_convention_version)
    if not lo <= ccv <= hi:
        raise ArtifactError(
            "Forest artifact %s was serialized with jax %s (calling "
            "convention %d); this process runs jax %s, which supports "
            "%d..%d (manifest section 'calling_convention_version'). "
            "Re-export the artifact with a compatible jax."
            % (path, manifest.get("jax_version", "<unknown>"), ccv,
               jax.__version__, lo, hi))
    platforms = [str(p) for p in manifest.get("platforms", [])]
    backend = jax.default_backend()
    if platforms and backend not in platforms:
        raise ArtifactError(
            "Forest artifact %s was exported for platform(s) %s; this "
            "process runs on %r (manifest section 'platforms'). "
            "Re-export on a matching backend."
            % (path, platforms, backend))


def _read_section(path: str, fh, desc: Dict[str, Any]) -> bytes:
    fh.seek(int(desc["offset"]))
    raw = fh.read(int(desc["nbytes"]))
    if len(raw) != int(desc["nbytes"]):
        raise ArtifactError(
            "Forest artifact %s is truncated (section %r)"
            % (path, desc["name"]))
    if zlib.crc32(raw) & 0xFFFFFFFF != int(desc["crc32"]):
        raise ArtifactError(
            "Forest artifact %s failed its checksum (section %r); the "
            "file is corrupt — re-export or re-fetch it"
            % (path, desc["name"]))
    return raw


def read_manifest(path: str) -> Dict[str, Any]:
    """The artifact's manifest (cheap: header only, no payload reads)."""
    manifest, _ = _read_header(path)
    return manifest


class _ExportedFn:
    """One serialized StableHLO function: lazily deserialized, then
    served through `jax.jit(exported.call)` so steady-state calls hit
    the C++ dispatch fast path. Exposes `.nbytes` so
    `CompiledForest._tree_bytes` budget-accounts it like any stacked
    array (dropping the wrapper on eviction releases the deserialized
    executable too)."""

    __slots__ = ("name", "nbytes", "_raw", "_jax_version", "_call",
                 "_lock")

    def __init__(self, name: str, raw: bytes, jax_version: str):
        self.name = name
        self.nbytes = len(raw)
        self._raw = raw
        self._jax_version = jax_version
        self._call = None
        self._lock = threading.Lock()

    def __call__(self, *args):
        call = self._call
        if call is None:
            with self._lock:
                call = self._call
                if call is None:
                    import jax
                    from jax import export as jax_export
                    try:
                        exported = jax_export.deserialize(self._raw)
                    except Exception as exc:
                        raise ArtifactError(
                            "Section %r of the forest artifact failed to "
                            "deserialize (written by jax %s, running jax "
                            "%s): %s" % (self.name, self._jax_version,
                                         jax.__version__, exc)) from exc
                    call = self._call = jax.jit(exported.call)
        return call(*args)


class ArtifactModel:
    """Predict-only GBDT stand-in rehydrated from a forest artifact.

    Satisfies the `serving.Predictor` / `serving.ModelRegistry` model
    surface. The forest never mutates, so the compiled-forest version is
    frozen; eviction (registry byte budget) drops the deserialized
    executables and the next predict re-reads them from the artifact
    path."""

    _PREDICT_ROW_CHUNK = 1 << 17
    _PREDICT_ROW_CHUNK_MATMUL = 1 << 19

    def __init__(self, path: str, manifest: Dict[str, Any],
                 sections: Dict[str, Any], config) -> None:
        self._path = os.path.abspath(path)
        self._manifest = manifest
        self._sections = sections
        self.config = config
        forest = manifest["forest"]
        self.num_class = int(forest["num_class"])
        self.num_tree_per_iteration = int(forest["num_tree_per_iteration"])
        self.max_feature_idx = int(forest["max_feature_idx"])
        self.average_output = bool(forest["average_output"])
        self.init_score_bias = float(forest["init_score_bias"])
        self.feature_names = list(forest["feature_names"])
        self.objective_name = str(forest.get("objective_name", ""))
        self._total = int(forest["total_trees"])
        self._num_iteration = int(forest["num_iteration"])
        self._transform = forest.get("transform")
        self._has_conv = bool(forest.get("has_conv"))
        self._layouts = manifest["layouts"]
        self._buckets = [int(b) for b in manifest["buckets"]]
        self._bucket_min = int(manifest["bucket_min"])
        self._gate_deltas = dict(manifest.get("gate_deltas") or {})
        self.fingerprint = str(manifest.get("fingerprint", ""))
        self.model_sha256 = str(manifest.get("model_sha256", ""))
        self._jax_version = str(manifest.get("jax_version", "<unknown>"))
        self._compiled_forest = CompiledForest()
        self._version_listeners: List[Any] = []
        self._quant_gate_defer = False

    # -- GBDT serving-surface compatibility ---------------------------
    def finalize_training(self) -> None:  # frozen forest: nothing to drain
        pass

    def model_version(self) -> int:
        return self._compiled_forest.version

    def add_version_listener(self, fn) -> None:
        self._version_listeners.append(fn)

    def remove_version_listener(self, fn) -> None:
        try:
            self._version_listeners.remove(fn)
        except ValueError:
            pass

    def compiled_stack_bytes(self) -> int:
        return self._compiled_forest.device_bytes()

    def _forest_cache(self) -> CompiledForest:
        self._compiled_forest.enabled = bool(self.config.io.tpu_predict_cache)
        return self._compiled_forest

    def _predict_chunk_rows(self, default: int) -> int:
        c = int(self.config.io.tpu_predict_chunk)
        c = c if c > 0 else default
        # cap at the exported ladder top: every chunk's bucket must map
        # to a packed function (no retracing path exists here)
        return min(c, self._buckets[-1])

    # -- layout rehydration -------------------------------------------
    def _serving_mode(self) -> str:
        mode = str(self.config.io.tpu_predict_quantize or "none").lower()
        if mode not in QUANTIZE_MODES:
            raise log.LightGBMError(
                "tpu_predict_quantize must be one of %s (got %r)"
                % (QUANTIZE_MODES, mode))
        if mode not in self._layouts:
            raise ArtifactError(
                "Forest artifact %s does not carry layout %r (exported "
                "layouts: %s); re-export with tpu_export_layouts=%s or "
                "serve one of the packed layouts"
                % (self._path, mode, sorted(self._layouts), mode))
        return mode

    def _check_gate(self, mode: str) -> None:
        if mode == "none":
            return
        delta = self._gate_deltas.get(mode)
        tol = float(self.config.io.tpu_predict_quantize_tol)
        if delta is not None and float(delta) > tol:
            raise log.LightGBMError(
                "tpu_predict_quantize=%s refused: the artifact's "
                "recorded calibration delta %.3g exceeds "
                "tpu_predict_quantize_tol=%.3g. Raise the tolerance or "
                "serve with tpu_predict_quantize=none."
                % (mode, float(delta), tol))

    def _load_entry(self, mode: str) -> Dict[str, Any]:
        """Read one layout's leaves + functions from the artifact file
        (the CompiledForest build callback — also the re-admission path
        after a registry budget eviction)."""
        import jax.numpy as jnp
        manifest, sections = _read_header(self._path)
        if manifest.get("model_sha256") != self.model_sha256:
            raise ArtifactError(
                "Forest artifact %s changed on disk since it was loaded "
                "(model digest mismatch); reload it with "
                "export.load_artifact to serve the new model"
                % self._path)
        classes = self._layouts[mode]["classes"]
        k = self.num_tree_per_iteration
        leaves: Dict[int, List[Any]] = {}
        fns: Dict[Tuple[int, int], _ExportedFn] = {}
        conv: Dict[int, _ExportedFn] = {}
        with open(self._path, "rb") as fh:
            for cls in range(k):
                if cls >= len(classes) or classes[cls]["empty"]:
                    continue
                loaded = []
                for i in range(int(classes[cls]["num_leaves"])):
                    name = "leaves/%s/%d/%d" % (mode, cls, i)
                    desc = sections.get(name)
                    if desc is None:
                        raise ArtifactError(
                            "Forest artifact %s is missing section %r"
                            % (self._path, name))
                    raw = _read_section(self._path, fh, desc)
                    arr = np.frombuffer(
                        raw, dtype=_resolve_dtype(desc["dtype"])).reshape(
                            tuple(int(s) for s in desc["shape"]))
                    loaded.append(jnp.asarray(arr))
                leaves[cls] = loaded
                for b in self._buckets:
                    name = "fn/%s/b%d/c%d" % (mode, b, cls)
                    desc = sections.get(name)
                    if desc is None:
                        raise ArtifactError(
                            "Forest artifact %s is missing section %r"
                            % (self._path, name))
                    fns[(b, cls)] = _ExportedFn(
                        name, _read_section(self._path, fh, desc),
                        self._jax_version)
            if self._has_conv:
                for b in self._buckets:
                    name = "conv/b%d" % b
                    desc = sections.get(name)
                    if desc is None:
                        raise ArtifactError(
                            "Forest artifact %s is missing section %r"
                            % (self._path, name))
                    conv[b] = _ExportedFn(
                        name, _read_section(self._path, fh, desc),
                        self._jax_version)
        telemetry.counter_add("export/entry_loads", 1)
        return {"leaves": leaves, "fns": fns, "conv": conv}

    def model_text(self) -> str:
        """The packed tree-text model (CRC-verified)."""
        with open(self._path, "rb") as fh:
            desc = self._sections.get("model_text")
            if desc is None:
                raise ArtifactError(
                    "Forest artifact %s is missing section 'model_text'"
                    % self._path)
            return _read_section(self._path, fh, desc).decode("utf-8")

    # -- predict ------------------------------------------------------
    def _check_num_iteration(self, num_iteration: int) -> None:
        if num_iteration <= 0:
            return
        capped = min(self._total,
                     num_iteration * self.num_tree_per_iteration)
        if capped != self._total:
            raise ArtifactError(
                "Forest artifact %s is frozen at %d trees "
                "(num_iteration=%d at export); it cannot serve "
                "num_iteration=%d — re-export with that cap"
                % (self._path, self._total, self._num_iteration,
                   num_iteration))

    def _apply_transform(self, flat):
        """Replay the objective's convert_output from the manifest spec
        with the identical jnp expression (see objectives.py)."""
        import jax.numpy as jnp
        spec = self._transform or {"kind": "identity"}
        kind = spec["kind"]
        if kind == "identity":
            return flat
        if kind == "sigmoid_scaled":
            return 1.0 / (1.0 + jnp.exp(-float(spec["scale"]) * flat))
        if kind == "sigmoid":
            return 1.0 / (1.0 + jnp.exp(-flat))
        if kind == "softmax":
            import jax
            return jax.nn.softmax(
                flat.reshape(int(spec["num_class"]), -1),
                axis=0).reshape(-1)
        if kind == "exp":
            return jnp.exp(flat)
        if kind == "log1p_exp":
            return jnp.log1p(jnp.exp(flat))
        raise ArtifactError(
            "Forest artifact %s carries unknown transform spec %r; "
            "it was written by a newer lightgbm_tpu" % (self._path, kind))

    def predict(self, data, num_iteration: int = -1,
                raw_score: bool = False, pred_leaf: bool = False,
                pred_contrib: bool = False, pred_early_stop: bool = False,
                pred_early_stop_freq: int = 10,
                pred_early_stop_margin: float = 10.0) -> np.ndarray:
        import jax.numpy as jnp
        if pred_leaf or pred_contrib or pred_early_stop:
            raise ArtifactError(
                "Exported artifacts serve value predictions only; "
                "pred_leaf/pred_contrib/pred_early_stop need the full "
                "model (load the tree text with Booster(model_file=...))")
        self._check_num_iteration(num_iteration)
        data = np.asarray(data, np.float32)
        if data.ndim != 2:
            raise log.LightGBMError(
                "Prediction input must be 2-D [rows, features] "
                "(got shape %s)" % (tuple(data.shape),))
        n = data.shape[0]
        k = self.num_tree_per_iteration
        mode = self._serving_mode()
        self._check_gate(mode)
        entry = None
        if self._total > 0:
            entry = self._forest_cache()._get(
                ("artifact", mode), lambda: self._load_entry(mode))
        use_fused = (not raw_score) and self._has_conv \
            and entry is not None
        denom = float(max(self._total // k, 1)) \
            if self.average_output else 1.0
        bias = float(self.init_score_bias)
        out = np.zeros((k, n), np.float64)
        if entry is not None and n > 0:
            chunk = self._predict_chunk_rows(self._PREDICT_ROW_CHUNK)
            pipeline = bool(self.config.io.tpu_predict_pipeline)
            fns, conv = entry["fns"], entry["conv"]

            def dispatch(dj, bucket):
                devs = []
                for cls in range(k):
                    fn = fns.get((bucket, cls))
                    if fn is None:
                        devs.append(None)
                        continue
                    r = fn(entry["leaves"][cls], dj)
                    if use_fused:
                        r = conv[bucket](r, jnp.float32(denom),
                                         jnp.float32(bias))
                    devs.append(r)
                return devs

            def fetch(sl, nrows, devs):
                for cls, dev in enumerate(devs):
                    if dev is not None:
                        out[cls, sl] = np.asarray(dev, np.float64)[:nrows]

            pending = None
            for i in range(0, n, chunk):
                nrows = min(chunk, n - i)
                bucket = bucket_rows(nrows, self._bucket_min, chunk)
                if (bucket, 0) not in fns and any(
                        not c["empty"]
                        for c in self._layouts[mode]["classes"]):
                    raise ArtifactError(
                        "Forest artifact %s has no packed function for "
                        "bucket %d (exported buckets: %s); the serving "
                        "config's bucket ladder must match the export"
                        % (self._path, bucket, self._buckets))
                dj = jnp.asarray(pad_rows(data[i:i + nrows], bucket))
                telemetry.counter_add("export/serve_chunks", 1)
                devs = dispatch(dj, bucket)
                if pending is not None:
                    fetch(*pending)
                pending = (slice(i, i + nrows), nrows, devs)
                if not pipeline:
                    fetch(*pending)
                    pending = None
            if pending is not None:
                fetch(*pending)
        if use_fused:
            return out.T[:, 0]
        if self.average_output and self._total > 0:
            out /= max(self._total // k, 1)
        out += self.init_score_bias
        raw = out.T
        if raw_score or self._transform is None:
            return raw[:, 0] if raw.shape[1] == 1 else raw
        conv_host = np.asarray(self._apply_transform(
            jnp.asarray(raw.T.reshape(-1), jnp.float32)), np.float64)
        if k == 1:
            return conv_host
        return conv_host.reshape(k, -1).T


def load_artifact(path: str, params: Optional[Dict[str, Any]] = None,
                  expect_fingerprint: Optional[str] = None
                  ) -> ArtifactModel:
    """Open a forest artifact and return a serving-ready ArtifactModel.

    `params`: serving-side overrides merged over the io params frozen at
    export (e.g. {"tpu_predict_quantize": "int8"}).
    `expect_fingerprint`: the training-config fingerprint the caller
    believes current (`checkpoint.config_fingerprint`); a mismatch means
    the artifact is stale relative to a re-trained model and the load is
    refused.
    """
    from ..config import Config
    with telemetry.span("export/load"):
        manifest, sections = _read_header(path)
        _check_runtime_compat(path, manifest)
        fp = str(manifest.get("fingerprint", ""))
        if expect_fingerprint is not None and fp \
                and fp != expect_fingerprint:
            raise ArtifactError(
                "Forest artifact %s was exported from a different "
                "training configuration (artifact fingerprint %s..., "
                "expected %s...): the model has been re-trained since "
                "this artifact was packed. Re-export it."
                % (path, fp[:12], expect_fingerprint[:12]))
        merged = dict(manifest.get("io_params") or {})
        merged.update(params or {})
        cfg = Config.from_params(merged)
        model = ArtifactModel(path, manifest, sections, cfg)
    telemetry.counter_add("export/loads", 1)
    telemetry.counter_add("export/load_bytes", os.path.getsize(path))
    log.info("Loaded forest artifact %s: %d trees x %d class(es), "
             "layouts %s, buckets %s", path, model._total,
             model.num_tree_per_iteration, sorted(manifest["layouts"]),
             manifest["buckets"])
    return model
