"""Exported-forest artifacts: training-stack-free serving.

A trained booster's compiled-forest layouts (f32 + the f16/int8
quantized stacks, per bucket of the power-of-two row ladder) are traced
through `jax.export` to StableHLO and packed — together with the tree
text, the objective's output-transform spec, the feature schema, the
quantize-gate deltas, and a checksummed manifest — into ONE file a
serving replica loads without ever importing `boosting/`, `learner/`,
`ingest/`, or `parallel/` (the `export-import-hygiene` graftlint rule
keeps that import boundary from eroding):

    magic  b"lightgbm_tpu.forest_artifact.v1\n"
    <q     header length
    JSON   manifest: format/jax/StableHLO versions, config fingerprint,
           model digest, forest metadata (classes, layouts, buckets,
           transform spec, serving io params), and one descriptor per
           section {name, kind, dtype, shape, offset, nbytes, crc32}
    ...    raw section bytes, 64-byte aligned (tree text, stacked-forest
           leaf arrays, serialized StableHLO functions)

`writer.py` packs the artifact, `loader.py` rehydrates it into a
`CompiledForest`-backed `ArtifactModel` that satisfies the serving
surface (`Predictor`, `ModelRegistry`), and `runtime.py` is the
deliberately minimal replica front end.
"""
from __future__ import annotations

from .. import log

MAGIC = b"lightgbm_tpu.forest_artifact.v1\n"
FORMAT_VERSION = 1
#: format written for piecewise-linear forests (linear_tree): their
#: stacked-leaf sections carry per-leaf coefficient tables a format-1
#: reader would silently drop, so the writer bumps the manifest format
#: ONLY for them — constant-leaf artifacts stay format 1 and remain
#: loadable by older readers, while older readers refuse linear
#: artifacts by name (manifest section 'format')
FORMAT_VERSION_LINEAR = 2
#: default artifact filename inside `tpu_export_dir`
DEFAULT_NAME = "forest.artifact"


class ArtifactError(log.LightGBMError):
    """A forest artifact could not be written, or refused to load
    (version skew, checksum failure, fingerprint mismatch, or a layout
    the artifact does not carry)."""


def is_artifact(path: str) -> bool:
    """True when `path` starts with the forest-artifact magic (the CLI
    uses this to route `input_model` between text models and
    artifacts)."""
    try:
        with open(path, "rb") as fh:
            return fh.read(len(MAGIC)) == MAGIC
    except OSError:
        return False


# Lazy submodule attribute access keeps `import lightgbm_tpu.export`
# cheap (the writer pulls in jax.export; a replica that only loads never
# needs it).
_LAZY = {
    "write_artifact": ("lightgbm_tpu.export.writer", "write_artifact"),
    "read_manifest": ("lightgbm_tpu.export.loader", "read_manifest"),
    "load_artifact": ("lightgbm_tpu.export.loader", "load_artifact"),
    "ArtifactModel": ("lightgbm_tpu.export.loader", "ArtifactModel"),
    "ArtifactServer": ("lightgbm_tpu.export.runtime", "ArtifactServer"),
}


def __getattr__(name):
    try:
        module_name, attr = _LAZY[name]
    except KeyError:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}") from None
    import importlib
    value = getattr(importlib.import_module(module_name), attr)
    globals()[name] = value
    return value


def __dir__():
    return sorted(set(globals()) | set(_LAZY))
