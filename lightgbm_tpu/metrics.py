"""Evaluation metrics.

Re-implements the reference metric factory and the full metric surface
(`src/metric/metric.cpp:11-46` plus regression_metric.hpp,
binary_metric.hpp, multiclass_metric.hpp, rank_metric.hpp +
dcg_calculator.cpp, map_metric.hpp, xentropy_metric.hpp). Metrics run on
host numpy in float64 — they are O(N) per iteration and off the device
critical path; only scores cross the device boundary.

Convention mirrored from the reference: `is_bigger_better` decides early
stopping direction; multiclass scores arrive class-major
`[num_class, num_data]` flattened.
"""
from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from . import log
from .config import Config
from .dataset import Metadata


class Metric:
    name: List[str] = []
    is_bigger_better = False

    def init(self, metadata: Metadata, num_data: int) -> None:
        self.num_data = num_data
        self.label = np.asarray(metadata.label, np.float64) if metadata.label is not None else None
        self.weights = np.asarray(metadata.weights, np.float64) if metadata.weights is not None else None
        self.sum_weights = float(self.weights.sum()) if self.weights is not None else float(num_data)

    def eval(self, score: np.ndarray, objective) -> List[Tuple[str, float]]:
        raise NotImplementedError

    def _avg(self, losses: np.ndarray) -> float:
        if self.weights is not None:
            return float(np.sum(losses * self.weights) / self.sum_weights)
        return float(np.mean(losses))


def _convert(score, objective):
    if objective is not None:
        import jax.numpy as jnp
        return np.asarray(objective.convert_output(jnp.asarray(score)))
    return np.asarray(score)


class L2Metric(Metric):
    """reference: regression_metric.hpp (L2/MSE)."""
    def __init__(self, config=None):
        self.name = ["l2"]

    def eval(self, score, objective):
        pred = _convert(score, objective)
        return [(self.name[0], self._avg((self.label - pred) ** 2))]


class RMSEMetric(L2Metric):
    def __init__(self, config=None):
        self.name = ["rmse"]

    def eval(self, score, objective):
        pred = _convert(score, objective)
        return [(self.name[0], float(np.sqrt(self._avg((self.label - pred) ** 2))))]


class L1Metric(Metric):
    def __init__(self, config=None):
        self.name = ["l1"]

    def eval(self, score, objective):
        pred = _convert(score, objective)
        return [(self.name[0], self._avg(np.abs(self.label - pred)))]


class HuberMetric(Metric):
    def __init__(self, config: Config):
        self.name = ["huber"]
        self.delta = config.objective_config.huber_delta

    def eval(self, score, objective):
        pred = _convert(score, objective)
        diff = pred - self.label
        a = np.abs(diff)
        loss = np.where(a <= self.delta, 0.5 * diff * diff,
                        self.delta * (a - 0.5 * self.delta))
        return [(self.name[0], self._avg(loss))]


class FairMetric(Metric):
    def __init__(self, config: Config):
        self.name = ["fair"]
        self.c = config.objective_config.fair_c

    def eval(self, score, objective):
        pred = _convert(score, objective)
        x = np.abs(pred - self.label)
        c = self.c
        loss = c * x - c * c * np.log1p(x / c)
        return [(self.name[0], self._avg(loss))]


class PoissonMetric(Metric):
    def __init__(self, config=None):
        self.name = ["poisson"]

    def eval(self, score, objective):
        pred = _convert(score, objective)  # exp link applied
        eps = 1e-10
        loss = pred - self.label * np.log(np.maximum(pred, eps))
        return [(self.name[0], self._avg(loss))]


class BinaryLoglossMetric(Metric):
    """reference: binary_metric.hpp (log loss via sigmoid probability)."""
    def __init__(self, config=None):
        self.name = ["binary_logloss"]

    def eval(self, score, objective):
        prob = _convert(score, objective)
        eps = 1e-15
        prob = np.clip(prob, eps, 1 - eps)
        is_pos = self.label > 0
        loss = np.where(is_pos, -np.log(prob), -np.log(1 - prob))
        return [(self.name[0], self._avg(loss))]


class BinaryErrorMetric(Metric):
    def __init__(self, config=None):
        self.name = ["binary_error"]

    def eval(self, score, objective):
        prob = _convert(score, objective)
        pred_pos = prob > 0.5
        err = (pred_pos != (self.label > 0)).astype(np.float64)
        return [(self.name[0], self._avg(err))]


class AUCMetric(Metric):
    """reference: binary_metric.hpp:160-266 (weighted rank-sum AUC).
    is_bigger_better — reference treats AUC specially in early stopping."""
    is_bigger_better = True

    def __init__(self, config=None):
        self.name = ["auc"]

    def eval(self, score, objective):
        # AUC is monotone-invariant; raw scores suffice
        score = np.asarray(score, np.float64)
        w = self.weights if self.weights is not None else np.ones_like(score)
        order = np.argsort(score, kind="mergesort")
        s, lab, ww = score[order], self.label[order], w[order]
        pos_w = np.where(lab > 0, ww, 0.0)
        neg_w = np.where(lab > 0, 0.0, ww)
        # tie-aware trapezoidal accumulation
        total_pos = pos_w.sum()
        total_neg = neg_w.sum()
        if total_pos == 0 or total_neg == 0:
            return [(self.name[0], 1.0)]
        # group by unique score
        _, idx_start = np.unique(s, return_index=True)
        grp_pos = np.add.reduceat(pos_w, idx_start)
        grp_neg = np.add.reduceat(neg_w, idx_start)
        cum_neg_before = np.concatenate([[0.0], np.cumsum(grp_neg)[:-1]])
        auc = np.sum(grp_pos * (cum_neg_before + 0.5 * grp_neg))
        return [(self.name[0], float(auc / (total_pos * total_neg)))]


class MultiLoglossMetric(Metric):
    def __init__(self, config: Config):
        self.name = ["multi_logloss"]
        self.num_class = config.objective_config.num_class

    def eval(self, score, objective):
        prob = _convert(score, objective).reshape(self.num_class, -1)
        eps = 1e-15
        lab = self.label.astype(int)
        p = np.clip(prob[lab, np.arange(len(lab))], eps, 1.0)
        return [(self.name[0], self._avg(-np.log(p)))]


class MultiErrorMetric(Metric):
    def __init__(self, config: Config):
        self.name = ["multi_error"]
        self.num_class = config.objective_config.num_class

    def eval(self, score, objective):
        prob = _convert(score, objective).reshape(self.num_class, -1)
        pred = np.argmax(prob, axis=0)
        err = (pred != self.label.astype(int)).astype(np.float64)
        return [(self.name[0], self._avg(err))]


class KLDivMetric(Metric):
    """reference: xentropy_metric.hpp (kullback_leibler)."""
    def __init__(self, config=None):
        self.name = ["kldiv"]

    def eval(self, score, objective):
        p = np.clip(_convert(score, objective), 1e-15, 1 - 1e-15)
        y = np.clip(self.label, 0, 1)
        # KL(y || p) = xent(y,p) - entropy(y)
        xent = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        with np.errstate(divide="ignore", invalid="ignore"):
            ent = -(np.where(y > 0, y * np.log(y), 0.0)
                    + np.where(y < 1, (1 - y) * np.log(1 - y), 0.0))
        return [(self.name[0], self._avg(xent - ent))]


class CrossEntropyMetric(Metric):
    def __init__(self, config=None):
        self.name = ["xentropy"]

    def eval(self, score, objective):
        p = np.clip(_convert(score, objective), 1e-15, 1 - 1e-15)
        y = np.clip(self.label, 0, 1)
        loss = -(y * np.log(p) + (1 - y) * np.log(1 - p))
        return [(self.name[0], self._avg(loss))]


class CrossEntropyLambdaMetric(Metric):
    def __init__(self, config=None):
        self.name = ["xentlambda"]

    def eval(self, score, objective):
        # hhat in (0, inf); loss per xentropy_metric.hpp:240-330
        hhat = np.maximum(_convert(score, objective), 1e-15)
        y = np.clip(self.label, 0, 1)
        z = np.clip(1.0 - np.exp(-hhat), 1e-15, 1 - 1e-15)
        loss = y * (-np.log(z)) + (1 - y) * hhat
        return [(self.name[0], self._avg(loss))]


def query_layout(qb: np.ndarray):
    """(qid, pos) row layout for query-contiguous arrays: qid[r] = query of
    row r, pos[r] = row r's offset inside its query. Tolerates zero-size
    queries (np.repeat skips them)."""
    sizes = np.diff(qb)
    qid = np.repeat(np.arange(len(sizes)), sizes)
    pos = np.arange(int(qb[-1])) - np.repeat(qb[:-1], sizes)
    return qid, pos


def segment_sum(arr: np.ndarray, qb: np.ndarray) -> np.ndarray:
    """Per-query sums of a query-contiguous array via exclusive-cumsum
    differences — unlike np.add.reduceat this is correct for zero-size
    queries (their sum is 0) and for qb entries equal to len(arr)."""
    csum = np.concatenate([[0], np.cumsum(arr, dtype=np.float64)])
    return csum[qb[1:]] - csum[qb[:-1]]


def _dcg_at_k(labels: np.ndarray, order: np.ndarray, k: int,
              label_gain: np.ndarray) -> float:
    top = order[:k]
    discounts = 1.0 / np.log2(np.arange(len(top)) + 2.0)
    return float(np.sum(label_gain[labels[top]] * discounts))


class NDCGMetric(Metric):
    """reference: rank_metric.hpp + dcg_calculator.cpp (NDCG at eval_at)."""
    is_bigger_better = True

    def __init__(self, config: Config):
        self.eval_at = list(config.metric.ndcg_eval_at) or [1, 2, 3, 4, 5]
        self.name = [f"ndcg@{k}" for k in self.eval_at]
        gains = config.objective_config.label_gain or \
            [float((1 << i) - 1) for i in range(31)]
        self.label_gain = np.asarray(gains, np.float64)

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("NDCG metric requires query information")
        qb = np.asarray(metadata.query_boundaries)
        self.query_boundaries = qb
        self.query_weights = metadata.query_weights
        # everything score-independent is precomputed once: row layout,
        # per-row gains/discounts, and the per-k MAX DCG (label order is
        # fixed) — eval then only sorts by score and segment-sums
        lab = np.asarray(metadata.label).astype(int)
        self._qid, self._pos = query_layout(qb)
        self._gain = self.label_gain[
            np.clip(lab, 0, len(self.label_gain) - 1)]
        self._disc = 1.0 / np.log2(self._pos + 2.0)
        by_label = np.lexsort((-lab, self._qid))
        self._max_dcg = {
            k: segment_sum(self._gain[by_label] * self._disc
                           * (self._pos < k), qb)
            for k in self.eval_at}

    def eval(self, score, objective):
        score = np.asarray(score, np.float64)
        qb = self.query_boundaries
        nq = len(qb) - 1
        qw = self.query_weights if self.query_weights is not None else np.ones(nq)
        # rows sorted by (query, -score) stay query-contiguous, so DCG@k
        # is a per-query segment sum of masked discounted gains — one
        # vectorized pass over all queries (replaces the reference's OMP
        # per-query loop, rank_metric.hpp / dcg_calculator)
        by_score = np.lexsort((-score, self._qid))
        gain_sorted = self._gain[by_score] * self._disc
        results = np.zeros((len(self.eval_at), nq))
        for ki, k in enumerate(self.eval_at):
            dcg = segment_sum(gain_sorted * (self._pos < k), qb)
            max_dcg = self._max_dcg[k]
            # reference counts queries with no positive docs as 1
            results[ki] = np.where(max_dcg > 0,
                                   dcg / np.maximum(max_dcg, 1e-300), 1.0)
        sum_w = qw.sum()
        return [(self.name[ki], float(np.sum(results[ki] * qw) / sum_w))
                for ki in range(len(self.eval_at))]


class MAPMetric(Metric):
    """reference: map_metric.hpp (mean average precision at k)."""
    is_bigger_better = True

    def __init__(self, config: Config):
        self.eval_at = list(config.metric.ndcg_eval_at) or [1, 2, 3, 4, 5]
        self.name = [f"map@{k}" for k in self.eval_at]

    def init(self, metadata, num_data):
        super().init(metadata, num_data)
        if metadata.query_boundaries is None:
            log.fatal("MAP metric requires query information")
        qb = np.asarray(metadata.query_boundaries)
        self.query_boundaries = qb
        self.query_weights = metadata.query_weights
        self._qid, self._pos = query_layout(qb)
        self._rel_raw = (np.asarray(metadata.label) > 0).astype(np.float64)
        self._row_start = np.repeat(qb[:-1], np.diff(qb))

    def eval(self, score, objective):
        score = np.asarray(score, np.float64)
        qb = self.query_boundaries
        nq = len(qb) - 1
        qw = self.query_weights if self.query_weights is not None else np.ones(nq)
        qid, pos = self._qid, self._pos
        by_score = np.lexsort((-score, qid))
        rel = self._rel_raw[by_score]
        # within-query running hit count: inclusive cumsum minus the
        # exclusive cumsum at each query's start (rows stay
        # query-contiguous; excl has length n+1 so qb values of n are safe)
        excl = np.concatenate([[0.0], np.cumsum(rel)])
        hits = excl[1:] - excl[self._row_start]
        prec_rel = (hits / (pos + 1.0)) * rel
        results = np.zeros((len(self.eval_at), nq))
        for ki, k in enumerate(self.eval_at):
            at_k = pos < k
            ap_sum = segment_sum(prec_rel * at_k, qb)
            num_rel = segment_sum(rel * at_k, qb)
            results[ki] = np.where(num_rel > 0,
                                   ap_sum / np.maximum(num_rel, 1e-300), 0.0)
        sum_w = qw.sum()
        return [(self.name[ki], float(np.sum(results[ki] * qw) / sum_w))
                for ki in range(len(self.eval_at))]


_METRIC_REGISTRY = {
    "l2": L2Metric, "mse": L2Metric, "mean_squared_error": L2Metric,
    "regression": L2Metric, "regression_l2": L2Metric,
    "rmse": RMSEMetric, "root_mean_squared_error": RMSEMetric, "l2_root": RMSEMetric,
    "l1": L1Metric, "mae": L1Metric, "mean_absolute_error": L1Metric,
    "regression_l1": L1Metric,
    "huber": HuberMetric,
    "fair": FairMetric,
    "poisson": PoissonMetric,
    "binary_logloss": BinaryLoglossMetric, "binary": BinaryLoglossMetric,
    "binary_error": BinaryErrorMetric,
    "auc": AUCMetric,
    "multi_logloss": MultiLoglossMetric, "multiclass": MultiLoglossMetric,
    "softmax": MultiLoglossMetric, "multiclassova": MultiLoglossMetric,
    "multi_error": MultiErrorMetric,
    "xentropy": CrossEntropyMetric, "cross_entropy": CrossEntropyMetric,
    "xentlambda": CrossEntropyLambdaMetric,
    "cross_entropy_lambda": CrossEntropyLambdaMetric,
    "kldiv": KLDivMetric, "kullback_leibler": KLDivMetric,
    "ndcg": NDCGMetric, "lambdarank": NDCGMetric,
    "map": MAPMetric, "mean_average_precision": MAPMetric,
}


def create_metric(name: str, config: Config) -> Optional[Metric]:
    """Factory (reference: Metric::CreateMetric, metric.cpp:11-46)."""
    name = name.strip().lower()
    if name in ("", "none", "null", "na"):
        return None
    if name not in _METRIC_REGISTRY:
        log.fatal("Unknown metric type name: %s" % name)
    cls = _METRIC_REGISTRY[name]
    try:
        return cls(config)
    except TypeError:
        return cls()


def default_metric_for_objective(objective: str) -> str:
    """When `metric` is unset the objective implies one (config.cpp)."""
    mapping = {
        "regression": "l2", "regression_l2": "l2", "l2": "l2", "mse": "l2",
        "rmse": "rmse", "l2_root": "rmse",
        "regression_l1": "l1", "l1": "l1", "mae": "l1",
        "huber": "huber", "fair": "fair", "poisson": "poisson",
        "binary": "binary_logloss",
        "multiclass": "multi_logloss", "softmax": "multi_logloss",
        "multiclassova": "multi_logloss", "ova": "multi_logloss", "ovr": "multi_logloss",
        "multiclass_ova": "multi_logloss",
        "xentropy": "xentropy", "cross_entropy": "xentropy",
        "xentlambda": "xentlambda", "cross_entropy_lambda": "xentlambda",
        "lambdarank": "ndcg",
    }
    return mapping.get(objective, "l2")
