"""Binned training matrix + metadata.

TPU-native re-design of the reference Dataset stack
(`include/LightGBM/dataset.h:280-570`, `src/io/dataset.cpp`):

Instead of per-feature-group Bin objects with dense/sparse/4-bit variants
(dense_bin.hpp / sparse_bin.hpp / ordered_sparse_bin.hpp), the whole
training set is ONE dense `uint8`/`int32` matrix `[num_data, num_features]`
of bin indices, resident in HBM for the entire run — the analogue of the
GPU learner's `Feature4` packed device matrix (gpu_tree_learner.cpp:385-441)
generalized to the native layout XLA tiles best. Sparse features are made
dense by binning (a bin index per row costs 1 byte regardless of sparsity);
Exclusive Feature Bundling further collapses mutually-exclusive sparse
columns (dataset.cpp:66-211) so width stays manageable.

Metadata mirrors `dataset.h:36-248`: label, weights, query boundaries,
query weights, init score.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import log
from .binning import (BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper,
                      find_bin_mappers)

_BINARY_MAGIC = b"lightgbm_tpu.dataset.v1\n"


class Metadata:
    """Labels / weights / query info (reference: Metadata, dataset.h:36-248)."""

    def __init__(self, num_data: int = 0):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weights: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None
        self.query_weights: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None

    def set_label(self, label: Sequence[float]) -> None:
        arr = np.asarray(label, dtype=np.float32).ravel()
        if self.num_data and len(arr) != self.num_data:
            log.fatal("Length of label (%d) != num_data (%d)" % (len(arr), self.num_data))
        self.label = arr
        self.num_data = len(arr)

    def set_weights(self, weights: Optional[Sequence[float]]) -> None:
        if weights is None:
            self.weights = None
            return
        arr = np.asarray(weights, dtype=np.float32).ravel()
        if self.num_data and len(arr) != self.num_data:
            log.fatal("Length of weights (%d) != num_data (%d)" % (len(arr), self.num_data))
        self.weights = arr
        self._update_query_weights()

    def set_group(self, group: Optional[Sequence[int]]) -> None:
        """`group` is per-query sizes; converted to boundaries
        (reference: Metadata::SetQuery, metadata.cpp)."""
        if group is None:
            self.query_boundaries = None
            self.query_weights = None
            return
        sizes = np.asarray(group, dtype=np.int64).ravel()
        bounds = np.zeros(len(sizes) + 1, dtype=np.int64)
        np.cumsum(sizes, out=bounds[1:])
        if self.num_data and bounds[-1] != self.num_data:
            log.fatal("Sum of query counts (%d) != num_data (%d)" % (bounds[-1], self.num_data))
        self.query_boundaries = bounds
        self._update_query_weights()

    def set_init_score(self, init_score: Optional[Sequence[float]]) -> None:
        if init_score is None:
            self.init_score = None
            return
        self.init_score = np.asarray(init_score, dtype=np.float64).ravel()

    def _update_query_weights(self) -> None:
        # mean of row weights per query (reference: metadata.cpp query weights)
        if self.weights is not None and self.query_boundaries is not None:
            nq = len(self.query_boundaries) - 1
            qw = np.zeros(nq, dtype=np.float32)
            for i in range(nq):
                s, e = self.query_boundaries[i], self.query_boundaries[i + 1]
                qw[i] = self.weights[s:e].mean() if e > s else 0.0
            self.query_weights = qw

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1


class Dataset:
    """The binned training matrix (reference: Dataset, dataset.h:280-570).

    Attributes:
      binned:  `[num_data, num_features]` int32/uint8 bin indices (dense, HBM-ready)
      mappers: per-feature BinMapper
      metadata: labels / weights / queries
      feature_names: column names
      used_features: indices of non-trivial features in the ORIGINAL column
        space (trivial features are dropped from `binned`, as the reference
        drops them from feature groups, dataset.cpp:212-260)
    """

    def __init__(self):
        self.binned: Optional[np.ndarray] = None  # [num_data, num_groups]
        self.raw: Optional[np.ndarray] = None  # kept optionally for valid-set binning
        self.mappers: List[BinMapper] = []
        self.metadata = Metadata()
        self.feature_names: List[str] = []
        self.used_features: List[int] = []
        self.num_total_features: int = 0
        self.max_bin: int = 255
        self.groups = None  # efb.FeatureGroups over used features

    # ------------------------------------------------------------------
    @classmethod
    def from_numpy(cls, data: np.ndarray, label: Optional[Sequence[float]] = None,
                   max_bin: int = 255, min_data_in_bin: int = 3,
                   min_split_data: int = 0,
                   bin_construct_sample_cnt: int = 200000,
                   data_random_seed: int = 1,
                   categorical_features: Optional[Sequence[int]] = None,
                   use_missing: bool = True, zero_as_missing: bool = False,
                   feature_names: Optional[Sequence[str]] = None,
                   weight: Optional[Sequence[float]] = None,
                   group: Optional[Sequence[int]] = None,
                   init_score: Optional[Sequence[float]] = None,
                   reference: Optional["Dataset"] = None,
                   keep_raw: bool = False,
                   enable_bundle: bool = True,
                   max_conflict_rate: float = 0.0,
                   sparse_threshold: float = 0.8,
                   mappers: Optional[List[BinMapper]] = None) -> "Dataset":
        """Build a Dataset from a dense float matrix.

        When `reference` is given, its BinMappers are reused so validation
        data lands in the same bin space (reference: Dataset::CreateValid,
        dataset.cpp + python basic.py set_reference chain).
        """
        data = np.asarray(data)
        if data.ndim != 2:
            log.fatal("Dataset data must be 2-dimensional")
        n, f = data.shape
        ds = cls()
        ds.num_total_features = f
        ds.max_bin = max_bin if reference is None else reference.max_bin
        ds.feature_names = list(feature_names) if feature_names is not None else \
            [f"Column_{i}" for i in range(f)]

        if reference is not None:
            if f != reference.num_total_features:
                log.fatal("Validation data feature count (%d) != train (%d)"
                          % (f, reference.num_total_features))
            ds.mappers = reference.mappers
            ds.used_features = reference.used_features
            ds.groups = reference.groups
        elif mappers is not None:
            # pre-computed BinMappers (C API sampled-column / push-rows
            # streaming path, c_api.h:67-141: bins come from the sample,
            # rows arrive later)
            ds.mappers = list(mappers)
            ds.used_features = [j for j, m in enumerate(ds.mappers)
                                if not m.is_trivial]
        else:
            ds.mappers = find_bin_mappers(
                data.astype(np.float64, copy=False), max_bin, min_data_in_bin,
                min_split_data, bin_construct_sample_cnt, data_random_seed,
                categorical_features, use_missing, zero_as_missing)
            ds.used_features = [j for j, m in enumerate(ds.mappers) if not m.is_trivial]
            if not ds.used_features:
                log.warning("All features are trivial (constant); "
                            "model will predict a constant")

        # per-feature binning in a thread pool: searchsorted and the mask
        # ops release the GIL, and the single-threaded column loop was
        # ~4s of dataset construction at 2M x 28
        from concurrent.futures import ThreadPoolExecutor

        def _bin_col(j):
            return ds.mappers[j].values_to_bins(
                np.asarray(data[:, j], dtype=np.float64))

        if len(ds.used_features) > 4 and data.shape[0] > 100_000:
            with ThreadPoolExecutor(max_workers=8) as ex:
                cols = list(ex.map(_bin_col, ds.used_features))
        else:
            cols = [_bin_col(j) for j in ds.used_features]
        num_bins = np.asarray(
            [ds.mappers[j].num_bin for j in ds.used_features], np.int32)
        default_bins = np.asarray(
            [ds.mappers[j].default_bin for j in ds.used_features], np.int32)
        if ds.groups is None:
            from .efb import find_groups
            ds.groups = find_groups(
                cols, default_bins, num_bins, enable_bundle=enable_bundle,
                max_conflict_rate=max_conflict_rate,
                sparse_threshold=sparse_threshold, seed=data_random_seed)
        ds.binned = (ds.groups.bundle_rows(cols, default_bins) if cols
                     else np.zeros((n, 0), dtype=np.uint8))
        if keep_raw:
            ds.raw = data
        ds.metadata = Metadata(n)
        if label is not None:
            ds.metadata.set_label(label)
        if weight is not None:
            ds.metadata.set_weights(weight)
        if group is not None:
            ds.metadata.set_group(group)
        if init_score is not None:
            ds.metadata.set_init_score(init_score)
        return ds

    # ------------------------------------------------------------------
    @property
    def num_data(self) -> int:
        return 0 if self.binned is None else self.binned.shape[0]

    @property
    def num_features(self) -> int:
        """Number of used (non-trivial) LOGICAL features (the stored
        `binned` width is num_groups <= num_features after EFB)."""
        return len(self.used_features)

    @property
    def num_groups(self) -> int:
        return 0 if self.binned is None else self.binned.shape[1]

    @property
    def has_bundles(self) -> bool:
        return self.groups is not None and bool(self.groups.is_bundled.any())

    def feature_mapper(self, inner_idx: int) -> BinMapper:
        return self.mappers[self.used_features[inner_idx]]

    def feature_infos(self) -> List[str]:
        """Per-ORIGINAL-column info strings for the model text header
        (reference: Dataset::feature_infos, dataset.h:518-530)."""
        used = set(self.used_features)
        return [self.mappers[j].bin_info() if j in used else "none"
                for j in range(self.num_total_features)]

    def real_feature_index(self, inner_idx: int) -> int:
        return self.used_features[inner_idx]

    def num_bins_per_feature(self) -> np.ndarray:
        return np.asarray([self.feature_mapper(j).num_bin
                           for j in range(self.num_features)], dtype=np.int32)

    def max_num_bin(self) -> int:
        """Histogram width: max bins over stored GROUPS (feature-space
        scans use per-feature num_bin from feature_meta_arrays)."""
        if self.groups is not None and self.groups.num_groups:
            return int(self.groups.group_num_bin.max())
        nb = self.num_bins_per_feature()
        return int(nb.max()) if len(nb) else 1

    def feature_meta_arrays(self) -> Dict[str, np.ndarray]:
        """Static per-feature metadata consumed by the device split finder.

        Includes the EFB layout: `group` / `offset` locate each feature's
        bin slice inside the stored group columns; `is_bundled` marks
        features whose default-bin mass must be reconstructed from leaf
        totals (FixHistogram, dataset.cpp:747-767)."""
        f = self.num_features
        num_bin = np.zeros(f, dtype=np.int32)
        missing_type = np.zeros(f, dtype=np.int32)
        default_bin = np.zeros(f, dtype=np.int32)
        is_categorical = np.zeros(f, dtype=bool)
        for j in range(f):
            m = self.feature_mapper(j)
            num_bin[j] = m.num_bin
            missing_type[j] = m.missing_type
            default_bin[j] = m.default_bin
            is_categorical[j] = m.bin_type == BIN_CATEGORICAL
        if self.groups is not None and f:
            group = self.groups.group_of.astype(np.int32)
            offset = self.groups.offset_of.astype(np.int32)
            is_bundled = self.groups.is_bundled.copy()
        else:
            group = np.arange(f, dtype=np.int32)
            offset = np.zeros(f, dtype=np.int32)
            is_bundled = np.zeros(f, dtype=bool)
        return {"num_bin": num_bin, "missing_type": missing_type,
                "default_bin": default_bin, "is_categorical": is_categorical,
                "group": group, "offset": offset, "is_bundled": is_bundled}

    # ------------------------------------------------------------------
    # binary serialization (reference: Dataset::SaveBinaryFile, dataset.h:386,
    # DatasetLoader::LoadFromBinFile, dataset_loader.cpp:265-430)
    def save_binary(self, filename: str) -> None:
        import json
        meta = {
            "feature_names": self.feature_names,
            "used_features": self.used_features,
            "num_total_features": self.num_total_features,
            "max_bin": self.max_bin,
            "mappers": [m.to_dict() for m in self.mappers],
            "groups": ([[int(j) for j in g] for g in self.groups.groups]
                       if self.groups is not None else None),
        }
        meta_bytes = json.dumps(meta).encode()
        with open(filename, "wb") as fh:
            fh.write(_BINARY_MAGIC)
            fh.write(struct.pack("<q", len(meta_bytes)))
            fh.write(meta_bytes)
            for arr, code in [(self.binned, b"B"), (self.metadata.label, b"L"),
                              (self.metadata.weights, b"W"),
                              (self.metadata.query_boundaries, b"Q"),
                              (self.metadata.init_score, b"I")]:
                if arr is None:
                    fh.write(b"N")
                    continue
                fh.write(code)
                header = np.lib.format.header_data_from_array_1_0(np.asarray(arr))
                np.save(fh, np.asarray(arr), allow_pickle=False)
        log.info("Saved binary dataset to %s", filename)

    @classmethod
    def load_binary(cls, filename: str) -> "Dataset":
        import json
        ds = cls()
        with open(filename, "rb") as fh:
            magic = fh.read(len(_BINARY_MAGIC))
            if magic != _BINARY_MAGIC:
                log.fatal("%s is not a lightgbm_tpu binary dataset" % filename)
            (mlen,) = struct.unpack("<q", fh.read(8))
            meta = json.loads(fh.read(mlen).decode())
            ds.feature_names = meta["feature_names"]
            ds.used_features = [int(x) for x in meta["used_features"]]
            ds.num_total_features = int(meta["num_total_features"])
            ds.max_bin = int(meta["max_bin"])
            ds.mappers = [BinMapper.from_dict(d) for d in meta["mappers"]]
            if meta.get("groups") is not None:
                from .efb import FeatureGroups
                num_bins = np.asarray(
                    [ds.mappers[j].num_bin for j in ds.used_features], np.int32)
                ds.groups = FeatureGroups(
                    [[int(j) for j in g] for g in meta["groups"]], num_bins)
            arrays = []
            for _ in range(5):
                code = fh.read(1)
                arrays.append(None if code == b"N" else np.load(fh, allow_pickle=False))
        ds.binned, label, weights, qb, init = arrays
        ds.metadata = Metadata(0 if ds.binned is None else ds.binned.shape[0])
        if label is not None:
            ds.metadata.set_label(label)
        if weights is not None:
            ds.metadata.set_weights(weights)
        if qb is not None:
            ds.metadata.query_boundaries = qb
            ds.metadata._update_query_weights()
        if init is not None:
            ds.metadata.set_init_score(init)
        return ds
