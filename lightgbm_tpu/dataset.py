"""Binned training matrix + metadata.

TPU-native re-design of the reference Dataset stack
(`include/LightGBM/dataset.h:280-570`, `src/io/dataset.cpp`):

Instead of per-feature-group Bin objects with dense/sparse/4-bit variants
(dense_bin.hpp / sparse_bin.hpp / ordered_sparse_bin.hpp), the whole
training set is ONE dense `uint8`/`int32` matrix `[num_data, num_features]`
of bin indices, resident in HBM for the entire run — the analogue of the
GPU learner's `Feature4` packed device matrix (gpu_tree_learner.cpp:385-441)
generalized to the native layout XLA tiles best. Sparse features are made
dense by binning (a bin index per row costs 1 byte regardless of sparsity);
Exclusive Feature Bundling further collapses mutually-exclusive sparse
columns (dataset.cpp:66-211) so width stays manageable.

Metadata mirrors `dataset.h:36-248`: label, weights, query boundaries,
query weights, init score.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence

import numpy as np

from . import log
from .binning import BIN_CATEGORICAL, BIN_NUMERICAL, BinMapper

_BINARY_MAGIC = b"lightgbm_tpu.dataset.v1\n"


class Metadata:
    """Labels / weights / query info (reference: Metadata, dataset.h:36-248)."""

    def __init__(self, num_data: int = 0):
        self.num_data = num_data
        self.label: Optional[np.ndarray] = None
        self.weights: Optional[np.ndarray] = None
        self.query_boundaries: Optional[np.ndarray] = None
        self.query_weights: Optional[np.ndarray] = None
        self.init_score: Optional[np.ndarray] = None

    def set_label(self, label: Sequence[float]) -> None:
        arr = np.asarray(label, dtype=np.float32).ravel()
        if self.num_data and len(arr) != self.num_data:
            log.fatal("Length of label (%d) != num_data (%d)" % (len(arr), self.num_data))
        self.label = arr
        self.num_data = len(arr)

    def set_weights(self, weights: Optional[Sequence[float]]) -> None:
        if weights is None:
            self.weights = None
            return
        arr = np.asarray(weights, dtype=np.float32).ravel()
        if self.num_data and len(arr) != self.num_data:
            log.fatal("Length of weights (%d) != num_data (%d)" % (len(arr), self.num_data))
        self.weights = arr
        self._update_query_weights()

    def set_group(self, group: Optional[Sequence[int]]) -> None:
        """`group` is per-query sizes; converted to boundaries
        (reference: Metadata::SetQuery, metadata.cpp)."""
        if group is None:
            self.query_boundaries = None
            self.query_weights = None
            return
        sizes = np.asarray(group, dtype=np.int64).ravel()
        bounds = np.zeros(len(sizes) + 1, dtype=np.int64)
        np.cumsum(sizes, out=bounds[1:])
        if self.num_data and bounds[-1] != self.num_data:
            log.fatal("Sum of query counts (%d) != num_data (%d)" % (bounds[-1], self.num_data))
        self.query_boundaries = bounds
        self._update_query_weights()

    def set_init_score(self, init_score: Optional[Sequence[float]]) -> None:
        if init_score is None:
            self.init_score = None
            return
        self.init_score = np.asarray(init_score, dtype=np.float64).ravel()

    def _update_query_weights(self) -> None:
        # mean of row weights per query (reference: metadata.cpp query weights)
        if self.weights is not None and self.query_boundaries is not None:
            nq = len(self.query_boundaries) - 1
            qw = np.zeros(nq, dtype=np.float32)
            for i in range(nq):
                s, e = self.query_boundaries[i], self.query_boundaries[i + 1]
                qw[i] = self.weights[s:e].mean() if e > s else 0.0
            self.query_weights = qw

    @property
    def num_queries(self) -> int:
        return 0 if self.query_boundaries is None else len(self.query_boundaries) - 1


class Dataset:
    """The binned training matrix (reference: Dataset, dataset.h:280-570).

    Attributes:
      binned:  `[num_data, num_features]` int32/uint8 bin indices (dense, HBM-ready)
      mappers: per-feature BinMapper
      metadata: labels / weights / queries
      feature_names: column names
      used_features: indices of non-trivial features in the ORIGINAL column
        space (trivial features are dropped from `binned`, as the reference
        drops them from feature groups, dataset.cpp:212-260)
    """

    def __init__(self):
        self.binned: Optional[np.ndarray] = None  # [num_data, num_groups]
        self.raw: Optional[np.ndarray] = None  # kept optionally for valid-set binning
        self.mappers: List[BinMapper] = []
        self.metadata = Metadata()
        self.feature_names: List[str] = []
        self.used_features: List[int] = []
        self.num_total_features: int = 0
        self.max_bin: int = 255
        self.groups = None  # efb.FeatureGroups over used features
        # device-landed alternative to `binned` (ingest.ShardedLanding):
        # a row-padded jax.Array sharded over the data mesh; `binned`
        # stays None and `_num_rows` carries the real row count
        self.device_binned = None
        self.device_layout = None
        self._num_rows: int = 0

    # ------------------------------------------------------------------
    @classmethod
    def from_numpy(cls, data: np.ndarray, label: Optional[Sequence[float]] = None,
                   max_bin: int = 255, min_data_in_bin: int = 3,
                   min_split_data: int = 0,
                   bin_construct_sample_cnt: int = 200000,
                   data_random_seed: int = 1,
                   categorical_features: Optional[Sequence[int]] = None,
                   use_missing: bool = True, zero_as_missing: bool = False,
                   feature_names: Optional[Sequence[str]] = None,
                   weight: Optional[Sequence[float]] = None,
                   group: Optional[Sequence[int]] = None,
                   init_score: Optional[Sequence[float]] = None,
                   reference: Optional["Dataset"] = None,
                   keep_raw: bool = False,
                   enable_bundle: bool = True,
                   max_conflict_rate: float = 0.0,
                   sparse_threshold: float = 0.8,
                   mappers: Optional[List[BinMapper]] = None,
                   chunk_rows: int = 65536,
                   landing_factory=None) -> "Dataset":
        """Build a Dataset from a dense float matrix.

        When `reference` is given, its BinMappers are reused so validation
        data lands in the same bin space (reference: Dataset::CreateValid,
        dataset.cpp + python basic.py set_reference chain).

        Construction rides the streaming ingest subsystem
        (lightgbm_tpu/ingest): the matrix is streamed in row chunks
        through the same two-pass sketch-then-bin pipeline files use, so
        in-memory and streamed construction are one code path (and
        bit-identical by construction, tests/test_ingest.py).
        """
        data = np.asarray(data)
        if data.ndim != 2:
            log.fatal("Dataset data must be 2-dimensional")
        from .ingest import ArraySource, build_inner
        return build_inner(
            ArraySource(data, chunk_rows=chunk_rows),
            max_bin=max_bin, min_data_in_bin=min_data_in_bin,
            min_split_data=min_split_data,
            bin_construct_sample_cnt=bin_construct_sample_cnt,
            data_random_seed=data_random_seed,
            categorical_features=categorical_features,
            use_missing=use_missing, zero_as_missing=zero_as_missing,
            feature_names=feature_names, label=label, weight=weight,
            group=group, init_score=init_score, reference=reference,
            mappers=mappers, enable_bundle=enable_bundle,
            max_conflict_rate=max_conflict_rate,
            sparse_threshold=sparse_threshold, keep_raw=keep_raw,
            landing_factory=landing_factory)

    # ------------------------------------------------------------------
    @property
    def num_data(self) -> int:
        if self.binned is not None:
            return self.binned.shape[0]
        # device-landed matrix: the jax.Array is row-PADDED; the real
        # row count was recorded at landing time
        if self.device_binned is not None:
            return self._num_rows
        return 0

    @property
    def num_features(self) -> int:
        """Number of used (non-trivial) LOGICAL features (the stored
        `binned` width is num_groups <= num_features after EFB)."""
        return len(self.used_features)

    @property
    def num_groups(self) -> int:
        if self.binned is not None:
            return self.binned.shape[1]
        if self.device_binned is not None:
            return int(self.device_binned.shape[1])
        return 0

    @property
    def has_bundles(self) -> bool:
        return self.groups is not None and bool(self.groups.is_bundled.any())

    def feature_mapper(self, inner_idx: int) -> BinMapper:
        return self.mappers[self.used_features[inner_idx]]

    def feature_infos(self) -> List[str]:
        """Per-ORIGINAL-column info strings for the model text header
        (reference: Dataset::feature_infos, dataset.h:518-530)."""
        used = set(self.used_features)
        return [self.mappers[j].bin_info() if j in used else "none"
                for j in range(self.num_total_features)]

    def real_feature_index(self, inner_idx: int) -> int:
        return self.used_features[inner_idx]

    def num_bins_per_feature(self) -> np.ndarray:
        return np.asarray([self.feature_mapper(j).num_bin
                           for j in range(self.num_features)], dtype=np.int32)

    def max_num_bin(self) -> int:
        """Histogram width: max bins over stored GROUPS (feature-space
        scans use per-feature num_bin from feature_meta_arrays)."""
        if self.groups is not None and self.groups.num_groups:
            return int(self.groups.group_num_bin.max())
        nb = self.num_bins_per_feature()
        return int(nb.max()) if len(nb) else 1

    def feature_meta_arrays(self) -> Dict[str, np.ndarray]:
        """Static per-feature metadata consumed by the device split finder.

        Includes the EFB layout: `group` / `offset` locate each feature's
        bin slice inside the stored group columns; `is_bundled` marks
        features whose default-bin mass must be reconstructed from leaf
        totals (FixHistogram, dataset.cpp:747-767)."""
        f = self.num_features
        num_bin = np.zeros(f, dtype=np.int32)
        missing_type = np.zeros(f, dtype=np.int32)
        default_bin = np.zeros(f, dtype=np.int32)
        is_categorical = np.zeros(f, dtype=bool)
        for j in range(f):
            m = self.feature_mapper(j)
            num_bin[j] = m.num_bin
            missing_type[j] = m.missing_type
            default_bin[j] = m.default_bin
            is_categorical[j] = m.bin_type == BIN_CATEGORICAL
        if self.groups is not None and f:
            group = self.groups.group_of.astype(np.int32)
            offset = self.groups.offset_of.astype(np.int32)
            is_bundled = self.groups.is_bundled.copy()
        else:
            group = np.arange(f, dtype=np.int32)
            offset = np.zeros(f, dtype=np.int32)
            is_bundled = np.zeros(f, dtype=bool)
        return {"num_bin": num_bin, "missing_type": missing_type,
                "default_bin": default_bin, "is_categorical": is_categorical,
                "group": group, "offset": offset, "is_bundled": is_bundled}

    # ------------------------------------------------------------------
    # binary serialization (reference: Dataset::SaveBinaryFile, dataset.h:386,
    # DatasetLoader::LoadFromBinFile, dataset_loader.cpp:265-430).
    # Writes ride the ingest cache (versioned + checksummed + mmap-able,
    # ingest/cache.py); the v1 reader below stays for old artifacts.
    def save_binary(self, filename: str, fingerprint: str = "") -> None:
        from .ingest import save_cache
        save_cache(self, filename, fingerprint=fingerprint)

    @classmethod
    def load_binary(cls, filename: str, expected_fingerprint=None,
                    mmap_binned: bool = True) -> "Dataset":
        from .ingest import CACHE_MAGIC, load_cache
        with open(filename, "rb") as fh:
            head = fh.read(max(len(CACHE_MAGIC), len(_BINARY_MAGIC)))
        if head.startswith(CACHE_MAGIC):
            return load_cache(filename,
                              expected_fingerprint=expected_fingerprint,
                              mmap_binned=mmap_binned)
        return cls._load_binary_v1(filename)

    @classmethod
    def _load_binary_v1(cls, filename: str) -> "Dataset":
        import json
        ds = cls()
        with open(filename, "rb") as fh:
            magic = fh.read(len(_BINARY_MAGIC))
            if magic != _BINARY_MAGIC:
                log.fatal("%s is not a lightgbm_tpu binary dataset" % filename)
            (mlen,) = struct.unpack("<q", fh.read(8))
            meta = json.loads(fh.read(mlen).decode())
            ds.feature_names = meta["feature_names"]
            ds.used_features = [int(x) for x in meta["used_features"]]
            ds.num_total_features = int(meta["num_total_features"])
            ds.max_bin = int(meta["max_bin"])
            ds.mappers = [BinMapper.from_dict(d) for d in meta["mappers"]]
            if meta.get("groups") is not None:
                from .efb import FeatureGroups
                num_bins = np.asarray(
                    [ds.mappers[j].num_bin for j in ds.used_features], np.int32)
                ds.groups = FeatureGroups(
                    [[int(j) for j in g] for g in meta["groups"]], num_bins)
            arrays = []
            for _ in range(5):
                code = fh.read(1)
                arrays.append(None if code == b"N" else np.load(fh, allow_pickle=False))
        ds.binned, label, weights, qb, init = arrays
        ds.metadata = Metadata(0 if ds.binned is None else ds.binned.shape[0])
        if label is not None:
            ds.metadata.set_label(label)
        if weights is not None:
            ds.metadata.set_weights(weights)
        if qb is not None:
            ds.metadata.query_boundaries = qb
            ds.metadata._update_query_weights()
        if init is not None:
            ds.metadata.set_init_score(init)
        return ds
