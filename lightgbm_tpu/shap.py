"""TreeSHAP feature contributions, vectorized over rows.

Re-implements the reference's `Tree::PredictContrib` path
(`src/io/tree.cpp:522-633`, the Lundberg & Lee TreeSHAP recursion with the
EXTEND/UNWIND path algebra — validated against brute-force Shapley
enumeration in tests). The reference recurses once per ROW per tree; here
the key observation is that the recursion's branching structure is
row-independent — only the hot/cold ("one") fractions differ per row — so
ONE walk of the tree carries [num_rows] vectors through the path algebra,
replacing the O(rows) Python recursions per tree with numpy elementwise
ops (100-1000x at MSLR/Higgs scale).

Output layout matches the reference / python-package: per row,
`num_features + 1` values per model-per-iteration (last column is the
expected value / bias).
"""
from __future__ import annotations

from typing import List

import numpy as np

from .binning import MISSING_NAN, MISSING_ZERO
from .tree import Tree


def _decision_vec(tree: Tree, node: int, data: np.ndarray) -> np.ndarray:
    """Vectorized go-left decision of one node for all rows [n]."""
    fval = data[:, tree.split_feature[node]]
    if tree.is_categorical_node(node):
        idx = int(tree.threshold[node])
        lo, hi = tree.cat_boundaries[idx], tree.cat_boundaries[idx + 1]
        words = tree.cat_threshold[lo:hi]
        v = np.where(np.isnan(fval), -1, fval).astype(np.int64)
        word_i = v // 32
        valid = (v >= 0) & (word_i < len(words))
        bits = np.zeros(len(fval), bool)
        if len(words):
            wi = np.clip(word_i, 0, len(words) - 1)
            bits = (words[wi] >> (v % 32).astype(np.uint32)) & 1 == 1
        return valid & bits
    mt = tree.missing_type_node(node)
    if mt == MISSING_NAN:
        is_missing = np.isnan(fval)
    elif mt == MISSING_ZERO:
        is_missing = np.isnan(fval) | (np.abs(fval) <= 1e-35)
    else:
        is_missing = np.zeros(len(fval), bool)
    numeric = fval <= tree.threshold[node]
    return np.where(is_missing, tree.default_left_node(node), numeric)


def _tree_shap_batch(tree: Tree, data: np.ndarray, phi: np.ndarray) -> None:
    """Accumulate one tree's SHAP values for ALL rows into
    phi[n, num_features + 1]."""
    n = data.shape[0]
    counts = tree.leaf_count[:tree.num_leaves].astype(np.float64)
    total_count = max(counts.sum(), 1.0)
    # bias = count-weighted expectation of the tree output (efficiency:
    # sum(phi) == f(x) exactly)
    phi[:, -1] += float((tree.leaf_value[:tree.num_leaves] * counts).sum()
                        / total_count)
    if tree.num_leaves <= 1:
        return

    def cnt(node: int) -> float:
        return float(tree.leaf_count[~node]) if node < 0 \
            else float(tree.internal_count[node])

    go_left_cache = {}

    def rec(node: int, ud: int, m_d: List[int], m_z: List[np.ndarray],
            m_o: List[np.ndarray], m_w: List[np.ndarray],
            pz: np.ndarray, po: np.ndarray, pf: int) -> None:
        # copy the path state (EXTEND mutates it)
        m_d = list(m_d[:ud]) + [pf]
        m_z = [a.copy() for a in m_z[:ud]] + [pz]
        m_o = [a.copy() for a in m_o[:ud]] + [po]
        m_w = [a.copy() for a in m_w[:ud]] + [
            np.ones(n) if ud == 0 else np.zeros(n)]
        # EXTEND (tree.cpp:560-575), elementwise over rows
        for i in range(ud - 1, -1, -1):
            m_w[i + 1] += po * m_w[i] * (i + 1) / (ud + 1)
            m_w[i] = pz * m_w[i] * (ud - i) / (ud + 1)

        if node < 0:
            leaf_value = float(tree.leaf_value[~node])
            for i in range(1, ud + 1):
                # UNWOUND PATH SUM (tree.cpp:599-615)
                one = m_o[i]
                zero = m_z[i]
                nn = m_w[ud].copy()
                total = np.zeros(n)
                for j in range(ud - 1, -1, -1):
                    safe_one = np.where(one != 0, one, 1.0)
                    tmp = nn * (ud + 1) / ((j + 1) * safe_one)
                    with_one = tmp
                    with_zero = m_w[j] / (zero * (ud - j) / (ud + 1))
                    total += np.where(one != 0, with_one, with_zero)
                    nn = m_w[j] - tmp * zero * (ud - j) / (ud + 1)
                phi[:, m_d[i]] += total * (one - zero) * leaf_value
            return

        f = int(tree.split_feature[node])
        if node not in go_left_cache:
            go_left_cache[node] = _decision_vec(tree, node, data)
        go_left = go_left_cache[node]
        left, right = int(tree.left_child[node]), int(tree.right_child[node])
        denom = max(cnt(node), 1.0)
        iz = np.ones(n)
        io = np.ones(n)
        pi_found = -1
        for i in range(1, ud + 1):
            if m_d[i] == f:
                pi_found = i
                break
        if pi_found >= 0:
            iz = m_z[pi_found].copy()
            io = m_o[pi_found].copy()
            # UNWIND (tree.cpp:577-597), elementwise over rows
            one = m_o[pi_found]
            zero = m_z[pi_found]
            nn = m_w[ud].copy()
            for j in range(ud - 1, -1, -1):
                safe_one = np.where(one != 0, one, 1.0)
                new_w_one = nn * (ud + 1) / ((j + 1) * safe_one)
                new_w_zero = m_w[j] * (ud + 1) / (zero * (ud - j))
                tmp = m_w[j].copy()
                m_w[j] = np.where(one != 0, new_w_one, new_w_zero)
                nn = tmp - m_w[j] * zero * (ud - j) / (ud + 1)
            for j in range(pi_found, ud):
                m_d[j] = m_d[j + 1]
                m_z[j] = m_z[j + 1]
                m_o[j] = m_o[j + 1]
                # weights stay in place
            m_d = m_d[:ud]
            m_z = m_z[:ud]
            m_o = m_o[:ud]
            m_w = m_w[:ud]
            ud -= 1

        # each child is visited once; the per-row hot/cold split lives in
        # the "one" fraction: rows that went to this child carry io, the
        # rest 0 (the reference's hot/cold recursion collapses into this)
        for child, went in ((left, go_left), (right, ~go_left)):
            cz = cnt(child) / denom
            rec(child, ud + 1, m_d, m_z, m_o, m_w,
                cz * iz, np.where(went, io, 0.0), f)

    rec(0, 0, [-1], [np.ones(n)], [np.ones(n)], [np.ones(n)],
        np.ones(n), np.ones(n), -1)


def _tree_shap(tree: Tree, row: np.ndarray, phi: np.ndarray) -> None:
    """Single-row convenience wrapper over the batched recursion."""
    out = phi[None, :].copy()
    _tree_shap_batch(tree, row[None, :], out)
    phi[:] = out[0]


def predict_contrib(booster, data: np.ndarray, num_iteration: int = -1) -> np.ndarray:
    """SHAP contributions for every row (reference: PredictContrib path via
    c_api predict_type=C_API_PREDICT_CONTRIB)."""
    data = np.atleast_2d(np.asarray(data, np.float64))
    n = data.shape[0]
    nf = booster.max_feature_idx + 1
    k = booster.num_tree_per_iteration
    total = len(booster.models)
    if num_iteration > 0:
        total = min(total, num_iteration * k)
    if any(getattr(booster.models[i], "is_linear", False)
           for i in range(total)):
        from . import log
        raise log.LightGBMError(
            "predict_contrib does not support linear_tree models: the "
            "TreeSHAP recursion attributes constant leaf outputs only "
            "and would silently drop the per-leaf linear terms; use "
            "predict() or retrain with linear_tree=false")
    out = np.zeros((n, k, nf + 1))
    for i in range(total):
        tree = booster.models[i]
        cls = i % k
        _tree_shap_batch(tree, data, out[:, cls])
    if booster.average_output and total > 0:
        out /= max(total // k, 1)
    out[:, :, -1] += booster.init_score_bias
    return out.reshape(n, k * (nf + 1)) if k > 1 else out.reshape(n, nf + 1)
